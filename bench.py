"""Driver benchmark: TPC-H Q1 wall-clock through the full engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value       = lineitem rows/sec through the flagship Q1 pipeline
              (parse -> plan -> jitted scan/filter/project/grouped-agg), best
              of BENCH_RUNS timed runs after a compile warmup.
vs_baseline = speedup vs the single-threaded numpy reference interpreter
              (exec/reference.py) on the same machine/data — the stand-in for
              the reference's single-node row-at-a-time engine, measured fresh
              each round so the ratio tracks engine improvements only.

Env knobs: BENCH_SF (default 10), BENCH_RUNS (default 3),
BENCH_QUERY (q1|q6|q6z|q3g|q3k|xchg|serve|spill|ft|aqe).

q1/q6/q6z/q1g/q3k lines also carry a "scan_kernel" object: best-of-N
walls and effective_scan_gbps for the same query pinned to
scan_kernel=pallas and scan_kernel=xla (plus pallas_vs_xla, the
xla/pallas wall ratio), so TPU rounds measure the fused Pallas scan
kernel against the XLA chain and the r04 15 GB/s baseline directly.
BENCH_QUERY=q3k is the Q3-shaped probe-join+agg: the orders build
table rides inside the scan kernel launch (kernels/join.py), so the
pinned comparison covers the in-kernel join probe alongside the
scan/agg-only shapes.

BENCH_QUERY=serve is the serving-tier benchmark: BENCH_SERVE_CLIENTS
concurrent statement-protocol clients (default 4) each issuing
BENCH_SERVE_REQUESTS parameterized EXECUTEs (default 15) over repeated
TPC-H shapes against one coordinator.  Reports p50/p99 latency, QPS,
and the canonical plan-cache hit rate (>= 0.9 expected after warmup —
everything after the first compile of each shape skips
parse/plan/optimize and XLA compilation).

BENCH_QUERY=q6z is Q6 plus a selective orderkey range predicate
(cutting the bottom BENCH_Q6Z_FRACTION of the key domain, default 2%).
lineitem is laid out in orderkey order, so the resident store's zone
maps prune almost every chunk — the run demonstrates zone-map skipping
(zone_map_skip_fraction > 0) where plain Q6's uniformly random shipdate
cannot.  Every run reports a "storage" object: cache hit rate,
encoded-vs-plain resident bytes (the HBM traffic the encodings saved),
and the zone-map skip fraction.

BENCH_QUERY=xchg is the shuffle benchmark: a hash-exchange-heavy
aggregation over a real loopback HTTP cluster (BENCH_XCHG_WORKERS
workers, default 2; BENCH_XCHG_TASKS tasks per stage, default 4; sf
defaults to 0.1).  It reports bytes moved on the wire, the exchange
compression ratio, pull/decode walls, and the network/compute overlap
fraction (1 - consumer wait / client drain wall), plus
vs_sequential_client = sequential-client wall / concurrent-client wall
for the same query — the headline of the concurrent ExchangeClient
round.  Grouped-execution overlap mode:
BENCH_GROUPED_LIFESPANS (0=auto, 1=off, N>=2 force N bucket lifespans)
and BENCH_PREFETCH_DEPTH (lifespans staged ahead; 0 = serial) — when the
run produced grouped runtime stats, the JSON line gains a
"grouped" object with per-bucket gen/compute/run walls and the measured
overlap fraction (1 - run / (gen + compute); 0 means fully serial).
BENCH_QUERY=q3g is the grouped-eligible shape (TPC-H Q3 keyed on
l_orderkey, the lineitem/orders bucket column).

BENCH_QUERY=spill is the memory-arbitration benchmark: a q18-shaped
join+agg run once unconstrained (to measure peak pool reservation),
then re-run under a budget of BENCH_SPILL_BUDGET_FRACTION of that peak
(default 0.2, i.e. <25%).  The constrained run must return identical
rows; the JSON line reports spilled bytes (host + disk tiers), spill
throughput GB/s, the async-eviction overlap fraction, revocation/
arbitration counts, and wall_ratio = constrained / unconstrained wall
— the slowdown paid to run a query ~5x bigger than its memory.

BENCH_QUERY=ft is the fault-tolerance cost benchmark: the q18-shaped
join+agg through a loopback HTTP cluster (BENCH_FT_WORKERS workers,
default 2; BENCH_FT_TASKS tasks per stage, default 4) run side by side
under retry-policy=query (streamed exchange) and retry-policy=task
(every stage output durably spooled through the two-tier LZ4 spool
before the producer acks).  Both runs must return identical rows; the
JSON line reports wall_ratio = task / query wall — the steady-state
price of durability — plus spooled pages/bytes, the spool compression
ratio, bytes flushed to the disk tier, and spool_throughput_gbps (raw
bytes through the staging path per second spent staging).

BENCH_QUERY=aqe is the adaptive-execution benchmark: a Q19-shaped
selective join (the orders build side cut to BENCH_AQE_FRACTION of its
key domain, default 0.2%) through the multi-task scheduler with runtime
dynamic filters + cardinality-driven exchange decisions ON vs OFF.  All
runs — off, on, and the zero wait-timeout fallback — must match the
numpy reference oracle row for row; the JSON line reports the
dynamic-only zone-map chunk_prune_fraction, rows scanned with/without
runtime filters, the adaptive exchange decisions taken (broadcast
flips / side swaps / kept), and wall_ratio = adaptive-on / adaptive-off.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# BENCH_XCHG_DEVICES=N virtualizes N host devices so the xchg benchmark's
# ICI-fabric pass has a mesh even on CPU (must land before jax init).
if os.environ.get("BENCH_XCHG_DEVICES"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ["BENCH_XCHG_DEVICES"]).strip()

# Honor JAX_PLATFORMS=cpu even under the axon TPU plugin, which ignores the
# env var (same dance as tests/conftest.py / __graft_entry__.py).
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

Q1 = """
SELECT returnflag, linestatus,
       sum(quantity) AS sum_qty,
       sum(extendedprice) AS sum_base_price,
       sum(extendedprice * (1 - discount)) AS sum_disc_price,
       sum(extendedprice * (1 - discount) * (1 + tax)) AS sum_charge,
       avg(quantity) AS avg_qty,
       avg(extendedprice) AS avg_price,
       avg(discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY returnflag, linestatus
ORDER BY returnflag, linestatus
"""

Q6 = """
SELECT sum(extendedprice * discount) AS revenue
FROM lineitem
WHERE shipdate >= DATE '1994-01-01'
  AND shipdate < DATE '1995-01-01'
  AND discount BETWEEN 0.05 AND 0.07
  AND quantity < 24
"""

# high-cardinality grouped Q1 variant: the Q1 aggregate core re-keyed on
# orderkey % BENCH_Q1G_GROUPS (default 4096), so the scan kernel's
# grouped modes (span / hashed open addressing) carry the aggregation
# instead of the direct G<=64 grid; {groups} substituted in main()
Q1G = """
SELECT gkey,
       sum(quantity) AS sum_qty,
       sum(extendedprice) AS sum_base_price,
       sum(extendedprice * (1 - discount)) AS sum_disc_price,
       avg(discount) AS avg_disc,
       count(*) AS count_order
FROM (SELECT orderkey % {groups} AS gkey, quantity, extendedprice,
             discount, shipdate
      FROM lineitem)
WHERE shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY gkey
"""

# grouped-eligible: aggregation keyed on the lineitem/orders bucket
# column, so forced lifespans (BENCH_GROUPED_LIFESPANS >= 2) run the
# bucket-at-a-time pipeline and expose the prefetch overlap stats
Q3G = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM orders, lineitem
WHERE l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey
ORDER BY revenue DESC LIMIT 10
"""

# join-kernel eligible: the same Q3 probe chain (filtered orders build,
# lineitem probe side) WITHOUT the order/limit tail, grouped on the
# bucket key — BENCH_QUERY=q3k pins the pallas-vs-xla scan_kernel
# comparison on it so the real-TPU re-measure covers the in-kernel join
# probe (kernels/join.py) end to end
Q3K = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       count(*) AS cnt
FROM orders, lineitem
WHERE l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey
"""


# shuffle-heavy: high-cardinality group-by forces a partial agg -> hash
# exchange -> final agg plan, so most of the partial-agg output crosses
# the wire between stages
XCHG = """
SELECT l_orderkey, count(*) AS cnt, sum(l_quantity) AS qty,
       sum(l_extendedprice) AS price
FROM lineitem
GROUP BY l_orderkey
"""


def bench_xchg(runs):
    sf = float(os.environ.get("BENCH_SF", "0.1"))
    n_workers = int(os.environ.get("BENCH_XCHG_WORKERS", "2"))
    n_tasks = int(os.environ.get("BENCH_XCHG_TASKS", "4"))

    from presto_tpu.connectors import tpch
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.exchange import EXCHANGE_METRICS
    from presto_tpu.worker.server import WorkerServer

    schema = f"sf{sf:g}"
    n_rows = tpch._table_rows("lineitem", sf)
    workers = [WorkerServer() for _ in range(n_workers)]
    try:
        uris = [w.uri for w in workers]
        session = {"exchange_compression": "true"}
        runner = HttpQueryRunner(uris, schema, n_tasks=n_tasks,
                                 session=session)
        runner.execute(XCHG)              # warmup: compiles + faults data

        EXCHANGE_METRICS.reset()
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            result = runner.execute(XCHG)
            best = min(best, time.perf_counter() - t0)
        assert result.rows, "benchmark query returned no rows"
        x = EXCHANGE_METRICS.snapshot()

        # sequential-client baseline: same cluster, same query, pullers
        # forced to one thread (drains one upstream location at a time)
        seq = HttpQueryRunner(uris, schema, n_tasks=n_tasks,
                              session={**session,
                                       "exchange_client_threads": "1"})
        seq.execute(XCHG)                 # warmup
        seq_best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            seq.execute(XCHG)
            seq_best = min(seq_best, time.perf_counter() - t0)

        drain = x["drain_wall_s"]
        out = {
            "metric": f"xchg_sf{sf:g}_rows_per_sec",
            "value": round(n_rows / best, 1),
            "unit": "rows/s",
            "wall_s": round(best, 4),
            "vs_sequential_client": round(seq_best / best, 3),
            "exchange": {
                "workers": n_workers,
                "tasks_per_stage": n_tasks,
                "clients": x["clients"],
                "pages_moved": x["pages"],
                "bytes_moved": x["bytes"],
                "uncompressed_bytes": x["uncompressed_bytes"],
                "compression_ratio": round(
                    x["uncompressed_bytes"] / x["bytes"], 3)
                if x["bytes"] else 0.0,
                "responses": x["responses"],
                "pull_wall_s": round(x["pull_wall_s"], 4),
                "decode_wall_s": round(x["decode_wall_s"], 4),
                "wait_wall_s": round(x["wait_wall_s"], 4),
                "drain_wall_s": round(drain, 4),
                # fraction of client-open time the consumers were NOT
                # blocked waiting on the network: shuffle hidden behind
                # compute (and behind sibling pulls)
                "overlap_fraction": round(
                    max(0.0, 1.0 - x["wait_wall_s"] / drain), 4)
                if drain else 0.0,
                "buffered_peak_bytes": x["buffered_bytes_peak"],
            },
        }

        # --- fabric comparison: the same shuffle through the in-process
        # mesh scheduler with the ICI all_to_all fabric (needs >= 2
        # devices; BENCH_XCHG_DEVICES=N virtualizes a CPU mesh).  Both
        # fabrics must return identical rows; ici moves ~0 host bytes and
        # reports the chunked compute/collective overlap fraction.
        import jax
        devs = jax.devices()
        out["fabrics"] = {
            "http": {
                "wall_s": round(best, 4),
                "bytes_moved": x["bytes"],
                "host_bytes": x["bytes"],
                "wait_wall_s": round(x["wait_wall_s"], 4),
                "drain_wall_s": round(drain, 4),
            },
        }
        if len(devs) >= 2:
            from presto_tpu.exec.pipeline import ExecutionConfig
            from presto_tpu.exec.runner import (DistributedQueryRunner,
                                                _assert_rows_equal)
            from presto_tpu.parallel.fabric import FABRIC_METRICS
            from presto_tpu.parallel.mesh import make_mesh
            mesh = make_mesh(len(devs))
            ici = DistributedQueryRunner(
                schema, config=ExecutionConfig(exchange_fabric="ici"),
                n_tasks=len(devs), mesh=mesh)
            ici.execute(XCHG)             # warmup: compiles the exchange
            FABRIC_METRICS.reset()
            ici_best = float("inf")
            for _ in range(runs):
                t0 = time.perf_counter()
                ici_result = ici.execute(XCHG)
                ici_best = min(ici_best, time.perf_counter() - t0)
            _assert_rows_equal(ici_result, result, ordered=False)
            fi = FABRIC_METRICS.snapshot()["ici"]
            out["fabrics"]["ici"] = {
                "wall_s": round(ici_best, 4),
                "devices": len(devs),
                "exchanges": fi["exchanges"],
                "chunks": fi["chunks"],
                "bytes_moved": fi["bytes_moved"],
                "host_bytes": fi["host_bytes"],
                "dispatch_wall_s": round(fi["exchange_wall_s"], 4),
                "wait_wall_s": round(fi["wait_wall_s"], 4),
                "drain_wall_s": round(fi["compute_wall_s"], 4),
            }
            out["ici_overlap_fraction"] = round(
                fi["overlap_fraction"], 4)
        out["process_metrics"] = _process_metrics()
        print(json.dumps(out))
    finally:
        for w in workers:
            w.close()


# q18 core: every order's total quantity via a lineitem<->orders hash
# join feeding a high-cardinality grouped aggregation — both the join
# build and the agg state scale with the data, so a small budget forces
# the arbitrator to revoke the build into the two-tier spill store
SPILL = """
SELECT l_orderkey, max(o_totalprice) AS price, sum(l_quantity) AS qty
FROM lineitem, orders
WHERE l_orderkey = o_orderkey
GROUP BY l_orderkey
ORDER BY qty DESC, l_orderkey
LIMIT 100
"""


def bench_spill(runs):
    """Budget-constrained join+agg: measure the cost of running a query
    whose working set exceeds the memory pool by ~5x."""
    import dataclasses

    from presto_tpu.exec.memory import MEMORY_METRICS
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.exec.runner import LocalQueryRunner, _assert_rows_equal

    sf = float(os.environ.get("BENCH_SF", "0.1"))
    fraction = float(os.environ.get("BENCH_SPILL_BUDGET_FRACTION", "0.2"))
    schema = f"sf{sf:g}"

    from presto_tpu.connectors import tpch
    n_rows = tpch._table_rows("lineitem", sf)

    # moderate batches: the constrained run's agg-state estimate (and so
    # its re-partition depth / recompile count) scales with batch size
    base_cfg = ExecutionConfig(batch_rows=1 << 16, spill_enabled=True)
    free = LocalQueryRunner(schema=schema, config=base_cfg)
    free.execute(SPILL)                   # warmup: compiles + faults data
    free_best, free_result = float("inf"), None
    peak = 0
    for _ in range(runs):
        t0 = time.perf_counter()
        free_result = free.execute(SPILL)
        free_best = min(free_best, time.perf_counter() - t0)
        peak = max(peak, free_result.peak_memory_bytes or 0)
    assert free_result.rows, "benchmark query returned no rows"
    assert peak > 0, "unconstrained run recorded no peak reservation"

    budget = max(1, int(peak * fraction))
    constrained = LocalQueryRunner(schema=schema, config=dataclasses.replace(
        base_cfg, memory_budget_bytes=budget))
    constrained.execute(SPILL)            # warmup under the budget
    MEMORY_METRICS.reset()
    con_best, con_result = float("inf"), None
    for _ in range(runs):
        t0 = time.perf_counter()
        con_result = constrained.execute(SPILL)
        con_best = min(con_best, time.perf_counter() - t0)
    _assert_rows_equal(con_result, free_result, ordered=True)
    m = MEMORY_METRICS.snapshot()

    spilled = m["spilled_bytes"]
    out = {
        "metric": f"spill_sf{sf:g}_rows_per_sec",
        "value": round(n_rows / con_best, 1),
        "unit": "rows/s",
        "wall_s": round(con_best, 4),
        "unconstrained_wall_s": round(free_best, 4),
        # the headline: the slowdown paid to run under fraction*peak
        "wall_ratio": round(con_best / free_best, 3),
        "spill": {
            "unconstrained_peak_bytes": peak,
            "budget_bytes": budget,
            "budget_fraction": fraction,
            "spilled_bytes": spilled,
            "disk_spilled_bytes": m["disk_spilled_bytes"],
            "unspilled_bytes": m["unspilled_bytes"],
            "spill_throughput_gbps": round(
                spilled / m["spill_wall_s"] / 1e9, 3)
            if m["spill_wall_s"] else 0.0,
            # fraction of device->host eviction hidden behind operator
            # compute by the double-buffered staging thread
            "eviction_overlap_fraction": round(
                m["spill_overlap_fraction"], 4),
            "revocations": m["revocations"],
            "revoked_bytes": m["revoked_bytes"],
            "arbitrations": m["arbitrations"],
            "arbitration_failures": m["arbitration_failures"],
        },
    }
    out["process_metrics"] = _process_metrics()
    print(json.dumps(out))


def bench_ft(runs):
    """Fault-tolerance cost benchmark: the q18-shaped join+agg through a
    real loopback HTTP cluster under retry-policy=query (direct streamed
    exchange, a failure restarts the ancestor cascade) vs
    retry-policy=task (every stage output durably spooled, a failure
    restarts one task).  No fault is injected — this measures the
    steady-state price of durability: wall_ratio = task / query wall,
    plus spooled bytes and the spool staging throughput."""
    sf = float(os.environ.get("BENCH_SF", "0.1"))
    n_workers = int(os.environ.get("BENCH_FT_WORKERS", "2"))
    n_tasks = int(os.environ.get("BENCH_FT_TASKS", "4"))

    from presto_tpu.connectors import tpch
    from presto_tpu.exec.runner import _assert_rows_equal
    from presto_tpu.worker.coordinator import HttpQueryRunner
    from presto_tpu.worker.spooling import SPOOL_METRICS
    from presto_tpu.worker.server import WorkerServer

    schema = f"sf{sf:g}"
    n_rows = tpch._table_rows("lineitem", sf)
    workers = [WorkerServer() for _ in range(n_workers)]
    try:
        uris = [w.uri for w in workers]

        base = HttpQueryRunner(uris, schema, n_tasks=n_tasks,
                               session={"retry_policy": "query"})
        base.execute(SPILL)               # warmup: compiles + faults data
        base_best, base_result = float("inf"), None
        for _ in range(runs):
            t0 = time.perf_counter()
            base_result = base.execute(SPILL)
            base_best = min(base_best, time.perf_counter() - t0)
        assert base_result.rows, "benchmark query returned no rows"

        ft = HttpQueryRunner(uris, schema, n_tasks=n_tasks,
                             session={"retry_policy": "task"})
        ft.execute(SPILL)                 # warmup under the spool path
        SPOOL_METRICS.reset()
        ft_best, ft_result = float("inf"), None
        for _ in range(runs):
            t0 = time.perf_counter()
            ft_result = ft.execute(SPILL)
            ft_best = min(ft_best, time.perf_counter() - t0)
        _assert_rows_equal(ft_result, base_result, ordered=True)
        s = SPOOL_METRICS.snapshot()

        out = {
            "metric": f"ft_sf{sf:g}_rows_per_sec",
            "value": round(n_rows / ft_best, 1),
            "unit": "rows/s",
            "wall_s": round(ft_best, 4),
            "query_policy_wall_s": round(base_best, 4),
            # the headline: the steady-state price of durable spooling
            "wall_ratio": round(ft_best / base_best, 3),
            "spool": {
                "workers": n_workers,
                "tasks_per_stage": n_tasks,
                "timed_runs": runs,
                "spooled_pages": s["spooled_pages"],
                "spooled_bytes": s["spooled_bytes"],
                "spooled_raw_bytes": s["spooled_raw_bytes"],
                "compression_ratio": round(
                    s["spooled_raw_bytes"] / s["spooled_bytes"], 3)
                if s["spooled_bytes"] else 0.0,
                "disk_bytes": s["disk_bytes"],
                "flushes": s["flushes"],
                "read_pages": s["read_pages"],
                "read_bytes": s["read_bytes"],
                "spool_throughput_gbps": round(
                    s["spooled_raw_bytes"] / s["spool_wall_s"] / 1e9, 3)
                if s["spool_wall_s"] else 0.0,
            },
        }
        out["process_metrics"] = _process_metrics()
        print(json.dumps(out))
    finally:
        for w in workers:
            w.close()


# Q19-shaped selective join: the orders build side collapses to a tiny
# fraction of its key domain, lineitem is laid out in orderkey order —
# so the runtime dynamic filter's [min, max] lands on the zone maps and
# prunes almost every probe-side chunk that static planning had to scan.
# The `o_orderkey + 0` spelling is deliberate: the arithmetic hides the
# range from the stats calculator (UNKNOWN_FILTER_COEFFICIENT), so the
# PLANNED build stays near the full orders table while the OBSERVED
# build collapses to ~cutoff rows — exactly the >=10x gap the runtime
# partitioned->broadcast exchange flip exists to exploit
AQE = """
SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue, count(*) AS cnt
FROM lineitem, orders
WHERE l_orderkey = o_orderkey AND o_orderkey + 0 < {cutoff}
"""


def bench_aqe(runs):
    """Adaptive-query-execution benchmark: the selective join through the
    multi-task scheduler with runtime dynamic filters + cardinality-driven
    exchange decisions ON vs OFF.  All runs (off, on, and the zero
    wait-timeout fallback) must return rows identical to the numpy
    reference oracle; the JSON line reports the dynamic-only prune
    fraction, rows scanned with/without runtime filters, the adaptive
    exchange decisions taken, and the on/off wall ratio."""
    import dataclasses

    from presto_tpu.connectors import tpch
    from presto_tpu.exec.adaptive import (ADAPTIVE_METRICS,
                                          reset_adaptive_metrics)
    from presto_tpu.exec.pipeline import ExecutionConfig
    from presto_tpu.exec.runner import (DistributedQueryRunner,
                                        _assert_rows_equal)

    sf = float(os.environ.get("BENCH_SF", "0.1"))
    frac = float(os.environ.get("BENCH_AQE_FRACTION", "0.002"))
    n_tasks = int(os.environ.get("BENCH_AQE_TASKS", "2"))
    # plan-time threshold BELOW the (opaque-predicate-inflated) build
    # estimate of ~0.9x orders, so the join plans partitioned — and the
    # runtime flip to broadcast (observed rows >= 10x below plan) is the
    # adaptive path's call to make
    thresh = int(os.environ.get("BENCH_AQE_BROADCAST_THRESHOLD", "5000"))
    schema = f"sf{sf:g}"
    n_rows = tpch._table_rows("lineitem", sf)
    cutoff = max(2, int(tpch._table_rows("orders", sf) * frac))
    sql = AQE.format(cutoff=cutoff)

    # zones finer than scan chunks: the default 64k-row zones collapse a
    # small-SF table into one zone, leaving nothing for the dynamic
    # filter's bounds to discriminate
    base = ExecutionConfig(batch_rows=1 << 16, storage_zone_rows=8192)

    def timed(cfg):
        runner = DistributedQueryRunner(schema, config=cfg,
                                        n_tasks=n_tasks,
                                        broadcast_threshold=thresh)
        runner.execute(sql)                  # warmup: compiles
        reset_adaptive_metrics()
        best, result = float("inf"), None
        for _ in range(runs):
            t0 = time.perf_counter()
            result = runner.execute(sql)
            best = min(best, time.perf_counter() - t0)
        return runner, best, result, ADAPTIVE_METRICS.snapshot()

    off_cfg = dataclasses.replace(base, dynamic_filtering=False,
                                  adaptive_exchange=False)
    off_runner, off_best, off_result, _ = timed(off_cfg)
    oracle = off_runner.execute_reference(sql)
    _assert_rows_equal(off_result, oracle, ordered=False)

    _on_runner, on_best, on_result, m = timed(base)
    _assert_rows_equal(on_result, oracle, ordered=False)

    # wait-timeout fallback: scans that would miss their filter proceed
    # unfiltered after a 0s wait — rows must STILL match the oracle
    fb_cfg = dataclasses.replace(base,
                                 dynamic_filtering_wait_timeout_s=0.0)
    _fb_runner, _fb_best, fb_result, _ = timed(fb_cfg)
    _assert_rows_equal(fb_result, oracle, ordered=False)

    rows_in = m["filter_rows_in"]
    pruned = m["filter_rows_pruned"]
    scanned_without = n_rows * runs
    assert m["filter_chunks_skipped"] > 0 or pruned > 0, \
        "adaptive run applied no dynamic pruning"
    assert m["exchange_broadcast_flips"] > 0, \
        "planned-partitioned join did not flip to broadcast at runtime"
    out = {
        "metric": f"aqe_sf{sf:g}_wall_ratio",
        "value": round(on_best / off_best, 4) if off_best else None,
        "unit": "adaptive_on/off wall",
        "wall_on_s": round(on_best, 4),
        "wall_off_s": round(off_best, 4),
        "lineitem_rows": n_rows,
        "cutoff": cutoff,
        "timed_runs": runs,
        "dynamic_filters": {
            "collected": m["filters_collected"],
            "applied": m["filters_applied"],
            "chunks_skipped": m["filter_chunks_skipped"],
            "rows_scanned_without_filters": scanned_without,
            "rows_scanned_with_filters": rows_in,
            # fraction of probe-side rows never read: dynamic-only
            # zone-map chunk pruning (no static predicate on lineitem)
            "chunk_prune_fraction": round(
                1 - rows_in / scanned_without, 4) if scanned_without
            else 0.0,
            # of the rows that WERE read, what the traced row filter cut
            "row_prune_fraction": round(pruned / rows_in, 4)
            if rows_in else 0.0,
            "wait_timeouts": m["filter_wait_timeouts"],
            "late_arrivals": m["filter_late_arrivals"],
        },
        "adaptive_exchange": {
            "broadcast_flips": m["exchange_broadcast_flips"],
            "side_swaps": m["exchange_side_swaps"],
            "kept": m["exchange_kept"],
        },
    }
    out["process_metrics"] = _process_metrics()
    print(json.dumps(out))


SERVE_SHAPES = [
    # (name, template, [value tuples cycled by the clients])
    ("q6p",
     "SELECT sum(l_extendedprice * l_discount) FROM lineitem "
     "WHERE l_discount BETWEEN ? AND ? AND l_quantity < ?",
     [("0.05", "0.07", "24"), ("0.04", "0.06", "25"),
      ("0.06", "0.08", "23"), ("0.03", "0.05", "30")]),
    ("scanp",
     "SELECT count(*), sum(l_extendedprice) FROM lineitem "
     "WHERE l_quantity < ? AND l_orderkey < ?",
     [("10", "1000"), ("20", "2000"), ("30", "3000"), ("15", "1500")]),
]


def _serve_warmup(server, schema, rows_check=True):
    """One compile per shape; every shape's template registered on the
    returned results map so client threads replay it via headers."""
    from presto_tpu.client import StatementClient
    warm = StatementClient(server.uri, schema=schema)
    first_ms = {}
    for name, template, values in SERVE_SHAPES:
        warm.prepared[name] = template
        t0 = time.perf_counter()
        r = warm.execute(f"EXECUTE {name} USING {', '.join(values[0])}")
        first_ms[name] = (time.perf_counter() - t0) * 1000
        if rows_check:
            assert r.rows, f"warmup {name} returned no rows"
    return first_ms


def _serve_load(server, schema, n_clients, per_client):
    """The measured phase: N client threads replaying the shape mix.
    Returns (sorted latencies seconds, wall seconds)."""
    import threading
    from presto_tpu.client import StatementClient
    latencies, lat_lock = [], threading.Lock()

    def client_loop(cid):
        c = StatementClient(server.uri, schema=schema,
                            source=f"bench-{cid}")
        c.prepared = {n: t for n, t, _ in SERVE_SHAPES}
        mine = []
        for i in range(per_client):
            name, _t, values = SERVE_SHAPES[(cid + i) % len(SERVE_SHAPES)]
            vals = values[(cid * per_client + i) % len(values)]
            t0 = time.perf_counter()
            r = c.execute(f"EXECUTE {name} USING {', '.join(vals)}")
            mine.append(time.perf_counter() - t0)
            assert r.rows, "serve query returned no rows"
        with lat_lock:
            latencies.extend(mine)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client_loop, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()
    return latencies, wall


def _serve_pass_stats(latencies, wall):
    n = len(latencies)
    return {
        "requests": n,
        "qps": round(n / wall, 2),
        "p50_latency_ms": round(latencies[n // 2] * 1000, 2),
        "p99_latency_ms": round(
            latencies[min(n - 1, int(n * 0.99))] * 1000, 2),
    }


def _reset_serving_process_state():
    """Approximate a process restart for the warm-restart phase: drop
    every in-memory serving artifact (plan cache, prepared registry,
    fragment jits) so the next boot re-derives them — from the persistent
    compilation cache + sidecar when configured, from scratch when not."""
    from presto_tpu.serving import (FRAGMENT_JIT_CACHE, GLOBAL_PLAN_CACHE,
                                    PREPARED_REGISTRY, SERVING_METRICS)
    GLOBAL_PLAN_CACHE.invalidate_all()
    PREPARED_REGISTRY.clear()
    FRAGMENT_JIT_CACHE.invalidate_all()
    SERVING_METRICS.reset()


def bench_serve(runs):
    """Serving-tier benchmark: N concurrent clients hammering repeated
    parameterized shapes through the statement protocol.

    Three phases, one JSON line:
      batched / unbatched — the same load with the micro-batcher on vs
        off (serving.max-batch-size=1), side by side: p50/p99/QPS, the
        batch-occupancy histogram, and device-launch count vs query
        count (launches = queries - launches_saved).
      warm_restart — boot a server with the persistent compilation cache
        + plan-cache sidecar, serve, 'restart' (drop all in-memory
        serving state), boot again: the replayed boot should leave
        serving traffic with ZERO template recompiles, and the first
        query after reload far below the cold first query."""
    sf = float(os.environ.get("BENCH_SF", "0.1"))
    n_clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
    per_client = int(os.environ.get("BENCH_SERVE_REQUESTS", "15"))

    import shutil
    import tempfile

    from presto_tpu.serving import (GLOBAL_PLAN_CACHE, PREPARED_REGISTRY,
                                    SERVING_METRICS)
    from presto_tpu.worker.server import WorkerServer

    schema = f"sf{sf:g}"

    # -- pass 1: batching OFF (the baseline) ------------------------------
    server = WorkerServer(coordinator=True, max_batch_size=1)
    try:
        _serve_warmup(server, schema)
        SERVING_METRICS.reset()
        lat_off, wall_off = _serve_load(server, schema, n_clients,
                                        per_client)
        unbatched = _serve_pass_stats(lat_off, wall_off)
    finally:
        server.close()

    # -- pass 2: batching ON (same process, caches equally warm) ----------
    server = WorkerServer(coordinator=True)
    try:
        _serve_warmup(server, schema)
        # compile the vmapped batch widths OUTSIDE the measured phase:
        # two concurrent bursts let the adaptive batcher form (and trace)
        # the pow2 widths the measured load will hit
        for _ in range(2):
            _serve_load(server, schema, n_clients, 2)
        SERVING_METRICS.reset()
        lat_on, wall_on = _serve_load(server, schema, n_clients,
                                      per_client)
        sv = SERVING_METRICS.snapshot()
        batched = _serve_pass_stats(lat_on, wall_on)
        batched.update({
            "queries": batched["requests"],
            "device_launches":
                batched["requests"] - sv["servingBatchLaunchesSaved"],
            "batches": sv["servingBatches"],
            "batched_queries": sv["servingBatchQueries"],
            "launches_saved": sv["servingBatchLaunchesSaved"],
            "fallbacks": sv["servingBatchFallbacks"],
            "occupancy_histogram": sv["servingBatchOccupancy"],
            "padded_lanes": sv["servingBatchPaddedLanes"],
            "demux_ms": round(sv["servingBatchDemuxNanos"] / 1e6, 2),
        })
    finally:
        server.close()

    # -- pass 3: warm restart through the persistent caches ---------------
    persist_dir = tempfile.mkdtemp(prefix="presto_tpu_serve_bench_")
    warm_restart = {}
    try:
        kw = {"compilation_cache_dir": f"{persist_dir}/xla",
              "plan_cache_path": f"{persist_dir}/plans.jsonl"}
        _reset_serving_process_state()
        t0 = time.perf_counter()
        server = WorkerServer(coordinator=True, **kw)
        try:
            cold_first = _serve_warmup(server, schema)
            cold_boot_s = time.perf_counter() - t0
        finally:
            server.close()

        _reset_serving_process_state()          # the 'restart'
        t0 = time.perf_counter()
        server = WorkerServer(coordinator=True, **kw)   # replays sidecar
        try:
            boot_s = time.perf_counter() - t0
            SERVING_METRICS.reset()
            warm_first = _serve_warmup(server, schema)
            sv2 = SERVING_METRICS.snapshot()
            warm_restart = {
                "cold_first_query_ms": round(
                    max(cold_first.values()), 2),
                "cold_boot_s": round(cold_boot_s, 3),
                "warm_boot_s": round(boot_s, 3),
                "warm_first_query_ms": round(
                    max(warm_first.values()), 2),
                # the acceptance signal: serving traffic after the
                # replayed boot plans nothing from scratch
                "recompiles_after_reload":
                    sv2["planCacheMisses"] + sv2["preparedReplans"],
            }
        finally:
            server.close()
    finally:
        shutil.rmtree(persist_dir, ignore_errors=True)

    out = {
        "metric": f"serve_sf{sf:g}_qps",
        "value": batched["qps"],
        "unit": "queries/s",
        "wall_s": round(wall_on, 4),
        "serve": {
            "clients": n_clients,
            "requests": batched["requests"],
            "p50_latency_ms": batched["p50_latency_ms"],
            "p99_latency_ms": batched["p99_latency_ms"],
            "batched": batched,
            "unbatched": unbatched,
            "qps_speedup": round(
                batched["qps"] / unbatched["qps"], 2)
            if unbatched["qps"] else None,
            "warm_restart": warm_restart,
            "plan_cache_hit_rate": round(SERVING_METRICS.hit_rate(), 4),
            "plan_cache_hits": sv["planCacheHits"],
            "plan_cache_misses": sv["planCacheMisses"],
            "executable_builds": sv["executableBuilds"],
            "prepared_fast_path": sv["preparedFastPath"],
            "prepared_replans": sv["preparedReplans"],
            "plan_cache_entries": GLOBAL_PLAN_CACHE.info()["entries"],
            "prepared_statements":
                PREPARED_REGISTRY.info()["statements"],
        },
    }
    out["process_metrics"] = _process_metrics()
    print(json.dumps(out))


def _process_metrics():
    """Compact process-metrics snapshot attached to every BENCH_* JSON
    line — the same registries the telemetry exporter scrapes
    (presto_tpu/telemetry/otlp.py), so each benchmark record carries the
    engine state it ran under: fabric byte movement, serving-cache hit
    rates, storage-cache hit rate, and the scan-kernel counters."""
    from presto_tpu.exec.kernels.scan_kernel import KERNEL_METRICS
    from presto_tpu.parallel.fabric import FABRIC_METRICS
    from presto_tpu.serving import SERVING_METRICS
    from presto_tpu.storage import STORAGE_METRICS
    rates = FABRIC_METRICS.byte_rates()
    fabrics = {
        f: {"bytes_moved": s["bytes_moved"], "exchanges": s["exchanges"],
            "bytes_per_sec": round(rates.get(f, 0.0), 1)}
        for f, s in sorted(FABRIC_METRICS.snapshot().items())
        if s["exchanges"]}
    sm = STORAGE_METRICS
    lookups = sm["cache_hits"] + sm["cache_misses"]
    k = KERNEL_METRICS.snapshot()
    return {
        "fabric": fabrics,
        "serving": SERVING_METRICS.compact_snapshot(),
        "storage_cache_hit_rate": round(sm["cache_hits"] / lookups, 4)
        if lookups else 0.0,
        "kernel": {"scan_programs": k["scan_programs"],
                   "declined": k["declined"],
                   "dma_overlap_fraction": k["dma_overlap_fraction"]},
    }


def _backend_diagnostic(qname, exc):
    """Structured JSON on backend-init failure: the opaque rc=1 of
    BENCH_r05.json becomes an actionable record (what failed, on which
    platform request, and the knob that routes around it)."""
    return {
        "metric": f"tpch_{qname}_rows_per_sec",
        "value": None,
        "unit": "rows/s",
        "error": {
            "stage": "backend_init",
            "type": type(exc).__name__,
            "message": str(exc),
            "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
            "hint": "accelerator backend failed to initialize — an "
                    "environment problem, not an engine regression; set "
                    "JAX_PLATFORMS=cpu to fall back to the host backend",
        },
    }


def main():
    qname = os.environ.get("BENCH_QUERY", "q1")
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    try:
        import jax
        jax.devices()          # forces backend init (TPU plugin et al.)
    except Exception as e:
        print(json.dumps(_backend_diagnostic(qname, e)))
        return 1
    if qname == "xchg":
        return bench_xchg(runs)
    if qname == "serve":
        return bench_serve(runs)
    if qname == "spill":
        return bench_spill(runs)
    if qname == "ft":
        return bench_ft(runs)
    if qname == "aqe":
        return bench_aqe(runs)
    sf = float(os.environ.get("BENCH_SF", "10"))
    sql = {"q1": Q1, "q6": Q6, "q6z": Q6, "q3g": Q3G, "q1g": Q1G,
           "q3k": Q3K}[qname]
    if qname == "q1g":
        groups = int(os.environ.get("BENCH_Q1G_GROUPS", "4096"))
        sql = sql.format(groups=groups)
    if qname == "q6z":
        from presto_tpu.connectors import tpch as _t
        frac = float(os.environ.get("BENCH_Q6Z_FRACTION", "0.02"))
        cutoff = max(2, int(_t._table_rows("orders", sf) * frac))
        sql = sql.rstrip() + f"\n  AND orderkey < {cutoff}\n"
    grouped_lifespans = int(os.environ.get("BENCH_GROUPED_LIFESPANS", "0"))
    prefetch_depth = int(os.environ.get("BENCH_PREFETCH_DEPTH", "1"))

    from presto_tpu.connectors import tpch
    from presto_tpu.exec.runner import LocalQueryRunner

    schema = f"sf{sf:g}"
    n_rows = tpch._table_rows("lineitem", sf)
    from presto_tpu.exec.pipeline import ExecutionConfig
    runner = LocalQueryRunner(schema=schema, config=ExecutionConfig(
        batch_rows=1 << 20, join_out_capacity=1 << 21,
        grouped_lifespans=grouped_lifespans,
        grouped_prefetch_depth=prefetch_depth))

    # Warmup: traces + compiles every pipeline shape bucket and faults the
    # generated lineitem columns into memory/HBM.
    runner.execute(sql)

    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        result = runner.execute(sql)
        best = min(best, time.perf_counter() - t0)
    assert result.rows, "benchmark query returned no rows"

    # Baseline: numpy reference interpreter, same plan + data, one timed
    # run (deterministic, no compile step).  At large BENCH_SF the row
    # engine becomes the bottleneck of the *benchmark harness* itself, so
    # it is measured at a capped scale factor and compared by throughput
    # (rows/s vs rows/s) — the ratio is scale-invariant for these
    # scan-bound queries.
    ref_sf = min(sf, float(os.environ.get("BENCH_REF_SF", "1")))
    ref_runner = runner if ref_sf == sf else LocalQueryRunner(
        schema=f"sf{ref_sf:g}", config=runner.config)
    ref_rows = tpch._table_rows("lineitem", ref_sf)
    t0 = time.perf_counter()
    ref_runner.execute_reference(sql)
    ref_wall = time.perf_counter() - t0

    rows_per_sec = n_rows / best
    ref_rows_per_sec = ref_rows / ref_wall

    # effective scan bandwidth vs the chip's HBM peak (VERDICT weak #4:
    # make the roofline distance visible).  Bytes/row = the widths of the
    # columns the query touches (the scan generates columns on device, so
    # this is the rate an HBM-resident columnar table would have to be
    # streamed at to match).
    col_bytes = {
        "q1": 8 + 8 + 8 + 8 + 4 + 4 + 4,   # qty,price,disc,tax,shipdate,rf,ls
        "q6": 4 + 8 + 8 + 8,               # shipdate,disc,price,qty
        "q6z": 4 + 8 + 8 + 8 + 8,          # q6 + orderkey
        "q3g": 8 + 8 + 8 + 4,              # orderkey,price,disc,shipdate
        "q1g": 8 + 8 + 8 + 8 + 4,          # orderkey,qty,price,disc,shipdate
        "q3k": 8 + 8 + 8 + 4,              # orderkey,price,disc,shipdate
    }[qname]
    achieved_gbps = rows_per_sec * col_bytes / 1e9
    hbm_peak_gbps = float(os.environ.get("BENCH_HBM_PEAK_GBPS", "819"))

    out = {
        "metric": f"tpch_{qname}_sf{sf:g}_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        # throughput-normalized ratio: engine rows/s at BENCH_SF over the
        # numpy row engine's rows/s at BENCH_REF_SF (engine throughput is
        # not scale-invariant, so this is NOT a same-scale wall-clock ratio
        # unless vs_baseline_kind says so)
        "vs_baseline": round(rows_per_sec / ref_rows_per_sec, 3),
        "vs_baseline_kind": (
            f"same_sf_wall_clock" if ref_sf == sf
            else f"throughput_normalized_ref_at_sf{ref_sf:g}"),
        "effective_scan_gbps": round(achieved_gbps, 2),
        "hbm_peak_gbps": hbm_peak_gbps,
        "hbm_fraction": round(achieved_gbps / hbm_peak_gbps, 4),
    }
    # resident-storage observability (presto_tpu/storage): warmup builds
    # the columns (misses), timed runs hit; the skip fraction is exact
    # even though chunk counters accumulate across runs
    from presto_tpu.storage import STORAGE_METRICS
    sm = STORAGE_METRICS
    lookups = sm["cache_hits"] + sm["cache_misses"]
    out["zone_map_skip_fraction"] = round(
        sm["chunks_skipped"] / sm["chunks_total"], 4) \
        if sm["chunks_total"] else 0.0
    out["storage"] = {
        "cache_hit": round(sm["cache_hits"] / lookups, 4)
        if lookups else 0.0,
        "cache_hits": sm["cache_hits"],
        "cache_misses": sm["cache_misses"],
        "columns_built": sm["columns_built"],
        "build_rejected": sm["build_rejected"],
        "evictions": sm["evictions"],
        "resident_bytes": sm["resident_bytes"],
        # encoded-vs-plain: what HBM holds vs what a plain layout would
        # hold — the per-scan traffic the encodings save
        "encoded_bytes": sm["encoded_bytes"],
        "plain_bytes": sm["plain_bytes"],
        "encoding_ratio": round(sm["plain_bytes"] / sm["encoded_bytes"], 3)
        if sm["encoded_bytes"] else 0.0,
        "chunks_total": sm["chunks_total"],
        "chunks_skipped": sm["chunks_skipped"],
    }
    # Pallas-vs-XLA scan kernel side-by-side: same plan, same resident
    # data, only the scan hot-path implementation differs.  Each mode gets
    # its own warmup + best-of-N so the comparison is compile-free on both
    # sides; kernel_programs counts fused scan programs that actually took
    # the Pallas path (0 under xla or when every scan declined), and
    # declined carries the per-reason counters for ineligible scans.
    if qname in ("q1", "q6", "q6z", "q1g", "q3k"):
        import dataclasses
        kcmp = {}
        for mode in ("pallas", "xla"):
            kr = LocalQueryRunner(schema=schema, config=dataclasses.replace(
                runner.config, scan_kernel=mode))
            kr.execute(sql)           # warmup: compiles this variant
            kbest = float("inf")
            for _ in range(runs):
                t0 = time.perf_counter()
                kres = kr.execute(sql)
                kbest = min(kbest, time.perf_counter() - t0)
            rs = kres.runtime_stats or {}
            kcmp[mode] = {
                "wall_s": round(kbest, 4),
                "rows_per_sec": round(n_rows / kbest, 1),
                "effective_scan_gbps": round(
                    n_rows / kbest * col_bytes / 1e9, 2),
                "kernel_programs": int(
                    rs.get("kernelScanPrograms", {}).get("sum", 0)),
                "declined": {
                    k[len("kernelDeclined"):]: int(v.get("sum", 0))
                    for k, v in sorted(rs.items())
                    if k.startswith("kernelDeclined")},
            }
        out["scan_kernel"] = {
            **kcmp,
            # > 1.0 means the Pallas fused pass beat the XLA chain
            "pallas_vs_xla": round(
                kcmp["xla"]["wall_s"] / kcmp["pallas"]["wall_s"], 3)
            if kcmp["pallas"]["wall_s"] else 0.0,
        }

    # operator-level breakdown from the stats spine: one EXPLAIN ANALYZE
    # pass (same plan, fused path) and the top-5 operators by wall — where
    # the headline wall actually went
    runner.execute("EXPLAIN ANALYZE " + sql.strip())
    ops = runner.last_operator_stats or {}
    out["operators"] = [
        {"planNodeId": nid,
         "operator": s.get("operatorType") or nid.split(".", 1)[0],
         "rows": s.get("rows", 0),
         "wall_ms": round(s.get("wall_s", 0.0) * 1e3, 2),
         "fused": bool(s.get("fused"))}
        for nid, s in sorted(ops.items(),
                             key=lambda kv: kv[1].get("wall_s", 0.0),
                             reverse=True)[:5]]
    gstats = {k: v for k, v in (result.runtime_stats or {}).items()
              if k.startswith("grouped")}
    if gstats:
        gen = gstats.get("groupedBucketGenWallNanos", {}).get("sum", 0)
        comp = gstats.get("groupedBucketComputeWallNanos", {}).get("sum", 0)
        run = gstats.get("groupedRunWallNanos", {}).get("sum", 0)
        out["grouped"] = {
            "lifespans": gstats.get(
                "groupedBucketComputeWallNanos", {}).get("count", 0),
            "prefetch_depth": prefetch_depth,
            "gen_wall_s": round(gen / 1e9, 4),
            "compute_wall_s": round(comp / 1e9, 4),
            "run_wall_s": round(run / 1e9, 4),
            # how much staging hid behind compute: 0 = fully serial
            "overlap_fraction": round(1 - run / (gen + comp), 4)
            if gen + comp else 0.0,
        }
    out["process_metrics"] = _process_metrics()
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
