"""Micro-batched execution of one compiled point-query template.

The serving tier's bound-parameter design makes concurrent EXECUTE..USING
requests against the same canonical plan differ ONLY in the parameter
vector riding the jitted program as a traced argument
(`Batch.with_params`, exec/pipeline.py).  A BatchedTemplateRunner
exploits that: it vmaps the template's fused scan→chain→agg-update loop
over a leading batch axis of stacked parameter vectors, so N in-flight
queries cost ONE device launch instead of N.  Per-lane aggregation
states are then demultiplexed and finalized independently, so each
query still gets its own result pages, stats, and history record.

Eligibility is deliberately the same envelope as the fused XLA
direct-mode aggregation path (one-hot grid, BASIC_AGGS, closed small key
domains) — the batched program replays exactly the per-lane computation
the sequential fused path would run, chunk loop and all, which is what
makes the bit-identical-results guarantee of the batching layer hold.
Anything outside that envelope (hash-table aggs, sort paths, Pallas
kernel engagements, parameterized build sides or pushdown pruning whose
CHUNK LIST depends on the bound constants) declines batching and the
queries run sequentially as before.

Batch widths are padded to powers of two (padding lanes replicate lane
0's parameters and are discarded at demux) so the per-width retrace
count stays logarithmic in the configured max batch size.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..common.types import DoubleType, RealType
from ..spi import plan as P
from ..exec import operators as ops
from ..exec.batch import Batch, batch_to_page
from ..exec.fused import assemble_chain
from ..exec.lowering import canonical_name
from ..exec.pipeline import _direct_mode_info, _rewrite_agg_masks


class BatchedTemplateRunner:
    """One compiled template's vmapped executor.  Built (once, cached on
    the owning PlanCompiler) from a checked-out canonical-cache entry;
    `run` takes per-lane device parameter tuples and returns one host
    Page per lane."""

    def __init__(self, compiler, output, chain, aux_base, expands,
                 leaf_cap, specs, input_exprs, key_names, info, projects):
        self.compiler = compiler
        self.output = output
        self.chain = chain
        self.aux_base = aux_base        # prep aux WITHOUT the params slot
        self.expands = expands
        self.leaf_cap = leaf_cap
        self.specs = specs
        self.input_exprs = input_exprs
        self.key_names = key_names
        self.doms, self.G, self.strides, self.kdts, self.kdicts = info
        self.projects = projects        # ProjectNodes root->down above agg
        self.low = compiler.lowering
        self._run_jit = jax.jit(self._run_all)

    # -- the single-launch program ---------------------------------------

    def _run_all(self, pos_arr, cnt_arr, aux_base, stacked):
        """vmap over stacked parameter vectors of the SAME fori_loop the
        sequential fused direct path runs (exec/pipeline.py `loop`): each
        lane's update sequence — chunk order, one-hot grid, masked
        reductions — is identical to its solo execution, so per-lane
        results are bit-identical to unbatched runs.  Finalize and the
        scalar projections above the aggregation run INSIDE the vmapped
        program (elementwise, so vmap changes nothing bitwise): demux is
        then a per-lane slice of one small stacked result instead of a
        per-lane eager finalize chain."""
        chain, expands, leaf_cap = self.chain, self.expands, self.leaf_cap
        specs, G, strides = self.specs, self.G, self.strides
        key_names, low = self.key_names, self.low
        input_exprs = self.input_exprs
        inner = [v.name for v in self.output.source.output_variables]
        outer = [v.name for v in self.output.outputs]

        def per_lane(params):
            aux = aux_base + (params,)

            def body(i, st):
                b = chain.make(pos_arr[i], cnt_arr[i], aux, expands,
                               leaf_cap)
                codes = None
                for k, stride in zip(key_names, strides):
                    c = b.columns[k].values.astype(jnp.int64)
                    codes = (c * stride if codes is None
                             else codes + c * stride)
                if codes is None:       # global aggregation: one group
                    codes = jnp.zeros(b.capacity, dtype=jnp.int64)
                pb = b.with_params(params)
                agg_cols = {out: (low.eval(e, pb) if e is not None
                                  else None)
                            for out, e in input_exprs.items()}
                return ops.agg_direct_update(st, b, codes, agg_cols,
                                             specs, G)
            state = jax.lax.fori_loop(0, pos_arr.shape[0], body,
                                      ops.agg_direct_init(G, specs))
            out = ops.agg_direct_finalize(
                state, specs, key_names, self.doms, self.kdts,
                self.kdicts, force_row=not key_names)
            for node in reversed(self.projects):
                pb = out.with_params(params)
                cols = {v.name: low.eval(e, pb)
                        for v, e in node.assignments.items()}
                out = Batch(cols, out.mask)
            return Batch({o: out.columns[i_]
                          for i_, o in zip(inner, outer)}, out.mask)
        return jax.vmap(per_lane)(stacked)

    # -- execution --------------------------------------------------------

    def run(self, dev_list: List[Tuple]) -> Tuple[List, int, int]:
        """dev_list: per-lane tuples of device parameter scalars (one per
        slot, `sql.canonical.device_params` order).  Returns (pages,
        launch_nanos, demux_nanos) with one Page per input lane."""
        n = len(dev_list)
        width = 1 << max(0, n - 1).bit_length()
        lanes = list(dev_list) + [dev_list[0]] * (width - n)
        stacked = tuple(jnp.stack([lane[s] for lane in lanes])
                        for s in range(len(dev_list[0])))
        # ONE chunk list for every lane: suppress ["param", i] zone-map
        # markers (they resolve per-binding) and prune by plan constants
        # and dynamic-filter summaries only.  Chunks a per-lane prune
        # would have skipped contribute the aggregation identity (their
        # rows are filter-masked), so lane results stay bit-identical to
        # solo runs over the pruned list.
        ctx = self.compiler.ctx
        saved_fp = ctx.params_fingerprint
        ctx.params_fingerprint = None
        try:
            chunks = self.chain.chunks_for(self.expands)
        finally:
            ctx.params_fingerprint = saved_fp
        pos_arr = jnp.asarray([c0 for c0, _ in chunks], dtype=jnp.int64)
        cnt_arr = jnp.asarray([c1 for _, c1 in chunks], dtype=jnp.int64)
        t0 = time.perf_counter_ns()  # lint: allow-wall-clock
        stacked_out = self._run_jit(pos_arr, cnt_arr, self.aux_base,
                                    stacked)
        launch = time.perf_counter_ns() - t0  # lint: allow-wall-clock

        t1 = time.perf_counter_ns()  # lint: allow-wall-clock
        outer = [v.name for v in self.output.outputs]
        types = [v.type for v in self.output.outputs]
        pages = []
        for i in range(n):
            lane = jax.tree_util.tree_map(lambda a, _i=i: a[_i],
                                          stacked_out)
            pages.append(batch_to_page(lane, outer, types))
        demux = time.perf_counter_ns() - t1  # lint: allow-wall-clock
        return pages, launch, demux


def _eligible(compiler, output) -> Optional[BatchedTemplateRunner]:
    ctx = compiler.ctx
    cfg = ctx.config
    # the sequential execution these lanes must match bit-for-bit is the
    # fused XLA direct path; decline whenever that path would not run
    if not cfg.fuse_pipelines or ctx.stats is not None:
        return None
    if ctx.memory is not None and ctx.memory.limited:
        return None
    if ctx.params is None:
        return None
    if cfg.scan_kernel == "pallas":
        return None
    if cfg.scan_kernel == "auto" and jax.default_backend() == "tpu":
        # sequential runs engage the Pallas scan kernel here; batching
        # through the XLA vmap would change the computation
        return None

    projects = []
    node = output.source
    while isinstance(node, P.ProjectNode):
        projects.append(node)
        node = node.source
    if not isinstance(node, P.AggregationNode):
        return None
    agg = _rewrite_agg_masks(node)
    if any(a.distinct for a in agg.aggregations.values()):
        return None
    specs = []
    input_exprs: Dict[str, object] = {}
    for v, a in agg.aggregations.items():
        fname = canonical_name(a.call.display_name)
        args = a.call.arguments
        if fname == "count" and not args:
            fname = "count_star"
        if fname not in ops.BASIC_AGGS:
            return None
        is_float = isinstance(v.type, (DoubleType, RealType))
        specs.append(ops.AggSpec(fname, v.name, is_float, None))
        input_exprs[v.name] = args[0] if args else None
    specs = tuple(specs)
    key_names = tuple(v.name for v in agg.grouping_keys)

    chain = assemble_chain(compiler, agg.source)
    if chain is None or not chain.chunks:
        return None
    if not chain.has_params:
        return None                 # nothing varies between lanes
    if chain.build_params:
        # the build tables would be a function of the bound constants —
        # not lane-shareable.  params_pushdown is fine: run() prunes the
        # shared chunk list by plan constants only, and the lanes' own
        # filters mask the rows a per-lane prune would have skipped.
        return None
    try:
        prep_res = chain.prep()
    except Exception:   # noqa: BLE001 — decline, never fail the query
        return None
    if prep_res is None:
        return None
    aux, expands, _deferred = prep_res
    aux = aux[:-1] + (ctx.params,)
    leaf_cap = chain.leaf_cap(expands)
    try:
        probe = jax.eval_shape(
            lambda p, v: chain.make(p, v, aux, expands, leaf_cap),
            jnp.int64(0), jnp.int64(1))
    except Exception:   # noqa: BLE001
        return None
    key_cols = [probe.columns.get(k) for k in key_names]
    if any(c is None for c in key_cols):
        return None
    info = _direct_mode_info(key_names, key_cols)
    if info is None:
        return None
    return BatchedTemplateRunner(compiler, output, chain, aux[:-1],
                                 expands, leaf_cap, specs, input_exprs,
                                 key_names, info, projects)


def batched_runner_for(compiler, output) -> Optional[BatchedTemplateRunner]:
    """Get-or-build the template's batched runner, cached on the owning
    PlanCompiler (the attribute rides the compiler through the PlanCache
    pool's checkin/checkout; a rebuilt compiler re-derives it once).
    Returns None — and remembers the refusal — when the template is
    outside the batchable envelope."""
    cached = getattr(compiler, "_batched_runner", None)
    if cached is not None:
        return cached or None       # False == remembered refusal
    runner = _eligible(compiler, output)
    compiler._batched_runner = runner if runner is not None else False
    return runner


def disable_for(compiler) -> None:
    """A batched drain failed at runtime: pin this compiler's template to
    the sequential path (callers already re-ran the lanes solo)."""
    compiler._batched_runner = False
