"""Durable serving-plane state: warm restarts without recompiling.

Two pieces, both wired by the worker server when the corresponding etc/
properties are set:

1. `enable_compilation_cache(dir)` points JAX's persistent compilation
   cache (`jax_compilation_cache_dir`) at a directory, so the XLA
   executables behind every jitted step survive process restarts — a
   re-trace after reload hits the on-disk cache instead of the compiler.

2. `PlanCacheSidecar` — a JSONL record of the statements the serving
   tier compiled (one exemplar per prepared template / catalog / schema
   / session combination, the same append-then-rewrite discipline as
   telemetry/history.py).  On restart the coordinator REPLAYS each
   record through the same runner path that serves traffic: the replay
   re-registers the prepared statement, re-records the fast path, and
   re-inserts the canonical PlanCache entry (its jitted steps loading
   from the compilation cache above), so the first real client request
   after a restart is a warm hit — measured as cold-vs-warm restart p99
   in `BENCH_QUERY=serve`.

DDL invalidates the sidecar along with the plan cache: a replayed plan
against changed tables would resurrect stale state.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..common.locks import OrderedLock

DEFAULT_SIDECAR_MAX_COUNT = 512


def enable_compilation_cache(path: str) -> bool:
    """Point JAX's persistent compilation cache at `path`.  Thresholds
    drop to zero so the serving tier's small point-query executables
    qualify.  Each knob is applied independently — older JAX builds
    missing one still get the rest.  Returns True when the cache dir
    itself was accepted."""
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", str(path))
    except Exception:   # noqa: BLE001 — persistence is advisory
        return False
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            import jax
            jax.config.update(knob, val)
        except Exception:   # noqa: BLE001
            pass
    return True


class PlanCacheSidecar:
    """Append-mostly JSONL of served statement exemplars.

    A record is `{"sql", "prepared", "catalog", "schema", "session"}` —
    everything `LocalQueryRunner.execute` needs to replay it.  Dedup is
    by (resolved statement text, catalog, schema, session): EXECUTE
    traffic against one template collapses to a single exemplar, since
    replaying ANY binding re-creates the template's cache entry."""

    def __init__(self, path: str,
                 max_count: int = DEFAULT_SIDECAR_MAX_COUNT):
        self.path = str(path)
        self.max_count = int(max_count)
        # rank 55: taken after serving-cache (50) would be wrong — record()
        # and load() run with NO other serving lock held (server layer,
        # post-execution), and SERVING_METRICS (100) nests fine
        self._lock = OrderedLock("serving-sidecar", 55)  # lint: guarded-by(_lock)
        self._seen = set()
        self._count = 0
        self._load_seen()

    # -- internal ---------------------------------------------------------

    def _dedup_key(self, rec: dict) -> tuple:
        prepared = rec.get("prepared") or {}
        text = "\x00".join(sorted(prepared.values())) or rec.get("sql", "")
        session = tuple(sorted((rec.get("session") or {}).items()))
        return (text, rec.get("catalog"), rec.get("schema"), session)

    def _load_seen(self) -> None:
        with self._lock:
            self._seen.clear()
            self._count = 0
            for rec in self._read_all():
                self._seen.add(self._dedup_key(rec))
                self._count += 1

    def _read_all(self) -> List[dict]:
        out: List[dict] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue    # torn tail write: keep the prefix
        except OSError:
            pass
        return out

    # -- recording --------------------------------------------------------

    def record(self, sql: str, prepared: Optional[Dict[str, str]],
               catalog: str, schema: str,
               session: Optional[Dict[str, str]] = None) -> bool:
        """Record one successfully-served statement; returns True when a
        new exemplar was appended."""
        rec = {"sql": sql, "prepared": dict(prepared or {}),
               "catalog": catalog, "schema": schema,
               "session": dict(session or {})}
        key = self._dedup_key(rec)
        with self._lock:
            if key in self._seen or self._count >= self.max_count:
                return False
            self._seen.add(key)
            self._count += 1
            try:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
            except OSError:
                return False
        return True

    # -- replay -----------------------------------------------------------

    def load(self) -> List[dict]:
        with self._lock:
            return self._read_all()

    def clear(self) -> None:
        """DDL: the recorded plans may reference changed tables."""
        with self._lock:
            self._seen.clear()
            self._count = 0
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def info(self) -> dict:
        with self._lock:
            return {"path": self.path, "entries": self._count,
                    "maxEntries": self.max_count}
