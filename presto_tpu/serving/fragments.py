"""Fragment-level executable sharing.

The canonical PlanCache shares compiled state between executions of the
SAME whole plan.  Queries that differ above a common scan→filter→agg
subchain still recompile every jitted step from scratch, because the
per-compiler jit caches key on plan-node ids.  This module is the
process-global complement: jitted step callables keyed on the
STRUCTURAL key of the subtree they compile (`spi.plan.structural_key` —
node ids blanked, variables renamed) plus the execution-config
fingerprint, so two different plans sharing a fragment share one
compiled artifact.  `PlanCompiler.fragment_jit` (exec/pipeline.py)
routes the scan/filter/project step sites here when the
`fragment_share` config knob is on and the compiler is not running
under a task-scoped shared-jit cache (distributed tasks keep their
node-id keyed cache: their fragments are already deduplicated by the
fragmenter).

Safety: a cached callable is a PURE function of its traced arguments —
bound parameters, scan chunk positions, HBM-resident columns all ride
as arguments — plus host constants fully determined by (subtree
structural key, config fingerprint, first-batch signature), which is
exactly the cache key.  jax.jit's own per-aval retracing handles shape
and dtype drift between sharers.  DDL clears the cache alongside the
plan cache (runner._invalidate_plans): generated-connector fragments
are immutable, but a dropped-and-recreated stored table must not
resurrect callables probed against the old data's encodings.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable

from ..common.locks import OrderedLock
from .metrics import SERVING_METRICS

DEFAULT_FRAGMENT_ENTRIES = 512


class FragmentJitCache:
    def __init__(self, max_entries: int = DEFAULT_FRAGMENT_ENTRIES):
        # rank 95: SERVING_METRICS (100) is bumped while held; taken from
        # inside compiler step construction with no serving lock held
        self._lock = OrderedLock("serving-fragments", 95)  # lint: guarded-by(_lock)
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()
        self.max_entries = int(max_entries)

    def get_or_build(self, key: tuple, build: Callable):
        """Return the cached jitted callable for `key`, building (and
        LRU-inserting) it on first sight.  Building under the lock is
        fine: jax.jit is lazy — tracing and compilation happen at first
        CALL, outside this lock."""
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self._entries.move_to_end(key)
                SERVING_METRICS.incr("fragment_jit_hits")
                return fn
            fn = build()
            self._entries[key] = fn
            SERVING_METRICS.incr("fragment_jit_misses")
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            return fn

    def invalidate_all(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            return n

    def info(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "maxEntries": self.max_entries}


FRAGMENT_JIT_CACHE = FragmentJitCache()
