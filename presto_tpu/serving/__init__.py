"""Serving tier: prepared statements, the canonical plan/executable cache,
and admission support for heavy repeated-shape traffic.

The pieces:
  sql/canonical.py   plan parameterization + cache keys (lives in sql/ so
                     the planner layer owns plan rewriting)
  serving/cache.py   LRU of (optimized template, PlanCompiler) entries
  serving/prepared.py  PREPARE/EXECUTE registry + the skip-parse-and-plan
                     fast path
  serving/metrics.py process-wide counters for /v1/metrics and /v1/status
  worker/statement.py  weighted fair-share + memory-headroom admission
"""
from .cache import GLOBAL_PLAN_CACHE, PlanCache
from .metrics import SERVING_METRICS
from .prepared import PREPARED_REGISTRY, PreparedRegistry

__all__ = ["GLOBAL_PLAN_CACHE", "PlanCache", "SERVING_METRICS",
           "PREPARED_REGISTRY", "PreparedRegistry"]
