"""Serving tier: prepared statements, the canonical plan/executable cache,
and admission support for heavy repeated-shape traffic.

The pieces:
  sql/canonical.py   plan parameterization + cache keys (lives in sql/ so
                     the planner layer owns plan rewriting)
  serving/cache.py   LRU of (optimized template, PlanCompiler) entries
  serving/prepared.py  PREPARE/EXECUTE registry + the skip-parse-and-plan
                     fast path
  serving/metrics.py process-wide counters for /v1/metrics and /v1/status
  serving/batching.py  micro-batcher: concurrent same-template EXECUTEs
                     collapse into one device launch
  serving/batched.py vmapped per-template executor behind the batcher
  serving/persist.py durable sidecar + JAX compilation cache: restart
                     warm-starts without recompiling
  serving/fragments.py  structural-key jit sharing across plans
  worker/statement.py  weighted fair-share + memory-headroom admission

serving/batched.py imports exec.pipeline, so it is NOT imported here —
exec.pipeline lazily imports serving.fragments, and an eager import
would make that a cycle.  Import it as `presto_tpu.serving.batched`.
"""
from .batching import MicroBatcher
from .cache import GLOBAL_PLAN_CACHE, PlanCache
from .fragments import FRAGMENT_JIT_CACHE, FragmentJitCache
from .metrics import SERVING_METRICS
from .persist import PlanCacheSidecar, enable_compilation_cache
from .prepared import PREPARED_REGISTRY, PreparedRegistry

__all__ = ["GLOBAL_PLAN_CACHE", "PlanCache", "SERVING_METRICS",
           "PREPARED_REGISTRY", "PreparedRegistry", "MicroBatcher",
           "FRAGMENT_JIT_CACHE", "FragmentJitCache", "PlanCacheSidecar",
           "enable_compilation_cache"]
