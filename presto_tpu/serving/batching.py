"""Request micro-batcher for the serving plane.

Collects concurrent EXECUTE..USING statements that target the same
prepared template (same canonical cache key) inside a bounded window and
hands them to `LocalQueryRunner.execute_prepared_batch` as ONE device
launch — the inference-server batching pattern applied to point queries,
where the batch dimension is QPS itself.

Leader/follower protocol: the first arrival for a group key becomes the
leader, waits up to `window_ms` (cut short when `max_batch` lanes have
joined), closes the group, and runs the batch.  Followers block on
per-slot events.  Every slot whose batched result is unavailable — the
template is cold or ineligible, its binds failed, or the whole drain
errored — falls back to a SEQUENTIAL run on its own thread, so one
query's failure never fails its batchmates and a fallback never
serializes behind the leader.

Adaptive accumulation: while a drain for the same key is already
executing, the next group's leader holds its group open until that
drain completes (or the group fills) — under sustained load batch
occupancy converges on the offered concurrency instead of on however
many requests land inside one fixed window, exactly like continuous
batching in inference servers.  At low load the in-flight gate is
never taken and the fixed window is the only added latency.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .metrics import SERVING_METRICS

DEFAULT_BATCH_WINDOW_MS = 3.0
DEFAULT_MAX_BATCH_SIZE = 16


class _Slot:
    __slots__ = ("item", "result", "event", "batched")

    def __init__(self, item):
        self.item = item
        self.result = None
        self.event = threading.Event()
        self.batched = False    # joined a >=2-lane drain attempt


class _Group:
    __slots__ = ("slots", "full", "closed")

    def __init__(self):
        self.slots: List[_Slot] = []
        self.full = threading.Event()
        self.closed = False


class MicroBatcher:
    def __init__(self, window_ms: float = DEFAULT_BATCH_WINDOW_MS,
                 max_batch: int = DEFAULT_MAX_BATCH_SIZE):
        self.window_s = max(0.0, float(window_ms)) / 1000.0
        self.max_batch = int(max_batch)
        # plain mutex (not an OrderedLock): only guards the group map and
        # slot lists; nothing else is ever acquired under it
        self._lock = threading.Lock()
        self._groups: dict = {}
        # key -> event set when that key's executing drain finishes
        self._inflight: Dict[object, threading.Event] = {}

    @property
    def enabled(self) -> bool:
        return self.max_batch > 1

    def run(self, key, item, execute_batch: Callable, run_one: Callable):
        """Run `item` through the batcher.  `execute_batch(items)` must
        return a list aligned with its input — each entry a result or
        None (= run that item sequentially) — or None when no batch was
        possible at all.  `run_one(item)` is the sequential path; it is
        invoked on the CALLER's thread, so per-query errors propagate to
        the right request."""
        if not self.enabled:
            return run_one(item)
        with self._lock:
            g = self._groups.get(key)
            if (g is not None and not g.closed
                    and len(g.slots) < self.max_batch):
                slot = _Slot(item)
                g.slots.append(slot)
                if len(g.slots) >= self.max_batch:
                    g.full.set()
                leader = False
            else:
                g = _Group()
                slot = _Slot(item)
                g.slots.append(slot)
                self._groups[key] = g
                leader = True

        if leader:
            g.full.wait(self.window_s)
            with self._lock:
                prev = self._inflight.get(key)
            if prev is not None and not g.full.is_set():
                # adaptive accumulation: a drain for this key is on the
                # device right now — keep the group open until it
                # finishes (or this group fills), so the next launch
                # carries everyone who arrived meanwhile.  Bounded: a
                # wedged drain must not serialize this group forever.
                prev.wait(120.0)
                g.full.wait(self.window_s)
            with self._lock:
                g.closed = True
                if self._groups.get(key) is g:
                    del self._groups[key]
                slots = list(g.slots)
                done = None
                if len(slots) > 1:
                    done = threading.Event()
                    self._inflight[key] = done
            results: Optional[list] = None
            if len(slots) > 1:
                for s in slots:
                    s.batched = True
                try:
                    results = execute_batch([s.item for s in slots])
                except Exception:   # noqa: BLE001 — isolate to fallbacks
                    results = None
                finally:
                    done.set()
                    with self._lock:
                        if self._inflight.get(key) is done:
                            del self._inflight[key]
            for i, s in enumerate(slots):
                s.result = results[i] if results is not None else None
                if s is not slot:
                    s.event.set()
        else:
            # generous ceiling over the window: the leader may be waiting
            # out an in-flight drain (<=120s) and then running a cold
            # compile; a lost leader (process-fatal error paths) must not
            # wedge followers forever
            slot.event.wait(self.window_s + 300.0)

        if slot.result is None:
            if slot.batched:
                SERVING_METRICS.incr("serving_batch_fallbacks")
            return run_one(item)
        return slot.result
