"""Canonical plan/executable cache.

Entries are keyed by `sql.canonical.plan_cache_key` — catalog + schema +
execution-config fingerprint + the structural key of the PARAMETERIZED
pre-optimizer plan — and hold the optimized template plus a small pool of
PlanCompiler instances.  Compilers are checked out exclusively (a
TaskContext holds per-execution state: params, memory pool, runtime
stats) and returned after a successful drain, mirroring the pop/recache
discipline of the old exact-SQL-text cache it replaces
(exec/runner.py:53 before this change).

Why a pool and not one compiler: the statement path executes concurrent
queries against one runner; two executions sharing a compiler would race
on ctx.params.  When the pool is empty a hit still returns the optimized
template — the caller rebuilds only the compiler (cheap construction;
XLA executables re-specialize lazily), never re-running
parse→plan→optimize.

Invalidation: DDL (tables changed) clears everything; session-property /
config / catalog changes need no invalidation because they are part of
the key.  Eviction is LRU by last checkout/insert.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from typing import List, Optional, Tuple

from ..common.locks import OrderedLock
from .metrics import SERVING_METRICS

DEFAULT_PLAN_CACHE_ENTRIES = 128
_POOL_PER_ENTRY = 4             # compilers retained per entry


class _Entry:
    __slots__ = ("template", "slot_types", "pool", "out", "out_peak")

    def __init__(self, template, slot_types):
        self.template = template          # optimized OutputNode
        self.slot_types = slot_types      # parameter slot types, in order
        self.pool: List[object] = []      # idle PlanCompiler instances
        self.out = 0                      # compilers currently checked out
        self.out_peak = 0                 # high-water concurrent checkouts


class PlanCache:
    def __init__(self, max_entries: int = DEFAULT_PLAN_CACHE_ENTRIES):
        # rank 50: SERVING_METRICS (a rank-100 registry) is bumped while
        # this is held; nothing engine-side nests inside it
        self._lock = OrderedLock("serving-cache", 50)  # lint: guarded-by(_lock)
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self.max_entries = max_entries
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.pool_exhausted = 0

    # -- configuration ----------------------------------------------------
    def set_max_entries(self, n: int) -> None:
        with self._lock:
            self.max_entries = max(1, int(n))
            self._evict_locked()

    # -- lookup -----------------------------------------------------------
    def checkout(self, key: str) -> Optional[Tuple[object, list, object]]:
        """Hit -> (optimized template, slot types, compiler-or-None); the
        compiler, when present, is exclusively owned until checkin()."""
        t0 = time.perf_counter_ns()  # lint: allow-wall-clock
        with self._lock:
            # lock-acquisition wall = how long concurrent executions
            # queued behind the cache (the "checkout wait" of a
            # contended serving plane)
            wait = time.perf_counter_ns() - t0  # lint: allow-wall-clock
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                SERVING_METRICS.incr("plan_cache_misses")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            SERVING_METRICS.incr("plan_cache_hits")
            SERVING_METRICS.incr("compiler_checkouts")
            if wait:
                SERVING_METRICS.incr("compiler_checkout_wait_nanos", wait)
            compiler = ent.pool.pop() if ent.pool else None
            ent.out += 1
            if ent.out > ent.out_peak:
                ent.out_peak = ent.out
            SERVING_METRICS.max_update("compiler_checkout_depth_peak",
                                       ent.out)
            if compiler is None:
                # exhausted pool: the caller rebuilds a compiler — that
                # fallback used to be silent; now it is the contention
                # signal the admission layer can watch
                self.pool_exhausted += 1
                SERVING_METRICS.incr("compiler_pool_exhausted")
            return ent.template, ent.slot_types, compiler

    def insert(self, key: str, template, slot_types, compiler) -> None:
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = _Entry(template, slot_types)
                self._entries[key] = ent
            self._entries.move_to_end(key)
            if compiler is not None \
                    and len(ent.pool) < _POOL_PER_ENTRY:
                ent.pool.append(compiler)
            self._evict_locked()

    def checkin(self, key: str, compiler) -> None:
        """Return a compiler after a successful execution; dropped when the
        entry was evicted/invalidated meanwhile (a stale compiler must not
        resurrect a dead key)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return
            # the checkout is over whether or not the compiler survives
            # (pool-full drops still end the exclusive ownership window)
            if ent.out > 0:
                ent.out -= 1
            if compiler is not None and len(ent.pool) < _POOL_PER_ENTRY:
                ent.pool.append(compiler)

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    # -- invalidation -----------------------------------------------------
    def invalidate_all(self) -> int:
        """Drop every entry (DDL changed table contents: any cached plan —
        and any compiler-internal materialization — may be stale)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            if n:
                self.invalidations += n
                SERVING_METRICS.incr("plan_cache_invalidations", n)
            return n

    def _evict_locked(self) -> None:
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            self.evictions += 1
            SERVING_METRICS.incr("plan_cache_evictions")

    # -- observability ----------------------------------------------------
    def info(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "maxEntries": self.max_entries,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "poolExhausted": self.pool_exhausted,
                "checkedOut": sum(e.out for e in self._entries.values()),
                "checkoutDepthPeak": max(
                    (e.out_peak for e in self._entries.values()),
                    default=0),
            }


# One cache per process (the statement path builds ≤16 runners per
# coordinator but the same shapes flow through all of them; config /
# catalog / schema live in the key so sharing is safe).
GLOBAL_PLAN_CACHE = PlanCache()
