"""Prepared-statement registry.

The protocol is stateless like the reference's (PreparedStatement headers,
presto-client StatementClient): every request carries the session's
prepared statements as `X-Presto-Prepared-Statement: name=urlencoded-sql`
headers, PREPARE answers with `X-Presto-Added-Prepare`, DEALLOCATE with
`X-Presto-Deallocated-Prepare`.  What this process-global registry adds is
the SERVER-side memo per statement TEXT: the parsed AST (parse once per
process, not per request) and — after the first successful execution — the
fast-path record mapping USING positions onto the canonical cache
template's parameter slots, so a repeat EXECUTE with different constants
skips parse→plan→optimize entirely and goes straight to the plan cache.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from .metrics import SERVING_METRICS

_MAX_STATEMENTS = 256


@dataclass
class FastPath:
    """Everything needed to rebuild a plan-cache key + parameter vector
    from raw USING values, without planning.  `slots[i]` is
    (origin, type, fixed_value): origin None means the slot's value is a
    fixed literal of the statement (recorded from the first run); an
    integer origin binds USING position `origin` coerced to the slot
    type."""
    template_key: str                  # structural key of the template
    slots: List[Tuple[Optional[int], Any, Any]]

    def bind(self, raw_values: List[Any]) -> List[Any]:
        """Raw USING values (plan-unit python literals) -> slot values, in
        slot order.  Raises canonical.BindError on any mismatch."""
        from ..sql.canonical import BindError, bind_literal
        out = []
        for origin, typ, fixed in self.slots:
            if origin is None:
                out.append(fixed)
            else:
                if origin >= len(raw_values):
                    raise BindError(f"missing value for ?{origin + 1}")
                out.append(bind_literal(raw_values[origin], typ))
        return out


@dataclass
class PreparedStatement:
    text: str
    statement: Any                      # parsed inner AST (parser.Node)
    param_count: int
    fast: Optional[FastPath] = None
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def record_fast_path(self, fast: FastPath) -> None:
        with self._lock:
            if self.fast is None:
                self.fast = fast


class PreparedRegistry:
    """text -> PreparedStatement memo (LRU, process-global).  Session
    scoping stays with the header map / dbapi connection; this only
    deduplicates parse work and carries fast-path records across
    requests."""

    def __init__(self, max_statements: int = _MAX_STATEMENTS):
        self._lock = threading.Lock()
        self._by_text: "OrderedDict[str, PreparedStatement]" = OrderedDict()
        self.max_statements = max_statements

    def get_or_parse(self, text: str) -> PreparedStatement:
        with self._lock:
            ps = self._by_text.get(text)
            if ps is not None:
                self._by_text.move_to_end(text)
                return ps
        # parse outside the lock (a slow parse must not serialize lookups)
        from ..sql import parser as A
        sub = A.Parser(text)
        stmt = sub.parse()
        ps = PreparedStatement(text, stmt, sub._param_count)
        with self._lock:
            cur = self._by_text.get(text)
            if cur is not None:
                self._by_text.move_to_end(text)
                return cur
            self._by_text[text] = ps
            while len(self._by_text) > self.max_statements:
                self._by_text.popitem(last=False)
            SERVING_METRICS.incr("prepared_registered")
            return ps

    def clear(self) -> None:
        with self._lock:
            self._by_text.clear()

    def invalidate_fast_paths(self) -> None:
        """DDL: recorded template keys may point at dropped tables; keep
        the parse memo, drop the binding records."""
        with self._lock:
            for ps in self._by_text.values():
                ps.fast = None

    def info(self) -> dict:
        with self._lock:
            return {
                "statements": len(self._by_text),
                "fastPaths": sum(1 for p in self._by_text.values()
                                 if p.fast is not None),
            }


PREPARED_REGISTRY = PreparedRegistry()
