"""Process-wide serving-tier counters for /v1/metrics and /v1/status.

Same shape as worker/exchange.py's ExchangeMetrics: one worker per process
in deployment, tests reset() before asserting.  The cache counters are fed
by serving/cache.py; the prepared counters by exec/runner.py's
PREPARE/EXECUTE handling; compiler builds by the runner's canonical plan
path (a build is the expensive event the cache exists to avoid — the
acceptance gate asserts it does NOT move on a warm repeated shape).
"""
from __future__ import annotations

from ..common.locks import OrderedLock


class ServingMetrics:
    def __init__(self):
        # rank 100: metrics registries are leaf locks
        self._lock = OrderedLock("metrics:serving", 100)  # lint: guarded-by(_lock)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.plan_cache_hits = 0
            self.plan_cache_misses = 0
            self.plan_cache_evictions = 0
            self.plan_cache_invalidations = 0
            # PlanCompiler constructions on the serving path.  A hit whose
            # pooled compiler is checked out by a concurrent execution
            # rebuilds one from the cached optimized template (counted
            # here, not as a miss: parse/plan/optimize were still skipped).
            self.executable_builds = 0
            self.prepared_registered = 0
            self.prepared_fast_path = 0     # EXECUTE skipped parse+plan
            self.prepared_replans = 0       # EXECUTE took the full pipeline
            # micro-batched EXECUTE..USING (serving/batching.py): one
            # batched drain = ONE device launch serving `occupancy`
            # queries; launches saved = batch_queries - batches
            self.serving_batches = 0
            self.serving_batch_queries = 0
            self.serving_batch_fallbacks = 0   # joined a group, ran solo
            self.serving_batch_demux_nanos = 0
            self.serving_batch_padded_lanes = 0
            self.serving_batch_occupancy: dict = {}   # str(n) -> count
            # PlanCache compiler-pool contention (serving/cache.py): an
            # exhausted pool silently rebuilds a compiler — meter it
            self.compiler_checkouts = 0
            self.compiler_pool_exhausted = 0
            self.compiler_checkout_wait_nanos = 0
            self.compiler_checkout_depth_peak = 0
            # fragment-level jit sharing (serving/fragments.py)
            self.fragment_jit_hits = 0
            self.fragment_jit_misses = 0

    def incr(self, name: str, delta: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + delta)

    def max_update(self, name: str, value: int) -> None:
        """Monotonic high-water counter (checkout depth peaks)."""
        with self._lock:
            if value > getattr(self, name):
                setattr(self, name, value)

    def record_batch(self, occupancy: int, demux_nanos: int,
                     padded_lanes: int = 0) -> None:
        """One batched drain: `occupancy` real queries in one launch."""
        with self._lock:
            self.serving_batches += 1
            self.serving_batch_queries += occupancy
            self.serving_batch_demux_nanos += int(demux_nanos)
            self.serving_batch_padded_lanes += padded_lanes
            k = str(occupancy)
            self.serving_batch_occupancy[k] = \
                self.serving_batch_occupancy.get(k, 0) + 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "planCacheHits": self.plan_cache_hits,
                "planCacheMisses": self.plan_cache_misses,
                "planCacheEvictions": self.plan_cache_evictions,
                "planCacheInvalidations": self.plan_cache_invalidations,
                "executableBuilds": self.executable_builds,
                "preparedRegistered": self.prepared_registered,
                "preparedFastPath": self.prepared_fast_path,
                "preparedReplans": self.prepared_replans,
                "servingBatches": self.serving_batches,
                "servingBatchQueries": self.serving_batch_queries,
                "servingBatchLaunchesSaved": (self.serving_batch_queries
                                              - self.serving_batches),
                "servingBatchFallbacks": self.serving_batch_fallbacks,
                "servingBatchDemuxNanos": self.serving_batch_demux_nanos,
                "servingBatchPaddedLanes": self.serving_batch_padded_lanes,
                "servingBatchOccupancy": dict(self.serving_batch_occupancy),
                "compilerCheckouts": self.compiler_checkouts,
                "compilerPoolExhausted": self.compiler_pool_exhausted,
                "compilerCheckoutWaitNanos":
                    self.compiler_checkout_wait_nanos,
                "compilerCheckoutDepthPeak":
                    self.compiler_checkout_depth_peak,
                "fragmentJitHits": self.fragment_jit_hits,
                "fragmentJitMisses": self.fragment_jit_misses,
            }

    def hit_rate(self) -> float:
        with self._lock:
            total = self.plan_cache_hits + self.plan_cache_misses
            return self.plan_cache_hits / total if total else 0.0

    def compact_snapshot(self) -> dict:
        """The bench/telemetry digest: absolute counters collapse to the
        two rates that explain a perf trajectory line."""
        snap = self.snapshot()
        total = snap["planCacheHits"] + snap["planCacheMisses"]
        prepared = snap["preparedFastPath"] + snap["preparedReplans"]
        return {
            "planCacheHitRate": (snap["planCacheHits"] / total
                                 if total else 0.0),
            "preparedFastPathRate": (snap["preparedFastPath"] / prepared
                                     if prepared else 0.0),
            "executableBuilds": snap["executableBuilds"],
            "servingBatches": snap["servingBatches"],
            "servingBatchLaunchesSaved": snap["servingBatchLaunchesSaved"],
            "compilerPoolExhausted": snap["compilerPoolExhausted"],
        }


SERVING_METRICS = ServingMetrics()
