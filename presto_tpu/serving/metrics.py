"""Process-wide serving-tier counters for /v1/metrics and /v1/status.

Same shape as worker/exchange.py's ExchangeMetrics: one worker per process
in deployment, tests reset() before asserting.  The cache counters are fed
by serving/cache.py; the prepared counters by exec/runner.py's
PREPARE/EXECUTE handling; compiler builds by the runner's canonical plan
path (a build is the expensive event the cache exists to avoid — the
acceptance gate asserts it does NOT move on a warm repeated shape).
"""
from __future__ import annotations

from ..common.locks import OrderedLock


class ServingMetrics:
    def __init__(self):
        # rank 100: metrics registries are leaf locks
        self._lock = OrderedLock("metrics:serving", 100)  # lint: guarded-by(_lock)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.plan_cache_hits = 0
            self.plan_cache_misses = 0
            self.plan_cache_evictions = 0
            self.plan_cache_invalidations = 0
            # PlanCompiler constructions on the serving path.  A hit whose
            # pooled compiler is checked out by a concurrent execution
            # rebuilds one from the cached optimized template (counted
            # here, not as a miss: parse/plan/optimize were still skipped).
            self.executable_builds = 0
            self.prepared_registered = 0
            self.prepared_fast_path = 0     # EXECUTE skipped parse+plan
            self.prepared_replans = 0       # EXECUTE took the full pipeline

    def incr(self, name: str, delta: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + delta)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "planCacheHits": self.plan_cache_hits,
                "planCacheMisses": self.plan_cache_misses,
                "planCacheEvictions": self.plan_cache_evictions,
                "planCacheInvalidations": self.plan_cache_invalidations,
                "executableBuilds": self.executable_builds,
                "preparedRegistered": self.prepared_registered,
                "preparedFastPath": self.prepared_fast_path,
                "preparedReplans": self.prepared_replans,
            }

    def hit_rate(self) -> float:
        with self._lock:
            total = self.plan_cache_hits + self.plan_cache_misses
            return self.plan_cache_hits / total if total else 0.0

    def compact_snapshot(self) -> dict:
        """The bench/telemetry digest: absolute counters collapse to the
        two rates that explain a perf trajectory line."""
        snap = self.snapshot()
        total = snap["planCacheHits"] + snap["planCacheMisses"]
        prepared = snap["preparedFastPath"] + snap["preparedReplans"]
        return {
            "planCacheHitRate": (snap["planCacheHits"] / total
                                 if total else 0.0),
            "preparedFastPathRate": (snap["preparedFastPath"] / prepared
                                     if prepared else 0.0),
            "executableBuilds": snap["executableBuilds"],
        }


SERVING_METRICS = ServingMetrics()
