"""SerializedPage wire format, byte-compatible with the reference.

Framing (presto-spi/.../spi/page/PagesSerdeUtil.java:64-88):
    positionCount:int32 | codecMarkers:byte | uncompressedSize:int32 |
    size:int32 | checksum:int64 | <size bytes of page data>
Page data (PagesSerdeUtil.writeRawPage:45-51):
    channelCount:int32 then each block via writeBlock.
Block framing (BlockEncodingManager.java:79-99): length-prefixed UTF-8 encoding
name, then the encoding-specific payload.  All integers little-endian (airlift
Slice).  Codec marker bits (PageCodecMarker.java:27-29): COMPRESSED=1,
ENCRYPTED=2, CHECKSUMMED=4.  Checksum = CRC32 over (pageData, markers byte,
positionCount LE32, uncompressedSize LE32) per PagesSerdeUtil.java:102-119.

Null bitmaps (EncoderUtil.java): one boolean byte mayHaveNull; if set, one bit
per position MSB-first within each byte, 1 == null; fixed-width encodings then
write values for NON-NULL positions only.
"""
from __future__ import annotations

import io
import struct
import zlib
from typing import List, Optional

import numpy as np

from . import compression
from .block import (
    ArrayBlock, Block, DictionaryBlock, FixedWidthBlock, Int128Block,
    RowBlock, RunLengthBlock, VariableWidthBlock,
)
from .page import Page

COMPRESSED = 0x01
ENCRYPTED = 0x02
CHECKSUMMED = 0x04

PAGE_METADATA_SIZE = 21

_WIDTH_BY_NAME = {"BYTE_ARRAY": 1, "SHORT_ARRAY": 2, "INT_ARRAY": 4, "LONG_ARRAY": 8}


# ---------------------------------------------------------------------------
# null bitmap helpers
# ---------------------------------------------------------------------------

def _encode_nulls(out: io.BytesIO, block: Block) -> Optional[np.ndarray]:
    if not block.may_have_null:
        out.write(b"\x00")
        return None
    mask = block.null_mask()
    out.write(b"\x01")
    out.write(np.packbits(mask).tobytes())  # MSB-first, matches EncoderUtil
    return mask


def _decode_nulls(buf: memoryview, pos: int, n: int):
    may_have = buf[pos]
    pos += 1
    if not may_have:
        return None, pos
    nbytes = (n + 7) // 8
    bits = np.unpackbits(
        np.frombuffer(buf[pos:pos + nbytes], dtype=np.uint8))[:n].astype(bool)
    return bits, pos + nbytes


# ---------------------------------------------------------------------------
# block write
# ---------------------------------------------------------------------------

def write_block(out: io.BytesIO, block: Block) -> None:
    name = block.encoding
    nb = name.encode("utf-8")
    out.write(struct.pack("<i", len(nb)))
    out.write(nb)
    _write_block_body(out, block)


def _write_block_body(out: io.BytesIO, block: Block) -> None:
    name = block.encoding
    if name in _WIDTH_BY_NAME:
        _write_fixed(out, block)
    elif name == "INT128_ARRAY":
        _write_int128(out, block)
    elif name == "VARIABLE_WIDTH":
        _write_varwidth(out, block)
    elif name == "DICTIONARY":
        _write_dictionary(out, block)
    elif name == "RLE":
        out.write(struct.pack("<i", block.position_count))
        write_block(out, block.value)
    elif name == "ARRAY":
        _write_array(out, block)
    elif name == "ROW":
        _write_row(out, block)
    else:
        raise NotImplementedError(f"encoding {name}")


def _write_fixed(out: io.BytesIO, block: FixedWidthBlock) -> None:
    out.write(struct.pack("<i", block.position_count))
    mask = _encode_nulls(out, block)
    values = block.values
    if mask is not None:
        values = values[~mask]  # non-null values only
    out.write(np.ascontiguousarray(values).tobytes())


def _write_int128(out: io.BytesIO, block: Int128Block) -> None:
    out.write(struct.pack("<i", block.position_count))
    mask = _encode_nulls(out, block)
    values = block.values
    if mask is not None:
        values = values[~mask]
    out.write(np.ascontiguousarray(values).tobytes())


def _write_varwidth(out: io.BytesIO, block: VariableWidthBlock) -> None:
    n = block.position_count
    out.write(struct.pack("<i", n))
    # cumulative end offsets, rebased to zero
    offs = (block.offsets[1:] - block.offsets[0]).astype(np.int32)
    out.write(offs.tobytes())
    _encode_nulls(out, block)
    total = int(offs[-1]) if n else 0
    out.write(struct.pack("<i", total))
    start = int(block.offsets[0])
    out.write(block.data[start:start + total].tobytes())


def _write_dictionary(out: io.BytesIO, block: DictionaryBlock) -> None:
    block = block.compact()
    out.write(struct.pack("<i", block.position_count))
    write_block(out, block.dictionary)
    out.write(block.ids.tobytes())
    msb, lsb, seq = block.source_id
    out.write(struct.pack("<qqq", msb, lsb, seq))


def _write_array(out: io.BytesIO, block: ArrayBlock) -> None:
    start = int(block.offsets[0])
    end = int(block.offsets[-1])
    write_block(out, block.elements.region(start, end - start)
                if (start != 0 or end != block.elements.position_count)
                else block.elements)
    out.write(struct.pack("<i", block.position_count))
    out.write((block.offsets - start).astype(np.int32).tobytes())
    _encode_nulls(out, block)


def _write_row(out: io.BytesIO, block: RowBlock) -> None:
    out.write(struct.pack("<i", len(block.field_blocks)))
    start = int(block.offsets[0])
    end = int(block.offsets[-1])
    for f in block.field_blocks:
        write_block(out, f.region(start, end - start)
                    if (start != 0 or end != f.position_count) else f)
    out.write(struct.pack("<i", block.position_count))
    out.write((block.offsets - start).astype(np.int32).tobytes())
    _encode_nulls(out, block)


# ---------------------------------------------------------------------------
# block read
# ---------------------------------------------------------------------------

def read_block(buf: memoryview, pos: int = 0):
    (nlen,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    name = bytes(buf[pos:pos + nlen]).decode("utf-8")
    pos += nlen
    return _read_block_body(name, buf, pos)


def _read_block_body(name: str, buf: memoryview, pos: int):
    if name in _WIDTH_BY_NAME:
        return _read_fixed(buf, pos, _WIDTH_BY_NAME[name])
    if name == "INT128_ARRAY":
        return _read_int128(buf, pos)
    if name == "VARIABLE_WIDTH":
        return _read_varwidth(buf, pos)
    if name == "DICTIONARY":
        return _read_dictionary(buf, pos)
    if name == "RLE":
        (n,) = struct.unpack_from("<i", buf, pos)
        value, pos = read_block(buf, pos + 4)
        return RunLengthBlock(value, n), pos
    if name == "ARRAY":
        return _read_array(buf, pos)
    if name == "ROW":
        return _read_row(buf, pos)
    raise NotImplementedError(f"encoding {name}")


_DTYPES = {1: np.int8, 2: np.int16, 4: np.int32, 8: np.int64}


def _read_fixed(buf, pos, width):
    (n,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    nulls, pos = _decode_nulls(buf, pos, n)
    dtype = _DTYPES[width]
    if nulls is None:
        values = np.frombuffer(buf[pos:pos + n * width], dtype=dtype).copy()
        pos += n * width
    else:
        k = int((~nulls).sum())
        packed = np.frombuffer(buf[pos:pos + k * width], dtype=dtype)
        pos += k * width
        values = np.zeros(n, dtype=dtype)
        values[~nulls] = packed
    return FixedWidthBlock(values, nulls), pos


def _read_int128(buf, pos):
    (n,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    nulls, pos = _decode_nulls(buf, pos, n)
    if nulls is None:
        values = np.frombuffer(buf[pos:pos + n * 16], dtype=np.int64).copy().reshape(n, 2)
        pos += n * 16
    else:
        k = int((~nulls).sum())
        packed = np.frombuffer(buf[pos:pos + k * 16], dtype=np.int64).reshape(k, 2)
        pos += k * 16
        values = np.zeros((n, 2), dtype=np.int64)
        values[~nulls] = packed
    return Int128Block(values, nulls), pos


def _read_varwidth(buf, pos):
    (n,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    ends = np.frombuffer(buf[pos:pos + 4 * n], dtype=np.int32)
    pos += 4 * n
    nulls, pos = _decode_nulls(buf, pos, n)
    (total,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    data = np.frombuffer(buf[pos:pos + total], dtype=np.uint8).copy()
    pos += total
    offsets = np.zeros(n + 1, dtype=np.int32)
    offsets[1:] = ends
    return VariableWidthBlock(offsets, data, nulls), pos


def _read_dictionary(buf, pos):
    (n,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    dictionary, pos = read_block(buf, pos)
    ids = np.frombuffer(buf[pos:pos + 4 * n], dtype=np.int32).copy()
    pos += 4 * n
    msb, lsb, seq = struct.unpack_from("<qqq", buf, pos)
    pos += 24
    return DictionaryBlock(ids, dictionary, (msb, lsb, seq)), pos


def _read_array(buf, pos):
    elements, pos = read_block(buf, pos)
    (n,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    offsets = np.frombuffer(buf[pos:pos + 4 * (n + 1)], dtype=np.int32).copy()
    pos += 4 * (n + 1)
    nulls, pos = _decode_nulls(buf, pos, n)
    return ArrayBlock(offsets, elements, nulls), pos


def _read_row(buf, pos):
    (nfields,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    fields = []
    for _ in range(nfields):
        f, pos = read_block(buf, pos)
        fields.append(f)
    (n,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    offsets = np.frombuffer(buf[pos:pos + 4 * (n + 1)], dtype=np.int32).copy()
    pos += 4 * (n + 1)
    nulls, pos = _decode_nulls(buf, pos, n)
    return RowBlock(fields, offsets, nulls), pos


# ---------------------------------------------------------------------------
# page-level serde
# ---------------------------------------------------------------------------

def _checksum(page_data: bytes, markers: int, position_count: int,
              uncompressed_size: int) -> int:
    crc = zlib.crc32(page_data)
    crc = zlib.crc32(bytes([markers & 0xFF]), crc)
    crc = zlib.crc32(struct.pack("<i", position_count), crc)
    crc = zlib.crc32(struct.pack("<i", uncompressed_size), crc)
    return crc & 0xFFFFFFFF


# pages smaller than this are stored raw: compression overhead beats the
# saved bytes for tiny pages (the reference compresses unconditionally and
# relies on the ratio gate; we additionally skip sub-4KiB bodies)
MIN_COMPRESS_BYTES = 1 << 12

# reference PagesSerde.MINIMUM_COMPRESSION_RATIO (PagesSerde.java:44):
# keep the compressed form only when compressed/uncompressed <= 0.9
MINIMUM_COMPRESSION_RATIO = 0.9

DEFAULT_CODEC = "LZ4"


def serialize_page(page: Page, checksummed: bool = True,
                   compress: bool = False,
                   codec: str = DEFAULT_CODEC) -> bytes:
    """Wire-format page (21-byte header + channel data); compress=True
    compresses the body with `codec` (LZ4 raw block format by default,
    matching PagesSerdeFactory.java:75-76's aircompressor Lz4Compressor)
    when it shrinks the page below the reference's MINIMUM_COMPRESSION_RATIO
    gate (PagesSerde.java:44,138-141).  The marker bit and uncompressedSize
    field follow PageCodecMarker.java:27-29 / PagesSerdeUtil.java:79-88."""
    body = io.BytesIO()
    body.write(struct.pack("<i", page.channel_count))
    for b in page.blocks:
        write_block(body, b)
    data = body.getvalue()
    uncompressed = len(data)
    markers = CHECKSUMMED if checksummed else 0
    if compress and codec != "NONE" and uncompressed >= MIN_COMPRESS_BYTES:
        packed = compression.compress(codec, data)
        if len(packed) <= uncompressed * MINIMUM_COMPRESSION_RATIO:
            data = packed
            markers |= COMPRESSED
    checksum = (_checksum(data, markers, page.position_count, uncompressed)
                if checksummed else 0)
    header = struct.pack("<ibiiq", page.position_count, markers,
                         uncompressed, len(data), checksum)
    return header + data


def deserialize_page(buf: bytes, pos: int = 0, codec: str = DEFAULT_CODEC):
    """Returns (Page, next_pos).  `codec` names the decompressor for
    COMPRESSED pages — cluster config, not wire metadata, exactly like the
    reference (PagesSerde carries the configured decompressor)."""
    view = memoryview(buf)
    position_count, markers, uncompressed_size, size, checksum = struct.unpack_from(
        "<ibiiq", view, pos)
    pos += PAGE_METADATA_SIZE
    data = view[pos:pos + size]
    if markers & ENCRYPTED:
        raise NotImplementedError("encrypted pages not supported")
    if markers & CHECKSUMMED:
        # checksum covers the wire form (compressed bytes if compressed);
        # zlib.crc32 accepts the memoryview directly — no body copy
        actual = _checksum(data, markers, position_count,
                           uncompressed_size)
        if actual != (checksum & 0xFFFFFFFF):
            raise ValueError(
                f"page checksum mismatch: {actual:#x} != {checksum:#x}")
    if markers & COMPRESSED:
        # every codec backend (pyarrow, zlib, the pure lz4 block fallback)
        # takes buffer-like input: hand it the view, copy nothing
        data = memoryview(compression.decompress(
            codec, data, uncompressed_size))
        if len(data) != uncompressed_size:
            raise ValueError(
                f"decompressed size {len(data)} != header "
                f"{uncompressed_size}")
    (channels,) = struct.unpack_from("<i", data, 0)
    p = 4
    blocks: List[Block] = []
    for _ in range(channels):
        b, p = read_block(data, p)
        blocks.append(b)
    return Page(blocks, position_count), pos + size


def serialize_pages(pages, compress: bool = False,
                    codec: str = DEFAULT_CODEC) -> bytes:
    return b"".join(serialize_page(p, compress=compress, codec=codec)
                    for p in pages)


def deserialize_pages(buf: bytes, codec: str = DEFAULT_CODEC):
    pages, pos = [], 0
    while pos < len(buf):
        page, pos = deserialize_page(buf, pos, codec=codec)
        pages.append(page)
    return pages
