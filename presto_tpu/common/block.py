"""Columnar Block hierarchy, host side, numpy-backed.

Re-implements the behavior of the reference block model
(presto-common/src/main/java/com/facebook/presto/common/block/Block.java and its
concrete classes) with vectorized numpy storage instead of per-position accessors.
The wire encodings (serde.py) are byte-compatible with the reference
*BlockEncoding.java classes; this module is the in-memory model.

Null convention: `nulls` is a bool ndarray where True == null, or None when the
block provably has no nulls (mirrors Block.mayHaveNull()).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from .types import (
    BYTE_ARRAY, SHORT_ARRAY, INT_ARRAY, LONG_ARRAY, INT128_ARRAY,
    VARIABLE_WIDTH, ARRAY, MAP, ROW, ArrayType, Type, DateType, DecimalType,
    DoubleType, RealType, BooleanType, VarcharType, CharType, VarbinaryType,
)

_WIDTH_TO_ENCODING = {1: BYTE_ARRAY, 2: SHORT_ARRAY, 4: INT_ARRAY, 8: LONG_ARRAY}


class Block:
    """Abstract block. position_count positions of one column."""

    position_count: int
    nulls: Optional[np.ndarray]  # bool array, True == null; None == no nulls

    @property
    def encoding(self) -> str:
        raise NotImplementedError

    @property
    def may_have_null(self) -> bool:
        return self.nulls is not None and bool(self.nulls.any())

    def null_mask(self) -> np.ndarray:
        if self.nulls is None:
            return np.zeros(self.position_count, dtype=bool)
        return self.nulls

    def __len__(self) -> int:
        return self.position_count

    # --- generic ops used by the engine ---------------------------------
    def take(self, positions: np.ndarray) -> "Block":
        """New block with the given positions (DictionaryBlock.getPositions analog,
        but materialized)."""
        raise NotImplementedError

    def region(self, offset: int, length: int) -> "Block":
        return self.take(np.arange(offset, offset + length))

    def to_pylist(self) -> list:
        """Decode to python objects (None for nulls) — test/debug path."""
        raise NotImplementedError


class FixedWidthBlock(Block):
    """BYTE/SHORT/INT/LONG array blocks.  `values` may be stored under any dtype
    of the right itemsize (e.g. float64 for DOUBLE — the wire just sees bits)."""

    def __init__(self, values: np.ndarray, nulls: Optional[np.ndarray] = None):
        values = np.ascontiguousarray(values)
        if values.ndim != 1:
            raise ValueError("FixedWidthBlock values must be 1-D")
        self.values = values
        self.position_count = len(values)
        self.nulls = nulls if (nulls is not None and nulls.any()) else None

    @property
    def encoding(self) -> str:
        return _WIDTH_TO_ENCODING[self.values.dtype.itemsize]

    def take(self, positions: np.ndarray) -> "FixedWidthBlock":
        return FixedWidthBlock(
            self.values[positions],
            None if self.nulls is None else self.nulls[positions],
        )

    def to_pylist(self) -> list:
        vals = self.values.tolist()
        if self.nulls is None:
            return vals
        return [None if n else v for v, n in zip(vals, self.nulls.tolist())]


def byte_array_block(values, nulls=None):
    return FixedWidthBlock(np.asarray(values, dtype=np.int8), _mask(nulls))


def short_array_block(values, nulls=None):
    return FixedWidthBlock(np.asarray(values, dtype=np.int16), _mask(nulls))


def int_array_block(values, nulls=None):
    return FixedWidthBlock(np.asarray(values, dtype=np.int32), _mask(nulls))


def long_array_block(values, nulls=None):
    return FixedWidthBlock(np.asarray(values, dtype=np.int64), _mask(nulls))


def double_block(values, nulls=None):
    return FixedWidthBlock(np.asarray(values, dtype=np.float64), _mask(nulls))


def _mask(nulls):
    if nulls is None:
        return None
    return np.asarray(nulls, dtype=bool)


class Int128Block(Block):
    """INT128_ARRAY: values shape (n, 2) int64 in wire order (first long, second
    long).  For long decimals the reference layout
    (UnscaledDecimal128Arithmetic.java:33-39) is sign-magnitude little-endian:
    word 0 = low 64 bits of |value|, word 1 = high 63 bits | sign bit in MSB."""

    def __init__(self, values: np.ndarray, nulls: Optional[np.ndarray] = None):
        values = np.ascontiguousarray(values, dtype=np.int64)
        if values.ndim != 2 or values.shape[1] != 2:
            raise ValueError("Int128Block values must be (n, 2) int64")
        self.values = values
        self.position_count = len(values)
        self.nulls = nulls if (nulls is not None and nulls.any()) else None

    @property
    def encoding(self) -> str:
        return INT128_ARRAY

    def take(self, positions):
        return Int128Block(
            self.values[positions],
            None if self.nulls is None else self.nulls[positions],
        )

    def to_pylist(self):
        """Decode as signed int128 under the reference sign-magnitude layout."""
        out = []
        for i in range(self.position_count):
            if self.nulls is not None and self.nulls[i]:
                out.append(None)
            else:
                lo = int(self.values[i, 0]) & 0xFFFFFFFFFFFFFFFF
                hi = int(self.values[i, 1]) & 0xFFFFFFFFFFFFFFFF
                negative = bool(hi >> 63)
                magnitude = ((hi & 0x7FFFFFFFFFFFFFFF) << 64) | lo
                out.append(-magnitude if negative else magnitude)
        return out

    @staticmethod
    def from_ints(values, nulls=None) -> "Int128Block":
        """Build from python ints using the reference sign-magnitude layout."""
        arr = np.zeros((len(values), 2), dtype=np.uint64)
        for i, v in enumerate(values):
            if v is None:
                continue
            magnitude = abs(int(v))
            lo = magnitude & 0xFFFFFFFFFFFFFFFF
            hi = (magnitude >> 64) & 0x7FFFFFFFFFFFFFFF
            if v < 0:
                hi |= 1 << 63
            arr[i, 0] = lo
            arr[i, 1] = hi
        return Int128Block(arr.view(np.int64), _mask(nulls))


class VariableWidthBlock(Block):
    """VARIABLE_WIDTH: concatenated bytes + (n+1) int32 offsets."""

    def __init__(self, offsets: np.ndarray, data: np.ndarray,
                 nulls: Optional[np.ndarray] = None):
        self.offsets = np.ascontiguousarray(offsets, dtype=np.int32)
        self.data = np.ascontiguousarray(data, dtype=np.uint8)
        self.position_count = len(self.offsets) - 1
        self.nulls = nulls if (nulls is not None and nulls.any()) else None

    @property
    def encoding(self) -> str:
        return VARIABLE_WIDTH

    @staticmethod
    def from_bytes(items: Sequence[Optional[bytes]]) -> "VariableWidthBlock":
        encoded = [(b if b is not None else b"") for b in items]
        offsets = np.zeros(len(encoded) + 1, dtype=np.int32)
        np.cumsum([len(b) for b in encoded], out=offsets[1:])
        data = np.frombuffer(b"".join(encoded), dtype=np.uint8).copy()
        nulls = np.array([b is None for b in items], dtype=bool)
        return VariableWidthBlock(offsets, data, nulls if nulls.any() else None)

    @staticmethod
    def from_strings(strings: Sequence[Optional[str]]) -> "VariableWidthBlock":
        return VariableWidthBlock.from_bytes(
            [None if s is None else s.encode("utf-8") for s in strings])

    def take(self, positions) -> "VariableWidthBlock":
        positions = np.asarray(positions)
        lengths = (self.offsets[1:] - self.offsets[:-1])[positions]
        new_offsets = np.zeros(len(positions) + 1, dtype=np.int32)
        np.cumsum(lengths, out=new_offsets[1:])
        total = int(new_offsets[-1])
        if total == 0:
            out = np.empty(0, dtype=np.uint8)
        else:
            # vectorized byte gather: source index = row start + offset
            # within the row (no per-row python loop — this sits on the
            # exchange partition-split path)
            within = np.arange(total, dtype=np.int64) - np.repeat(
                new_offsets[:-1].astype(np.int64), lengths)
            src = np.repeat(self.offsets[positions].astype(np.int64),
                            lengths) + within
            out = self.data[src]
        return VariableWidthBlock(
            new_offsets, out,
            None if self.nulls is None else self.nulls[positions])

    def slice_at(self, i: int) -> bytes:
        return self.data[self.offsets[i]:self.offsets[i + 1]].tobytes()

    def to_pylist(self):
        out = []
        for i in range(self.position_count):
            if self.nulls is not None and self.nulls[i]:
                out.append(None)
            else:
                out.append(self.slice_at(i).decode("utf-8", errors="replace"))
        return out


# Sequence id for dictionary blocks written on the wire (reference DictionaryId).
_DICT_ID_COUNTER = [0]


def _next_dictionary_id():
    _DICT_ID_COUNTER[0] += 1
    # (mostSignificantBits, leastSignificantBits, sequenceId)
    return (0x7075_7470, 0x7463_6F6C, _DICT_ID_COUNTER[0])


class DictionaryBlock(Block):
    """DICTIONARY: int32 ids into a dictionary block."""

    def __init__(self, ids: np.ndarray, dictionary: Block, source_id=None):
        self.ids = np.ascontiguousarray(ids, dtype=np.int32)
        self.dictionary = dictionary
        self.position_count = len(self.ids)
        self.source_id = source_id or _next_dictionary_id()
        self.nulls = None

    @property
    def encoding(self) -> str:
        return "DICTIONARY"

    @property
    def may_have_null(self) -> bool:
        return self.dictionary.may_have_null

    def null_mask(self) -> np.ndarray:
        return self.dictionary.null_mask()[self.ids]

    def compact(self) -> "DictionaryBlock":
        """Rewrite so the dictionary contains only referenced entries
        (DictionaryBlock.compact in the reference — required before
        serializing).  An already-compact block is returned unchanged so
        its dictionary instance id survives re-serialization."""
        used, inverse = np.unique(self.ids, return_inverse=True)
        if len(used) == self.dictionary.position_count \
                and np.array_equal(used, np.arange(len(used))):
            return self
        return DictionaryBlock(inverse.astype(np.int32), self.dictionary.take(used))

    def decode(self) -> Block:
        return self.dictionary.take(self.ids)

    def take(self, positions):
        return DictionaryBlock(self.ids[positions], self.dictionary)

    def to_pylist(self):
        d = self.dictionary.to_pylist()
        return [d[i] for i in self.ids.tolist()]


class RunLengthBlock(Block):
    """RLE: one value repeated position_count times."""

    def __init__(self, value: Block, position_count: int):
        if value.position_count != 1:
            raise ValueError("RLE value block must have exactly 1 position")
        self.value = value
        self.position_count = position_count
        self.nulls = None

    @property
    def encoding(self) -> str:
        return "RLE"

    @property
    def may_have_null(self) -> bool:
        return self.value.may_have_null

    def null_mask(self) -> np.ndarray:
        return np.full(self.position_count, bool(self.value.null_mask()[0]))

    def decode(self) -> Block:
        return self.value.take(np.zeros(self.position_count, dtype=np.int64))

    def take(self, positions):
        return RunLengthBlock(self.value, len(np.asarray(positions)))

    def to_pylist(self):
        return self.value.to_pylist() * self.position_count


class ArrayBlock(Block):
    """ARRAY: (n+1) int32 offsets into an elements block."""

    def __init__(self, offsets: np.ndarray, elements: Block,
                 nulls: Optional[np.ndarray] = None):
        self.offsets = np.ascontiguousarray(offsets, dtype=np.int32)
        self.elements = elements
        self.position_count = len(self.offsets) - 1
        self.nulls = nulls if (nulls is not None and nulls.any()) else None

    @property
    def encoding(self) -> str:
        return ARRAY

    def take(self, positions):
        positions = np.asarray(positions)
        lengths = (self.offsets[1:] - self.offsets[:-1])[positions]
        new_offsets = np.zeros(len(positions) + 1, dtype=np.int32)
        np.cumsum(lengths, out=new_offsets[1:])
        idx = np.concatenate(
            [np.arange(self.offsets[p], self.offsets[p + 1]) for p in positions]
        ) if len(positions) else np.array([], dtype=np.int64)
        return ArrayBlock(
            new_offsets, self.elements.take(idx.astype(np.int64)),
            None if self.nulls is None else self.nulls[positions])

    def to_pylist(self):
        elems = self.elements.to_pylist()
        out = []
        for i in range(self.position_count):
            if self.nulls is not None and self.nulls[i]:
                out.append(None)
            else:
                out.append(elems[self.offsets[i]:self.offsets[i + 1]])
        return out


class RowBlock(Block):
    """ROW: parallel field blocks + (n+1) offsets (non-null rows are dense)."""

    def __init__(self, field_blocks: List[Block], offsets: np.ndarray,
                 nulls: Optional[np.ndarray] = None):
        self.field_blocks = field_blocks
        self.offsets = np.ascontiguousarray(offsets, dtype=np.int32)
        self.position_count = len(self.offsets) - 1
        self.nulls = nulls if (nulls is not None and nulls.any()) else None

    @staticmethod
    def from_fields(field_blocks: List[Block]) -> "RowBlock":
        n = field_blocks[0].position_count
        return RowBlock(field_blocks, np.arange(n + 1, dtype=np.int32))

    @property
    def encoding(self) -> str:
        return ROW

    def take(self, positions):
        positions = np.asarray(positions)
        nulls = None if self.nulls is None else self.nulls[positions]
        # Null rows occupy no field entries in the sparse reference layout
        # (RowBlockEncoding offsets), so only gather rows for non-null positions.
        null_mask = (np.zeros(len(positions), dtype=bool)
                     if nulls is None else nulls)
        rows = self.offsets[positions][~null_mask]
        new_offsets = np.zeros(len(positions) + 1, dtype=np.int32)
        np.cumsum(~null_mask, out=new_offsets[1:])
        return RowBlock(
            [f.take(rows) for f in self.field_blocks], new_offsets, nulls)

    def to_pylist(self):
        fields = [f.to_pylist() for f in self.field_blocks]
        out = []
        for i in range(self.position_count):
            if self.nulls is not None and self.nulls[i]:
                out.append(None)
            else:
                r = int(self.offsets[i])
                out.append([f[r] for f in fields])
        return out


def decode_to_flat(block: Block) -> Block:
    """Flatten DICTIONARY/RLE wrappers to a direct block."""
    while isinstance(block, (DictionaryBlock, RunLengthBlock)):
        block = block.decode()
    return block


# ---------------------------------------------------------------------------
# Typed construction helpers: python values -> storage block for a Type
# ---------------------------------------------------------------------------

def block_from_values(typ: Type, values: Sequence) -> Block:
    """Build a block from python values (None == null) under `typ` semantics."""
    nulls = np.array([v is None for v in values], dtype=bool)
    has_null = bool(nulls.any())
    n = len(values)

    if isinstance(typ, (VarcharType, CharType)):
        return VariableWidthBlock.from_strings(values)
    if isinstance(typ, VarbinaryType):
        return VariableWidthBlock.from_bytes(values)
    if isinstance(typ, DecimalType) and not typ.is_short:
        return Int128Block.from_ints(values, nulls if has_null else None)
    if isinstance(typ, ArrayType):
        offsets = np.zeros(n + 1, dtype=np.int32)
        flat: list = []
        for i, v in enumerate(values):
            if v is not None:
                flat.extend(v)
            offsets[i + 1] = len(flat)
        return ArrayBlock(offsets, block_from_values(typ.element, flat),
                          nulls if has_null else None)

    if isinstance(typ, DoubleType):
        dtype = np.float64
    elif isinstance(typ, RealType):
        dtype = np.float32
    elif isinstance(typ, BooleanType):
        dtype = np.int8
    else:
        dtype = typ.np_dtype
    arr = np.zeros(n, dtype=dtype)
    for i, v in enumerate(values):
        if v is not None:
            arr[i] = v
    if isinstance(typ, RealType):
        # REAL stores float bits in an INT_ARRAY on the wire
        arr = arr.view(np.int32) if arr.dtype == np.float32 else arr
    return FixedWidthBlock(arr, nulls if has_null else None)


def block_to_values(typ: Type, block: Block) -> list:
    """Decode a block to python values under `typ` semantics."""
    block = decode_to_flat(block)
    if isinstance(typ, ArrayType) and isinstance(block, ArrayBlock):
        elems = block_to_values(typ.element, block.elements)
        out = []
        for i in range(block.position_count):
            if block.nulls is not None and block.nulls[i]:
                out.append(None)
            else:
                out.append(elems[block.offsets[i]:block.offsets[i + 1]])
        return out
    if isinstance(typ, (VarcharType, CharType)):
        return block.to_pylist()
    if isinstance(typ, VarbinaryType):
        return [
            None if (block.nulls is not None and block.nulls[i])
            else block.slice_at(i)
            for i in range(block.position_count)
        ]
    if isinstance(typ, DoubleType):
        vals = block.values.view(np.float64) if block.values.dtype != np.float64 else block.values
        return [None if n else float(v)
                for v, n in zip(vals, block.null_mask())]
    if isinstance(typ, RealType):
        vals = block.values.view(np.float32) if block.values.dtype != np.float32 else block.values
        return [None if n else float(v)
                for v, n in zip(vals, block.null_mask())]
    if isinstance(typ, BooleanType):
        return [None if n else bool(v)
                for v, n in zip(block.values, block.null_mask())]
    if isinstance(typ, DateType):
        return [None if n else str(np.datetime64(int(v), "D"))
                for v, n in zip(block.values, block.null_mask())]
    if isinstance(typ, DecimalType):
        raw = block.to_pylist()  # Int128Block.to_pylist handles sign-magnitude
        from decimal import Decimal
        q = Decimal(1).scaleb(-typ.scale)
        return [None if v is None else (Decimal(v) * q) for v in raw]
    return block.to_pylist()
