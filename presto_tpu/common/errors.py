"""Typed error classification shared by the local batch scheduler and the
distributed HTTP runtime.

The analog of presto-spark-base's ErrorClassifier.java (which decides
whether a Spark executor loss / task failure may retry) and of the
reference coordinator's remote-task error budget + error-type taxonomy
(ErrorType.java: USER_ERROR | INTERNAL_ERROR | INSUFFICIENT_RESOURCES |
EXTERNAL, carried in ExecutionFailureInfo.errorCode).  One place decides
which failures are RETRYABLE (transport loss, worker death, 503 refusal,
oom-kill, injected chaos) and which are the user's (bad SQL, bad session
property) and must fail fast with no retry attempt.

Worker tasks tag their failure messages with ``[ERROR_TYPE]`` so
classification survives the string-typed failure chain: a producer's
USER_ERROR propagated through a consumer's exchange pull stays
non-retryable at the coordinator.
"""
from __future__ import annotations

import re
from typing import Optional

# reference ErrorType.java values (also the thrift ERROR_TYPE enum)
USER_ERROR = "USER_ERROR"
INTERNAL_ERROR = "INTERNAL_ERROR"
INSUFFICIENT_RESOURCES = "INSUFFICIENT_RESOURCES"
EXTERNAL = "EXTERNAL"
# INTERNAL_ERROR subcategory for plan-validation failures (the analog of
# the reference's PLAN_VALIDATION error-code names raised by
# sql/planner/sanity): the plan itself is malformed, so unlike a lost
# executor the same failure reproduces on every attempt — never retried.
PLAN_VALIDATION = "PLAN_VALIDATION"

# USER_ERROR never retries; everything infrastructure-shaped may.
# INTERNAL_ERROR stays retryable like the batch scheduler's executor-loss
# path (presto-spark re-runs lost tasks from durable inputs); an engine
# bug then fails after the attempt budget instead of masquerading as
# permanently transient.  PLAN_VALIDATION is the deterministic exception:
# replanning the same query yields the same malformed plan.
RETRYABLE_TYPES = {INTERNAL_ERROR, INSUFFICIENT_RESOURCES, EXTERNAL}

_TYPE_TAG = re.compile(r"\[(USER_ERROR|INTERNAL_ERROR|"
                       r"INSUFFICIENT_RESOURCES|EXTERNAL|PLAN_VALIDATION)\]")
# producer buffer locations embedded in failure text:
# http://host:port/v1/task/{taskId}/results/{bufferId}
_LOCATION_TASK = re.compile(r"/v1/task/([^/\s]+)/results/")


class PrestoQueryError(RuntimeError):
    """Base typed query error; subclasses pin the reference error type."""
    error_type = INTERNAL_ERROR


class PrestoUserError(PrestoQueryError):
    """The query (or its session) is wrong; retrying cannot help."""
    error_type = USER_ERROR


class PlanValidationError(PrestoQueryError):
    """A plan failed a sanity/type check (presto_tpu/analysis).  Message
    carries the ``[PLAN_VALIDATION]`` tag so non-retryability survives the
    string-typed failure chain across task boundaries."""
    error_type = PLAN_VALIDATION

    def __init__(self, message: str, diagnostics=None):
        super().__init__(f"[{PLAN_VALIDATION}] {message}")
        self.diagnostics = list(diagnostics or [])


class InjectedTaskFailure(PrestoQueryError):
    """Chaos-injected task failure (retryable, like an executor loss)."""
    error_type = INTERNAL_ERROR


class WorkerLostError(PrestoQueryError):
    """A worker stopped answering (process death / network partition)."""
    error_type = EXTERNAL

    def __init__(self, worker_uri: str, message: str = ""):
        super().__init__(message or f"worker {worker_uri} lost")
        self.worker_uri = worker_uri


class TaskLostError(PrestoQueryError):
    """A task the coordinator created is gone (404: the worker restarted
    and lost its registry) — reschedule, don't surface KeyError."""
    error_type = EXTERNAL

    def __init__(self, task_id: str, worker_uri: str = ""):
        super().__init__(f"task {task_id} lost"
                         + (f" (worker {worker_uri})" if worker_uri else ""))
        self.task_id = task_id
        self.worker_uri = worker_uri


class ExchangeLostError(PrestoQueryError):
    """An exchange source stayed unreachable past the error budget (or its
    task vanished mid-stream).  Carries the producer location so the
    coordinator can map the loss back to the producing task and retry it
    instead of failing the query (reference exchange.max-error-duration)."""
    error_type = EXTERNAL

    def __init__(self, location: str, last_token: int = 0,
                 message: str = ""):
        super().__init__(
            message or f"exchange source {location} lost "
                       f"(last delivered token {last_token})")
        self.location = location
        self.last_token = last_token


class QueryDeadlineExceededError(PrestoUserError):
    """`query.max-execution-time` elapsed (reference EXCEEDED_TIME_LIMIT,
    QueryTracker.enforceTimeLimits): the query ran past its configured
    wall budget.  A deadline is the user's constraint, so this fails fast
    — the [USER_ERROR] tag and `error_type` keep it non-retryable across
    the string-typed distributed failure chain, exactly like the memory
    limit's EXCEEDED_MEMORY_LIMIT."""

    error_code = "EXCEEDED_TIME_LIMIT"

    def __init__(self, elapsed_s: float, limit_s: float, context: str = ""):
        super().__init__(
            f"[USER_ERROR] EXCEEDED_TIME_LIMIT: query exceeded "
            f"query.max-execution-time {limit_s:g}s "
            f"(ran {elapsed_s:.3f}s)"
            + (f" (context {context})" if context else ""))
        self.elapsed_s = elapsed_s
        self.limit_s = limit_s


class PoisonSplitError(PrestoUserError):
    """A split whose task failed with the SAME internal error signature on
    two distinct workers is deterministic, not infrastructure: burning the
    rest of the retry budget would reproduce it (the presto-spark
    ErrorClassifier's 'consistent failure' fast-fail).  Quarantine the
    split and fail the query with its identity in the tag."""

    error_code = "POISON_SPLIT"

    def __init__(self, lineage: str, workers, signature: str = ""):
        ws = ", ".join(sorted(workers))
        super().__init__(
            f"[USER_ERROR] POISON_SPLIT: task {lineage} quarantined after "
            f"failing with the same internal error on {len(set(workers))} "
            f"distinct workers ({ws})"
            + (f": {signature}" if signature else ""))
        self.lineage = lineage
        self.workers = set(workers)


class RemoteTaskError(PrestoQueryError):
    """A producer task reported failure through its buffer (HTTP 500 on a
    results pull).  The error type is parsed from the producer's tagged
    message so non-retryability propagates across task chains."""

    def __init__(self, location: str, detail: str):
        super().__init__(f"exchange source {location} failed: {detail}")
        self.location = location
        self.error_type = parse_error_type(detail, INTERNAL_ERROR)


def parse_error_type(text: str, default: str = INTERNAL_ERROR) -> str:
    """First ``[ERROR_TYPE]`` tag embedded in a failure message."""
    m = _TYPE_TAG.search(text or "")
    return m.group(1) if m else default


def producer_task_from_text(text: str) -> Optional[str]:
    """Task id of a producer buffer location mentioned in failure text
    (.../v1/task/{taskId}/results/...), for mapping an exchange loss back
    to the producing task."""
    m = _LOCATION_TASK.search(text or "")
    return m.group(1) if m else None


# exceptions that mean the QUERY is wrong, not the cluster
_USER_EXC = (ValueError, TypeError, KeyError, NotImplementedError,
             ZeroDivisionError)


def classify_exception(exc: BaseException) -> str:
    """Exception -> reference error type.  Typed errors carry their own;
    untyped ones classify by shape, with FileNotFoundError (missing user
    data) split off from the transport OSErrors."""
    et = getattr(exc, "error_type", None)
    if isinstance(et, str) and et:
        return et
    import urllib.error
    if isinstance(exc, urllib.error.HTTPError):
        return EXTERNAL if exc.code in (408, 429, 500, 502, 503, 504) \
            else USER_ERROR
    if type(exc).__name__ == "MemoryExceededError" \
            or isinstance(exc, MemoryError):
        return INSUFFICIENT_RESOURCES
    if isinstance(exc, (FileNotFoundError, IsADirectoryError)):
        return USER_ERROR
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return EXTERNAL
    if isinstance(exc, _USER_EXC):
        return USER_ERROR
    return parse_error_type(str(exc), INTERNAL_ERROR)


def is_retryable(exc: BaseException) -> bool:
    return classify_exception(exc) in RETRYABLE_TYPES


def is_retryable_type(error_type: str) -> bool:
    return (error_type or INTERNAL_ERROR) in RETRYABLE_TYPES
