"""Page compression codecs, byte-interoperable with the reference's
``PagesSerdeFactory`` codec set (PagesSerdeFactory.java:69-108):

    GZIP | LZ4 | LZO | SNAPPY | ZLIB | ZSTD | NONE

The reference compresses page bodies with airlift *aircompressor* codecs,
which use the raw container-less encodings: LZ4 block format (not LZ4
frame), raw Snappy block format, standard zstd frames, and RFC-1950/1952
for ZLIB/GZIP.  pyarrow's bundled codecs emit the same encodings
(``lz4_raw``/``snappy``/``zstd``), so bytes produced here decode on the
Java side and vice versa.  LZO has no system codec available and is the
one codec we do not support (it is effectively dead in the reference too).

The codec is cluster configuration, not wire metadata: the SerializedPage
header only carries the COMPRESSED marker bit (PageCodecMarker.java:27),
so serializer and deserializer must agree on the codec out of band exactly
like the reference's ``exchange.compression-codec`` config.
"""
from __future__ import annotations

import zlib
from typing import Callable, Dict, Tuple

try:
    import pyarrow as _pa
except Exception:  # pragma: no cover - pyarrow is baked into the image
    _pa = None


def _pa_compress(codec: str) -> Callable[[bytes], bytes]:
    def compress(data: bytes) -> bytes:
        return bytes(_pa.compress(data, codec=codec, asbytes=True))
    return compress


def _pa_decompress(codec: str) -> Callable[[bytes, int], bytes]:
    def decompress(data: bytes, uncompressed_size: int) -> bytes:
        return bytes(_pa.decompress(data, decompressed_size=uncompressed_size,
                                    codec=codec, asbytes=True))
    return decompress


# --- pure-python LZ4 block codec -------------------------------------------
# Fallback when pyarrow is unavailable; the decoder doubles as an
# independent spec check in tests (it shares no code with pyarrow's C LZ4).

def lz4_block_decompress(data: bytes, uncompressed_size: int) -> bytes:
    """Decode one raw LZ4 block (lz4 block format spec 1.5.1)."""
    src = memoryview(data)
    out = bytearray()
    i, n = 0, len(src)
    while i < n:
        token = src[i]
        i += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                b = src[i]
                i += 1
                lit_len += b
                if b != 255:
                    break
        out += bytes(src[i:i + lit_len])
        i += lit_len
        if i >= n:  # last sequence has no match part
            break
        offset = src[i] | (src[i + 1] << 8)
        i += 2
        if offset == 0:
            raise ValueError("corrupt LZ4 block: zero match offset")
        match_len = (token & 0xF) + 4
        if (token & 0xF) == 15:
            while True:
                b = src[i]
                i += 1
                match_len += b
                if b != 255:
                    break
        start = len(out) - offset
        if start < 0:
            raise ValueError("corrupt LZ4 block: offset before start")
        for _ in range(match_len):  # byte-wise: matches may overlap forward
            out.append(out[start])
            start += 1
    if len(out) != uncompressed_size:
        raise ValueError(
            f"LZ4 decompressed {len(out)} bytes, expected {uncompressed_size}")
    return bytes(out)


def _lz4_literal_compress(data: bytes) -> bytes:
    """Literals-only LZ4 block (always valid, never smaller than input).

    One literal run covers the whole input — the LZ4 literal length
    extends indefinitely via 255-continuation bytes, and only the FINAL
    sequence of a block may omit the match part, so a single sequence is
    the only spec-valid literal-only form.  Only used when pyarrow is
    absent; the serde's ratio gate then keeps pages uncompressed.
    """
    n = len(data)
    out = bytearray()
    if n >= 15:
        out.append(0xF0)
        rest = n - 15
        while rest >= 255:
            out.append(255)
            rest -= 255
        out.append(rest)
    else:
        out.append(n << 4)
    out += data
    return bytes(out)


def _zlib_compress(data: bytes) -> bytes:
    return zlib.compress(data, 4)


def _zlib_decompress(data: bytes, uncompressed_size: int) -> bytes:
    return zlib.decompress(data)


def _gzip_compress(data: bytes) -> bytes:
    co = zlib.compressobj(4, zlib.DEFLATED, 16 + zlib.MAX_WBITS)
    return co.compress(data) + co.flush()


def _gzip_decompress(data: bytes, uncompressed_size: int) -> bytes:
    return zlib.decompress(data, 16 + zlib.MAX_WBITS)


_CODECS: Dict[str, Tuple[Callable[[bytes], bytes],
                         Callable[[bytes, int], bytes]]] = {
    "ZLIB": (_zlib_compress, _zlib_decompress),
    "GZIP": (_gzip_compress, _gzip_decompress),
}

if _pa is not None:
    _CODECS["LZ4"] = (_pa_compress("lz4_raw"), _pa_decompress("lz4_raw"))
    _CODECS["SNAPPY"] = (_pa_compress("snappy"), _pa_decompress("snappy"))
    _CODECS["ZSTD"] = (_pa_compress("zstd"), _pa_decompress("zstd"))
else:  # pragma: no cover
    _CODECS["LZ4"] = (_lz4_literal_compress, lz4_block_decompress)


def supported_codecs():
    return sorted(_CODECS) + ["NONE"]


def compress(codec: str, data: bytes) -> bytes:
    return _CODECS[codec.upper()][0](data)


def decompress(codec: str, data: bytes, uncompressed_size: int) -> bytes:
    return _CODECS[codec.upper()][1](data, uncompressed_size)
