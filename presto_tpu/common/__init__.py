from .types import (  # noqa: F401
    BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, SMALLINT, TIMESTAMP,
    TINYINT, UNKNOWN, VARBINARY, VARCHAR, ArrayType, BigintType, BooleanType,
    CharType, DateType, DecimalType, DoubleType, IntegerType, MapType,
    RealType, RowType, SmallintType, TimestampType, TinyintType, Type,
    UnknownType, VarbinaryType, VarcharType, parse_type,
)
from .block import (  # noqa: F401
    ArrayBlock, Block, DictionaryBlock, FixedWidthBlock, Int128Block,
    RowBlock, RunLengthBlock, VariableWidthBlock, block_from_values,
    block_to_values, byte_array_block, decode_to_flat, double_block,
    int_array_block, long_array_block, short_array_block,
)
from .page import Page, concat_pages  # noqa: F401
from .serde import (  # noqa: F401
    deserialize_page, deserialize_pages, serialize_page, serialize_pages,
)
