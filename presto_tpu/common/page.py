"""Page: a batch of positions across columns (reference presto-common/.../Page.java:45)."""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .block import Block


class Page:
    def __init__(self, blocks: List[Block], position_count: int = None):
        if position_count is None:
            if not blocks:
                raise ValueError("position_count required for zero-channel page")
            position_count = blocks[0].position_count
        for b in blocks:
            if b.position_count != position_count:
                raise ValueError(
                    f"block has {b.position_count} positions, expected {position_count}")
        self.blocks = blocks
        self.position_count = position_count

    @property
    def channel_count(self) -> int:
        return len(self.blocks)

    def block(self, channel: int) -> Block:
        return self.blocks[channel]

    def take(self, positions: np.ndarray) -> "Page":
        positions = np.asarray(positions)
        return Page([b.take(positions) for b in self.blocks], len(positions))

    def region(self, offset: int, length: int) -> "Page":
        return self.take(np.arange(offset, offset + length))

    def append_column(self, block: Block) -> "Page":
        return Page(self.blocks + [block], self.position_count)

    def __repr__(self):
        return f"Page({self.position_count} x {self.channel_count})"


def concat_pages(pages: Sequence[Page]) -> Page:
    """Concatenate pages with identical channel layouts (materializes)."""
    pages = [p for p in pages if p.position_count > 0]
    if not pages:
        raise ValueError("no non-empty pages")
    if len(pages) == 1:
        return pages[0]
    from .block import (FixedWidthBlock, VariableWidthBlock, decode_to_flat)
    n_channels = pages[0].channel_count
    out = []
    total = sum(p.position_count for p in pages)
    for c in range(n_channels):
        blocks = [decode_to_flat(p.block(c)) for p in pages]
        first = blocks[0]
        nulls = None
        if any(b.nulls is not None for b in blocks):
            nulls = np.concatenate([b.null_mask() for b in blocks])
        if isinstance(first, FixedWidthBlock):
            out.append(FixedWidthBlock(
                np.concatenate([b.values for b in blocks]), nulls))
        elif isinstance(first, VariableWidthBlock):
            # Slice each block's referenced byte range; offsets may not start
            # at zero and data may have unreferenced tails.
            datas = [b.data[b.offsets[0]:b.offsets[-1]] for b in blocks]
            offs = np.zeros(total + 1, dtype=np.int64)
            lens = np.concatenate(
                [(b.offsets[1:] - b.offsets[:-1]) for b in blocks])
            np.cumsum(lens, out=offs[1:])
            out.append(VariableWidthBlock(
                offs.astype(np.int32), np.concatenate(datas), nulls))
        else:
            raise NotImplementedError(
                f"concat of {type(first).__name__} not supported")
    return Page(out, total)
