"""Presto type system, host side.

Re-implements the semantics of the reference type system
(presto-common/src/main/java/com/facebook/presto/common/type/, 84 files) for the
subset of types reachable from the TPC-H / TPC-DS vocabulary, plus the structural
types needed for nested data.  Each type knows its storage class (which Block kind
holds its values, mirroring Type.getBlockBuilder in the reference) and its device
representation (the numpy/JAX dtype used by the TPU execution engine).

Storage-class mapping (same as the reference):
  BOOLEAN, TINYINT          -> BYTE_ARRAY   (int8)
  SMALLINT                  -> SHORT_ARRAY  (int16)
  INTEGER, DATE, REAL       -> INT_ARRAY    (int32; REAL stores float bits)
  BIGINT, DOUBLE, TIMESTAMP,
  short DECIMAL(p<=18)      -> LONG_ARRAY   (int64; DOUBLE stores float bits,
                                             short decimal stores unscaled value)
  long DECIMAL(p>18)        -> INT128_ARRAY
  VARCHAR, CHAR, VARBINARY  -> VARIABLE_WIDTH
  ARRAY / MAP / ROW         -> nested blocks
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

# Wire/storage classes (match the BlockEncoding NAME constants in the reference,
# presto-common/.../block/*BlockEncoding.java)
BYTE_ARRAY = "BYTE_ARRAY"
SHORT_ARRAY = "SHORT_ARRAY"
INT_ARRAY = "INT_ARRAY"
LONG_ARRAY = "LONG_ARRAY"
INT128_ARRAY = "INT128_ARRAY"
VARIABLE_WIDTH = "VARIABLE_WIDTH"
ARRAY = "ARRAY"
MAP = "MAP"
ROW = "ROW"

_STORAGE_NP_DTYPE = {
    BYTE_ARRAY: np.int8,
    SHORT_ARRAY: np.int16,
    INT_ARRAY: np.int32,
    LONG_ARRAY: np.int64,
}


@dataclass(frozen=True)
class Type:
    """Base class for all Presto types.  `signature` round-trips through the
    TypeParser below (reference: TypeSignature.java / TypeParser in presto_cpp)."""

    @property
    def signature(self) -> str:
        raise NotImplementedError

    # Which block kind stores values of this type.
    @property
    def storage(self) -> str:
        raise NotImplementedError

    @property
    def fixed_width(self) -> bool:
        return self.storage in _STORAGE_NP_DTYPE or self.storage == INT128_ARRAY

    @property
    def np_dtype(self):
        """dtype of the *storage* array (bit pattern on the wire)."""
        if self.storage in _STORAGE_NP_DTYPE:
            return np.dtype(_STORAGE_NP_DTYPE[self.storage])
        raise TypeError(f"{self.signature} has no fixed-width numpy dtype")

    @property
    def value_dtype(self):
        """dtype of the *logical* value array used on device (e.g. float64 for
        DOUBLE even though the wire stores raw int64 bits)."""
        return self.np_dtype

    def __str__(self) -> str:
        return self.signature


@dataclass(frozen=True)
class BooleanType(Type):
    @property
    def signature(self):
        return "boolean"

    @property
    def storage(self):
        return BYTE_ARRAY

    @property
    def value_dtype(self):
        return np.dtype(np.bool_)


@dataclass(frozen=True)
class TinyintType(Type):
    @property
    def signature(self):
        return "tinyint"

    @property
    def storage(self):
        return BYTE_ARRAY


@dataclass(frozen=True)
class SmallintType(Type):
    @property
    def signature(self):
        return "smallint"

    @property
    def storage(self):
        return SHORT_ARRAY


@dataclass(frozen=True)
class IntegerType(Type):
    @property
    def signature(self):
        return "integer"

    @property
    def storage(self):
        return INT_ARRAY


@dataclass(frozen=True)
class BigintType(Type):
    @property
    def signature(self):
        return "bigint"

    @property
    def storage(self):
        return LONG_ARRAY


@dataclass(frozen=True)
class RealType(Type):
    @property
    def signature(self):
        return "real"

    @property
    def storage(self):
        return INT_ARRAY

    @property
    def value_dtype(self):
        return np.dtype(np.float32)


@dataclass(frozen=True)
class DoubleType(Type):
    @property
    def signature(self):
        return "double"

    @property
    def storage(self):
        return LONG_ARRAY

    @property
    def value_dtype(self):
        return np.dtype(np.float64)


@dataclass(frozen=True)
class DateType(Type):
    """Days since 1970-01-01, stored int32 (reference DateType.java)."""

    @property
    def signature(self):
        return "date"

    @property
    def storage(self):
        return INT_ARRAY


@dataclass(frozen=True)
class TimestampType(Type):
    """Milliseconds since epoch, stored int64 (reference TimestampType.java)."""

    @property
    def signature(self):
        return "timestamp"

    @property
    def storage(self):
        return LONG_ARRAY


@dataclass(frozen=True)
class DecimalType(Type):
    """DECIMAL(precision, scale); unscaled integer storage.  p<=18 is a "short"
    decimal in int64, larger is an int128 pair (reference DecimalType.java)."""

    precision: int = 38
    scale: int = 0

    @property
    def signature(self):
        return f"decimal({self.precision},{self.scale})"

    @property
    def is_short(self):
        return self.precision <= 18

    @property
    def storage(self):
        return LONG_ARRAY if self.is_short else INT128_ARRAY


@dataclass(frozen=True)
class VarcharType(Type):
    # length is a bound, not storage: unbounded signified by None
    length: Optional[int] = None

    @property
    def signature(self):
        if self.length is None:
            return "varchar"
        return f"varchar({self.length})"

    @property
    def storage(self):
        return VARIABLE_WIDTH


@dataclass(frozen=True)
class CharType(Type):
    length: int = 1

    @property
    def signature(self):
        return f"char({self.length})"

    @property
    def storage(self):
        return VARIABLE_WIDTH


@dataclass(frozen=True)
class VarbinaryType(Type):
    @property
    def signature(self):
        return "varbinary"

    @property
    def storage(self):
        return VARIABLE_WIDTH


@dataclass(frozen=True)
class UnknownType(Type):
    """Type of NULL literals (reference UnknownType.java); storage byte."""

    @property
    def signature(self):
        return "unknown"

    @property
    def storage(self):
        return BYTE_ARRAY


@dataclass(frozen=True)
class ArrayType(Type):
    element: Type = field(default_factory=lambda: UNKNOWN)

    @property
    def signature(self):
        return f"array({self.element.signature})"

    @property
    def storage(self):
        return ARRAY


@dataclass(frozen=True)
class MapType(Type):
    key: Type = field(default_factory=lambda: UNKNOWN)
    value: Type = field(default_factory=lambda: UNKNOWN)

    @property
    def signature(self):
        return f"map({self.key.signature},{self.value.signature})"

    @property
    def storage(self):
        return MAP


@dataclass(frozen=True)
class RowType(Type):
    names: Tuple[Optional[str], ...] = ()
    types: Tuple[Type, ...] = ()

    @property
    def signature(self):
        parts = []
        for name, typ in zip(self.names, self.types):
            if name:
                parts.append(f"{name} {typ.signature}")
            else:
                parts.append(typ.signature)
        return f"row({','.join(parts)})"

    @property
    def storage(self):
        return ROW


# Singletons
BOOLEAN = BooleanType()
TINYINT = TinyintType()
SMALLINT = SmallintType()
INTEGER = IntegerType()
BIGINT = BigintType()
REAL = RealType()
DOUBLE = DoubleType()
DATE = DateType()
TIMESTAMP = TimestampType()
VARCHAR = VarcharType()
VARBINARY = VarbinaryType()
UNKNOWN = UnknownType()

_SIMPLE = {
    "boolean": BOOLEAN,
    "tinyint": TINYINT,
    "smallint": SMALLINT,
    "integer": INTEGER,
    "int": INTEGER,
    "bigint": BIGINT,
    "real": REAL,
    "double": DOUBLE,
    "date": DATE,
    "timestamp": TIMESTAMP,
    "varchar": VARCHAR,
    "varbinary": VARBINARY,
    "unknown": UNKNOWN,
}

_PAREN_RE = re.compile(r"^(\w+)\((.*)\)$")


def _split_top_level(s: str) -> list:
    parts, depth, start = [], 0, 0
    for i, c in enumerate(s):
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        elif c == "," and depth == 0:
            parts.append(s[start:i].strip())
            start = i + 1
    if s[start:].strip():
        parts.append(s[start:].strip())
    return parts


def parse_type(sig: str) -> Type:
    """Parse a type signature string (reference: presto_cpp/main/types/TypeParser)."""
    s = sig.strip()
    low = s.lower()
    if low in _SIMPLE:
        return _SIMPLE[low]
    m = _PAREN_RE.match(s)
    if not m:
        raise ValueError(f"cannot parse type signature: {sig!r}")
    base, args = m.group(1).lower(), m.group(2)
    if base == "decimal":
        p, sc = [int(x) for x in _split_top_level(args)]
        return DecimalType(p, sc)
    if base == "varchar":
        return VarcharType(int(args))
    if base == "char":
        return CharType(int(args))
    if base == "array":
        return ArrayType(parse_type(args))
    if base == "map":
        k, v = _split_top_level(args)
        return MapType(parse_type(k), parse_type(v))
    if base == "row":
        names, types = [], []
        for part in _split_top_level(args):
            tokens = part.split(None, 1)
            # "name type" when the remainder parses as a type on its own;
            # handles field names that collide with type keywords (row(date date)).
            parsed = None
            if len(tokens) == 2 and "(" not in tokens[0]:
                try:
                    parsed = parse_type(tokens[1])
                except ValueError:
                    parsed = None
            if parsed is not None:
                names.append(tokens[0].strip('"'))
                types.append(parsed)
            else:
                names.append(None)
                types.append(parse_type(part))
        return RowType(tuple(names), tuple(types))
    raise ValueError(f"cannot parse type signature: {sig!r}")
