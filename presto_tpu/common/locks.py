"""Rank-ordered lock wrappers with a dev-mode validation harness.

The worker has grown six thread families (exchange pullers, spill
staging, telemetry flush, the heartbeat failure detector, the task
reaper, spool flush callbacks) whose locks nest: an arbitration pass
walks revoke callbacks into buffer conditions into the memory pool; a
task eviction walks the task-manager lock into buffer destruction.
The classic way such a graph deadlocks is an UNDECLARED edge — two
subsystems each correct in isolation, acquired in opposite orders by
two threads.

`OrderedLock` / `OrderedCondition` make the order DECLARED: every lock
carries a rank, and the process-wide rank map (documented in the
README's static-analysis section) is the one sanctioned acquisition
order — a thread may only acquire ranks strictly greater than any it
already holds.  The discipline is free in production: when validation
is off, acquire/release delegate straight to the underlying primitive.
Under `debug.lock-validation=on` (worker property, or the
`lock_validation` session override) every acquisition is checked
against the calling thread's held stack, a rank inversion raises a
typed `LockOrderError` at the exact acquisition site (instead of a
silent deadlock hours later), and hold time / contention are metered
into `LOCK_METRICS` — surfaced at /v1/metrics as `presto_tpu_lock_*`
so a chaos run doubles as a lock-discipline check.

The static half lives in `analysis/concurrency.py`: LOCK004 extracts
the nested-`with` lock-order graph from source and fails CI on a cycle
or a rank-inverting edge, so most inversions never reach runtime.

Rank map (gaps left for future subsystems; reentrant locks noted):

    10  dispatch-manager        worker/statement.py DispatchManager
    12  resource-groups         worker/statement.py ResourceGroupManager
    14  task-manager            worker/task.py      TaskManager
    16  task-state              worker/task.py      TpuTask (condition)
    18  exchange-client         worker/exchange.py  ExchangeClient (cond)
    20  memory-arbitrator       exec/memory.py      MemoryPool._arb_lock
    30  output-buffer           worker/buffers.py   PageBuffer (condition)
    32  task-spool              worker/spooling.py  TaskSpool (reentrant)
    40  memory-pool             exec/memory.py      MemoryPool (reentrant)
    50  serving-cache           serving/cache.py    PlanCache
    60  query-history           telemetry/history.py QueryHistoryStore
    70  telemetry-exporter      telemetry/export.py TelemetryExporter
    72  telemetry-idle          telemetry/export.py TelemetryExporter._idle
    74  telemetry-sink          telemetry/export.py Collector/Jsonl sinks
    80  failure-detector        worker/coordinator.py HeartbeatFailureDetector
    82  status-watcher          worker/coordinator.py _StatusWatcher
    100 metrics-registry        every process-wide metrics singleton (leaf)

`LOCK_METRICS` itself uses a raw `threading.Lock` and is never wrapped:
the meter must not recurse into itself.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

__all__ = [
    "LockOrderError", "OrderedLock", "OrderedCondition", "LOCK_METRICS",
    "LockMetrics", "set_validation", "validation_enabled",
    "validation_scope",
]


class LockOrderError(RuntimeError):
    """A thread acquired a lower- or equal-ranked lock while holding a
    higher one: the declared acquisition order was inverted.  Raised at
    the acquisition site (under debug.lock-validation=on) instead of
    letting the inversion mature into a silent cross-thread deadlock.
    Classified INTERNAL_ERROR by common/errors.py — a lock inversion is
    a worker bug, never the user's query."""

    error_type = "INTERNAL_ERROR"
    error_code = "LOCK_ORDER_VIOLATION"

    def __init__(self, acquiring: "OrderedLock", holding: "OrderedLock"):
        super().__init__(
            f"[INTERNAL_ERROR] LOCK_ORDER_VIOLATION: acquiring "
            f"'{acquiring.name}' (rank {acquiring.rank}) while holding "
            f"'{holding.name}' (rank {holding.rank}); ranks must be "
            f"strictly increasing along any acquisition chain")
        self.acquiring = acquiring.name
        self.holding = holding.name


class LockMetrics:
    """Process-wide lock validation counters (the /v1/metrics
    presto_tpu_lock_* section, same singleton shape as SpoolMetrics).
    Raw threading.Lock on purpose: the meter is below every rank and
    must never recurse into the ordered-lock machinery it measures."""

    _COUNTERS = ("acquisitions", "contended", "contention_wall_s",
                 "hold_wall_s", "violations")
    _GAUGES = ()

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:  # lint: guarded-by(_lock)
            for name in self._COUNTERS + self._GAUGES:
                setattr(self, name, 0)

    def incr(self, name: str, delta=1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + delta)

    def snapshot(self) -> dict:
        with self._lock:
            return {name: getattr(self, name)
                    for name in self._COUNTERS + self._GAUGES}


LOCK_METRICS = LockMetrics()


# ---------------------------------------------------------------------------
# validation switch: a process-global base flag (worker property) plus a
# COUNTING scope overlay (session override) so concurrent tasks compose —
# the flag is process-global rather than thread-local because the locks
# it validates are shared across threads.
# ---------------------------------------------------------------------------

_STATE_LOCK = threading.Lock()
_BASE_ON = False
_SCOPES = 0
# Derived fast-path flag; reads are racy-but-atomic by design: a toggle
# concurrent with an acquisition may miss validating that one acquisition,
# which is fine for a dev-mode tripwire.
_ENABLED = False


def _recompute_locked() -> None:
    global _ENABLED
    _ENABLED = _BASE_ON or _SCOPES > 0


def set_validation(on: bool) -> None:
    """Set the process base flag (the `debug.lock-validation` worker
    property).  Scoped session overrides stack on top of it."""
    global _BASE_ON
    with _STATE_LOCK:
        _BASE_ON = bool(on)
        _recompute_locked()


def validation_enabled() -> bool:
    return _ENABLED


class _ValidationScope:
    """Counting context manager: validation stays on while ANY scope is
    live, so two concurrent tasks with the session override don't turn
    each other's checking off on exit."""

    def __enter__(self):
        global _SCOPES
        with _STATE_LOCK:
            _SCOPES += 1
            _recompute_locked()
        return self

    def __exit__(self, *exc):
        global _SCOPES
        with _STATE_LOCK:
            _SCOPES = max(0, _SCOPES - 1)
            _recompute_locked()
        return False


def validation_scope() -> _ValidationScope:
    """Session-scoped enable (the `lock_validation` session property):
    `with validation_scope(): ...` validates for the duration."""
    return _ValidationScope()


# per-thread stack of (lock, t_acquired) in acquisition order
_TLS = threading.local()


def _held() -> List[Tuple["OrderedLock", float]]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


class OrderedLock:
    """A named, ranked mutex.

    Pass-through when validation is off: `acquire`/`release` delegate
    straight to the wrapped `threading.Lock` (or `RLock` when
    `reentrant=True`) with no bookkeeping.  Under validation each
    acquisition is checked against the calling thread's held stack —
    acquiring rank r while holding rank >= r raises `LockOrderError`
    (reentrant re-acquisition of the SAME lock is exempt) — and
    contention + hold walls are metered into LOCK_METRICS.

    Implements the `_is_owned` / `_release_save` / `_acquire_restore`
    protocol so `OrderedCondition` (and `threading.Condition`) can wrap
    it directly.
    """

    def __init__(self, name: str, rank: int, reentrant: bool = False):
        self.name = name
        self.rank = int(rank)
        self.reentrant = bool(reentrant)
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def __repr__(self) -> str:
        return f"OrderedLock({self.name!r}, rank={self.rank})"

    # -- validation bookkeeping --------------------------------------------
    def _check_order_and_mark(self) -> None:
        """Rank check BEFORE touching the underlying lock, so a raise
        leaves no state behind."""
        stack = _held()
        if any(entry[0] is self for entry in stack):
            if self.reentrant:
                return          # same-lock re-acquisition: always legal
            LOCK_METRICS.incr("violations")
            raise LockOrderError(self, self)
        if stack:
            top = max(stack, key=lambda e: e[0].rank)[0]
            if top.rank >= self.rank:
                LOCK_METRICS.incr("violations")
                raise LockOrderError(self, top)

    def _push(self) -> None:
        _held().append((self, time.perf_counter()))

    def _pop(self) -> Optional[float]:
        """Pop this lock's most recent stack entry; None if absent
        (acquired while validation was off)."""
        stack = getattr(_TLS, "stack", None)
        if not stack:
            return None
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                return stack.pop(i)[1]
        return None

    # -- lock protocol -------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _ENABLED:
            return self._lock.acquire(blocking, timeout)
        self._check_order_and_mark()
        got = self._lock.acquire(False)
        if not got:
            if not blocking:
                return False
            LOCK_METRICS.incr("contended")
            t0 = time.perf_counter()
            got = self._lock.acquire(True, timeout)
            LOCK_METRICS.incr("contention_wall_s",
                              time.perf_counter() - t0)
            if not got:
                return False
        LOCK_METRICS.incr("acquisitions")
        self._push()
        return True

    def release(self) -> None:
        # Always reconcile the held stack (a leaked entry from an
        # acquire made while validation was on must not pin the stack
        # after a mid-flight toggle); the scan is bounded by held-lock
        # depth, which is single digits.
        t0 = self._pop()
        if t0 is not None:
            LOCK_METRICS.incr("hold_wall_s", time.perf_counter() - t0)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        inner = getattr(self._lock, "locked", None)
        if inner is not None:
            return bool(inner())
        # RLock grows .locked() only in 3.14; _is_owned covers the
        # common "am I inside my own with-block" probe before that
        owned = getattr(self._lock, "_is_owned", None)
        return bool(owned()) if owned is not None else False

    # -- condition-variable protocol (threading.Condition delegation) -------
    def _is_owned(self) -> bool:
        inner = getattr(self._lock, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _release_save(self):
        # A wait() releases the lock: drop the held-stack entry so locks
        # taken while waiting are checked against the true held set.
        t0 = self._pop()
        if t0 is not None:
            LOCK_METRICS.incr("hold_wall_s", time.perf_counter() - t0)
        inner = getattr(self._lock, "_release_save", None)
        if inner is not None:
            return inner()
        self._lock.release()
        return None

    def _acquire_restore(self, state) -> None:
        # Re-entry after a wait(): the rank was already validated at the
        # original acquisition, so restore without re-checking (waking
        # while a sibling thread holds an unrelated lock is not an
        # inversion by THIS thread).
        inner = getattr(self._lock, "_acquire_restore", None)
        if inner is not None:
            inner(state)
        else:
            self._lock.acquire()
        if _ENABLED:
            self._push()


class OrderedCondition(threading.Condition):
    """`threading.Condition` over an `OrderedLock`: `with cond:` obeys
    the rank discipline and `wait()` correctly drops/restores the held
    stack entry through the `_release_save`/`_acquire_restore` hooks.
    Reentrant by default, matching `threading.Condition()`'s RLock."""

    def __init__(self, name: str, rank: int, reentrant: bool = True):
        self.ordered_lock = OrderedLock(name, rank, reentrant=reentrant)
        super().__init__(self.ordered_lock)

    @property
    def name(self) -> str:
        return self.ordered_lock.name

    @property
    def rank(self) -> int:
        return self.ordered_lock.rank
