"""Plan canonicalization + parameterization for the serving tier.

The serving plan cache (presto_tpu/serving/cache.py) wants the same cache
entry for `WHERE l_discount < 0.05` and `WHERE l_discount < 0.07`: the
compiled XLA executable is identical if the literal rides as a jit ARGUMENT
instead of baking into the trace.  `parameterize` rewrites an analyzed
(pre-optimizer) plan, extracting eligible literal constants out of filter
predicates and project assignments into a bound-parameter vector; each
occurrence becomes a BoundParameterExpression leaf that lowering evaluates
as `batch.params[index]`.  The cache key is then the structural key of the
TEMPLATE — canonical plan structure, value-free for the extracted slots —
plus an execution-config fingerprint, so a session-property change can
never serve a stale plan.

Eligibility is a strict whitelist.  Only constants that are *data* to the
executable may move: arguments of plain comparisons and +-* arithmetic,
of numeric/date/boolean type.  Everything else (LIKE patterns, round
digits, cast targets, IN lists, string literals, LIMIT counts, interval
foldings) stays literal in the template, keeping its value inside the key
— a changed value simply replans, which is always correct.

This mirrors the reference's prepared-statement parameter rewriting
(presto-main-base ParameterRewriter / QueryPreparer), moved down to the
plan level where the TPU executable cache needs it.
"""
from __future__ import annotations

from dataclasses import dataclass
from decimal import Decimal, InvalidOperation
from typing import Any, List, Optional, Tuple

import numpy as np

from ..common.types import (BigintType, BooleanType, DateType, DecimalType,
                            DoubleType, IntegerType, RealType, Type)
from ..spi import plan as P
from ..spi.expr import (BoundParameterExpression, CallExpression,
                        ConstantExpression, RowExpression,
                        SpecialFormExpression)

# Calls whose constant arguments are safe to turn into runtime parameters:
# lowering evaluates every argument of these dynamically (no host-side
# constant requirement).  divide/modulus are excluded on purpose — a
# parameterized denominator would move the division-by-zero decision from
# plan time to device time.
_ALLOWED_OPS = frozenset({
    "eq", "neq", "lt", "lte", "gt", "gte",
    "between", "add", "subtract", "multiply",
})

_ALLOWED_TYPES = (IntegerType, BigintType, DoubleType, RealType,
                  DateType, DecimalType, BooleanType)


class BindError(ValueError):
    """An EXECUTE value does not fit the cached template's slot (type or
    range mismatch); the caller falls back to a full replan."""


@dataclass
class ParamSlot:
    value: Any                  # plan-unit value (int / Decimal / str date)
    type: Type
    origin: Optional[int]       # `?` ordinal this literal came from, or None


@dataclass
class ParameterizedPlan:
    template: P.OutputNode      # plan with BoundParameterExpression leaves
    slots: List[ParamSlot]
    # True when every origin-tagged literal landed in a slot: the prepared
    # fast path may bind new USING values directly.  False means some `?`
    # was folded into a fixed constant or sits in a non-extractable
    # position — new values must replan (still correct: the leftover value
    # stays inside the cache key).
    origins_complete: bool


def parameterize(plan: P.OutputNode) -> ParameterizedPlan:
    """Extract eligible literals from `plan` (mutated in place) into a
    bound-parameter vector."""
    slots: List[ParamSlot] = []

    def eligible(c: ConstantExpression) -> bool:
        return c.value is not None and isinstance(c.type, _ALLOWED_TYPES)

    def rewrite(e: RowExpression) -> RowExpression:
        from ..exec.lowering import canonical_name
        if isinstance(e, CallExpression):
            extract = canonical_name(e.display_name) in _ALLOWED_OPS
            args = []
            for a in e.arguments:
                if extract and isinstance(a, ConstantExpression) \
                        and eligible(a):
                    idx = len(slots)
                    slots.append(ParamSlot(a.value, a.type, a.origin))
                    args.append(BoundParameterExpression(idx, a.type))
                else:
                    args.append(rewrite(a))
            return CallExpression(e.display_name, e.type, args,
                                  e.function_handle)
        if isinstance(e, SpecialFormExpression):
            return SpecialFormExpression(
                e.form, e.type, [rewrite(a) for a in e.arguments])
        return e

    leftover_origins = False
    for node in P.walk_plan(plan):
        if isinstance(node, P.FilterNode):
            node.predicate = rewrite(node.predicate)
        elif isinstance(node, P.ProjectNode):
            node.assignments = {v: rewrite(x)
                                for v, x in node.assignments.items()}
    # any origin-tagged literal still in the template blocks the prepared
    # fast path for that statement (its value is baked into the key)
    for node in P.walk_plan(plan):
        for e in _node_expressions(node):
            if _has_tagged_constant(e):
                leftover_origins = True
    return ParameterizedPlan(plan, slots, not leftover_origins)


def _node_expressions(node: P.PlanNode):
    if isinstance(node, P.FilterNode):
        yield node.predicate
    elif isinstance(node, P.ProjectNode):
        yield from node.assignments.values()


def _has_tagged_constant(e: RowExpression) -> bool:
    if isinstance(e, ConstantExpression):
        return e.origin is not None
    if isinstance(e, (CallExpression, SpecialFormExpression)):
        return any(_has_tagged_constant(a) for a in e.arguments)
    return False


def has_parameters(key: str) -> bool:
    """Whether a structural key covers a subtree containing bound-parameter
    leaves (used by materialization caches to add a value fingerprint)."""
    return '"@type": "parameter"' in key


# ---------------------------------------------------------------------------
# cache key
# ---------------------------------------------------------------------------

def config_fingerprint(config) -> str:
    """Execution-config identity for the cache key.  Walks dataclass fields
    by NAME so adding a knob changes every key (never aliases old entries),
    and a session-property override always lands in a different entry."""
    import dataclasses
    return repr(sorted(
        (f.name, getattr(config, f.name))
        for f in dataclasses.fields(config)))


def cache_key_from_parts(structural: str, config, catalog: str,
                         schema: str) -> str:
    """Cache key from a precomputed structural key (the prepared fast path
    stores the structural key and re-derives the full key per request, so
    session-property and catalog changes always re-key)."""
    return "\x00".join((
        str(catalog), str(schema),
        config_fingerprint(config),
        structural,
    ))


def plan_cache_key(template: P.OutputNode, config, catalog: str,
                   schema: str) -> str:
    return cache_key_from_parts(P.structural_key(template), config,
                                catalog, schema)


# ---------------------------------------------------------------------------
# value binding
# ---------------------------------------------------------------------------

def literal_value(node) -> Any:
    """EXECUTE ... USING literal AST -> plain python value in plan units
    (int / Decimal / float / bool / str / None), mirroring the planner's
    literal typing so the fast path and the replan path agree."""
    from . import parser as A
    if isinstance(node, A.NumberLit):
        if "." in node.text:
            return Decimal(node.text)
        return int(node.text)
    if isinstance(node, A.UnaryOp) and node.op == "-":
        v = literal_value(node.operand)
        if isinstance(v, (int, Decimal, float)) \
                and not isinstance(v, bool):
            return -v
        raise BindError(f"cannot negate {v!r}")
    if isinstance(node, A.StringLit):
        return node.value
    if isinstance(node, A.BoolLit):
        return node.value
    if isinstance(node, A.NullLit):
        return None
    if isinstance(node, A.DateLit):
        from .planner import _parse_date_str
        return _parse_date_str(node.value)
    raise BindError(f"unsupported EXECUTE value {type(node).__name__}")


def bind_literal(value: Any, typ: Type) -> Any:
    """Coerce a raw literal value onto a template slot's type, raising
    BindError when the value would have planned to a DIFFERENT type than
    the cached template records (forcing the caller to replan)."""
    if value is None:
        raise BindError("NULL parameter values replan")
    if isinstance(typ, BooleanType):
        if isinstance(value, bool):
            return value
        raise BindError(f"boolean slot, got {value!r}")
    if isinstance(value, bool):
        raise BindError(f"{typ} slot, got boolean {value!r}")
    if isinstance(typ, IntegerType):
        if isinstance(value, int) and -2**31 <= value < 2**31:
            return value
        raise BindError(f"integer slot, got {value!r}")
    if isinstance(typ, BigintType):
        if isinstance(value, int) and -2**63 <= value < 2**63:
            return value
        raise BindError(f"bigint slot, got {value!r}")
    if isinstance(typ, (DoubleType, RealType)):
        if isinstance(value, (int, float, Decimal)):
            return float(value)
        raise BindError(f"double slot, got {value!r}")
    if isinstance(typ, DecimalType):
        if isinstance(value, (int, Decimal)):
            try:
                d = Decimal(value)
                scaled = d.scaleb(typ.scale)
            except InvalidOperation as exc:
                raise BindError(str(exc))
            if scaled != scaled.to_integral_value():
                raise BindError(
                    f"value {value!r} does not fit decimal scale "
                    f"{typ.scale}")
            return d
        raise BindError(f"decimal slot, got {value!r}")
    if isinstance(typ, DateType):
        if isinstance(value, str):
            try:
                return str(np.datetime64(value, "D"))
            except ValueError:
                raise BindError(f"bad date literal {value!r}")
        raise BindError(f"date slot, got {value!r}")
    raise BindError(f"unsupported slot type {typ}")


def device_params(values: List[Any],
                  types: List[Type]) -> Tuple[Tuple, Tuple]:
    """Plan-unit slot values -> (device scalar tuple for ctx.params, host
    fingerprint tuple for value-sensitive cache keys)."""
    import jax.numpy as jnp
    from ..exec.lowering import _jnp_dtype, constant_device_value
    host = tuple(constant_device_value(v, t)
                 for v, t in zip(values, types))
    dev = tuple(jnp.asarray(h, dtype=_jnp_dtype(t))
                for h, t in zip(host, types))
    return dev, host
