"""SQL frontend: lexer + recursive-descent parser for the Presto SQL subset
reachable from TPC-H / TPC-DS (reference grammar:
presto-parser/src/main/antlr4/.../SqlBase.g4; this is a hand-written parser for
the query shapes the engine executes, not a full ANTLR port).

Supported: SELECT [DISTINCT] items FROM relations (comma + [INNER|LEFT|RIGHT]
JOIN .. ON) WHERE .. GROUP BY .. HAVING .. ORDER BY .. LIMIT ..; subqueries in
FROM / IN / EXISTS / scalar positions; CASE, CAST, BETWEEN, IN, LIKE, IS NULL,
EXTRACT, date/interval literals and arithmetic.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "exists", "between", "like", "is",
    "null", "case", "when", "then", "else", "end", "cast", "join", "inner",
    "left", "right", "full", "outer", "cross", "on", "asc", "desc", "distinct",
    "date", "interval", "extract", "union", "intersect", "except", "all",
    "true", "false", "nulls", "first", "last", "substring", "with",
}
# interval units are plain identifiers ("year" etc. must stay callable as
# functions: year(x))

TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<number>\d+\.\d*|\.\d+|\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|\|\||[(),.*/%<>=+\-;\[\]?])
""", re.VERBOSE)


@dataclass
class Token:
    kind: str   # number / string / ident / keyword / op / eof
    value: str
    pos: int


def tokenize(sql: str) -> List[Token]:
    out, pos = [], 0
    while pos < len(sql):
        m = TOKEN_RE.match(sql, pos)
        if not m:
            raise SyntaxError(f"cannot tokenize at {sql[pos:pos+30]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "ident" and text.lower() in KEYWORDS:
            out.append(Token("keyword", text.lower(), m.start()))
        elif kind == "qident":
            out.append(Token("ident", text[1:-1].replace('""', '"'), m.start()))
        elif kind == "string":
            out.append(Token("string", text[1:-1].replace("''", "'"), m.start()))
        else:
            out.append(Token(kind, text, m.start()))
    out.append(Token("eof", "", len(sql)))
    return out


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass
class Node:
    pass


@dataclass
class Ident(Node):
    parts: List[str]          # e.g. ["lineitem", "l_quantity"]


@dataclass
class NumberLit(Node):
    text: str


@dataclass
class StringLit(Node):
    value: str


@dataclass
class BoolLit(Node):
    value: bool


@dataclass
class NullLit(Node):
    pass


@dataclass
class DateLit(Node):
    value: str


@dataclass
class IntervalLit(Node):
    value: str
    unit: str                 # day / month / year


@dataclass
class ParamLit(Node):
    """A `?` placeholder inside a prepared statement (reference
    sql/tree/Parameter.java).  `index` is the 0-based ordinal in text
    order; EXECUTE ... USING binds values positionally."""
    index: int


@dataclass
class ArrayLit(Node):
    items: List[Node]         # ARRAY[e1, e2, ...]


@dataclass
class Subscript(Node):
    base: Node                # arr[idx] (1-based, SqlBase.g4 subscript)
    index: Node


@dataclass
class Star(Node):
    qualifier: Optional[str] = None


@dataclass
class BinaryOp(Node):
    op: str                   # + - * / % = <> < <= > >= and or ||
    left: Node
    right: Node


@dataclass
class UnaryOp(Node):
    op: str                   # - / not
    operand: Node


@dataclass
class FuncCall(Node):
    name: str
    args: List[Node]
    distinct: bool = False


@dataclass
class CastExpr(Node):
    operand: Node
    type_name: str


@dataclass
class Between(Node):
    value: Node
    low: Node
    high: Node
    negated: bool = False


@dataclass
class InList(Node):
    value: Node
    items: List[Node]
    negated: bool = False


@dataclass
class InSubquery(Node):
    value: Node
    query: "Query"
    negated: bool = False


@dataclass
class Exists(Node):
    query: "Query"
    negated: bool = False


@dataclass
class ScalarSubquery(Node):
    query: "Query"


@dataclass
class IsNull(Node):
    value: Node
    negated: bool = False


@dataclass
class Like(Node):
    value: Node
    pattern: Node
    negated: bool = False


@dataclass
class Case(Node):
    operand: Optional[Node]
    whens: List[Tuple[Node, Node]]
    default: Optional[Node]


@dataclass
class ExtractExpr(Node):
    part: str
    operand: Node


@dataclass
class WindowFrame(Node):
    """ROWS|RANGE frame.  Bound kinds: UNBOUNDED_PRECEDING, PRECEDING(n),
    CURRENT, FOLLOWING(n), UNBOUNDED_FOLLOWING."""
    frame_type: str                       # ROWS | RANGE
    start_kind: str
    start_offset: Optional[int]
    end_kind: str
    end_offset: Optional[int]


@dataclass
class WindowCall(Node):
    """fn(args) OVER (PARTITION BY ... ORDER BY ... [frame]); frame None =
    default RANGE UNBOUNDED PRECEDING .. CURRENT ROW."""
    func: "FuncCall"
    partition_by: List[Node]
    order_by: List["OrderItem"]
    frame: Optional[WindowFrame] = None


# relations
@dataclass
class TableRef(Node):
    name: str
    alias: Optional[str] = None


@dataclass
class SubqueryRef(Node):
    query: "Query"
    alias: str


@dataclass
class UnnestRef(Node):
    """UNNEST(arr, ...) [WITH ORDINALITY] [AS alias(c1, c2, ...)] — a
    lateral relation over the preceding FROM items (SqlBase.g4 unnest)."""
    exprs: List[Node]
    alias: Optional[str] = None
    column_aliases: List[str] = field(default_factory=list)
    ordinality: bool = False


@dataclass
class JoinRel(Node):
    join_type: str            # INNER / LEFT / RIGHT / CROSS
    left: Node
    right: Node
    on: Optional[Node]


@dataclass
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None


@dataclass
class OrderItem(Node):
    expr: Node
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclass
class Query(Node):
    select_items: List[SelectItem]
    relations: List[Node]                  # implicit cross join of these
    where: Optional[Node] = None
    group_by: List[Node] = field(default_factory=list)
    having: Optional[Node] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    distinct: bool = False
    ctes: List[Tuple[str, "Query"]] = field(default_factory=list)
    parenthesized: bool = False            # written as "( query )"
    # GROUPING SETS / ROLLUP / CUBE: the expanded list of key sets
    # (None = plain GROUP BY); group_by still holds every distinct key expr
    grouping_sets: Optional[List[List[Node]]] = None


@dataclass
class Explain(Node):
    """EXPLAIN [ANALYZE] [(TYPE t)] <query> (reference sql/tree/Explain.java
    + ExplainType.java; text format only).  explain_type is "" for plain
    EXPLAIN; "VALIDATE" prints the plan-checker diagnostic list instead of
    the plan (presto_tpu/analysis)."""
    query: Node                            # Query | SetOp
    analyze: bool = False
    explain_type: str = ""                 # "" | VALIDATE | LOGICAL | DISTRIBUTED


@dataclass
class CreateTableAs(Node):
    """CREATE TABLE [IF NOT EXISTS] name AS <query>
    (reference sql/tree/CreateTableAsSelect.java)."""
    table: str
    query: Node                            # Query | SetOp
    if_not_exists: bool = False


@dataclass
class InsertInto(Node):
    """INSERT INTO name <query> (reference sql/tree/Insert.java; positional
    columns only)."""
    table: str
    query: Node


@dataclass
class DropTable(Node):
    """DROP TABLE [IF EXISTS] name (reference sql/tree/DropTable.java)."""
    table: str
    if_exists: bool = False


@dataclass
class Prepare(Node):
    """PREPARE name FROM <statement> (reference sql/tree/Prepare.java).
    `text` is the inner statement's SQL text (what travels in the
    X-Presto-Prepared-Statement header); `statement` its parsed AST."""
    name: str
    text: str
    statement: Node
    param_count: int = 0


@dataclass
class ExecuteStmt(Node):
    """EXECUTE name [USING expr, ...] (reference sql/tree/Execute.java).
    USING values must plan to literals; they bind `?` slots positionally."""
    name: str
    values: List[Node] = field(default_factory=list)


@dataclass
class Deallocate(Node):
    """DEALLOCATE [PREPARE] name (reference sql/tree/Deallocate.java)."""
    name: str


@dataclass
class SetOp(Node):
    """UNION / INTERSECT / EXCEPT.  ORDER BY / LIMIT apply to the whole
    set operation (trailing clauses of the last branch are hoisted here)."""
    op: str                                # union | intersect | except
    left: Node                             # Query | SetOp
    right: Node
    all: bool = False
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    ctes: List[Tuple[str, "Query"]] = field(default_factory=list)
    parenthesized: bool = False


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0
        self._param_count = 0    # `?` placeholders seen, in text order

    # -- token helpers ----------------------------------------------------
    def peek(self, k=0) -> Token:
        return self.tokens[min(self.i + k, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def accept(self, kind, value=None) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind, value=None) -> Token:
        t = self.accept(kind, value)
        if t is None:
            got = self.peek()
            raise SyntaxError(
                f"expected {value or kind}, got {got.value!r} at {got.pos}")
        return t

    def accept_kw(self, *words) -> bool:
        save = self.i
        for w in words:
            if not self.accept("keyword", w):
                self.i = save
                return False
        return True

    # -- entry ------------------------------------------------------------
    def _peek_word(self, k=0) -> str:
        t = self.peek(k)
        return t.value.lower() if t.kind in ("ident", "keyword") else ""

    def _ident(self) -> str:
        """Possibly-qualified identifier; keeps only the table part."""
        name = self.expect("ident").value
        while self.accept("op", "."):
            name = self.expect("ident").value
        return name.lower()

    def _expect_word(self, w: str):
        t = self.next()
        if t.kind not in ("ident", "keyword") or t.value.lower() != w:
            raise SyntaxError(f"expected {w}, got {t.value!r} at {t.pos}")

    def parse(self):
        word = self._peek_word()
        if word == "explain":
            self.next()
            explain_type = ""
            if self.accept("op", "("):
                # EXPLAIN ( TYPE t ) — reference ExplainType.java options
                self._expect_word("type")
                t = self.next()
                if t.kind not in ("ident", "keyword"):
                    raise SyntaxError(
                        f"expected explain type, got {t.value!r} at {t.pos}")
                explain_type = t.value.upper()
                if explain_type not in ("LOGICAL", "DISTRIBUTED",
                                        "VALIDATE"):
                    raise SyntaxError(
                        f"unsupported explain type {explain_type!r} "
                        f"(LOGICAL | DISTRIBUTED | VALIDATE)")
                self.expect("op", ")")
            analyze = self._peek_word() == "analyze"
            if analyze:
                self.next()
            q = Explain(self.parse_query(), analyze, explain_type)
        elif word == "create":
            self.next()
            self._expect_word("table")
            ine = False
            if self._peek_word() == "if":
                self.next()
                self.expect("keyword", "not")
                self._expect_word("exists")
                ine = True
            name = self._ident()
            self.expect("keyword", "as")
            q = CreateTableAs(name, self.parse_query(), ine)
        elif word == "insert":
            self.next()
            if self._peek_word() == "into":
                self.next()
            q = InsertInto(self._ident(), self.parse_query())
        elif word == "drop":
            self.next()
            self._expect_word("table")
            ie = False
            if self._peek_word() == "if":
                self.next()
                self._expect_word("exists")
                ie = True
            q = DropTable(self._ident(), ie)
        elif word == "prepare":
            self.next()
            name = self.expect("ident").value.lower()
            self._expect_word("from")
            # the rest of the text IS the inner statement; a sub-parse
            # validates it and counts its `?` slots
            inner = self.sql[self.peek().pos:].rstrip()
            if inner.endswith(";"):
                inner = inner[:-1].rstrip()
            sub = Parser(inner)
            stmt = sub.parse()
            q = Prepare(name, inner, stmt, sub._param_count)
            self.i = len(self.tokens) - 1   # sub-parser consumed the rest
        elif word == "execute":
            self.next()
            name = self.expect("ident").value.lower()
            values: List[Node] = []
            if self._peek_word() == "using":
                self.next()
                values.append(self.parse_expr())
                while self.accept("op", ","):
                    values.append(self.parse_expr())
            q = ExecuteStmt(name, values)
        elif word == "deallocate":
            self.next()
            if self._peek_word() == "prepare":
                self.next()
            q = Deallocate(self.expect("ident").value.lower())
        else:
            q = self.parse_query()
        self.accept("op", ";")
        self.expect("eof")
        return q

    def parse_query(self):
        ctes = []
        if self.accept("keyword", "with"):
            while True:
                name = self.expect("ident").value
                self.expect("keyword", "as")
                self.expect("op", "(")
                sub = self.parse_query()
                self.expect("op", ")")
                ctes.append((name, sub))
                if not self.accept("op", ","):
                    break
        q = self.parse_set_expr()
        q.ctes = ctes
        return q

    # set-operation grammar (INTERSECT binds tighter than UNION/EXCEPT,
    # reference SqlBase.g4 queryTerm rules)
    def parse_set_expr(self):
        left = self.parse_intersect_term()
        while True:
            if self.accept("keyword", "union"):
                op = "union"
            elif self.accept("keyword", "except"):
                op = "except"
            else:
                break
            all_ = bool(self.accept("keyword", "all"))
            if not all_:
                self.accept("keyword", "distinct")
            right = self.parse_intersect_term()
            left = SetOp(op, left, right, all_)
        if isinstance(left, SetOp):
            self._hoist_trailing_clauses(left)
            # a parenthesized last branch leaves ORDER BY / LIMIT unconsumed
            if not left.order_by and self.accept_kw("order", "by"):
                left.order_by.append(self.parse_order_item())
                while self.accept("op", ","):
                    left.order_by.append(self.parse_order_item())
            if left.limit is None and self.accept("keyword", "limit"):
                left.limit = int(self.expect("number").value)
        return left

    def parse_intersect_term(self):
        left = self.parse_query_primary()
        while self.accept("keyword", "intersect"):
            all_ = bool(self.accept("keyword", "all"))
            if not all_:
                self.accept("keyword", "distinct")
            right = self.parse_query_primary()
            left = SetOp("intersect", left, right, all_)
        return left

    def parse_query_primary(self):
        if self.peek().kind == "op" and self.peek().value == "(" \
                and self.peek(1).kind == "keyword" \
                and self.peek(1).value in ("select", "with"):
            self.next()
            q = self.parse_query()
            self.expect("op", ")")
            q.parenthesized = True
            return q
        return self.parse_select()

    def _hoist_trailing_clauses(self, top: "SetOp"):
        """Move ORDER BY / LIMIT parsed into the rightmost unparenthesized
        branch up to the set operation they actually govern."""
        last = top
        while isinstance(last.right, SetOp) and not last.right.parenthesized:
            last = last.right
        branch = last.right
        if branch.parenthesized or not isinstance(branch, Query):
            return
        top.order_by, branch.order_by = branch.order_by, []
        top.limit, branch.limit = branch.limit, None

    def parse_select(self) -> Query:
        self.expect("keyword", "select")
        distinct = bool(self.accept("keyword", "distinct"))
        self.accept("keyword", "all")
        items = [self.parse_select_item()]
        while self.accept("op", ","):
            items.append(self.parse_select_item())

        relations: List[Node] = []
        if self.accept("keyword", "from"):
            relations.append(self.parse_relation())
            while self.accept("op", ","):
                relations.append(self.parse_relation())

        where = self.parse_expr() if self.accept("keyword", "where") else None
        group_by: List[Node] = []
        grouping_sets: Optional[List[List[Node]]] = None
        if self.accept_kw("group", "by"):
            group_by, grouping_sets = self.parse_group_by()
        having = self.parse_expr() if self.accept("keyword", "having") else None
        order_by: List[OrderItem] = []
        if self.accept_kw("order", "by"):
            order_by.append(self.parse_order_item())
            while self.accept("op", ","):
                order_by.append(self.parse_order_item())
        limit = None
        if self.accept("keyword", "limit"):
            limit = int(self.expect("number").value)
        return Query(items, relations, where, group_by, having, order_by,
                     limit, distinct, grouping_sets=grouping_sets)

    def parse_group_by(self):
        """GROUP BY elements: plain expressions, ROLLUP(...), CUBE(...),
        GROUPING SETS ((..), ..) — mixed elements combine by cross product
        (reference SqlBase.g4 groupingElement / the analyzer's
        GroupingSetAnalysis).  Returns (all key exprs, expanded sets or
        None for a plain GROUP BY)."""
        from itertools import combinations, product
        elements: List[List[List[Node]]] = []   # element -> its set list
        structured = False
        while True:
            t = self.peek()
            tl = t.value.lower() if t.kind == "ident" else None
            if tl in ("rollup", "cube") and self.peek(1).value == "(":
                structured = True
                self.next()
                self.expect("op", "(")
                exprs = [self.parse_expr()]
                while self.accept("op", ","):
                    exprs.append(self.parse_expr())
                self.expect("op", ")")
                if tl == "rollup":
                    sets = [exprs[:i] for i in range(len(exprs), -1, -1)]
                else:
                    sets = []
                    for r in range(len(exprs), -1, -1):
                        for c in combinations(range(len(exprs)), r):
                            sets.append([exprs[j] for j in c])
                elements.append(sets)
            elif tl == "grouping" and self.peek(1).kind == "ident" \
                    and self.peek(1).value.lower() == "sets":
                structured = True
                self.next()
                self.next()
                self.expect("op", "(")
                sets = []
                while True:
                    if self.accept("op", "("):
                        s: List[Node] = []
                        if not self.accept("op", ")"):
                            s.append(self.parse_expr())
                            while self.accept("op", ","):
                                s.append(self.parse_expr())
                            self.expect("op", ")")
                        sets.append(s)
                    else:
                        sets.append([self.parse_expr()])
                    if not self.accept("op", ","):
                        break
                self.expect("op", ")")
                elements.append(sets)
            else:
                elements.append([[self.parse_expr()]])
            if not self.accept("op", ","):
                break
        all_exprs = [e for el in elements for s in el for e in s]
        if not structured:
            return all_exprs, None
        grouping_sets = [sum(combo, []) for combo in product(*elements)]
        return all_exprs, grouping_sets

    def parse_select_item(self) -> SelectItem:
        if self.peek().kind == "op" and self.peek().value == "*":
            self.next()
            return SelectItem(Star())
        if (self.peek().kind == "ident" and self.peek(1).value == "."
                and self.peek(2).value == "*"):
            q = self.next().value
            self.next()
            self.next()
            return SelectItem(Star(q))
        expr = self.parse_expr()
        alias = None
        if self.accept("keyword", "as"):
            alias = self.next().value
        elif self.peek().kind == "ident":
            alias = self.next().value
        return SelectItem(expr, alias)

    def parse_order_item(self) -> OrderItem:
        expr = self.parse_expr()
        asc = True
        if self.accept("keyword", "desc"):
            asc = False
        else:
            self.accept("keyword", "asc")
        nulls_first = None
        if self.accept("keyword", "nulls"):
            if self.accept("keyword", "first"):
                nulls_first = True
            else:
                self.expect("keyword", "last")
                nulls_first = False
        return OrderItem(expr, asc, nulls_first)

    # -- relations --------------------------------------------------------
    def parse_relation(self) -> Node:
        rel = self.parse_relation_primary()
        while True:
            jt = None
            if self.accept("keyword", "join") or self.accept_kw("inner", "join"):
                jt = "INNER"
            elif self.accept_kw("left", "outer", "join") or self.accept_kw("left", "join"):
                jt = "LEFT"
            elif self.accept_kw("right", "outer", "join") or self.accept_kw("right", "join"):
                jt = "RIGHT"
            elif self.accept_kw("full", "outer", "join") or self.accept_kw("full", "join"):
                jt = "FULL"
            elif self.accept_kw("cross", "join"):
                jt = "CROSS"
            else:
                return rel
            right = self.parse_relation_primary()
            on = None
            if jt != "CROSS":
                self.expect("keyword", "on")
                on = self.parse_expr()
            rel = JoinRel(jt, rel, right, on)

    def parse_relation_primary(self) -> Node:
        if self.accept("op", "("):
            if self.peek().value in ("select", "with") \
                    or self.peek().value == "(":
                # `(` could open a parenthesized query ((SELECT..) UNION
                # (SELECT..)) or a parenthesized join relation; try the
                # query grammar first and backtrack (SqlBase.g4 resolves
                # the same ambiguity via aliasedRelation | subquery)
                save = self.i
                try:
                    q = self.parse_query()
                    self.expect("op", ")")
                except SyntaxError:
                    self.i = save
                else:
                    if self.accept("keyword", "as"):
                        alias = self.expect("ident").value
                    elif self.peek().kind == "ident":
                        alias = self.next().value
                    else:
                        # Presto allows an unaliased derived table; scope
                        # needs a name, so synthesize a unique one
                        self._subq_n = getattr(self, "_subq_n", 0) + 1
                        alias = f"__subq{self._subq_n}"
                    return SubqueryRef(q, alias)
            rel = self.parse_relation()
            self.expect("op", ")")
            return rel
        if self.peek().kind == "ident" \
                and self.peek().value.lower() == "unnest" \
                and self.peek(1).kind == "op" and self.peek(1).value == "(":
            return self.parse_unnest()
        name = self.expect("ident").value
        # optional schema qualifier: schema.table
        while self.accept("op", "."):
            name = self.expect("ident").value  # keep last part
        alias = None
        if self.accept("keyword", "as"):
            alias = self.expect("ident").value
        elif self.peek().kind == "ident":
            alias = self.next().value
        return TableRef(name, alias)

    def parse_unnest(self) -> "UnnestRef":
        """UNNEST(expr, ...) [WITH ORDINALITY] [AS a(c1, ...)]"""
        self.next()                       # unnest
        self.expect("op", "(")
        exprs = [self.parse_expr()]
        while self.accept("op", ","):
            exprs.append(self.parse_expr())
        self.expect("op", ")")
        ordinality = False
        if self.accept("keyword", "with"):
            w = self.next()
            if w.value.lower() != "ordinality":
                raise SyntaxError(f"expected ORDINALITY at {w.pos}")
            ordinality = True
        alias, col_aliases = None, []
        if self.accept("keyword", "as"):
            alias = self.expect("ident").value
        elif self.peek().kind == "ident":
            alias = self.next().value
        if alias is not None and self.accept("op", "("):
            col_aliases.append(self._ident())
            while self.accept("op", ","):
                col_aliases.append(self._ident())
            self.expect("op", ")")
        return UnnestRef(exprs, alias, col_aliases, ordinality)

    # -- expressions (precedence climbing) -------------------------------
    def parse_expr(self) -> Node:
        return self.parse_or()

    def parse_or(self) -> Node:
        left = self.parse_and()
        while self.accept("keyword", "or"):
            left = BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self) -> Node:
        left = self.parse_not()
        while self.accept("keyword", "and"):
            left = BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> Node:
        if self.accept("keyword", "not"):
            return UnaryOp("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> Node:
        left = self.parse_additive()
        while True:
            negated = False
            save = self.i
            if self.accept("keyword", "not"):
                negated = True
            if self.accept("keyword", "between"):
                low = self.parse_additive()
                self.expect("keyword", "and")
                high = self.parse_additive()
                left = Between(left, low, high, negated)
                continue
            if self.accept("keyword", "in"):
                self.expect("op", "(")
                if self.peek().value in ("select", "with"):
                    q = self.parse_query()
                    self.expect("op", ")")
                    left = InSubquery(left, q, negated)
                else:
                    items = [self.parse_expr()]
                    while self.accept("op", ","):
                        items.append(self.parse_expr())
                    self.expect("op", ")")
                    left = InList(left, items, negated)
                continue
            if self.accept("keyword", "like"):
                left = Like(left, self.parse_additive(), negated)
                continue
            if negated:
                self.i = save
                break
            if self.accept("keyword", "is"):
                neg = bool(self.accept("keyword", "not"))
                self.expect("keyword", "null")
                left = IsNull(left, neg)
                continue
            t = self.peek()
            if t.kind == "op" and t.value in ("=", "<>", "!=", "<", "<=", ">", ">="):
                self.next()
                op = "<>" if t.value == "!=" else t.value
                left = BinaryOp(op, left, self.parse_additive())
                continue
            break
        return left

    def parse_additive(self) -> Node:
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-", "||"):
                self.next()
                left = BinaryOp(t.value, left, self.parse_multiplicative())
            else:
                return left

    def parse_multiplicative(self) -> Node:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = BinaryOp(t.value, left, self.parse_unary())
            else:
                return left

    def parse_unary(self) -> Node:
        if self.accept("op", "-"):
            return UnaryOp("-", self.parse_unary())
        if self.accept("op", "+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Node:
        e = self._parse_primary_base()
        # postfix subscript binds tightest (SqlBase.g4 primaryExpression
        # '[' valueExpression ']')
        while self.peek().kind == "op" and self.peek().value == "[":
            self.next()
            idx = self.parse_expr()
            self.expect("op", "]")
            e = Subscript(e, idx)
        return e

    def _parse_primary_base(self) -> Node:
        t = self.peek()
        if t.kind == "ident" and t.value.lower() == "array" \
                and self.peek(1).kind == "op" and self.peek(1).value == "[":
            self.next()
            self.next()               # [
            items: List[Node] = []
            if not (self.peek().kind == "op" and self.peek().value == "]"):
                items.append(self.parse_expr())
                while self.accept("op", ","):
                    items.append(self.parse_expr())
            self.expect("op", "]")
            return ArrayLit(items)
        if t.kind == "op" and t.value == "?":
            self.next()
            p = ParamLit(self._param_count)
            self._param_count += 1
            return p
        if t.kind == "number":
            self.next()
            return NumberLit(t.value)
        if t.kind == "ident" and t.value.lower() == "decimal" \
                and self.peek(1).kind == "string":
            # typed literal DECIMAL '1.2' (SqlBase.g4 typeConstructor; the
            # Presto unparser emits every decimal this way)
            self.next()
            return NumberLit(self.expect("string").value)
        if t.kind == "string":
            self.next()
            return StringLit(t.value)
        if t.kind == "keyword":
            if t.value == "null":
                self.next()
                return NullLit()
            if t.value in ("true", "false"):
                self.next()
                return BoolLit(t.value == "true")
            if t.value == "date":
                self.next()
                return DateLit(self.expect("string").value)
            if t.value == "interval":
                self.next()
                v = self.expect("string").value
                unit = self.next().value.lower()
                return IntervalLit(v, unit)
            if t.value == "case":
                return self.parse_case()
            if t.value == "cast":
                self.next()
                self.expect("op", "(")
                operand = self.parse_expr()
                self.expect("keyword", "as")
                type_name = self.parse_type_name()
                self.expect("op", ")")
                return CastExpr(operand, type_name)
            if t.value == "extract":
                self.next()
                self.expect("op", "(")
                part = self.next().value.lower()
                self.expect("keyword", "from")
                operand = self.parse_expr()
                self.expect("op", ")")
                return ExtractExpr(part, operand)
            if t.value == "exists":
                self.next()
                self.expect("op", "(")
                q = self.parse_query()
                self.expect("op", ")")
                return Exists(q)
            if t.value == "substring":
                self.next()
                self.expect("op", "(")
                operand = self.parse_expr()
                if self.accept("keyword", "from"):
                    start = self.parse_expr()
                    length = None
                    if self.accept("ident", "for") or self.accept("keyword", "for"):
                        length = self.parse_expr()
                    args = [operand, start] + ([length] if length else [])
                else:
                    args = [operand]
                    while self.accept("op", ","):
                        args.append(self.parse_expr())
                self.expect("op", ")")
                return FuncCall("substr", args)
        if t.kind == "op" and t.value == "(":
            self.next()
            if self.peek().value in ("select", "with"):
                q = self.parse_query()
                self.expect("op", ")")
                return ScalarSubquery(q)
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if t.kind == "ident":
            # function call?
            if self.peek(1).kind == "op" and self.peek(1).value == "(":
                name = self.next().value.lower()
                self.next()  # (
                distinct = bool(self.accept("keyword", "distinct"))
                args: List[Node] = []
                if self.peek().value == "*":
                    self.next()
                    args = []
                elif not (self.peek().kind == "op" and self.peek().value == ")"):
                    args.append(self.parse_expr())
                    while self.accept("op", ","):
                        args.append(self.parse_expr())
                self.expect("op", ")")
                fc = FuncCall(name, args, distinct)
                if self.peek().kind == "ident" \
                        and self.peek().value.lower() == "over":
                    return self.parse_over(fc)
                return fc
            parts = [self.next().value]
            while self.accept("op", "."):
                parts.append(self.expect("ident").value)
            return Ident(parts)
        raise SyntaxError(f"unexpected token {t.value!r} at {t.pos}")

    def parse_over(self, fc: "FuncCall") -> "WindowCall":
        self.next()  # over
        self.expect("op", "(")
        partition_by: List[Node] = []
        order_by: List[OrderItem] = []
        if self.peek().kind == "ident" \
                and self.peek().value.lower() == "partition":
            self.next()
            self.expect("keyword", "by")
            partition_by.append(self.parse_expr())
            while self.accept("op", ","):
                partition_by.append(self.parse_expr())
        if self.accept_kw("order", "by"):
            order_by.append(self.parse_order_item())
            while self.accept("op", ","):
                order_by.append(self.parse_order_item())
        frame = None
        if self.peek().kind in ("ident", "keyword") \
                and self.peek().value.lower() in ("rows", "range", "groups"):
            frame = self.parse_window_frame()
        self.expect("op", ")")
        return WindowCall(fc, partition_by, order_by, frame)

    def parse_window_frame(self) -> "WindowFrame":
        ftype = self.next().value.upper()
        if ftype == "GROUPS":
            raise SyntaxError("GROUPS window frames not supported")

        def bound():
            t = self.peek()
            if t.value.lower() == "unbounded":
                self.next()
                d = self.next().value.lower()
                if d == "preceding":
                    return ("UNBOUNDED_PRECEDING", None)
                if d == "following":
                    return ("UNBOUNDED_FOLLOWING", None)
                raise SyntaxError(f"bad frame bound near {d!r}")
            if t.value.lower() == "current":
                self.next()
                if self.next().value.lower() != "row":
                    raise SyntaxError("expected CURRENT ROW")
                return ("CURRENT", None)
            if t.kind == "number":
                n = int(self.next().value)
                d = self.next().value.lower()
                if d == "preceding":
                    return ("PRECEDING", n)
                if d == "following":
                    return ("FOLLOWING", n)
                raise SyntaxError(f"bad frame bound near {d!r}")
            raise SyntaxError(f"bad frame bound near {t.value!r}")

        if self.peek().value.lower() == "between":
            self.next()
            sk, so = bound()
            if self.next().value.lower() != "and":
                raise SyntaxError("expected AND in frame BETWEEN")
            ek, eo = bound()
        else:
            sk, so = bound()
            ek, eo = "CURRENT", None
        return WindowFrame(ftype, sk, so, ek, eo)

    def parse_type_name(self) -> str:
        base = self.next().value.lower()
        if self.accept("op", "("):
            args = [self.expect("number").value]
            while self.accept("op", ","):
                args.append(self.expect("number").value)
            self.expect("op", ")")
            return f"{base}({','.join(args)})"
        return base

    def parse_case(self) -> Node:
        self.expect("keyword", "case")
        operand = None
        if self.peek().value != "when":
            operand = self.parse_expr()
        whens = []
        while self.accept("keyword", "when"):
            cond = self.parse_expr()
            self.expect("keyword", "then")
            whens.append((cond, self.parse_expr()))
        default = None
        if self.accept("keyword", "else"):
            default = self.parse_expr()
        self.expect("keyword", "end")
        return Case(operand, whens, default)


def parse_sql(sql: str) -> Query:
    return Parser(sql).parse()
