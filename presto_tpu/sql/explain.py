"""Plan pretty-printer for EXPLAIN / EXPLAIN ANALYZE.

The analog of the reference's PlanPrinter
(presto-main-base/.../sql/planner/planPrinter/PlanPrinter.java) in its
text mode: one indented line per node with the node's distinguishing
details, optionally annotated with runtime stats collected during an
EXPLAIN ANALYZE execution (ExplainAnalyzeOperator.java +
RuntimeStats, presto-common/.../common/RuntimeStats.java)."""
from __future__ import annotations

from typing import Dict, List, Optional

from ..spi import plan as P


def _vars(vs, limit: int = 6) -> str:
    names = [v.name for v in vs]
    if len(names) > limit:
        names = names[:limit] + [f"... {len(vs) - limit} more"]
    return ", ".join(names)


def _details(node: P.PlanNode) -> str:
    if isinstance(node, P.TableScanNode):
        s = (f"table = {node.table.connector_id}.{node.table.table_name}"
             f" [{_vars(node.outputs)}]")
        pd = getattr(node, "pushdown", None)
        if pd:
            s += ", pushdown = [" + ", ".join(
                f"{e['column']} {e['op']} {e['value']}" for e in pd) + "]"
        return s
    if isinstance(node, P.FilterNode):
        return f"predicate = {node.predicate}"
    if isinstance(node, P.ProjectNode):
        exprs = [f"{v.name} := {e}" for v, e in node.assignments.items()
                 if str(getattr(e, 'name', None)) != v.name]
        s = "; ".join(exprs[:4])
        if len(exprs) > 4:
            s += f"; ... {len(exprs) - 4} more"
        return s
    if isinstance(node, P.AggregationNode):
        aggs = [f"{v.name} := {a.call}" for v, a in node.aggregations.items()]
        return (f"step = {node.step}, keys = [{_vars(node.grouping_keys)}], "
                + "; ".join(aggs[:4]))
    if isinstance(node, P.JoinNode):
        crit = ", ".join(f"{l.name} = {r.name}" for l, r in node.criteria)
        extra = f", filter = {node.filter}" if node.filter is not None else ""
        if node.dynamic_filters:
            dfs = ", ".join(f"{df}:{v}" for v, df in
                            sorted(node.dynamic_filters.items()))
            extra += f", dynamicFilters = [{dfs}]"
        return f"type = {node.join_type}, criteria = [{crit}]{extra}"
    if isinstance(node, P.SemiJoinNode):
        return (f"{node.source_join_variable.name} IN "
                f"{node.filtering_source_join_variable.name} "
                f"-> {node.semi_join_output.name}")
    if isinstance(node, (P.SortNode, P.TopNNode)):
        keys = ", ".join(f"{v.name} {o}" for v, o in
                         node.ordering_scheme.orderings)
        n = f", count = {node.count}" if isinstance(node, P.TopNNode) else ""
        return f"orderBy = [{keys}]{n}"
    if isinstance(node, P.LimitNode):
        return f"count = {node.count}"
    if isinstance(node, P.WindowNode):
        funcs = ", ".join(f"{v.name} := {f.call}"
                          for v, f in node.window_functions.items())
        order = ""
        if node.ordering_scheme:
            order = " orderBy = [" + ", ".join(
                f"{v.name} {o}" for v, o in
                node.ordering_scheme.orderings) + "]"
        return (f"partitionBy = [{_vars(node.partition_by)}]{order} | "
                + funcs)
    if isinstance(node, P.ExchangeNode):
        fabric = ("" if node.partitioning_scheme.fabric is None
                  else f", fabric = {node.partitioning_scheme.fabric}")
        return (f"type = {node.exchange_type}, scope = {node.scope}, "
                f"partitioning = {node.partitioning_scheme.handle}"
                f"{fabric}")
    if isinstance(node, P.RemoteSourceNode):
        return f"sourceFragments = {node.source_fragment_ids}"
    if isinstance(node, P.OutputNode):
        return f"[{', '.join(node.column_names)}]"
    if isinstance(node, P.UnionNode):
        return f"{len(node.inputs)} inputs [{_vars(node.outputs)}]"
    if isinstance(node, P.ValuesNode):
        return f"{len(node.rows)} rows"
    if isinstance(node, P.DistinctLimitNode):
        return f"count = {node.count}, keys = [{_vars(node.distinct_variables)}]"
    return ""


def format_plan(node: P.PlanNode,
                stats: Optional[Dict[str, dict]] = None) -> str:
    """Indented textual plan with cost-based row estimates (the PlanPrinter's
    `Estimates: {rows: N}` annotations backed by sql/stats.py); stats
    (node id -> {rows, wall_s, invocations}) annotate each line when given
    (EXPLAIN ANALYZE)."""
    from .stats import StatsCalculator
    calc = StatsCalculator()
    lines: List[str] = []

    def walk(n: P.PlanNode, depth: int) -> None:
        name = type(n).__name__.replace("Node", "")
        detail = _details(n)
        line = "   " * depth + f"- {name}"
        if detail:
            line += f" [{detail}]"
        try:
            est = calc.rows(n)
        except Exception:
            est = None
        if est is not None:
            line += f"  {{rows≈{est:,.0f}}}"
        if stats is not None and n.id in stats:
            s = stats[n.id]
            line += (f"  {{rows: {s['rows']:,}, "
                     f"wall: {s['wall_s'] * 1e3:,.1f}ms, "
                     f"batches: {s['batches']}}}")
            if s.get("bytes"):
                line += f"  {{bytes≈{s['bytes']:,}}}"
            if s.get("fused"):
                # the node ran inside ONE fused XLA program: rows are its
                # device-side counter; the wall is the whole program's
                line += "  [fused]"
            if s.get("driver_walls"):
                # per-driver walls from task_concurrency leaf drains
                # (local_exchange.parallel_drain): sum(driver walls) -
                # stage wall is the measured overlap
                dw = ", ".join(f"{w * 1e3:,.0f}ms"
                               for w in s["driver_walls"])
                line += f"  {{driver_walls: [{dw}]}}"
            if s.get("dynamicFilterRowsDropped"):
                line += (f"  {{dynamicFilterRowsDropped: "
                         f"{s['dynamicFilterRowsDropped']:,}}}")
        lines.append(line)
        for ch in n.sources:
            walk(ch, depth + 1)

    walk(node, 0)
    rule_stats = getattr(node, "rule_stats", None)
    if rule_stats:
        # per-rule hit counts from the iterative optimizer (sql/rules.py;
        # the reference's optimizerInformation in the query plan JSON)
        fired = ", ".join(f"{k}: {v}"
                          for k, v in sorted(rule_stats.items()))
        lines.append(f"Optimizer rules fired: {{{fired}}}")
    return "\n".join(lines)


def format_analyze_footer(runtime_stats, profile_dir: str = None) -> str:
    """EXPLAIN ANALYZE footer: fusion-declined counters (the reasons a
    scan chain stayed on the streaming path) and the fused program wall,
    pulled from the execution's RuntimeStats; plus the device-profiler
    capture directory when the `profile` session property wrapped the
    run.  Empty string when nothing was recorded."""
    if runtime_stats is None:
        if profile_dir:
            return f"Device profile: {profile_dir}"
        return ""
    rs = runtime_stats.to_dict() if hasattr(runtime_stats, "to_dict") \
        else dict(runtime_stats)
    declined = {k[len("fusionDeclined"):]: int(v["sum"])
                for k, v in rs.items() if k.startswith("fusionDeclined")}
    lines: List[str] = []
    if declined:
        body = ", ".join(f"{k}: {v}" for k, v in sorted(declined.items()))
        lines.append(f"Fusion declined: {{{body}}}")
    # the Pallas scan-kernel twin of the fusion counters: how many fused
    # scans ran the hand-written kernel, and why the rest stayed on the
    # XLA chain (exec/kernels KERNEL_DECLINE_REASONS)
    kdeclined = {k[len("kernelDeclined"):]: int(v["sum"])
                 for k, v in rs.items() if k.startswith("kernelDeclined")}
    if kdeclined:
        body = ", ".join(f"{k}: {v}" for k, v in sorted(kdeclined.items()))
        lines.append(f"Scan kernel declined: {{{body}}}")
    kp = rs.get("kernelScanPrograms")
    if kp:
        lines.append(f"Pallas scan kernels: {int(kp['sum'])}")
    kw = rs.get("kernelWindowPrograms")
    if kw:
        lines.append(f"Pallas window kernels: {int(kw['sum'])}")
    ov = rs.get("kernelDmaOverlapFraction")
    if ov and ov.get("count"):
        # scan.kernel-dma = double: fraction of staged block slabs whose
        # HBM->VMEM copy was issued while the previous block computed
        lines.append(f"Kernel DMA overlap: "
                     f"{ov['sum'] / ov['count']:.2f} "
                     f"(double-buffered, {ov['count']} kernel(s))")
    fw = rs.get("fusedProgramWallNanos")
    if fw:
        lines.append(f"Fused program wall: {fw['sum'] / 1e6:,.1f}ms "
                     f"over {fw['count']} program(s)")
    cpu = rs.get("driverCpuNanos")
    wall = rs.get("driverWallNanos")
    if cpu and wall and wall.get("sum"):
        # cumulative thread-time vs wall at the driver boundaries: a low
        # ratio means drivers sat waiting (device, exchange, admission)
        # rather than computing
        lines.append(f"Driver CPU/wall: {cpu['sum'] / 1e6:,.1f}ms / "
                     f"{wall['sum'] / 1e6:,.1f}ms "
                     f"({cpu['sum'] / wall['sum']:.2f} busy)")
    sp = rs.get("spillBytes")
    if sp and sp.get("sum"):
        # two-tier spill: bytes staged to the host tier, the fraction of
        # device->host eviction that overlapped operator compute (async
        # staging), and what overflowed on to disk
        ovf = rs.get("spillOverlapFraction")
        frac = (ovf["sum"] / ovf["count"]
                if ovf and ovf.get("count") else 0.0)
        line = (f"Spilled: {sp['sum'] / (1 << 20):,.1f} MB "
                f"({frac * 100:.0f}% overlapped)")
        dk = rs.get("spillDiskBytes")
        if dk and dk.get("sum"):
            line += f", {dk['sum'] / (1 << 20):,.1f} MB to disk"
        lines.append(line)
    sb = rs.get("spoolBytes")
    if sb and sb.get("sum"):
        # retry-policy=task: raw page bytes durably staged through the
        # spooled exchange before the producers acknowledged them
        lines.append(f"Spooled: {sb['sum'] / (1 << 20):,.1f} MB "
                     f"across {sb['count']} task(s)")
    dfc = rs.get("dynamicFiltersCollected")
    dfi = rs.get("dynamicFilterRowsIn")
    if dfc or dfi:
        # runtime dynamic filters: how many build-side domains arrived,
        # how many scans applied one, and the fraction of scanned rows
        # the applied filters removed before the join
        collected = int(dfc["sum"]) if dfc else 0
        applied = int(dfi["count"]) if dfi else 0
        rows_in = int(dfi["sum"]) if dfi else 0
        dfp = rs.get("dynamicFilterRowsPruned")
        pruned = int(dfp["sum"]) if dfp else 0
        pct = 100.0 * pruned / rows_in if rows_in else 0.0
        lines.append(f"Dynamic filters: {collected} collected, "
                     f"{applied} applied, {pct:.1f}% rows pruned")
    flips = rs.get("adaptiveExchangeFlips")
    swaps = rs.get("adaptiveSideSwaps")
    if (flips and flips.get("sum")) or (swaps and swaps.get("sum")):
        # cardinality-driven exchange re-decisions made at stage
        # boundaries from OBSERVED build-side rows (adaptive.exchange)
        lines.append(f"Adaptive decisions: "
                     f"{int(flips['sum']) if flips else 0} "
                     f"exchange(s) flipped to broadcast, "
                     f"{int(swaps['sum']) if swaps else 0} "
                     f"join side swap(s)")
    # serving-plane micro-batching: process-wide counters (the batcher
    # lives above any single execution, so per-run RuntimeStats cannot
    # carry them); shown only once batches have actually formed
    try:
        from ..serving import SERVING_METRICS
        sv = SERVING_METRICS.snapshot()
        if sv.get("servingBatches"):
            occ = (sv["servingBatchQueries"] / sv["servingBatches"])
            lines.append(
                f"Serving micro-batches: {sv['servingBatches']} "
                f"({occ:.1f} avg occupancy, "
                f"{sv['servingBatchLaunchesSaved']} launch(es) saved, "
                f"demux {sv['servingBatchDemuxNanos'] / 1e6:,.1f}ms)")
    except Exception:   # noqa: BLE001 — footer is advisory
        pass
    if profile_dir:
        # where `jax.profiler.trace` wrote this run's device capture
        # (open with tensorboard / xprof)
        lines.append(f"Device profile: {profile_dir}")
    return "\n".join(lines)


def format_validation(diags_by_stage) -> str:
    """EXPLAIN (TYPE VALIDATE) body: one section per checker stage with
    its diagnostic list, "PASSED" for clean stages (the reference's
    VALIDATE explain prints nothing on success; listing each stage shows
    WHICH passes ran)."""
    lines: List[str] = []
    total = 0
    for stage, diags in diags_by_stage:
        lines.append(f"== {stage} ==")
        if not diags:
            lines.append("PASSED")
        else:
            total += len(diags)
            lines.extend(f"  {d}" for d in diags)
        lines.append("")
    lines.append(f"{total} diagnostic(s)"
                 if total else "plan validation PASSED")
    return "\n".join(lines)


def format_subplan(subplan, stats: Optional[Dict[str, dict]] = None) -> str:
    """Fragmented (distributed) plan: one section per fragment."""
    lines: List[str] = []

    def walk(sp, depth: int) -> None:
        f = sp.fragment
        scheme = f.output_partitioning_scheme
        fabric = ("" if getattr(scheme, "fabric", None) is None
                  else f" fabric={scheme.fabric}")
        lines.append(f"Fragment {f.fragment_id} [{f.partitioning}]"
                     f"{fabric}")
        lines.append(format_plan(f.root, stats))
        lines.append("")
        for ch in sp.children:
            walk(ch, depth + 1)

    walk(subplan, 0)
    return "\n".join(lines).rstrip()
