"""Whole-plan rewrites over the logical plan.

The analog of the reference's PlanOptimizers pass list
(PlanOptimizers.java:209), split like the reference's Optimizer: local
algebraic rewrites (filter/limit/projection merging, join-side choice)
run through the iterative rule driver in sql/rules.py; the passes here
need GLOBAL plan context (requirement union across decorrelated copies,
dynamic-filter id allocation) and mutate the plan in place.
"""
from __future__ import annotations

from typing import Dict, Set

from ..spi import plan as P
from ..spi.expr import free_variables


# ---------------------------------------------------------------------------
# unused-output pruning (reference PruneUnreferencedOutputsRule family in
# presto-main-base/.../planner/iterative/rule/): drop columns no ancestor
# reads.  Critical on TPU: a table scan that materializes host-generated
# string columns nobody reads both wastes transfer AND disqualifies the
# scan from whole-pipeline fusion (exec/fused.py requires device-generated
# scans).  Decorrelated plans contain deep-copied subtrees SHARING node
# ids; the pipeline compiler memoizes by id, so requirements are unioned
# per id first and every copy is rewritten identically.
# ---------------------------------------------------------------------------

def prune_unused_outputs(root: P.PlanNode) -> P.PlanNode:
    req: Dict[str, Set[str]] = {}

    def expr_vars(*exprs) -> Set[str]:
        out: Set[str] = set()
        for e in exprs:
            if e is not None:
                out.update(v.name for v in free_variables(e))
        return out

    def visit(node: P.PlanNode, needed: Set[str]) -> None:
        prev = req.get(node.id)
        if prev is not None and needed <= prev:
            return
        needed = (prev or set()) | needed
        req[node.id] = set(needed)
        t = type(node).__name__
        if t == "OutputNode":
            visit(node.source, set(v.name
                                   for v in node.source.output_variables))
        elif t == "ProjectNode":
            child: Set[str] = set()
            for v, e in node.assignments.items():
                if v.name in needed:
                    child |= expr_vars(e)
            if not child:
                # keep at least one input column for row-count semantics
                if node.assignments:
                    child |= expr_vars(next(iter(node.assignments.values())))
                if not child and node.source.output_variables:
                    child.add(node.source.output_variables[0].name)
            visit(node.source, child)
        elif t == "FilterNode":
            visit(node.source, needed | expr_vars(node.predicate))
        elif t == "TableScanNode":
            pass
        elif t == "AggregationNode":
            child = {v.name for v in node.grouping_keys}
            for agg in node.aggregations.values():
                child |= expr_vars(agg.call)
                if agg.mask is not None:
                    child |= expr_vars(agg.mask)
            visit(node.source, child)
        elif t == "JoinNode":
            child = set(needed)
            for l, r in node.criteria:
                child.add(l.name)
                child.add(r.name)
            child |= expr_vars(node.filter)
            visit(node.left, child)
            visit(node.right, child)
        elif t == "SemiJoinNode":
            visit(node.source, (needed - {node.semi_join_output.name})
                  | {node.source_join_variable.name})
            visit(node.filtering_source,
                  {node.filtering_source_join_variable.name})
        elif t in ("SortNode", "TopNNode"):
            keys = {v.name for v, _o in node.ordering_scheme.orderings}
            visit(node.source, needed | keys)
        elif t == "WindowNode":
            child = needed & {v.name for v in node.source.output_variables}
            child |= {v.name for v in node.partition_by}
            if node.ordering_scheme:
                child |= {v.name for v, _o in
                          node.ordering_scheme.orderings}
            for wf in node.window_functions.values():
                child |= expr_vars(wf.call)
            visit(node.source, child)
        elif t == "DistinctLimitNode":
            visit(node.source, {v.name for v in node.distinct_variables})
        elif t == "MarkDistinctNode":
            visit(node.source, (needed - {node.marker.name})
                  | {v.name for v in node.distinct_variables})
        elif t == "AssignUniqueIdNode":
            visit(node.source, needed - {node.id_variable.name})
        elif t in ("LimitNode", "EnforceSingleRowNode"):
            visit(node.source, needed)
        elif t == "UnionNode":
            # every source is projected to the union's output variables;
            # a row-count-only consumer still needs one column to exist
            # in both the union's outputs and its branch projections
            if not needed and node.outputs:
                needed = {node.outputs[0].name}
                req[node.id] = set(needed)
            for s in node.inputs:
                visit(s, set(needed))
        elif t == "ExchangeNode":
            if not node.inputs and len(node.exchange_sources) == 1:
                visit(node.exchange_sources[0], set(needed))
            else:
                for s in node.exchange_sources:
                    visit(s, {v.name for v in s.output_variables})
        else:
            # conservative: require everything below (Values, Unnest,
            # RemoteSource, TableWriter/Finish, unknown nodes)
            for s in node.sources:
                visit(s, {v.name for v in s.output_variables})

    visit(root, {v.name for v in root.output_variables})

    # rewrite pass: every node-id copy sees the same unioned requirement
    def rewrite(node: P.PlanNode) -> None:
        needed = req.get(node.id)
        t = type(node).__name__
        if needed is not None:
            if t == "TableScanNode":
                keep = [v for v in node.outputs if v.name in needed]
                if not keep and node.outputs:
                    # keep one (prefer non-string: stays device-generable)
                    keep = sorted(
                        node.outputs,
                        key=lambda v: type(v.type).__name__
                        in ("VarcharType", "CharType"))[:1]
                if len(keep) != len(node.outputs):
                    node.outputs = keep
                    node.assignments = {v: c for v, c
                                        in node.assignments.items()
                                        if v in keep}
            elif t == "ProjectNode":
                keep = {v: e for v, e in node.assignments.items()
                        if v.name in needed}
                if not keep and node.assignments:
                    v0 = next(iter(node.assignments))
                    keep = {v0: node.assignments[v0]}
                node.assignments = keep
            elif t == "JoinNode":
                keep = [v for v in node.outputs if v.name in needed]
                if not keep and node.outputs:
                    # keep one probe column for row-count semantics
                    left_names = {v.name for v in
                                  node.left.output_variables}
                    keep = ([v for v in node.outputs
                             if v.name in left_names]
                            or node.outputs)[:1]
                node.outputs = keep
            elif t == "UnionNode":
                # branch projections were pruned to `needed`; the union's
                # own output list must shrink with them or the union
                # compile demands columns no branch carries
                keep = [v for v in node.outputs if v.name in needed]
                if not keep and node.outputs:
                    keep = node.outputs[:1]
                node.outputs = keep
        for s in node.sources:
            rewrite(s)

    rewrite(root)
    return root


def plan_dynamic_filters(root: P.PlanNode) -> P.PlanNode:
    """Annotate joins with dynamic filters (reference
    DynamicFilterSourceOperator + LocalDynamicFilter planning).  Keys of
    `dynamic_filters` are the RECEIVING variables — the side whose rows
    the filter may drop — and a filter may only ever shrink a
    NON-PRESERVED side:

    - INNER: the probe (left) receives the build (right) key domain;
      applied intra-task before the probe step AND cross-stage as
      runtime scan pushdown (plan_runtime_filter_pushdown).
    - LEFT: the probe is preserved (unmatched rows survive
      null-extended), so it must NEVER be filtered — but the build side
      is not preserved: build rows no probe key can match produce
      nothing, so the probe key domain may prune BUILD scans.  RIGHT
      joins were normalized to LEFT-with-swapped-sides by the planner
      before this pass, so they take this path with the original probe
      side receiving.
    - FULL: both sides preserved; no filter is safe.
    - SemiJoinNode: the source receives the filtering-source domain,
      but ONLY when the membership marker is consumed as a bare
      positive filter conjunct — then a source row outside the domain
      would get marker NULL/false and be dropped by that filter anyway.
      Under negation (NOT IN) the marker's false/NULL rows are the ones
      that SURVIVE, so dropping them early would be wrong.
    """
    from ..spi.expr import VariableReferenceExpression
    from ..storage.pushdown import split_conjuncts

    positive_markers = set()
    for node in P.walk_plan(root):
        if isinstance(node, P.FilterNode):
            for c in split_conjuncts(node.predicate):
                if isinstance(c, VariableReferenceExpression):
                    positive_markers.add(c.name)

    n = 0
    for node in P.walk_plan(root):
        if isinstance(node, P.JoinNode) and node.criteria:
            if node.join_type == P.INNER:
                node.dynamic_filters = {
                    l.name: f"df_{n}_{i}"
                    for i, (l, _r) in enumerate(node.criteria)}
                n += 1
            elif node.join_type == P.LEFT:
                node.dynamic_filters = {
                    r.name: f"df_{n}_{i}"
                    for i, (_l, r) in enumerate(node.criteria)}
                n += 1
        elif isinstance(node, P.SemiJoinNode) \
                and node.semi_join_output.name in positive_markers:
            node.dynamic_filters = {
                node.source_join_variable.name: f"df_{n}_0"}
            n += 1
    return root


def _runtime_filter_pairs(node):
    """(receiving var name, source var name, fid, receiving subtree)
    tuples for one annotated node, honoring the direction convention
    documented on plan_dynamic_filters."""
    out = []
    if isinstance(node, P.JoinNode):
        for i, (l, r) in enumerate(node.criteria):
            if node.join_type == P.INNER and l.name in node.dynamic_filters:
                out.append((l.name, r.name,
                            node.dynamic_filters[l.name], node.left))
            elif node.join_type == P.LEFT \
                    and r.name in node.dynamic_filters:
                out.append((r.name, l.name,
                            node.dynamic_filters[r.name], node.right))
    elif isinstance(node, P.SemiJoinNode):
        sv = node.source_join_variable.name
        if sv in node.dynamic_filters:
            out.append((sv, node.filtering_source_join_variable.name,
                        node.dynamic_filters[sv], node.source))
    return out


def plan_runtime_filter_pushdown(root: P.PlanNode) -> P.PlanNode:
    """Push each dynamic filter's receiving key down to its table scans
    as RUNTIME pushdown (the cross-stage half of dynamic filtering,
    reference analog DynamicFilterService + TupleDomain pushdown).

    Each reachable scan gets a `runtime_filters` annotation plus
    ``["dyn", fid, min|max|set]`` marker entries in `pushdown`, resolved
    at prune time from the summary a completed filter-source stage
    published (exec/adaptive.py).  Unresolved markers keep every chunk,
    so annotation is always safe to plan; correctness only requires that
    every row dropped at the scan would have been dropped by the
    annotated join anyway.  That holds when the path from scan to join
    is strictly row-preserving-or-narrowing for the traced key — bare
    Project renames and Filters.  Anything else (aggregations, limits,
    sorts, unions) stops the descent, and a scan whose node id appears
    more than once in the plan (decorrelated shared subtree — the
    pipeline compiler memoizes by id) is never annotated: another
    consumer outside the join could observe the missing rows."""
    from collections import Counter
    from ..spi.expr import VariableReferenceExpression

    occurrences: Counter = Counter()

    def count(node):
        occurrences[node.id] += 1
        for s in node.sources:
            count(s)
    count(root)

    def trace(node, var_name, out):
        if isinstance(node, P.TableScanNode):
            if occurrences[node.id] != 1:
                return
            for v, col in node.assignments.items():
                if v.name == var_name:
                    out.append((node, col.name))
            return
        if isinstance(node, P.ProjectNode):
            e = next((e for v, e in node.assignments.items()
                      if v.name == var_name), None)
            if isinstance(e, VariableReferenceExpression):
                trace(node.source, e.name, out)
            return
        if isinstance(node, P.FilterNode):
            trace(node.source, var_name, out)
            return
        if isinstance(node, P.ExchangeNode):
            # inputs[i][j] feeds output_layout[j] from source i
            layout = node.partitioning_scheme.output_layout
            idx = next((j for j, v in enumerate(layout)
                        if v.name == var_name), None)
            if idx is None:
                return
            for i, src in enumerate(node.exchange_sources):
                row = node.inputs[i] if i < len(node.inputs) else None
                trace(src, row[idx].name if row else var_name, out)
            return
        # conservative stop: any other node may change which rows exist
        # (aggregation, limit) or carry the variable non-positionally

    for node in P.walk_plan(root):
        if not getattr(node, "dynamic_filters", None):
            continue
        for recv, _src, fid, subtree in _runtime_filter_pairs(node):
            scans = []
            trace(subtree, recv, scans)
            for scan, col in scans:
                if any(e.get("id") == fid and e.get("column") == col
                       for e in scan.runtime_filters):
                    continue
                scan.runtime_filters.append({"id": fid, "column": col})
                scan.pushdown.extend((
                    {"column": col, "op": "gte", "value": ["dyn", fid, "min"]},
                    {"column": col, "op": "lte", "value": ["dyn", fid, "max"]},
                    {"column": col, "op": "eq", "value": ["dyn", fid, "set"]}))
    return root


def plan_scan_pushdown(root: P.PlanNode) -> P.PlanNode:
    """Record range/equality-shaped conjuncts of a filter sitting directly
    on a table scan as the scan's pushdown metadata (the reference analog
    is PickTableLayout/TupleDomain pushdown into the connector).

    The FilterNode is NOT removed: pushdown here is advisory, consumed by
    the resident-storage scan for zone-map chunk skipping
    (storage/pushdown.py), and the residual exact filter preserves
    semantics unconditionally.  Runs after the iterative rules so filter
    merging/pushdown has already parked each scan's conjunction directly
    above it."""
    from ..storage.pushdown import extract_pushdown
    for node in P.walk_plan(root):
        if not isinstance(node, P.FilterNode) \
                or not isinstance(node.source, P.TableScanNode):
            continue
        scan = node.source
        var_to_col = {v.name: c.name for v, c in scan.assignments.items()}
        scan.pushdown = extract_pushdown(node.predicate, var_to_col)
    return root


def hoist_join_filter_string_calls(root: P.PlanNode) -> P.PlanNode:
    """Rewrite substr/like calls inside JOIN ON-filters into columns
    projected below the join when their argument is an open-domain
    (late-materialized) scan column.  A join filter evaluates inside the
    jitted probe step where a lazy column holds row ids and host hoisting
    cannot run; a projection below the join takes the Filter/Project
    hoisting path instead (the reference's analog is PushdownSubfields +
    expression pushdown below the join)."""
    from ..connectors import catalog
    from ..exec.lowering import canonical_name
    from ..spi.expr import (CallExpression, SpecialFormExpression,
                            VariableReferenceExpression)

    # variable name -> (table, column) for open-domain scan outputs
    open_vars: Dict[str, tuple] = {}
    for n in P.walk_plan(root):
        if isinstance(n, P.TableScanNode):
            for v in n.outputs:
                ch = n.assignments.get(v)
                if ch is not None and \
                        (n.table.table_name, ch.name) in catalog.OPEN_DOMAIN:
                    open_vars[v.name] = (n.table.table_name, ch.name)

    if not open_vars:
        return root
    counter = [0]

    def rewrite_filter(e, side_injections):
        if isinstance(e, CallExpression):
            name = canonical_name(e.display_name)
            if name in ("like", "substr") and e.arguments and isinstance(
                    e.arguments[0], VariableReferenceExpression) \
                    and e.arguments[0].name in open_vars:
                counter[0] += 1
                v = VariableReferenceExpression(
                    f"__jfhoist_{counter[0]}", e.type)
                side_injections.setdefault(
                    e.arguments[0].name, {})[v] = e
                return v
            return CallExpression(
                e.display_name, e.type,
                [rewrite_filter(a, side_injections) for a in e.arguments])
        if isinstance(e, SpecialFormExpression):
            return SpecialFormExpression(
                e.form, e.type,
                [rewrite_filter(a, side_injections) for a in e.arguments])
        return e

    def visit(node: P.PlanNode) -> None:
        for s in node.sources:
            visit(s)
        if not isinstance(node, P.JoinNode) or node.filter is None:
            return
        injections: Dict[str, Dict] = {}
        new_filter = rewrite_filter(node.filter, injections)
        if not injections:
            return
        for side_attr in ("left", "right"):
            side = getattr(node, side_attr)
            names = {v.name for v in side.output_variables}
            assigns = {}
            for src_name, mapping in injections.items():
                if src_name in names:
                    assigns.update(mapping)
            if assigns:
                full = {v: v for v in side.output_variables}
                full.update(assigns)
                setattr(node, side_attr, P.ProjectNode(
                    f"{node.id}.jfhoist_{side_attr}", side, full))
        node.filter = new_filter

    visit(root)
    return root


def optimize(root: P.PlanNode) -> P.PlanNode:
    """Reference Optimizer.java sequence, compressed: whole-plan passes
    (hoisting, pruning, dynamic filters) around the iterative rule driver
    (sql/rules.py).  Per-rule hit counts ride the root node for EXPLAIN
    (the reference's optimizerInformation)."""
    from .rules import DEFAULT_RULES, IterativeOptimizer
    root = hoist_join_filter_string_calls(root)
    rule_stats: Dict[str, int] = {}
    root = IterativeOptimizer(DEFAULT_RULES).run(root, rule_stats)
    root = prune_unused_outputs(root)
    root = plan_dynamic_filters(root)
    root = plan_scan_pushdown(root)
    root = plan_runtime_filter_pushdown(root)
    root.rule_stats = rule_stats
    return root
