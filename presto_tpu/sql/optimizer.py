"""Cost-based plan rewrites over the logical plan.

The (much smaller) analog of the reference's PlanOptimizers pass list
(PlanOptimizers.java:209).  Passes mutate the plan in place, like the
fragmenter's distribution planner does.

Current passes:
  * determine_join_sides — put the smaller estimated side on the BUILD
    (right) side of inner hash joins (reference
    DetermineJoinDistributionType / ReorderJoins' side selection): the
    executor builds its sorted lookup table from the right input, so a
    large build side costs sort+memory where a probe-side scan would
    stream.
"""
from __future__ import annotations

from ..spi import plan as P
from .stats import StatsCalculator

SWAP_RATIO = 1.25     # hysteresis: only swap on a clear size difference


def determine_join_sides(root: P.PlanNode,
                         calc: StatsCalculator = None) -> P.PlanNode:
    calc = calc or StatsCalculator()
    for n in P.walk_plan(root):
        if isinstance(n, P.JoinNode) and n.join_type == P.INNER \
                and n.criteria:
            l = calc.rows(n.left)
            r = calc.rows(n.right)
            if l is not None and r is not None and r > l * SWAP_RATIO:
                n.left, n.right = n.right, n.left
                n.criteria = [(rv, lv) for lv, rv in n.criteria]
    return root


def optimize(root: P.PlanNode) -> P.PlanNode:
    return determine_join_sides(root)
