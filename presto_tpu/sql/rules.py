"""Iterative rule-based plan optimizer.

The skeleton of the reference's IterativeOptimizer
(presto-main-base/.../sql/planner/iterative/IterativeOptimizer.java:62 +
the presto-matching pattern DSL, Match.java:22), compressed for this
engine: a rule declares the node class it matches and a pure `apply`
returning a replacement subtree (or None for no match); the driver
rewrites bottom-up to a fixpoint under an exploration budget, recording
per-rule hit counts that EXPLAIN surfaces (the reference's
optimizerInformation).

Rules are ported from the reference's iterative rule set
(presto-main-base/.../planner/iterative/rule/): filter/limit/projection
algebra plus the cost-based join-side choice.  Whole-plan passes that
need global context (column pruning, dynamic filters) stay in
optimizer.py, mirroring the reference's PlanOptimizer/IterativeOptimizer
split (PlanOptimizers.java:209).

Node identity: rewrites keep the REPLACED node's id, so decorrelated
deep-copied subtrees (which share ids) rewrite identically in every copy
and the pipeline compiler's per-id memo stays coherent.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple, Type

from ..spi import plan as P
from ..spi.expr import (CallExpression, ConstantExpression, RowExpression,
                        SpecialFormExpression, VariableReferenceExpression,
                        and_, free_variables)

EXPLORATION_BUDGET = 10_000     # total rule firings per plan


# ---------------------------------------------------------------------------
# expression utilities
# ---------------------------------------------------------------------------

def substitute(expr: RowExpression,
               mapping: Dict[str, RowExpression]) -> RowExpression:
    """Replace variable references by name (pure; shared subtrees reused
    when nothing changes underneath)."""
    if isinstance(expr, VariableReferenceExpression):
        return mapping.get(expr.name, expr)
    if isinstance(expr, CallExpression):
        args = [substitute(a, mapping) for a in expr.arguments]
        if all(a is b for a, b in zip(args, expr.arguments)):
            return expr
        return CallExpression(expr.display_name, expr.type, args)
    if isinstance(expr, SpecialFormExpression):
        args = [substitute(a, mapping) for a in expr.arguments]
        if all(a is b for a, b in zip(args, expr.arguments)):
            return expr
        return SpecialFormExpression(expr.form, expr.type, args)
    return expr


def _empty_values(node: P.PlanNode) -> P.ValuesNode:
    return P.ValuesNode(node.id, list(node.output_variables), [])


# ---------------------------------------------------------------------------
# the rule protocol + driver
# ---------------------------------------------------------------------------

class Rule:
    """One rewrite: `node_class` is the match pattern root (reference
    Pattern.typeOf), `apply` returns the replacement or None."""
    name: str = "rule"
    node_class: Tuple[Type, ...] = ()

    def apply(self, node: P.PlanNode,
              ctx: "RuleContext") -> Optional[P.PlanNode]:
        raise NotImplementedError


class RuleContext:
    def __init__(self):
        from .stats import StatsCalculator
        self.stats = StatsCalculator()


_CHILD_ATTRS = ("source", "left", "right", "filtering_source")
_CHILD_LIST_ATTRS = ("inputs", "exchange_sources")


def _set_child(parent: P.PlanNode, old: P.PlanNode,
               new: P.PlanNode) -> bool:
    for attr in _CHILD_ATTRS:
        if getattr(parent, attr, None) is old:
            setattr(parent, attr, new)
            return True
    for attr in _CHILD_LIST_ATTRS:
        lst = getattr(parent, attr, None)
        if isinstance(lst, list):
            for i, x in enumerate(lst):
                if x is old:
                    lst[i] = new
                    return True
    return False


class IterativeOptimizer:
    def __init__(self, rules: List[Rule]):
        self._by_class: Dict[type, List[Rule]] = {}
        self.rules = rules

    def _rules_for(self, node: P.PlanNode) -> List[Rule]:
        cls = type(node)
        cached = self._by_class.get(cls)
        if cached is None:
            cached = [r for r in self.rules
                      if isinstance(node, r.node_class)]
            self._by_class[cls] = cached
        return cached

    def run(self, root: P.PlanNode,
            stats: Optional[Dict[str, int]] = None) -> P.PlanNode:
        ctx = RuleContext()
        budget = [EXPLORATION_BUDGET]
        stats = stats if stats is not None else {}
        # plan_validation=strict: validate the replacement subtree after
        # every firing so a violation is attributed to the rule that
        # introduced it (the whole tree is mid-rewrite bottom-up, so only
        # the subtree is consistent here; parent-level breakage is caught
        # by the post-optimize pass)
        from ..analysis import VALIDATION_STRICT, validation_mode
        strict = validation_mode() == VALIDATION_STRICT

        def explore(node: P.PlanNode) -> P.PlanNode:
            for s in list(node.sources):
                ns = explore(s)
                if ns is not s:
                    _set_child(node, s, ns)
            progress = True
            while progress and budget[0] > 0:
                progress = False
                for rule in self._rules_for(node):
                    out = rule.apply(node, ctx)
                    if out is not None and out is not node:
                        budget[0] -= 1
                        stats[rule.name] = stats.get(rule.name, 0) + 1
                        if strict:
                            from ..analysis import validate_plan
                            validate_plan(out, f"rule:{rule.name}")
                        node = explore(out)
                        progress = True
                        break
            return node

        return explore(root)


# ---------------------------------------------------------------------------
# rules (reference analogs cited per rule)
# ---------------------------------------------------------------------------

class MergeFilters(Rule):
    """Filter(Filter(x)) -> Filter(x) with ANDed predicate
    (iterative/rule/MergeFilters.java)."""
    name = "MergeFilters"
    node_class = (P.FilterNode,)

    def apply(self, node, ctx):
        if not isinstance(node.source, P.FilterNode):
            return None
        inner = node.source
        return P.FilterNode(node.id, inner.source,
                            and_(inner.predicate, node.predicate))


class RemoveTrivialFilters(Rule):
    """Constant TRUE predicate -> drop the filter; FALSE/NULL -> empty
    values (iterative/rule/RemoveTrivialFilters.java)."""
    name = "RemoveTrivialFilters"
    node_class = (P.FilterNode,)

    def apply(self, node, ctx):
        p = node.predicate
        if isinstance(p, ConstantExpression):
            if p.value is True:
                return node.source
            if p.value in (False, None):
                return _empty_values(node)
        return None


class MergeLimits(Rule):
    """Limit(Limit(x)) -> Limit(x, min) (iterative/rule/MergeLimits.java)."""
    name = "MergeLimits"
    node_class = (P.LimitNode,)

    def apply(self, node, ctx):
        if not isinstance(node.source, P.LimitNode):
            return None
        return P.LimitNode(node.id, node.source.source,
                           min(node.count, node.source.count), node.step)


class EvaluateZeroLimit(Rule):
    """LIMIT 0 -> empty values (iterative/rule/EvaluateZeroLimit.java)."""
    name = "EvaluateZeroLimit"
    node_class = (P.LimitNode, P.TopNNode)

    def apply(self, node, ctx):
        if node.count == 0:
            return _empty_values(node)
        return None


class CreateTopN(Rule):
    """Limit(Sort(x)) -> TopN(x) (iterative/rule/CreateTopN.java — the
    O(n log n) full sort becomes a bounded heap; on this engine a bounded
    device sort per batch)."""
    name = "CreateTopN"
    node_class = (P.LimitNode,)

    def apply(self, node, ctx):
        if not isinstance(node.source, P.SortNode):
            return None
        sort = node.source
        return P.TopNNode(node.id, sort.source, node.count,
                          sort.ordering_scheme)


class PushLimitThroughProject(Rule):
    """Limit(Project(x)) -> Project(Limit(x))
    (iterative/rule/PushLimitThroughProject.java): the limit cuts rows
    before projection work."""
    name = "PushLimitThroughProject"
    node_class = (P.LimitNode,)

    def apply(self, node, ctx):
        if not isinstance(node.source, P.ProjectNode):
            return None
        proj = node.source
        return P.ProjectNode(proj.id,
                             P.LimitNode(node.id, proj.source, node.count,
                                         node.step),
                             proj.assignments)


class RemoveIdentityProjection(Rule):
    """Project that re-emits exactly its input variables -> source
    (iterative/rule/RemoveRedundantIdentityProjections.java)."""
    name = "RemoveIdentityProjection"
    node_class = (P.ProjectNode,)

    def apply(self, node, ctx):
        src_vars = node.source.output_variables
        if len(node.assignments) != len(src_vars):
            return None
        src_names = [v.name for v in src_vars]
        out_names = []
        for v, e in node.assignments.items():
            if not (isinstance(e, VariableReferenceExpression)
                    and e.name == v.name):
                return None
            out_names.append(v.name)
        if out_names != src_names:
            return None     # a reorder is not identity for positional users
        return node.source


class InlineProjections(Rule):
    """Project(Project(x)) -> one Project when the inner is pure
    renames/constants (iterative/rule/InlineProjections.java, restricted
    to substitutions that cannot duplicate computation)."""
    name = "InlineProjections"
    node_class = (P.ProjectNode,)

    def apply(self, node, ctx):
        if not isinstance(node.source, P.ProjectNode):
            return None
        inner = node.source
        if not all(isinstance(e, (VariableReferenceExpression,
                                  ConstantExpression))
                   for e in inner.assignments.values()):
            return None
        mapping = {v.name: e for v, e in inner.assignments.items()}
        merged = {v: substitute(e, mapping)
                  for v, e in node.assignments.items()}
        return P.ProjectNode(node.id, inner.source, merged)


class PushFilterThroughProject(Rule):
    """Filter(Project(x)) -> Project(Filter(x)) when the predicate only
    reads renamed/constant columns (PredicatePushDown through projections,
    PredicatePushDown.java) — unlocks scan-adjacent filtering and chain
    fusion."""
    name = "PushFilterThroughProject"
    node_class = (P.FilterNode,)

    def apply(self, node, ctx):
        if not isinstance(node.source, P.ProjectNode):
            return None
        proj = node.source
        mapping = {v.name: e for v, e in proj.assignments.items()}
        for v in free_variables(node.predicate):
            e = mapping.get(v.name)
            if not isinstance(e, (VariableReferenceExpression,
                                  ConstantExpression)):
                return None
        pred = substitute(node.predicate, mapping)
        return P.ProjectNode(proj.id,
                             P.FilterNode(node.id, proj.source, pred),
                             proj.assignments)


class SwapJoinSides(Rule):
    """Put the smaller estimated side on the build (right) side of an
    inner equi join (DetermineJoinDistributionType.java /
    ReorderJoins.java side choice; hysteresis avoids flip-flopping on
    close estimates)."""
    name = "SwapJoinSides"
    node_class = (P.JoinNode,)
    RATIO = 1.25

    def apply(self, node, ctx):
        if node.join_type != P.INNER or not node.criteria:
            return None
        left = ctx.stats.rows(node.left)
        right = ctx.stats.rows(node.right)
        if left is None or right is None or right <= left * self.RATIO:
            return None
        return P.JoinNode(node.id, node.join_type, node.right, node.left,
                          [(r, l) for l, r in node.criteria],
                          node.outputs, node.filter, node.distribution,
                          dict(node.dynamic_filters))


class MergeLimitWithDistinct(Rule):
    """Limit(Aggregation[no aggregates, keys=outputs]) -> DistinctLimit
    (iterative/rule/MergeLimitWithDistinct.java)."""
    name = "MergeLimitWithDistinct"
    node_class = (P.LimitNode,)

    def apply(self, node, ctx):
        agg = node.source
        if not isinstance(agg, P.AggregationNode) or agg.aggregations:
            return None
        if not agg.grouping_keys or agg.step != P.SINGLE:
            return None
        return P.DistinctLimitNode(node.id, agg.source, node.count,
                                   list(agg.grouping_keys))


class MergeLimitWithTopN(Rule):
    """Limit(TopN(x)) -> TopN(x, min)
    (iterative/rule/MergeLimitWithTopN.java)."""
    name = "MergeLimitWithTopN"
    node_class = (P.LimitNode,)

    def apply(self, node, ctx):
        if not isinstance(node.source, P.TopNNode):
            return None
        t = node.source
        return P.TopNNode(node.id, t.source, min(node.count, t.count),
                          t.ordering_scheme, t.step)


DEFAULT_RULES: List[Rule] = [
    RemoveTrivialFilters(),      # before MergeFilters: don't AND-in TRUE
    MergeFilters(),
    EvaluateZeroLimit(),
    MergeLimits(),
    MergeLimitWithTopN(),
    CreateTopN(),
    PushLimitThroughProject(),
    RemoveIdentityProjection(),
    InlineProjections(),
    PushFilterThroughProject(),
    SwapJoinSides(),
    MergeLimitWithDistinct(),
]
