"""Plan statistics: column stats + cardinality/selectivity estimation.

The analog of the reference's cost module (presto-main-base/.../cost/,
~9k LoC: StatsCalculator + per-node rules like FilterStatsCalculator /
JoinStatsRule) reduced to what drives real decisions here:

  * predicate selectivity from column (low, high, ndv, null_fraction)
    stats — range interpolation for comparisons, 1/ndv for equality,
    AND/OR/NOT composition (FilterStatsCalculator.java semantics);
  * join output cardinality |L|x|R| / max(ndv(l), ndv(r)) per equi-clause
    (JoinStatsRule.java);
  * aggregation group counts capped by the product of key NDVs.

Connector column stats are duck-typed: a connector module may expose
`column_stats(table, column, sf) -> ColumnStats | None` (the
ConnectorMetadata.getTableStatistics analog).  tpch/tpcds derive stats
analytically from their generator specs; the hive connector reads parquet
row-group metadata.

Consumers: the fragmenter's broadcast-vs-partitioned decision, the
build-side-swap optimizer pass (sql/optimizer.py), and EXPLAIN's per-node
`rows≈` annotations.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from decimal import Decimal
from typing import Dict, Optional

import numpy as np

from ..spi import plan as P
from ..spi.expr import (CallExpression, ConstantExpression, RowExpression,
                        SpecialFormExpression, VariableReferenceExpression)

UNKNOWN_FILTER_COEFFICIENT = 0.9   # reference: FilterStatsCalculator


@dataclass(frozen=True)
class ColumnStats:
    low: Optional[float] = None
    high: Optional[float] = None
    ndv: Optional[float] = None
    null_fraction: float = 0.0


@dataclass
class PlanStats:
    rows: Optional[float]
    columns: Dict[str, ColumnStats]

    def col(self, name: str) -> ColumnStats:
        return self.columns.get(name, ColumnStats())


def _const_float(e: ConstantExpression) -> Optional[float]:
    v = e.value
    if v is None:
        return None
    if isinstance(v, Decimal):
        return float(v)
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:   # date literals arrive as 'YYYY-MM-DD'
            return float(np.datetime64(v, "D").astype(np.int64))
        except ValueError:
            return None
    return None


def _canon(name: str) -> str:
    return name.lower().split(".")[-1].lstrip("$").replace("$operator$", "")


class StatsCalculator:
    """Memoized bottom-up estimator over a plan tree."""

    def __init__(self):
        self._memo: Dict[str, PlanStats] = {}

    def stats(self, node: P.PlanNode) -> PlanStats:
        got = self._memo.get(node.id)
        if got is None:
            fn = getattr(self, "_stats_" + type(node).__name__, None)
            got = fn(node) if fn else self._passthrough(node)
            self._memo[node.id] = got
        return got

    def rows(self, node: P.PlanNode) -> Optional[float]:
        return self.stats(node).rows

    # -- leaves -----------------------------------------------------------
    def _stats_TableScanNode(self, node: P.TableScanNode) -> PlanStats:
        from ..connectors import catalog
        th = node.table
        sf = dict(th.extra).get("scaleFactor", 0.01)
        try:
            conn = catalog.module(th.connector_id)
            rows = float(conn.table_row_count(th.table_name, sf))
        except Exception:
            return PlanStats(None, {})
        cols: Dict[str, ColumnStats] = {}
        stats_fn = getattr(conn, "column_stats", None)
        if stats_fn is not None:
            for v in node.outputs:
                cs = stats_fn(th.table_name, node.assignments[v].name, sf)
                if cs is not None:
                    cols[v.name] = cs
        return PlanStats(rows, cols)

    def _stats_ValuesNode(self, node: P.ValuesNode) -> PlanStats:
        return PlanStats(float(len(node.rows)), {})

    # -- streaming --------------------------------------------------------
    def _passthrough(self, node: P.PlanNode) -> PlanStats:
        srcs = node.sources
        if not srcs:
            return PlanStats(None, {})
        return self.stats(srcs[0])

    def _stats_FilterNode(self, node: P.FilterNode) -> PlanStats:
        src = self.stats(node.source)
        if src.rows is None:
            return src
        sel, cols = self._selectivity(node.predicate, src)
        return PlanStats(max(0.0, src.rows * sel), cols)

    def _stats_ProjectNode(self, node: P.ProjectNode) -> PlanStats:
        src = self.stats(node.source)
        cols = {}
        for v, e in node.assignments.items():
            if isinstance(e, VariableReferenceExpression):
                cols[v.name] = src.col(e.name)
            elif isinstance(e, CallExpression) and \
                    _canon(e.display_name) == "cast" and e.arguments and \
                    isinstance(e.arguments[0], VariableReferenceExpression):
                cols[v.name] = src.col(e.arguments[0].name)
        return PlanStats(src.rows, cols)

    def _stats_OutputNode(self, node: P.OutputNode) -> PlanStats:
        return self.stats(node.source)

    def _stats_LimitNode(self, node) -> PlanStats:
        src = self.stats(node.source)
        rows = (float(node.count) if src.rows is None
                else min(float(node.count), src.rows))
        return PlanStats(rows, src.columns)

    _stats_TopNNode = _stats_LimitNode
    _stats_DistinctLimitNode = _stats_LimitNode

    def _stats_AggregationNode(self, node: P.AggregationNode) -> PlanStats:
        src = self.stats(node.source)
        if not node.grouping_keys:
            return PlanStats(1.0, {})
        if src.rows is None:
            return PlanStats(None, {})
        groups = 1.0
        known = False
        for v in node.grouping_keys:
            ndv = src.col(v.name).ndv
            if ndv is not None:
                groups *= max(1.0, ndv)
                known = True
        if not known:
            groups = max(1.0, src.rows * 0.1)
        cols = {v.name: src.col(v.name) for v in node.grouping_keys}
        return PlanStats(min(groups, src.rows), cols)

    def _stats_JoinNode(self, node: P.JoinNode) -> PlanStats:
        l, r = self.stats(node.left), self.stats(node.right)
        cols = {**r.columns, **l.columns}
        if l.rows is None or r.rows is None:
            return PlanStats(None, cols)
        if not node.criteria:     # cross join
            rows = l.rows * r.rows
        else:
            rows = l.rows * r.rows
            for lv, rv in node.criteria:
                ndv = max(l.col(lv.name).ndv or 1.0,
                          r.col(rv.name).ndv or 1.0)
                rows /= max(1.0, ndv)
        if node.join_type == P.LEFT:
            rows = max(rows, l.rows)
        elif node.join_type == P.RIGHT:
            rows = max(rows, r.rows)
        elif node.join_type == P.FULL:
            rows = max(rows, l.rows, r.rows)
        return PlanStats(rows, cols)

    def _stats_SemiJoinNode(self, node: P.SemiJoinNode) -> PlanStats:
        src = self.stats(node.source)
        return PlanStats(src.rows, src.columns)

    def _stats_UnionNode(self, node: P.UnionNode) -> PlanStats:
        ests = [self.stats(s).rows for s in node.sources]
        if any(e is None for e in ests):
            return PlanStats(None, {})
        return PlanStats(float(sum(ests)), {})

    def _stats_ExchangeNode(self, node) -> PlanStats:
        ests = [self.stats(s) for s in node.sources]
        rows = [e.rows for e in ests]
        if any(e is None for e in rows):
            return PlanStats(None, ests[0].columns if ests else {})
        return PlanStats(float(sum(rows)), ests[0].columns if ests else {})

    # -- predicate selectivity -------------------------------------------
    def _selectivity(self, e: RowExpression, src: PlanStats):
        """Returns (selectivity, post-filter column stats)."""
        if isinstance(e, SpecialFormExpression):
            form = e.form.upper()
            if form == "AND":
                sel, cols = 1.0, dict(src.columns)
                cur = src
                for a in e.arguments:
                    s, cols = self._selectivity(a, cur)
                    sel *= s
                    cur = PlanStats(src.rows, cols)
                return sel, cols
            if form == "OR":
                sels = [self._selectivity(a, src)[0] for a in e.arguments]
                out = 0.0
                for s in sels:
                    out = out + s - out * s
                return out, dict(src.columns)
            if form == "IN":
                # IN (v1, v2, ...): value-list membership
                var = e.arguments[0]
                if isinstance(var, VariableReferenceExpression):
                    ndv = src.col(var.name).ndv
                    n = len(e.arguments) - 1
                    if ndv:
                        return min(1.0, n / ndv), dict(src.columns)
                return UNKNOWN_FILTER_COEFFICIENT, dict(src.columns)
        if isinstance(e, CallExpression):
            name = _canon(e.display_name)
            args = e.arguments
            if name == "not" and len(args) == 1:
                s, _ = self._selectivity(args[0], src)
                return 1.0 - s, dict(src.columns)
            if name == "between" and len(args) == 3 and \
                    isinstance(args[0], VariableReferenceExpression):
                v = args[0]
                lo = _maybe_const(args[1])
                hi = _maybe_const(args[2])
                return self._range_sel(src, v.name, lo, hi)
            cmp_ops = {"lt": "lt", "lte": "lte", "gt": "gt", "gte": "gte",
                       "less_than": "lt", "less_than_or_equal": "lte",
                       "greater_than": "gt",
                       "greater_than_or_equal": "gte",
                       "eq": "eq", "equal": "eq",
                       "neq": "neq", "not_equal": "neq"}
            if name in cmp_ops and len(args) == 2:
                op = cmp_ops[name]
                a, b = args
                if isinstance(b, VariableReferenceExpression) and \
                        isinstance(a, ConstantExpression):
                    a, b = b, a
                    op = {"lt": "gt", "lte": "gte", "gt": "lt",
                          "gte": "lte"}.get(op, op)
                if isinstance(a, VariableReferenceExpression) and \
                        isinstance(b, ConstantExpression):
                    return self._cmp_sel(src, a.name, op, b)
        return UNKNOWN_FILTER_COEFFICIENT, dict(src.columns)

    def _cmp_sel(self, src: PlanStats, var: str, op: str,
                 const: ConstantExpression):
        cs = src.col(var)
        cols = dict(src.columns)
        c = _const_float(const)
        if op == "eq":
            if cs.ndv:
                cols[var] = replace(cs, ndv=1.0,
                                    low=c if c is not None else cs.low,
                                    high=c if c is not None else cs.high)
                return min(1.0, 1.0 / cs.ndv), cols
            return UNKNOWN_FILTER_COEFFICIENT, cols
        if op == "neq":
            if cs.ndv:
                return 1.0 - min(1.0, 1.0 / cs.ndv), cols
            return UNKNOWN_FILTER_COEFFICIENT, cols
        if c is None or cs.low is None or cs.high is None \
                or cs.high <= cs.low:
            return UNKNOWN_FILTER_COEFFICIENT, cols
        frac = (c - cs.low) / (cs.high - cs.low)
        frac = min(1.0, max(0.0, frac))
        if op in ("lt", "lte"):
            cols[var] = replace(cs, high=min(cs.high, c))
            return frac if frac > 0 else 0.0, cols
        cols[var] = replace(cs, low=max(cs.low, c))
        return 1.0 - frac, cols

    def _range_sel(self, src: PlanStats, var: str,
                   lo: Optional[float], hi: Optional[float]):
        cs = src.col(var)
        cols = dict(src.columns)
        if lo is None or hi is None or cs.low is None or cs.high is None \
                or cs.high <= cs.low:
            return UNKNOWN_FILTER_COEFFICIENT, cols
        inter_lo = max(lo, cs.low)
        inter_hi = min(hi, cs.high)
        if inter_hi < inter_lo:
            return 0.0, cols
        cols[var] = replace(cs, low=inter_lo, high=inter_hi)
        return (inter_hi - inter_lo) / (cs.high - cs.low), cols


def _maybe_const(e) -> Optional[float]:
    return _const_float(e) if isinstance(e, ConstantExpression) else None


def estimate(node: P.PlanNode) -> Optional[float]:
    """One-shot row estimate (fresh memo)."""
    return StatsCalculator().rows(node)
