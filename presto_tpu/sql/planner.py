"""Analyzer + logical planner: SQL AST -> typed PlanNode IR.

Compresses the reference's Analyzer -> LogicalPlanner -> optimizer pipeline
(presto-main-base/.../sql/analyzer/Analyzer.java:101,
sql/planner/LogicalPlanner.java:142, optimizations/PredicatePushDown.java,
PushdownSubfields.java) into one pass sized for the TPC-H/TPC-DS query shapes:
scope-based name resolution, Presto type analysis (decimal precision/scale
rules from DecimalOperators), column pruning at the scan, single-table
predicate pushdown below joins, left-deep join tree construction from
FROM-order with equi-criteria extraction, and aggregation rewrite
(pre-projection of agg inputs, post-scope re-expression of SELECT items).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, Type,
                            DecimalType, DoubleType, IntegerType, BigintType,
                            RealType, VarcharType, CharType, DateType,
                            parse_type)
from ..connectors import tpch
from ..spi import plan as P
from ..spi.expr import (CallExpression, ConstantExpression, RowExpression,
                        SpecialFormExpression, VariableReferenceExpression,
                        call, constant, special, variable)
from . import parser as A

# recognized aggregate functions (reference FunctionAndTypeManager
# built-ins scoped to this engine's agg executor: exec/operators.py)
AGG_FUNCS = ("sum", "avg", "count", "min", "max",
             "stddev", "stddev_pop", "stddev_samp",
             "variance", "var_pop", "var_samp",
             "corr", "covar_pop", "covar_samp",
             "approx_distinct", "approx_percentile")
from ..connectors import catalog


class PlanningError(Exception):
    pass


@dataclass
class RelationScope:
    """Columns visible from one relation (alias)."""
    alias: str
    # visible name -> (variable, type); includes prefixed + bare names
    columns: Dict[str, VariableReferenceExpression]


@dataclass
class Scope:
    relations: List[RelationScope] = field(default_factory=list)
    # aggregation scope: canonical expr text -> variable
    expr_vars: Dict[str, VariableReferenceExpression] = field(default_factory=dict)

    def resolve(self, parts: List[str]) -> VariableReferenceExpression:
        if len(parts) == 1:
            name = parts[0].lower()
            hits = [r.columns[name] for r in self.relations if name in r.columns]
            # de-dup same variable reachable through multiple names
            uniq = {v.name: v for v in hits}
            if len(uniq) == 1:
                return next(iter(uniq.values()))
            if len(uniq) > 1:
                raise PlanningError(f"ambiguous column {parts[0]!r}")
            raise PlanningError(f"column {parts[0]!r} not found")
        qual, name = parts[-2].lower(), parts[-1].lower()
        for r in self.relations:
            if r.alias == qual and name in r.columns:
                return r.columns[name]
        raise PlanningError(f"column {'.'.join(parts)!r} not found")


class Planner:
    """Plans one session's queries; allocates globally unique variable names."""

    def __init__(self, default_schema: str = "sf0.01",
                 default_catalog: str = "tpch",
                 bound_params: Optional[List[A.Node]] = None):
        self._counter = itertools.count()
        self.default_sf = _schema_sf(default_schema)
        self.default_catalog = default_catalog
        # EXECUTE ... USING literal AST nodes, bound positionally to `?`
        # slots (A.ParamLit); None = statement may not contain parameters
        self.bound_params = bound_params
        # CTEs keep their AST: each reference is planned fresh so two uses of
        # the same CTE get distinct variables (a shared plan would alias them)
        self._ctes: Dict[str, A.Query] = {}

    def new_var(self, hint: str, typ: Type) -> VariableReferenceExpression:
        return variable(f"{hint}_{next(self._counter)}", typ)

    def new_id(self, hint: str) -> str:
        return f"{hint}.{next(self._counter)}"

    # ------------------------------------------------------------------
    def plan(self, sql: str) -> P.OutputNode:
        query = A.parse_sql(sql)
        return self.plan_query_to_output(query)

    def plan_query_to_output(self, query) -> P.OutputNode:
        return self.optimize_output(self.plan_query_unoptimized(query))

    def plan_query_unoptimized(self, query) -> P.OutputNode:
        """Analyzed-but-unoptimized plan: the form the serving tier
        canonicalizes (sql/canonical.py) before the optimizer runs, so the
        plan-cache key is independent of value-specific rule firings."""
        node, names, out_vars = self.plan_query_any(query)
        out = P.OutputNode(self.new_id("output"), node, names, out_vars)
        # sanity gates around the optimizer (the reference PlanChecker's
        # intermediate passes); mode comes from the plan_validation
        # session property via the analysis thread-local
        from ..analysis import validate_plan
        validate_plan(out, "post-plan")
        return out

    @staticmethod
    def optimize_output(out: P.OutputNode) -> P.OutputNode:
        from ..analysis import validate_plan
        from .optimizer import optimize
        out = optimize(out)
        validate_plan(out, "post-optimize")
        return out

    def plan_write(self, ast) -> P.OutputNode:
        """CREATE TABLE AS / INSERT INTO -> TableWriter + TableFinish plan
        (reference LogicalPlanner.createTableWriterPlan); the target
        connector is whichever registered connector can create tables."""
        inner = self.plan_query_to_output(ast.query)
        column_names = list(inner.column_names)
        if isinstance(ast, A.InsertInto):
            target_cid = catalog.resolve_table(ast.table,
                                               self.default_catalog)
            if target_cid is None:
                raise KeyError(f"unknown table {ast.table!r}")
            if not hasattr(catalog.module(target_cid), "begin_write"):
                raise ValueError(
                    f"connector {target_cid!r} does not support writes")
            # positional insert: part files must carry the TARGET schema's
            # column names and types, not the SELECT's output labels
            schema = catalog.module(target_cid).SCHEMAS[ast.table]
            if len(schema) != len(inner.outputs):
                raise ValueError(
                    f"INSERT has {len(inner.outputs)} columns but "
                    f"{ast.table!r} has {len(schema)}")
            for (tname, ttyp), v in zip(schema, inner.outputs):
                if str(ttyp) != str(v.type):
                    # unbounded varchar targets (ORC tables lose the
                    # length parameter) accept any varchar/char source
                    if isinstance(ttyp, VarcharType) \
                            and ttyp.length is None \
                            and isinstance(v.type, (VarcharType, CharType)):
                        continue
                    raise ValueError(
                        f"INSERT column {tname!r} expects {ttyp} but query "
                        f"produces {v.type}; add a CAST")
            column_names = [n for n, _t in schema]
        else:
            target_cid = None
            for cid in catalog._CONNECTORS:
                if hasattr(catalog.module(cid), "begin_write"):
                    target_cid = cid
                    break
            if target_cid is None:
                raise RuntimeError(
                    "no writable connector registered (register a hive "
                    "catalog: connectors.hive.HiveConnector + "
                    "catalog.register_connector)")
            existing = ast.table in catalog.module(target_cid).SCHEMAS
            if existing and not ast.if_not_exists:
                raise ValueError(f"table {ast.table!r} already exists")
        rows_v = self.new_var("rows", BIGINT)
        frag_v = self.new_var("fragment", VarcharType(None))
        writer = P.TableWriterNode(
            self.new_id("tablewriter"), inner, target_cid, ast.table,
            column_names, [rows_v, frag_v])
        out_rows = self.new_var("rows", BIGINT)
        finish = P.TableFinishNode(
            self.new_id("tablefinish"), writer, target_cid, ast.table,
            [out_rows])
        return P.OutputNode(self.new_id("output"), finish, ["rows"],
                            [out_rows])

    def plan_query_any(self, query):
        """Dispatch: plain SELECT block vs set operation."""
        if isinstance(query, A.SetOp):
            return self.plan_setop(query)
        return self.plan_query(query)

    # ------------------------------------------------------------------
    # set operations (reference: SetOperationNode + the
    # ImplementIntersectAsUnion / ImplementExceptAsUnion optimizer rules)
    # ------------------------------------------------------------------
    def plan_setop(self, s: A.SetOp):
        for name, cte in s.ctes:
            self._ctes[name.lower()] = cte
        ln, lnames, lvars = self.plan_query_any(s.left)
        rn, rnames, rvars = self.plan_query_any(s.right)
        if len(lvars) != len(rvars):
            raise PlanningError(
                f"{s.op.upper()} branches have {len(lvars)} vs {len(rvars)} "
                "columns")
        if s.op in ("intersect", "except") and s.all:
            raise PlanningError(f"{s.op.upper()} ALL is not supported")

        # unified output variables; cast branch columns where types differ
        out_vars: List[VariableReferenceExpression] = []
        l_assign: Dict[VariableReferenceExpression, RowExpression] = {}
        r_assign: Dict[VariableReferenceExpression, RowExpression] = {}
        for cname, lv, rv in zip(lnames, lvars, rvars):
            t = _common_result_type(lv.type, rv.type)
            ov = self.new_var(cname, t)
            l_assign[ov] = lv if lv.type.signature == t.signature \
                else call("cast", t, lv)
            r_assign[ov] = rv if rv.type.signature == t.signature \
                else call("cast", t, rv)
            out_vars.append(ov)

        marker = s.op in ("intersect", "except")
        if marker:
            ml = self.new_var("mark_l", BIGINT)
            mr = self.new_var("mark_r", BIGINT)
            l_assign[ml], l_assign[mr] = constant(1, BIGINT), constant(0, BIGINT)
            r_assign[ml], r_assign[mr] = constant(0, BIGINT), constant(1, BIGINT)
        lproj = P.ProjectNode(self.new_id("setop_l"), ln, l_assign)
        rproj = P.ProjectNode(self.new_id("setop_r"), rn, r_assign)
        union_outs = out_vars + ([ml, mr] if marker else [])
        node: P.PlanNode = P.UnionNode(self.new_id("union"), [lproj, rproj],
                                       union_outs)

        if marker:
            cl = self.new_var("cnt_l", BIGINT)
            cr = self.new_var("cnt_r", BIGINT)
            node = P.AggregationNode(
                self.new_id("setop_agg"), node,
                {cl: P.Aggregation(call("sum", BIGINT, ml)),
                 cr: P.Aggregation(call("sum", BIGINT, mr))},
                out_vars, P.SINGLE)
            present_l = call("gt", BOOLEAN, cl, constant(0, BIGINT))
            right_cond = (call("gt", BOOLEAN, cr, constant(0, BIGINT))
                          if s.op == "intersect"
                          else call("eq", BOOLEAN, cr, constant(0, BIGINT)))
            node = P.FilterNode(self.new_id("setop_filter"), node,
                                special("AND", BOOLEAN, present_l, right_cond))
            node = P.ProjectNode(self.new_id("setop_prune"), node,
                                 {v: v for v in out_vars})
        elif not s.all:
            node = P.AggregationNode(self.new_id("distinct"), node, {},
                                     out_vars, P.SINGLE)

        # ORDER BY / LIMIT over the set operation: names and ordinals only
        sort_items: List[Tuple[VariableReferenceExpression, str]] = []
        name_to_var = {}
        for n, v in zip(lnames, out_vars):
            name_to_var.setdefault(n.lower(), v)
        for oi in s.order_by:
            if isinstance(oi.expr, A.NumberLit):
                pos = int(oi.expr.text)
                if not 1 <= pos <= len(out_vars):
                    raise PlanningError(f"ORDER BY position {pos} out of range")
                v = out_vars[pos - 1]
            elif isinstance(oi.expr, A.Ident) and len(oi.expr.parts) == 1 \
                    and oi.expr.parts[0].lower() in name_to_var:
                v = name_to_var[oi.expr.parts[0].lower()]
            else:
                raise PlanningError(
                    "ORDER BY over a set operation must use output column "
                    "names or ordinals")
            order = ("ASC" if oi.ascending else "DESC")
            if oi.nulls_first is None:
                order += "_NULLS_LAST" if oi.ascending else "_NULLS_FIRST"
            else:
                order += "_NULLS_FIRST" if oi.nulls_first else "_NULLS_LAST"
            sort_items.append((v, order))
        if sort_items and s.limit is not None:
            node = P.TopNNode(self.new_id("topn"), node, s.limit,
                              P.OrderingScheme(sort_items))
        elif sort_items:
            node = P.SortNode(self.new_id("sort"), node,
                              P.OrderingScheme(sort_items))
        elif s.limit is not None:
            node = P.LimitNode(self.new_id("limit"), node, s.limit)
        return node, lnames, out_vars

    # ------------------------------------------------------------------
    def plan_query(self, query: A.Query):
        """Returns (plan node, column names, output variables)."""
        for name, cte in query.ctes:
            self._ctes[name.lower()] = cte

        # 1. FROM: plan relations, collect scopes (consumes WHERE when it can
        # push/attach conjuncts; tells us via the returned flag)
        node, scope, where_done = self.plan_from(query)

        # 2. WHERE
        if query.where is not None and not where_done:
            pred = self.plan_expr(query.where, scope)
            node = P.FilterNode(self.new_id("filter"), node,
                                _to_boolean(pred))

        # 3. aggregation
        agg_calls = _collect_agg_calls(query)
        if query.group_by or agg_calls:
            node, scope = self.plan_aggregation(query, node, scope, agg_calls)
            if query.having is not None:
                conjs = _conjuncts(query.having)
                plain = [c for c in conjs if not _has_subquery(c)]
                subq = [c for c in conjs if _has_subquery(c)]
                if plain:
                    from ..spi.expr import and_
                    preds = [_to_boolean(self.plan_expr(c, scope))
                             for c in plain]
                    node = P.FilterNode(self.new_id("having"), node,
                                        and_(*preds))
                for c in subq:
                    node = self._apply_subquery_conjunct(node, scope, c)
        elif query.having is not None:
            raise PlanningError("HAVING without aggregation")

        # 3b. window functions (evaluated over the grouped/filtered relation,
        # before the SELECT projection — reference WindowNode placement)
        window_calls = _collect_window_calls(query)
        if window_calls:
            node, scope = self.plan_windows(node, scope, window_calls)

        # 3c. subquery expressions in SELECT items (TPC-DS q09's
        # CASE WHEN (SELECT count..) > n THEN (SELECT avg..) shape): bind
        # each to a joined-in value/marker column, registered under its
        # canon so plan_expr resolves it like any pre-computed expression.
        # Aggregated queries are excluded: the binds would have to happen
        # below the aggregation, a rewrite the suites don't need.
        if not query.group_by and not agg_calls:
            sub_vars: Dict[str, RowExpression] = dict(scope.expr_vars or {})
            found_subq = [False]

            def bind_sel(n):
                nonlocal node
                if isinstance(n, A.ScalarSubquery):
                    node, var = self._bind_scalar_subquery(
                        node, scope, n.query, preserve=True)
                    sub_vars[_canon(n, scope)] = var
                    found_subq[0] = True
                    return
                if isinstance(n, (A.InSubquery, A.Exists)):
                    return   # boolean forms in SELECT stay unsupported
                _walk_ast_fields(n, bind_sel)

            for item in query.select_items:
                if not isinstance(item.expr, A.Star):
                    bind_sel(item.expr)
            if found_subq[0]:
                scope = Scope(scope.relations, sub_vars)

        # 4. SELECT projection
        select_exprs: List[RowExpression] = []
        names: List[str] = []
        for item in query.select_items:
            if isinstance(item.expr, A.Star):
                for r in scope.relations:
                    if item.expr.qualifier and r.alias != item.expr.qualifier.lower():
                        continue
                    seen = set()
                    for cname, v in r.columns.items():
                        if v.name in seen:
                            continue
                        seen.add(v.name)
                        select_exprs.append(v)
                        names.append(cname)
                continue
            e = self.plan_expr(item.expr, scope)
            select_exprs.append(e)
            names.append(item.alias or _default_name(item.expr))

        proj_assign: Dict[VariableReferenceExpression, RowExpression] = {}
        out_vars: List[VariableReferenceExpression] = []
        alias_vars: Dict[str, VariableReferenceExpression] = {}
        for name, e in zip(names, select_exprs):
            if isinstance(e, VariableReferenceExpression) and e not in proj_assign:
                v = e
            else:
                v = self.new_var(name, e.type)
            proj_assign[v] = e
            out_vars.append(v)
            alias_vars[name.lower()] = v

        # ORDER BY may reference select aliases, ordinals, or source columns
        sort_items: List[Tuple[VariableReferenceExpression, str]] = []
        extra_assign: Dict[VariableReferenceExpression, RowExpression] = {}
        # aliases referenced INSIDE order-by expressions substitute their
        # DEFINING expression (all assignments share one projection, so a
        # sibling output name is not visible to a sort-key assignment)
        alias_defs = {name: proj_assign[v]
                      for name, v in alias_vars.items()}
        for oi in query.order_by:
            v = self._resolve_order_item(oi, scope, out_vars, alias_vars,
                                         extra_assign, alias_defs)
            order = ("ASC" if oi.ascending else "DESC")
            if oi.nulls_first is None:
                order += "_NULLS_LAST" if oi.ascending else "_NULLS_FIRST"
                # Presto default: NULLS LAST for ASC, NULLS FIRST for DESC
            else:
                order += "_NULLS_FIRST" if oi.nulls_first else "_NULLS_LAST"
            sort_items.append((v, order))

        all_assign = dict(proj_assign)
        all_assign.update(extra_assign)
        node = P.ProjectNode(self.new_id("project"), node, all_assign)

        if query.distinct:
            node = P.AggregationNode(self.new_id("distinct"), node, {},
                                     out_vars, P.SINGLE)

        if sort_items and query.limit is not None:
            node = P.TopNNode(self.new_id("topn"), node, query.limit,
                              P.OrderingScheme(sort_items))
        elif sort_items:
            node = P.SortNode(self.new_id("sort"), node,
                              P.OrderingScheme(sort_items))
        elif query.limit is not None:
            node = P.LimitNode(self.new_id("limit"), node, query.limit)

        # final pruning projection to the select list
        if set(v.name for v in node.output_variables) != set(v.name for v in out_vars):
            node = P.ProjectNode(self.new_id("prune"), node,
                                 {v: v for v in out_vars})
        return node, names, out_vars

    # ------------------------------------------------------------------
    # FROM planning: scans, pushdown, joins
    # ------------------------------------------------------------------
    def plan_from(self, query: A.Query):
        """Returns (node, scope, where_consumed)."""
        if not query.relations:
            row = [constant(1, BIGINT)]
            v = self.new_var("dummy", BIGINT)
            return (P.ValuesNode(self.new_id("values"), [v], [row]),
                    Scope([]), False)

        # flatten JoinRel trees into (relation, join_type, on) sequence
        flat: List[Tuple[A.Node, str, Optional[A.Node]]] = []

        def flatten(rel, jt="INNER", on=None):
            if isinstance(rel, A.JoinRel):
                flatten(rel.left)
                flatten(rel.right, rel.join_type, rel.on)
            else:
                flat.append((rel, jt, on))

        for r in query.relations:
            flatten(r)

        # plan each base relation; UNNEST items are lateral (they read the
        # preceding relations' columns) so they defer to the join loop
        planned: List[Tuple[P.PlanNode, RelationScope, str, Optional[A.Node]]] = []
        for rel, jt, on in flat:
            if isinstance(rel, A.UnnestRef):
                if on is not None:
                    raise PlanningError("UNNEST join cannot have ON")
                planned.append((rel, None, jt, on))
                continue
            node, rscope = self.plan_base_relation(rel, query)
            planned.append((node, rscope, jt, on))
        has_unnest = any(isinstance(n, A.UnnestRef)
                         for n, _s, _j, _o in planned)
        if has_unnest and isinstance(planned[0][0], A.UnnestRef):
            # bare FROM UNNEST(...): unnest over a one-row values source
            v = self.new_var("dummy", BIGINT)
            one = P.ValuesNode(self.new_id("values"), [v],
                               [[constant(1, BIGINT)]])
            planned.insert(0, (one, RelationScope("__values", {}), "INNER",
                               None))

        # WHERE conjuncts for pushdown / join criteria.  Conjuncts holding
        # subqueries (EXISTS / IN / scalar comparisons) are set aside and
        # applied as semi joins / correlated joins after the join tree is
        # built (the reference's TransformExistsApplyToLateralNode /
        # TransformCorrelated* iterative rules, compressed to the TPC-H/DS
        # decorrelation shapes).
        all_conjuncts = _normalize_conjuncts(_conjuncts(query.where))
        subq_conjuncts = [c for c in all_conjuncts if _has_subquery(c)]
        where_conjuncts = [c for c in all_conjuncts if not _has_subquery(c)]
        on_conjuncts: List[A.Node] = []

        # Relations on the null-producing side of an outer join must not have
        # WHERE conjuncts pushed below the join: WHERE applies after
        # null-extension, so a pushed filter would let null-extended rows
        # survive that the post-join filter should eliminate.
        null_producing = set()
        for i, (_, _, jt, _) in enumerate(planned):
            if jt == "LEFT":
                null_producing.add(i)
            elif jt == "RIGHT":
                null_producing.update(range(i))
            elif jt == "FULL":
                null_producing.update(range(len(planned)))

        # push single-relation conjuncts to their relation
        remaining: List[A.Node] = []
        consumed_where: List[A.Node] = []
        for i, (node, rscope, jt, on) in enumerate(planned):
            if i in null_producing or rscope is None:
                continue
            single_scope = Scope([rscope])
            preds = []
            for c in where_conjuncts:
                if c in consumed_where:
                    continue
                if _resolvable(self, c, single_scope):
                    preds.append(c)
                    consumed_where.append(c)
            if preds:
                exprs = [self.plan_expr(p, single_scope) for p in preds]
                from ..spi.expr import and_
                node = P.FilterNode(self.new_id("pushdown"), node,
                                    and_(*[_to_boolean(e) for e in exprs]))
                planned[i] = (node, rscope, jt, on)
        remaining = [c for c in where_conjuncts if c not in consumed_where]

        # Pure comma-join lists (q8/q9-class) can name relations in an
        # order that forces a cross join mid-tree (part, supplier,
        # lineitem: part x supplier share no predicate).  Reorder greedily
        # by predicate connectivity — each next relation must share an
        # equi-conjunct with the joined prefix when any such relation
        # exists (reference ReorderJoins, reduced to the connectivity
        # heuristic).  Explicit JOIN ... ON syntax keeps its order.
        if len(planned) > 2 and not has_unnest \
                and all(jt == "INNER" and on is None
                        for _n, _s, jt, on in planned):
            plain = [c for c in where_conjuncts if not _has_subquery(c)]

            def connects(i, chosen) -> bool:
                chosen_sc = Scope([planned[k][1] for k in chosen])
                both_sc = Scope([planned[k][1] for k in chosen]
                                + [planned[i][1]])
                own_sc = Scope([planned[i][1]])
                for c in plain:
                    if _resolvable(self, c, both_sc) \
                            and not _resolvable(self, c, chosen_sc) \
                            and not _resolvable(self, c, own_sc):
                        return True
                return False

            order = [0]
            left = set(range(1, len(planned)))
            while left:
                nxt = next((i for i in sorted(left)
                            if connects(i, order)), None)
                if nxt is None:
                    nxt = min(left)
                order.append(nxt)
                left.discard(nxt)
            if order != list(range(len(planned))):
                planned = [planned[i] for i in order]

        # build left-deep join tree in FROM order
        node, rscope, _, _ = planned[0]
        scopes = [rscope]
        for j, (next_node, next_scope, jt, on) in enumerate(planned[1:], 1):
            if isinstance(next_node, A.UnnestRef):
                node, u_scope = self._plan_unnest(node, Scope(scopes),
                                                  next_node)
                scopes.append(u_scope)
                continue
            left_scope = Scope(scopes)
            right_scope = Scope([next_scope])
            conjs = list(_conjuncts(on))
            # ON conjuncts touching only the right relation filter it BEFORE
            # the join: for LEFT joins this is required (they must not be
            # applied post null-extension like WHERE would be), for INNER
            # it is the reference's PredicatePushDown through the join.
            if jt in ("INNER", "LEFT"):
                right_only = [c for c in conjs
                              if not _has_subquery(c)
                              and _resolvable(self, c, right_scope)]
                if right_only:
                    from ..spi.expr import and_
                    preds = [_to_boolean(self.plan_expr(c, right_scope))
                             for c in right_only]
                    next_node = P.FilterNode(self.new_id("on_push"),
                                             next_node, and_(*preds))
                    conjs = [c for c in conjs if c not in right_only]
            # A WHERE conjunct may fold into this INNER join only if no later
            # join null-extends the rows it sees (a later RIGHT/FULL join
            # would null-extend this side, and WHERE must run after that).
            later_extends_left = any(
                planned[k][2] in ("RIGHT", "FULL")
                for k in range(j + 1, len(planned)))
            if jt in ("INNER", "CROSS") and not later_extends_left:
                # pull applicable WHERE conjuncts into the join
                for c in list(remaining):
                    if _resolvable(self, c, Scope(scopes + [next_scope])):
                        conjs.append(c)
                        remaining.remove(c)
            criteria, leftover = self._extract_criteria(
                conjs, left_scope, right_scope)
            join_scope = Scope(scopes + [next_scope])
            outputs = _scope_vars(join_scope)
            jf = None
            if leftover:
                from ..spi.expr import and_
                jf_exprs = [
                    _to_boolean(self.plan_expr(c, join_scope)) for c in leftover]
                jf = and_(*jf_exprs)
            if not criteria:
                # cross join via constant-key equi join
                ck_l = self.new_var("xjoin_l", BIGINT)
                ck_r = self.new_var("xjoin_r", BIGINT)
                node = P.ProjectNode(
                    self.new_id("xl"), node,
                    {**{v: v for v in _scope_vars(Scope(scopes))},
                     ck_l: constant(0, BIGINT)})
                next_node = P.ProjectNode(
                    self.new_id("xr"), next_node,
                    {**{v: v for v in _scope_vars(right_scope)},
                     ck_r: constant(0, BIGINT)})
                criteria = [(ck_l, ck_r)]
            if jt == "RIGHT":
                # RIGHT = LEFT with sides swapped (reference join-side
                # normalization); the preserved side becomes the probe
                node = P.JoinNode(self.new_id("join"), P.LEFT,
                                  next_node, node,
                                  [(r, l) for l, r in criteria],
                                  outputs, jf)
            else:
                node = P.JoinNode(self.new_id("join"),
                                  "INNER" if jt == "CROSS" else jt,
                                  node, next_node, criteria, outputs, jf)
            scopes.append(next_scope)

        # leftovers that need the whole scope (e.g. cross-relation non-equi)
        scope = Scope(scopes)
        if remaining:
            from ..spi.expr import and_
            preds = [_to_boolean(self.plan_expr(c, scope)) for c in remaining]
            node = P.FilterNode(self.new_id("post_join_filter"), node,
                                and_(*preds))
        # subquery conjuncts last: each becomes a semi join / correlated join
        # over the assembled relation tree
        for c in subq_conjuncts:
            node = self._apply_subquery_conjunct(node, scope, c)
        # every WHERE conjunct was pushed, folded into a join, or applied in
        # the post-join filter; signal without mutating the AST (CTEs re-plan
        # their query AST on each reference)
        return node, scope, True

    def plan_base_relation(self, rel: A.Node, query: A.Query):
        if isinstance(rel, A.SubqueryRef):
            node, names, out_vars = self.plan_query_any(rel.query)
            cols = {}
            for n, v in zip(names, out_vars):
                cols[n.lower()] = v
            return node, RelationScope(rel.alias.lower(), cols)
        if isinstance(rel, A.TableRef):
            name = rel.name.lower()
            alias = (rel.alias or rel.name).lower()
            if name in self._ctes:
                node, names, out_vars = self.plan_query_any(self._ctes[name])
                cols = {n.lower(): v for n, v in zip(names, out_vars)}
                return node, RelationScope(alias, cols)
            cid = catalog.resolve_table(name, self.default_catalog)
            if cid is None:
                raise PlanningError(f"unknown table {rel.name!r}")
            used = _used_columns(query, name, alias)
            prefix = catalog.prefix(name, cid)
            outputs, assignments, cols = [], {}, {}
            for col, typ in catalog.schema(name, cid):
                visible = {col, prefix + col}
                if used is not None and not (visible & used):
                    continue
                v = self.new_var(prefix + col, typ)
                outputs.append(v)
                assignments[v] = P.ColumnHandle(col, typ)
                cols[col] = v
                cols[prefix + col] = v
            if not outputs:  # count(*)-style: keep the narrowest column
                col, typ = catalog.schema(name, cid)[0]
                v = self.new_var(prefix + col, typ)
                outputs, assignments = [v], {v: P.ColumnHandle(col, typ)}
                cols = {col: v, prefix + col: v}
            table = P.TableHandle(cid, cid, name,
                                  (("scaleFactor", self.default_sf),))
            node = P.TableScanNode(self.new_id("scan"), table, outputs,
                                   assignments)
            return node, RelationScope(alias, cols)
        raise PlanningError(f"unsupported relation {type(rel).__name__}")

    def _plan_unnest(self, node: P.PlanNode, scope: Scope,
                     uref: "A.UnnestRef"):
        """Lateral UNNEST over the assembled FROM prefix: one output row
        per array element, source columns replicated (reference
        UnnestNode / UnnestOperator.java semantics)."""
        from ..common.types import ArrayType, UNKNOWN
        replicate = _scope_vars(scope)
        proj: Dict = {v: v for v in replicate}
        need_proj = False
        unnest_vars: List[Tuple] = []
        cols: Dict[str, VariableReferenceExpression] = {}
        elem_i = 0
        for ex_ast in uref.exprs:
            ex = self.plan_expr(ex_ast, scope)
            if not isinstance(ex.type, ArrayType):
                raise PlanningError(
                    f"UNNEST argument must be an array, got "
                    f"{ex.type.signature}")
            if isinstance(ex, VariableReferenceExpression):
                av = ex
            else:
                av = self.new_var("unnest_arr", ex.type)
                proj[av] = ex
                need_proj = True
            if elem_i < len(uref.column_aliases):
                ename = uref.column_aliases[elem_i]
            else:
                ename = f"_col{elem_i}"
            elem_i += 1
            ev = self.new_var(ename, ex.type.element or UNKNOWN)
            unnest_vars.append((av, [ev]))
            cols[ename.lower()] = ev
        ord_var = None
        if uref.ordinality:
            oname = (uref.column_aliases[elem_i]
                     if elem_i < len(uref.column_aliases) else "ordinality")
            ord_var = self.new_var(oname, BIGINT)
            cols[oname.lower()] = ord_var
        if need_proj:
            node = P.ProjectNode(self.new_id("unnest_in"), node, proj)
        node = P.UnnestNode(self.new_id("unnest"), node, replicate,
                            unnest_vars, ord_var)
        alias = (uref.alias or "unnest").lower()
        return node, RelationScope(alias, cols)

    def _extract_criteria(self, conjuncts, left_scope: Scope,
                          right_scope: Scope):
        criteria, leftover = [], []
        for c in conjuncts:
            pair = self._as_equi(c, left_scope, right_scope)
            if pair is not None:
                criteria.append(pair)
            else:
                leftover.append(c)
        return criteria, leftover

    def _as_equi(self, c, left_scope, right_scope):
        if not (isinstance(c, A.BinaryOp) and c.op == "="):
            return None
        for a, b in ((c.left, c.right), (c.right, c.left)):
            if (_resolvable(self, a, left_scope)
                    and _resolvable(self, b, right_scope)):
                le = self.plan_expr(a, left_scope)
                re_ = self.plan_expr(b, right_scope)
                if (isinstance(le, VariableReferenceExpression)
                        and isinstance(re_, VariableReferenceExpression)):
                    return (le, re_)
        return None

    # ------------------------------------------------------------------
    # subquery conjuncts: decorrelation to semi joins / correlated joins
    # (reference iterative rules TransformExistsApplyToLateralNode,
    # TransformCorrelatedScalarAggregationToJoin,
    # TransformUncorrelatedInPredicateSubqueryToSemiJoin in
    # presto-main-base/.../planner/iterative/rule/)
    # ------------------------------------------------------------------

    def _apply_subquery_conjunct(self, node: P.PlanNode, scope: Scope,
                                 c: A.Node) -> P.PlanNode:
        neg = False
        while isinstance(c, A.UnaryOp) and c.op == "not":
            neg = not neg
            c = c.operand
        if isinstance(c, A.Exists):
            return self._apply_exists(node, scope, c.query, c.negated ^ neg)
        if isinstance(c, A.InSubquery):
            return self._apply_in_subquery(node, scope, c.value, c.query,
                                           c.negated ^ neg)
        cmps = {"=", "<>", "<", "<=", ">", ">="}
        if isinstance(c, A.BinaryOp) and c.op in cmps:
            flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<=",
                    "=": "=", "<>": "<>"}
            if isinstance(c.right, A.ScalarSubquery):
                return self._apply_scalar_compare(node, scope, c.op, c.left,
                                                  c.right.query, neg)
            if isinstance(c.left, A.ScalarSubquery):
                return self._apply_scalar_compare(node, scope, flip[c.op],
                                                  c.right, c.left.query, neg)
        # general shape: subquery expressions nested anywhere inside the
        # conjunct (x > 1.2 * (SELECT avg ...), OR of EXISTS marks,
        # BETWEEN with subquery bounds...).  Bind every subquery to a
        # joined-in value/marker column, then plan the conjunct as an
        # ordinary filter over those bindings (the reference models this
        # as ApplyNode creation + PredicatePushDown over the markers).
        if neg:
            c = A.UnaryOp("not", c)
        expr_vars = dict(scope.expr_vars or {})
        from ..spi.expr import call as _mkcall

        def bind(n):
            nonlocal node
            if isinstance(n, A.ScalarSubquery):
                node, var = self._bind_scalar_subquery(node, scope, n.query,
                                                       preserve=True)
                expr_vars[_canon(n, scope)] = var
                return
            if isinstance(n, A.InSubquery):
                node, mark = self._bind_in_subquery(node, scope, n.value,
                                                    n.query)
                expr_vars[_canon(n, scope)] = (
                    _mkcall("not", BOOLEAN, mark) if n.negated else mark)
                return
            if isinstance(n, A.Exists):
                node, mark = self._bind_exists(node, scope, n.query)
                expr_vars[_canon(n, scope)] = (
                    _mkcall("not", BOOLEAN, mark) if n.negated else mark)
                return
            _walk_ast_fields(n, bind)

        bind(c)
        scope2 = Scope(scope.relations, expr_vars)
        pred = _to_boolean(self.plan_expr(c, scope2))
        return P.FilterNode(self.new_id("subqfilter"), node, pred)

    def _subquery_parts(self, subq: A.Query, outer_scope: Scope):
        """Classify the subquery's WHERE conjuncts against its own FROM.

        Returns (inner_conjs, corr_pairs, mixed_conjs, inner_map) where
        corr_pairs are (outer_ast, inner_ast) equality correlations, and
        mixed_conjs reference both sides non-equi (Q21's l2.l_suppkey <>
        l1.l_suppkey).  inner_map: alias -> visible column-name set."""
        if isinstance(subq, A.SetOp):
            # set-operation subqueries are planned whole (uncorrelated only)
            return [], [], [], {}
        inner_map: Dict[str, set] = {}
        for rel in _flatten_relations(subq.relations):
            if isinstance(rel, A.TableRef):
                name = rel.name.lower()
                alias = (rel.alias or rel.name).lower()
                if name in self._ctes:
                    cols = {n.lower()
                            for n in _select_names(self._ctes[name])}
                else:
                    cid = catalog.resolve_table(name, self.default_catalog)
                    if cid is None:
                        raise PlanningError(f"unknown table {rel.name!r}")
                    prefix = catalog.prefix(name, cid)
                    cols = set()
                    for coln, _ in catalog.schema(name, cid):
                        cols.add(coln)
                        cols.add(prefix + coln)
            elif isinstance(rel, A.SubqueryRef):
                alias = rel.alias.lower()
                cols = {n.lower() for n in _select_names(rel.query)}
            else:
                raise PlanningError("unsupported subquery relation")
            inner_map[alias] = cols

        def ident_is_inner(ident: A.Ident) -> bool:
            parts = ident.parts
            if len(parts) >= 2:
                qual, name = parts[-2].lower(), parts[-1].lower()
                return qual in inner_map and name in inner_map[qual]
            return any(parts[0].lower() in s for s in inner_map.values())

        inner_conjs: List[A.Node] = []
        corr_pairs: List[Tuple[A.Node, A.Node]] = []
        mixed: List[A.Node] = []
        for conj in _conjuncts(_extract_common_predicates(subq.where)
                               if subq.where is not None else None):
            ids = _idents(conj)
            if all(ident_is_inner(i) for i in ids):
                inner_conjs.append(conj)
                continue
            if isinstance(conj, A.BinaryOp) and conj.op == "=":
                for a, b in ((conj.left, conj.right),
                             (conj.right, conj.left)):
                    a_ids, b_ids = _idents(a), _idents(b)
                    if a_ids and b_ids \
                            and all(ident_is_inner(i) for i in a_ids) \
                            and not any(ident_is_inner(i) for i in b_ids):
                        corr_pairs.append((b, a))
                        break
                else:
                    mixed.append(conj)
                continue
            mixed.append(conj)
        return inner_conjs, corr_pairs, mixed, inner_map

    def _ensure_var(self, node: P.PlanNode, expr: RowExpression,
                    hint: str) -> Tuple[P.PlanNode, VariableReferenceExpression]:
        """Make `expr` available as an output variable of `node`."""
        if isinstance(expr, VariableReferenceExpression) \
                and any(v.name == expr.name for v in node.output_variables):
            return node, expr
        v = self.new_var(hint, expr.type)
        assigns: Dict[VariableReferenceExpression, RowExpression] = {
            u: u for u in node.output_variables}
        assigns[v] = expr
        return P.ProjectNode(self.new_id("ensure"), node, assigns), v

    def _apply_exists(self, node: P.PlanNode, scope: Scope, subq: A.Query,
                      negated: bool) -> P.PlanNode:
        node, mark = self._bind_exists(node, scope, subq)
        pred: RowExpression = mark if not negated \
            else call("not", BOOLEAN, mark)
        return P.FilterNode(self.new_id("semifilter"), node, pred)

    def _bind_exists(self, node: P.PlanNode, scope: Scope,
                     subq: A.Query) -> Tuple[P.PlanNode, RowExpression]:
        """Attach an EXISTS marker column for `subq` to `node` (semi-join
        decorrelation); returns (new node, boolean marker expression)."""
        if isinstance(subq, A.SetOp):
            raise PlanningError("EXISTS over a set operation not supported")
        if subq.group_by or subq.having:
            raise PlanningError("EXISTS over grouped subquery")
        inner_conjs, corr, mixed, inner_map = self._subquery_parts(subq, scope)
        if not corr:
            if mixed:
                # outer references exist but none are equi-correlations:
                # dropping them would change results (confirmed-bug class:
                # EXISTS (... WHERE r > n + 100) is NOT uncorrelated)
                raise PlanningError(
                    "EXISTS with only non-equi outer references")
            # uncorrelated EXISTS: count the SUBQUERY's rows (wrapping it
            # keeps aggregate one-row semantics and LIMIT intact —
            # EXISTS(SELECT max(x) ...) is always TRUE) and cross-join
            # the count in
            cnt_q = A.Query(
                select_items=[A.SelectItem(
                    A.FuncCall("count", [], False), "__cnt")],
                relations=[A.SubqueryRef(subq, "__exists")])
            node, cnt_var = self._bind_scalar_subquery(node, scope, cnt_q)
            return node, call("gt", BOOLEAN, cnt_var,
                              constant(0, BIGINT))

        # modified subquery: project the correlated inner expressions (and any
        # inner columns the mixed conjuncts need); the original select list of
        # an EXISTS is irrelevant
        sel_items = [A.SelectItem(inner_ast, f"__corr{i}")
                     for i, (_, inner_ast) in enumerate(corr)]
        mixed_pos: Dict[Tuple[Optional[str], str], int] = {}
        for m in mixed:
            for ident in _idents(m):
                parts = ident.parts
                if len(parts) >= 2:
                    qual, name = parts[-2].lower(), parts[-1].lower()
                    if qual in inner_map and name in inner_map[qual]:
                        key = (qual, name)
                    else:
                        continue
                else:
                    name = parts[0].lower()
                    if not any(name in s for s in inner_map.values()):
                        continue
                    key = (None, name)
                if key not in mixed_pos:
                    mixed_pos[key] = len(sel_items)
                    sel_items.append(A.SelectItem(ident, f"__m{len(mixed_pos)}"))
        mod = A.Query(select_items=sel_items, relations=subq.relations,
                      where=_and_ast(inner_conjs), ctes=subq.ctes)
        sub_node, _, sub_vars = self.plan_query(mod)

        if not mixed and len(corr) == 1:
            # pure equality correlation: direct semi join on the key
            outer_e = self.plan_expr(corr[0][0], scope)
            node, outer_v = self._ensure_var(node, outer_e, "semikey")
            mark = self.new_var("mark", BOOLEAN)
            node = P.SemiJoinNode(self.new_id("semijoin"), node, sub_node,
                                  outer_v, sub_vars[0], mark)
            return node, mark

        # general path (mixed non-equi correlation, Q21): tag outer rows with
        # unique ids, inner-join against the subquery with the non-equi
        # conjuncts as join filter, reduce to the distinct matched ids, then
        # semi-join the tagged outer rows against those ids.  The outer
        # subtree appears twice (once under the join, once as semi-join
        # probe): a deepcopy keeps the plan a tree; node ids are shared so
        # split assignment and AssignUniqueId are deterministic replays.
        id_var = self.new_var("unique", BIGINT)
        cur: P.PlanNode = P.AssignUniqueIdNode(self.new_id("uid"), node,
                                               id_var)
        criteria = []
        for (outer_ast, _), sv in zip(corr, sub_vars):
            e = self.plan_expr(outer_ast, scope)
            cur, ov = self._ensure_var(cur, e, "corrkey")
            criteria.append((ov, sv))
        syn: Dict[str, Dict[str, VariableReferenceExpression]] = {}
        for (alias, colname), pos in mixed_pos.items():
            syn.setdefault(alias or "__inner", {})[colname] = sub_vars[pos]
        mixed_scope = Scope(scope.relations
                            + [RelationScope(a, cols)
                               for a, cols in syn.items()])
        from ..spi.expr import and_
        jf = and_(*[_to_boolean(self.plan_expr(m, mixed_scope))
                    for m in mixed]) if mixed else None
        # the join must output every variable its filter reads
        jf_vars = set()
        if jf is not None:
            _collect_vars(jf, jf_vars)
        join_out = [id_var] + [v for v in cur.output_variables
                               if v.name in jf_vars and v.name != id_var.name] \
                            + [v for v in sub_vars if v.name in jf_vars]
        import copy
        probe_copy = copy.deepcopy(cur)
        joined = P.JoinNode(self.new_id("existsjoin"), P.INNER, cur, sub_node,
                            criteria, join_out, jf)
        matched = P.AggregationNode(self.new_id("matched"), joined, {},
                                    [id_var], P.SINGLE)
        mark = self.new_var("mark", BOOLEAN)
        node = P.SemiJoinNode(self.new_id("semijoin"), probe_copy, matched,
                              id_var, id_var, mark)
        return node, mark

    def _apply_in_subquery(self, node: P.PlanNode, scope: Scope,
                           value_ast: A.Node, subq: A.Query,
                           negated: bool) -> P.PlanNode:
        node, mark = self._bind_in_subquery(node, scope, value_ast, subq)
        pred: RowExpression = mark if not negated \
            else call("not", BOOLEAN, mark)
        return P.FilterNode(self.new_id("semifilter"), node, pred)

    def _bind_in_subquery(self, node: P.PlanNode, scope: Scope,
                          value_ast: A.Node, subq: A.Query):
        """Attach an IN-subquery membership marker column; returns
        (new node, marker variable).  The marker is three-valued (NULL
        probe key, or miss against a NULL-bearing build side -> NULL);
        NOT over it is Kleene, per reference HashSemiJoinOperator."""
        inner_conjs, corr, mixed, _ = self._subquery_parts(subq, scope)
        if corr or mixed:
            raise PlanningError("correlated IN subquery not supported")
        sub_node, _, sub_vars = self.plan_query_any(subq)
        if len(sub_vars) != 1:
            raise PlanningError("IN subquery must produce one column")
        e = self.plan_expr(value_ast, scope)
        node, v = self._ensure_var(node, e, "inkey")
        mark = self.new_var("mark", BOOLEAN)
        node = P.SemiJoinNode(self.new_id("semijoin"), node, sub_node,
                              v, sub_vars[0], mark)
        return node, mark

    def _apply_scalar_compare(self, node: P.PlanNode, scope: Scope, op: str,
                              lhs_ast: A.Node, subq: A.Query,
                              negated: bool) -> P.PlanNode:
        node, val_var = self._bind_scalar_subquery(node, scope, subq)
        cmp = {"=": "eq", "<>": "neq", "<": "lt", "<=": "lte",
               ">": "gt", ">=": "gte"}[op]
        lhs = self.plan_expr(lhs_ast, scope)
        pred: RowExpression = call(cmp, BOOLEAN, lhs, val_var)
        if negated:
            pred = call("not", BOOLEAN, pred)
        return P.FilterNode(self.new_id("scalarfilter"), node, pred)

    def _bind_scalar_subquery(self, node: P.PlanNode, scope: Scope,
                              subq: A.Query, preserve: bool = False):
        """Join the scalar subquery's single value onto `node` as a
        column; returns (new node, value variable).  Correlated aggregate
        subqueries decorrelate to a group-by join (reference
        TransformCorrelatedScalarAggregationToJoin); uncorrelated ones
        cross-join an EnforceSingleRow result.  preserve=True keeps outer
        rows with no matching group (LEFT join, NULL value) — required
        when the subquery value feeds an arbitrary expression (an OR
        branch may still accept the row), vs. the direct-comparison path
        where INNER is exact because the comparison rejects NULL."""
        inner_conjs, corr, mixed, _ = self._subquery_parts(subq, scope)
        if mixed:
            raise PlanningError("non-equi correlated scalar subquery")
        if not isinstance(subq, A.SetOp) and len(subq.select_items) != 1:
            raise PlanningError("scalar subquery must select one column")
        if corr:
            if subq.group_by or subq.having:
                raise PlanningError("correlated grouped scalar subquery")
            # decorrelate: group the aggregate by the correlation columns and
            # join back on them (reference
            # TransformCorrelatedScalarAggregationToJoin).  An INNER join is
            # exact here because the comparison that follows rejects the NULL
            # a missing group would produce.
            sel = [A.SelectItem(subq.select_items[0].expr, "__val")]
            group = []
            for i, (_, inner_ast) in enumerate(corr):
                sel.append(A.SelectItem(inner_ast, f"__corr{i}"))
                group.append(inner_ast)
            mod = A.Query(select_items=sel, relations=subq.relations,
                          where=_and_ast(inner_conjs), group_by=group,
                          ctes=subq.ctes)
            sub_node, _, sub_vars = self.plan_query(mod)
            val_var, corr_vars = sub_vars[0], sub_vars[1:]
            cur = node
            criteria = []
            for (outer_ast, _), sv in zip(corr, corr_vars):
                e = self.plan_expr(outer_ast, scope)
                cur, ov = self._ensure_var(cur, e, "corrkey")
                criteria.append((ov, sv))
            outputs = list(cur.output_variables) + [val_var]
            node = P.JoinNode(self.new_id("corrjoin"),
                              P.LEFT if preserve else P.INNER, cur,
                              sub_node, criteria, outputs)
        else:
            # uncorrelated scalar: enforce the one-row contract at runtime,
            # then cross join the row in via a constant-key equi join
            sub_node, _, sub_vars = self.plan_query_any(subq)
            if len(sub_vars) != 1:
                raise PlanningError("scalar subquery must select one column")
            sub_node = P.EnforceSingleRowNode(self.new_id("single"), sub_node)
            val_var = sub_vars[0]
            ck_l = self.new_var("sjoin_l", BIGINT)
            ck_r = self.new_var("sjoin_r", BIGINT)
            left = P.ProjectNode(
                self.new_id("sjl"), node,
                {**{v: v for v in node.output_variables},
                 ck_l: constant(0, BIGINT)})
            right = P.ProjectNode(
                self.new_id("sjr"), sub_node,
                {val_var: val_var, ck_r: constant(0, BIGINT)})
            node = P.JoinNode(self.new_id("scalarjoin"),
                              P.LEFT if preserve else P.INNER, left, right,
                              [(ck_l, ck_r)],
                              list(node.output_variables) + [val_var])
        return node, val_var

    # ------------------------------------------------------------------
    # aggregation planning
    # ------------------------------------------------------------------
    def plan_aggregation(self, query: A.Query, node: P.PlanNode,
                         scope: Scope, agg_calls: List[A.FuncCall],
                         group_by: Optional[List[A.Node]] = None):
        if group_by is None:
            if query.grouping_sets is not None:
                return self._plan_grouping_sets(query, node, scope,
                                                agg_calls)
            group_by = query.group_by
        # group keys: resolve ordinals / aliases / expressions
        key_asts = [self._resolve_group_key(g, query) for g in group_by]

        pre_assign: Dict[VariableReferenceExpression, RowExpression] = {}
        key_vars: List[VariableReferenceExpression] = []
        expr_vars: Dict[str, VariableReferenceExpression] = {}
        for ast in key_asts:
            e = self.plan_expr(ast, scope)
            if isinstance(e, VariableReferenceExpression):
                v = e
            else:
                v = self.new_var("groupkey", e.type)
            pre_assign[v] = e
            key_vars.append(v)
            expr_vars[_canon(ast, scope)] = v

        distinct_calls = [fc for fc in agg_calls if fc.distinct]
        if distinct_calls:
            return self._plan_distinct_aggregation(
                query, node, scope, agg_calls, key_asts, pre_assign,
                key_vars, expr_vars)

        aggregations: Dict[VariableReferenceExpression, P.Aggregation] = {}
        for fc in agg_calls:
            key = _canon(fc, scope)
            if key in expr_vars:
                continue
            fname = fc.name
            if fc.args:
                planned_args = []
                for i, a in enumerate(fc.args):
                    e = self.plan_expr(a, scope)
                    if isinstance(e, ConstantExpression) and i > 0:
                        planned_args.append(e)   # e.g. percentile p
                        continue
                    if fname in ("stddev", "stddev_pop", "stddev_samp",
                                 "variance", "var_pop", "var_samp",
                                 "corr", "covar_pop", "covar_samp") \
                            and isinstance(e.type, DecimalType):
                        # moment aggregates are double-valued in LOGICAL
                        # units: descale decimal inputs up front
                        e = call("cast", DOUBLE, e)
                    if isinstance(e, VariableReferenceExpression):
                        av = e
                    else:
                        av = self.new_var("agginput", e.type)
                    pre_assign[av] = e
                    planned_args.append(av)
                out_type = _agg_output_type(fname, planned_args[0].type)
                acall = CallExpression(fname, out_type, planned_args)
            else:
                out_type = BIGINT
                acall = CallExpression("count", out_type, [])
            v = self.new_var(fname, out_type)
            aggregations[v] = P.Aggregation(acall)
            expr_vars[key] = v

        pre = P.ProjectNode(self.new_id("preagg"), node, pre_assign)
        agg = P.AggregationNode(self.new_id("agg"), pre, aggregations,
                                key_vars, P.SINGLE)
        post_scope = Scope(scope.relations, expr_vars)
        return agg, post_scope

    def _resolve_group_key(self, g: A.Node, query: A.Query) -> A.Node:
        """GROUP BY ordinals and select-alias references -> the select
        item's expression."""
        if isinstance(g, A.NumberLit):
            return query.select_items[int(g.text) - 1].expr
        if isinstance(g, A.Ident) and len(g.parts) == 1:
            for item in query.select_items:
                if item.alias and item.alias.lower() == g.parts[0].lower():
                    return item.expr
        return g

    def _plan_grouping_sets(self, query: A.Query, node: P.PlanNode,
                            scope: Scope, agg_calls: List[A.FuncCall]):
        """GROUPING SETS / ROLLUP / CUBE: one aggregation branch per key
        set over a replayed input subtree, unified by UNION ALL with the
        absent keys null-filled — semantically the reference's GroupIdNode +
        grouped aggregation (GroupIdOperator.java), realized as the
        branch-union form so every branch reuses the ordinary aggregation
        path (including distinct aggregates)."""
        import copy
        sets = [[self._resolve_group_key(k, query) for k in s]
                for s in query.grouping_sets]
        all_keys: List[A.Node] = []
        seen = set()
        for s in sets:
            for k in s:
                c = _canon(k, scope)
                if c not in seen:
                    seen.add(c)
                    all_keys.append(k)
        key_types = {_canon(k, scope): self.plan_expr(k, scope).type
                     for k in all_keys}

        # grouping(e, ...) calls (reference GroupingOperationRewriter):
        # within one branch each is a CONSTANT — bit i set when argument
        # i is absent from the branch's grouping set
        grouping_calls: List[A.FuncCall] = []
        gseen = set()

        def find_grouping(n):
            if isinstance(n, A.FuncCall) and n.name == "grouping":
                c = _canon(n, scope)
                if c not in gseen:
                    gseen.add(c)
                    grouping_calls.append(n)
                return
            for f in (vars(n).values() if isinstance(n, A.Node) else []):
                if isinstance(f, A.Node):
                    find_grouping(f)
                elif isinstance(f, list):
                    for x in f:
                        if isinstance(x, A.Node):
                            find_grouping(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, A.Node):
                                    find_grouping(y)
        for item in query.select_items:
            find_grouping(item.expr)
        if query.having is not None:
            find_grouping(query.having)
        for oi in query.order_by:
            find_grouping(oi.expr)

        # unified output variables
        union_vars: Dict[str, VariableReferenceExpression] = {}
        for k in all_keys:
            c = _canon(k, scope)
            union_vars[c] = self.new_var("gset", key_types[c])
        branches: List[P.PlanNode] = []
        agg_union_vars: Dict[str, VariableReferenceExpression] = {}
        for i, s in enumerate(sets):
            src = node if i == 0 else copy.deepcopy(node)
            bnode, bscope = self.plan_aggregation(query, src, scope,
                                                  agg_calls,
                                                  group_by=list(s))
            in_set = {_canon(k, scope) for k in s}
            assigns: Dict[VariableReferenceExpression, RowExpression] = {}
            for k in all_keys:
                c = _canon(k, scope)
                if c in in_set:
                    assigns[union_vars[c]] = bscope.expr_vars.get(
                        c, self.plan_expr(k, bscope))
                else:
                    assigns[union_vars[c]] = constant(None, key_types[c])
            for fc in agg_calls:
                c = _canon(fc, scope)
                bv = bscope.expr_vars[c]
                uv = agg_union_vars.setdefault(
                    c, self.new_var("gsetagg", bv.type))
                assigns[uv] = bv
            for gc in grouping_calls:
                c = _canon(gc, scope)
                uv = agg_union_vars.setdefault(
                    c, self.new_var("grouping", BIGINT))
                bits = 0
                for j, arg in enumerate(gc.args):
                    if _canon(arg, scope) not in in_set:
                        bits |= 1 << (len(gc.args) - 1 - j)
                assigns[uv] = constant(bits, BIGINT)
            branches.append(P.ProjectNode(self.new_id("gset_proj"), bnode,
                                          assigns))
        outs = list(union_vars.values()) + list(agg_union_vars.values())
        union = P.UnionNode(self.new_id("gset_union"), branches, outs)
        expr_vars = dict(union_vars)
        expr_vars.update(agg_union_vars)
        return union, Scope(scope.relations, expr_vars)

    def _plan_distinct_aggregation(self, query, node, scope, agg_calls,
                                   key_asts, pre_assign, key_vars, expr_vars):
        """Single-distinct rewrite (the planner-level equivalent of the
        reference's SingleDistinctAggregationToGroupBy rule): every aggregate
        must be DISTINCT over the same argument; dedup with an inner group-by
        on (keys, arg), then aggregate normally on top."""
        distinct_calls = [fc for fc in agg_calls if fc.distinct]
        plain_calls = [fc for fc in agg_calls if not fc.distinct]
        arg_keys = {_canon(fc.args[0], scope) for fc in distinct_calls}
        if len(arg_keys) != 1:
            raise PlanningError(
                "multiple distinct-aggregate arguments not supported")
        arg = self.plan_expr(distinct_calls[0].args[0], scope)
        if isinstance(arg, VariableReferenceExpression):
            av = arg
        else:
            av = self.new_var("distinctarg", arg.type)
        pre_assign[av] = arg

        # plain aggregates share the pre-projection
        plain_aggs: Dict[VariableReferenceExpression, P.Aggregation] = {}
        plain_vars: List[VariableReferenceExpression] = []
        for fc in plain_calls:
            if fc.args:
                parg = self.plan_expr(fc.args[0], scope)
                if isinstance(parg, VariableReferenceExpression):
                    pav = parg
                else:
                    pav = self.new_var("agginput", parg.type)
                pre_assign[pav] = parg
                out_type = _agg_output_type(fc.name, parg.type)
                acall = call(fc.name, out_type, pav)
            else:
                out_type = BIGINT
                acall = CallExpression("count", out_type, [])
            v = self.new_var(fc.name, out_type)
            plain_aggs[v] = P.Aggregation(acall)
            plain_vars.append(v)
            expr_vars[_canon(fc, scope)] = v

        pre = P.ProjectNode(self.new_id("preagg"), node, pre_assign)

        def build_distinct(source):
            """dedup group-by on (keys, arg), then aggregate (reference
            SingleDistinctAggregationToGroupBy)."""
            dedup = P.AggregationNode(self.new_id("dedup"), source, {},
                                      key_vars + [av], P.SINGLE)
            aggs: Dict[VariableReferenceExpression, P.Aggregation] = {}
            for fc in distinct_calls:
                out_type = _agg_output_type(fc.name, av.type)
                v = self.new_var(fc.name, out_type)
                aggs[v] = P.Aggregation(call(fc.name, out_type, av))
                expr_vars[_canon(fc, scope)] = v
            return P.AggregationNode(self.new_id("agg"), dedup, aggs,
                                     key_vars, P.SINGLE), aggs

        if not plain_calls:
            agg, _ = build_distinct(pre)
            return agg, Scope(scope.relations, expr_vars)

        # mixed DISTINCT + plain (the reference's
        # OptimizeMixedDistinctAggregations shape, realized as a split:
        # plain aggregation and deduped distinct aggregation computed
        # independently over the same input, then equi-joined on the group
        # keys — a constant key joins the two single rows of a global agg).
        # NOTE: groups whose key is NULL would not pair across the join;
        # TPC-H/DS grouping keys are non-null.
        import copy
        plain_node = P.AggregationNode(self.new_id("agg"), pre, plain_aggs,
                                       key_vars, P.SINGLE)
        distinct_node, dist_aggs = build_distinct(copy.deepcopy(pre))
        # rename the distinct side's keys so join criteria are distinct vars
        rmap = {kv: self.new_var("dkey", kv.type) for kv in key_vars}
        rename = {rmap[kv]: kv for kv in key_vars}
        rename.update({v: v for v in dist_aggs})
        if key_vars:
            distinct_node = P.ProjectNode(self.new_id("drename"),
                                          distinct_node, rename)
            criteria = [(kv, rmap[kv]) for kv in key_vars]
            left, right = plain_node, distinct_node
        else:
            ck_l = self.new_var("aggjoin_l", BIGINT)
            ck_r = self.new_var("aggjoin_r", BIGINT)
            left = P.ProjectNode(
                self.new_id("ajl"), plain_node,
                {**{v: v for v in plain_node.output_variables},
                 ck_l: constant(0, BIGINT)})
            right = P.ProjectNode(
                self.new_id("ajr"), distinct_node,
                {**{v: v for v in distinct_node.output_variables},
                 ck_r: constant(0, BIGINT)})
            criteria = [(ck_l, ck_r)]
        outputs = list(key_vars) + plain_vars + list(dist_aggs)
        agg = P.JoinNode(self.new_id("aggjoin"), P.INNER, left, right,
                         criteria, outputs)
        return agg, Scope(scope.relations, expr_vars)

    # ------------------------------------------------------------------
    # window planning
    # ------------------------------------------------------------------
    _RANKING_FUNCS = {"row_number", "rank", "dense_rank", "ntile",
                      "percent_rank", "cume_dist"}
    _WINDOW_AGGS = {"sum", "avg", "count", "min", "max"}
    _VALUE_FUNCS = {"lag", "lead", "first_value", "last_value",
                    "nth_value"}

    def plan_windows(self, node: P.PlanNode, scope: Scope,
                     wcalls: List[A.WindowCall]):
        """One WindowNode per distinct (partition, ordering) spec, functions
        sharing a spec computed together (reference WindowNode)."""
        expr_vars = dict(scope.expr_vars)
        pre_assign: Dict[VariableReferenceExpression, RowExpression] = {
            v: v for v in node.output_variables}

        def ensure(e: RowExpression, hint: str) -> VariableReferenceExpression:
            if isinstance(e, VariableReferenceExpression):
                pre_assign.setdefault(e, e)
                return e
            v = self.new_var(hint, e.type)
            pre_assign[v] = e
            return v

        groups: Dict[str, dict] = {}
        for wc in wcalls:
            fname = wc.func.name
            if wc.func.distinct:
                raise PlanningError(
                    "DISTINCT is not supported in window functions")
            part_vars = [ensure(self.plan_expr(p, scope), "wpart")
                         for p in wc.partition_by]
            orderings = []
            for oi in wc.order_by:
                v = ensure(self.plan_expr(oi.expr, scope), "wsort")
                order = "ASC" if oi.ascending else "DESC"
                if oi.nulls_first is None:
                    order += "_NULLS_LAST" if oi.ascending else "_NULLS_FIRST"
                else:
                    order += "_NULLS_FIRST" if oi.nulls_first \
                        else "_NULLS_LAST"
                orderings.append((v, order))
            frame = None
            if wc.frame is not None:
                if wc.frame.frame_type == "RANGE" and (
                        wc.frame.start_kind in ("PRECEDING", "FOLLOWING")
                        or wc.frame.end_kind in ("PRECEDING", "FOLLOWING")):
                    raise PlanningError(
                        "RANGE frames with numeric offsets are not "
                        "supported")
                frame = {"type": wc.frame.frame_type,
                         "startKind": wc.frame.start_kind,
                         "startOffset": wc.frame.start_offset,
                         "endKind": wc.frame.end_kind,
                         "endOffset": wc.frame.end_offset}
            if fname in self._RANKING_FUNCS:
                if not orderings:
                    raise PlanningError(f"{fname}() requires ORDER BY")
                if fname == "ntile":
                    if len(wc.func.args) != 1:
                        raise PlanningError("ntile(n) takes one argument")
                    n_expr = self.plan_expr(wc.func.args[0], scope)
                    if not isinstance(n_expr, ConstantExpression) \
                            or not isinstance(n_expr.value, int) \
                            or n_expr.value <= 0:
                        raise PlanningError(
                            "ntile(n) requires a constant positive "
                            "integer")
                    out_type = BIGINT
                    fcall = CallExpression(fname, out_type, [n_expr])
                elif fname in ("percent_rank", "cume_dist"):
                    out_type = DOUBLE
                    fcall = CallExpression(fname, out_type, [])
                else:
                    out_type: Type = BIGINT
                    fcall = CallExpression(fname, out_type, [])
            elif fname in self._VALUE_FUNCS:
                if not wc.func.args:
                    raise PlanningError(f"{fname}() requires an argument")
                arg = self.plan_expr(wc.func.args[0], scope)
                av = ensure(arg, "warg")
                out_type = arg.type
                extra = []
                for a in wc.func.args[1:]:
                    e = self.plan_expr(a, scope)
                    if not isinstance(e, ConstantExpression):
                        raise PlanningError(
                            f"{fname}: offset/default arguments must be "
                            f"constants")
                    extra.append(e)
                fcall = CallExpression(fname, out_type, [av] + extra)
            elif fname in self._WINDOW_AGGS:
                if wc.func.args:
                    arg = self.plan_expr(wc.func.args[0], scope)
                    av = ensure(arg, "warg")
                    out_type = _agg_output_type(fname, arg.type)
                    fcall = call(fname, out_type, av)
                else:
                    out_type = BIGINT
                    fcall = CallExpression("count", out_type, [])
            else:
                raise PlanningError(f"unknown window function {fname!r}")
            spec_key = ("|".join(v.name for v in part_vars) + "//"
                        + "|".join(f"{v.name}:{o}" for v, o in orderings))
            g = groups.setdefault(spec_key, {
                "partition": part_vars, "orderings": orderings, "funcs": {}})
            out_var = self.new_var(fname, out_type)
            g["funcs"][out_var] = P.WindowFunction(fcall, frame)
            expr_vars[_canon(wc, scope)] = out_var

        node = P.ProjectNode(self.new_id("prewindow"), node, pre_assign)
        for g in groups.values():
            scheme = (P.OrderingScheme(g["orderings"])
                      if g["orderings"] else None)
            node = P.WindowNode(self.new_id("window"), node, g["partition"],
                                scheme, g["funcs"])
        return node, Scope(scope.relations, expr_vars)

    def _resolve_order_item(self, oi: A.OrderItem, scope, out_vars,
                            alias_vars, extra_assign, alias_defs=None):
        e = oi.expr
        if isinstance(e, A.NumberLit):
            return out_vars[int(e.text) - 1]
        if isinstance(e, A.Ident) and len(e.parts) == 1 \
                and e.parts[0].lower() in alias_vars:
            return alias_vars[e.parts[0].lower()]
        # select aliases may appear INSIDE order-by expressions (TPC-DS
        # `case when lochierarchy = 0 then ...`): substitute the alias's
        # defining expression via expr_vars (bare-name canon); aliases
        # shadow source columns
        if alias_defs:
            scope = Scope(scope.relations,
                          {**(scope.expr_vars or {}), **alias_defs})
        expr = self.plan_expr(e, scope)
        if isinstance(expr, VariableReferenceExpression):
            # must be carried through the projection
            extra_assign[expr] = expr
            return expr
        v = self.new_var("sortkey", expr.type)
        extra_assign[v] = expr
        return v

    # ------------------------------------------------------------------
    # expression planning (with type analysis)
    # ------------------------------------------------------------------
    def plan_expr(self, e: A.Node, scope: Scope) -> RowExpression:
        if scope.expr_vars:
            key = _canon(e, scope)
            if key in scope.expr_vars:
                return scope.expr_vars[key]
        if isinstance(e, A.Ident):
            return scope.resolve(e.parts)
        if isinstance(e, A.NumberLit):
            return _number_literal(e.text)
        if isinstance(e, A.StringLit):
            return constant(e.value, VarcharType(len(e.value)))
        if isinstance(e, A.BoolLit):
            return constant(e.value, BOOLEAN)
        if isinstance(e, A.NullLit):
            from ..common.types import UNKNOWN
            return constant(None, UNKNOWN)
        if isinstance(e, A.DateLit):
            return constant(_parse_date_str(e.value), DATE)
        if isinstance(e, A.ParamLit):
            if self.bound_params is None:
                raise PlanningError(
                    "query contains `?` parameters; PREPARE it and run "
                    "EXECUTE ... USING <values>")
            if e.index >= len(self.bound_params):
                raise PlanningError(
                    f"no value bound for parameter ?{e.index + 1} "
                    f"(only {len(self.bound_params)} provided)")
            v = self.plan_expr(self.bound_params[e.index], scope)
            if not isinstance(v, ConstantExpression):
                raise PlanningError(
                    "EXECUTE ... USING values must be literals")
            # origin tags the literal with its `?` ordinal so the serving
            # canonicalizer can map cache-template slots back to USING
            # positions (the prepared-statement fast path)
            return ConstantExpression(v.value, v.type, origin=e.index)
        if isinstance(e, A.BinaryOp):
            return self._plan_binary(e, scope)
        if isinstance(e, A.UnaryOp):
            arg = self.plan_expr(e.operand, scope)
            if e.op == "not":
                return call("not", BOOLEAN, _to_boolean(arg))
            if isinstance(arg, ConstantExpression) and arg.value is not None:
                return _negate_const(arg)
            return call("negate", arg.type, arg)
        if isinstance(e, A.Between):
            v = self.plan_expr(e.value, scope)
            lo = self.plan_expr(e.low, scope)
            hi = self.plan_expr(e.high, scope)
            b = call("between", BOOLEAN, v, lo, hi)
            return call("not", BOOLEAN, b) if e.negated else b
        if isinstance(e, A.InList):
            v = self.plan_expr(e.value, scope)
            items = [self.plan_expr(i, scope) for i in e.items]
            out = special("IN", BOOLEAN, v, *items)
            return call("not", BOOLEAN, out) if e.negated else out
        if isinstance(e, A.IsNull):
            v = self.plan_expr(e.value, scope)
            out = special("IS_NULL", BOOLEAN, v)
            return call("not", BOOLEAN, out) if e.negated else out
        if isinstance(e, A.Like):
            v = self.plan_expr(e.value, scope)
            pat = self.plan_expr(e.pattern, scope)
            out = call("like", BOOLEAN, v, pat)
            return call("not", BOOLEAN, out) if e.negated else out
        if isinstance(e, A.Case):
            return self._plan_case(e, scope)
        if isinstance(e, A.CastExpr):
            arg = self.plan_expr(e.operand, scope)
            to = parse_type(e.type_name)
            if isinstance(to, DateType) \
                    and isinstance(arg, ConstantExpression) \
                    and isinstance(arg.type, (VarcharType, CharType)) \
                    and arg.value is not None:
                # fold cast('yyyy-mm-dd' as date) — the shape every
                # official TPC-DS date literal takes
                return constant(_parse_date_str(arg.value), DATE)
            if isinstance(to, DateType) and isinstance(arg.type, DateType):
                return arg                      # cast(date as date): no-op
            return call("cast", to, arg)
        if isinstance(e, A.ExtractExpr):
            arg = self.plan_expr(e.operand, scope)
            return call(e.part, BIGINT, arg)
        if isinstance(e, A.ArrayLit):
            from ..common.types import ArrayType, UNKNOWN
            items = [self.plan_expr(i, scope) for i in e.items]
            et = UNKNOWN
            for it in items:
                if it.type.signature == "unknown":
                    continue
                if et.signature == "unknown":
                    et = it.type
                elif et.signature != it.type.signature:
                    et = _arith_type("+", et, it.type)
            return call("array_constructor", ArrayType(et), *items)
        if isinstance(e, A.Subscript):
            from ..common.types import ArrayType, UNKNOWN
            base = self.plan_expr(e.base, scope)
            idx = self.plan_expr(e.index, scope)
            et = base.type.element if isinstance(base.type, ArrayType) \
                else UNKNOWN
            return call("subscript", et, base, idx)
        if isinstance(e, A.FuncCall):
            return self._plan_func(e, scope)
        if isinstance(e, (A.InSubquery, A.Exists, A.ScalarSubquery)):
            raise PlanningError(
                "subquery expressions must be rewritten before planning "
                "(supported positions: FROM; IN/EXISTS rewrites land in a "
                "later round)")
        raise PlanningError(f"unsupported expression {type(e).__name__}")

    def _plan_binary(self, e: A.BinaryOp, scope) -> RowExpression:
        if e.op == "and":
            return special("AND", BOOLEAN,
                           _to_boolean(self.plan_expr(e.left, scope)),
                           _to_boolean(self.plan_expr(e.right, scope)))
        if e.op == "or":
            return special("OR", BOOLEAN,
                           _to_boolean(self.plan_expr(e.left, scope)),
                           _to_boolean(self.plan_expr(e.right, scope)))
        left = self.plan_expr(e.left, scope)
        if isinstance(e.right, A.IntervalLit):
            return self._fold_interval(e.op, left, e.right)
        right = self.plan_expr(e.right, scope)
        cmp = {"=": "eq", "<>": "neq", "<": "lt", "<=": "lte",
               ">": "gt", ">=": "gte"}
        if e.op in cmp:
            left, right = _unify_comparison(left, right)
            return call(cmp[e.op], BOOLEAN, left, right)
        arith = {"+": "add", "-": "subtract", "*": "multiply",
                 "/": "divide", "%": "modulus"}
        if e.op in arith:
            out_type = _arith_type(e.op, left.type, right.type)
            if isinstance(left, ConstantExpression) \
                    and isinstance(right, ConstantExpression) \
                    and left.value is not None \
                    and right.value is not None \
                    and isinstance(left.value, int) \
                    and isinstance(right.value, int) \
                    and not isinstance(left.type, (DateType, DecimalType)) \
                    and not isinstance(right.type, (DateType, DecimalType)):
                # fold integer constant arithmetic (TPC-DS writes years as
                # `1999 + 2` and IN-lists as `(2000, 2000 + 1, ...)`; the
                # reference's ExpressionInterpreter folds these pre-plan)
                if not (e.op in ("/", "%") and right.value == 0):
                    def _tdiv(a, b):        # exact truncation toward zero
                        q = abs(a) // abs(b)
                        return q if (a >= 0) == (b >= 0) else -q
                    v = {"+": lambda a, b: a + b,
                         "-": lambda a, b: a - b,
                         "*": lambda a, b: a * b,
                         "/": _tdiv,
                         "%": lambda a, b: a - _tdiv(a, b) * b}[e.op](
                             left.value, right.value)
                    return constant(v, out_type)
            return call(arith[e.op], out_type, left, right)
        raise PlanningError(f"operator {e.op!r}")

    def _fold_interval(self, op: str, left: RowExpression,
                       iv: A.IntervalLit) -> RowExpression:
        """date ± interval: constant-fold literal dates; day-granular
        intervals over arbitrary date expressions lower to integer
        day-arithmetic (dates are epoch-day integers on device), the
        shape official TPC-DS uses (`cast(... as date) + interval '60'
        day` over columns)."""
        if isinstance(left, ConstantExpression) \
                and isinstance(left.type, VarcharType):
            # unfolded cast('yyyy-mm-dd' as date) constants
            left = constant(_parse_date_str(left.value), DATE)
        if not isinstance(left, ConstantExpression) \
                or not isinstance(left.type, DateType):
            if isinstance(left.type, DateType) and iv.unit == "day":
                n = int(iv.value)
                return call("add" if op == "+" else "subtract", DATE,
                            left, constant(n, BIGINT))
            raise PlanningError("interval arithmetic on non-literal date")
        d = np.datetime64(left.value, "D")
        n = int(iv.value)
        sign = 1 if op == "+" else -1
        if iv.unit == "day":
            d2 = d + sign * n
        elif iv.unit in ("month", "year"):
            months = sign * n * (12 if iv.unit == "year" else 1)
            m0 = d.astype("datetime64[M]")
            day_of_month = (d - m0.astype("datetime64[D]"))
            m2 = m0 + months
            # clamp to the target month's length (Presto: Jan 31 + 1 month
            # == Feb 29/28, not Mar 2/3)
            month_len = (m2 + 1).astype("datetime64[D]") - m2.astype("datetime64[D]")
            d2 = m2.astype("datetime64[D]") + min(day_of_month,
                                                  month_len - np.timedelta64(1, "D"))
        else:
            raise PlanningError(f"interval unit {iv.unit}")
        return constant(str(d2), DATE)

    def _plan_case(self, e: A.Case, scope) -> RowExpression:
        # CASE -> nested IF
        whens = e.whens
        default = (self.plan_expr(e.default, scope)
                   if e.default is not None else None)
        planned = []
        for cond, result in whens:
            if e.operand is not None:
                cond = A.BinaryOp("=", e.operand, cond)
            planned.append((_to_boolean(self.plan_expr(cond, scope)),
                            self.plan_expr(result, scope)))
        # unify branch result types (Presto coerces CASE branches to a common
        # super type: `when ... then volume else 0` is decimal, not int)
        result_type = planned[0][1].type
        for _, r in planned[1:]:
            result_type = _common_result_type(result_type, r.type)
        if default is not None:
            result_type = _common_result_type(result_type, default.type)
        planned = [(c, _coerce_to(r, result_type)) for c, r in planned]
        if default is None:
            default = constant(None, result_type)
        else:
            default = _coerce_to(default, result_type)
        out = default
        for cond, result in reversed(planned):
            out = special("IF", result_type, cond, result, out)
        return out

    def _plan_func(self, e: A.FuncCall, scope) -> RowExpression:
        args = [self.plan_expr(a, scope) for a in e.args]
        name = e.name
        if name in AGG_FUNCS:
            # bare aggregate call (used when planning inside agg rewrite)
            out = _agg_output_type(name, args[0].type if args else BIGINT)
            return CallExpression(name, out, args)
        if name in ("year", "month", "day", "quarter"):
            return call(name, BIGINT, *args)
        if name == "substr":
            return call("substr", args[0].type, *args)
        if name == "length":
            return call("length", BIGINT, *args)
        if name == "abs":
            return call("abs", args[0].type, *args)
        if name == "coalesce":
            t = next((a.type for a in args if a.type.signature != "unknown"),
                     args[0].type)
            return special("COALESCE", t, *args)
        # -- arrays (ArrayFunctions.java / ArraySubscriptOperator) --------
        if name == "cardinality":
            return call("cardinality", BIGINT, *args)
        if name == "element_at":
            from ..common.types import ArrayType, UNKNOWN
            et = args[0].type.element \
                if isinstance(args[0].type, ArrayType) else UNKNOWN
            return call("element_at", et, *args)
        if name == "contains":
            return call("contains", BOOLEAN, *args)
        if name in ("array_max", "array_min"):
            from ..common.types import ArrayType, UNKNOWN
            et = args[0].type.element \
                if isinstance(args[0].type, ArrayType) else UNKNOWN
            return call(name, et, *args)
        if name == "array_position":
            return call("array_position", BIGINT, *args)
        if name == "repeat":
            from ..common.types import ArrayType
            return call("repeat", ArrayType(args[0].type), *args)
        if name == "sequence":
            from ..common.types import ArrayType
            return call("sequence", ArrayType(args[0].type), *args)
        if name == "nullif":
            return special("NULL_IF", args[0].type, *args)
        if name == "round":
            if len(args) == 1:
                return call("cast", BIGINT, args[0]) if isinstance(
                    args[0].type, DecimalType) else call("round", args[0].type, *args)
            return call("round", args[0].type, *args)
        # -- math (FunctionAndTypeManager built-ins; MathFunctions.java) --
        if name == "pow":
            name = "power"
        if name in ("sqrt", "exp", "ln", "log2", "log10", "sin", "cos",
                    "tan", "asin", "acos", "atan", "cbrt", "degrees",
                    "radians", "power", "truncate"):
            return call(name, DOUBLE, *args)
        if name == "pi":
            return ConstantExpression(3.141592653589793, DOUBLE)
        if name == "e":
            return ConstantExpression(2.718281828459045, DOUBLE)
        if name in ("ceil", "ceiling", "floor"):
            t = args[0].type
            out = (DOUBLE if isinstance(t, (DoubleType, RealType))
                   else BIGINT)
            return call("ceiling" if name == "ceil" else name, out, *args)
        if name == "sign":
            t = args[0].type
            return call("sign", DOUBLE if isinstance(
                t, (DoubleType, RealType)) else BIGINT, *args)
        if name == "mod":
            return call("$operator$modulus",
                        _arith_type("%", args[0].type, args[1].type),
                        *args)
        if name in ("greatest", "least"):
            t = args[0].type
            for a in args[1:]:
                t = _arith_type("+", t, a.type) \
                    if not isinstance(t, (VarcharType, CharType)) else t
            return call(name, t, *args)
        # -- strings (StringFunctions.java) -------------------------------
        if name in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse",
                    "replace", "lpad", "rpad", "concat"):
            return call(name, VarcharType(None), *args)
        if name == "strpos":
            return call("strpos", BIGINT, *args)
        if name in ("starts_with", "ends_with", "regexp_like"):
            return call(name, BOOLEAN, *args)
        if name in ("regexp_extract", "regexp_replace", "split_part",
                    "url_extract_protocol", "url_extract_host",
                    "url_extract_path", "url_extract_query",
                    "url_extract_fragment", "json_extract_scalar"):
            return call(name, VarcharType(None), *args)
        if name in ("codepoint", "url_extract_port"):
            return call(name, BIGINT, *args)
        # -- math/bitwise breadth (MathFunctions.java,
        # BitwiseFunctions.java) ------------------------------------------
        if name in ("log", "atan2", "sinh", "cosh", "tanh"):
            return call(name, DOUBLE, *args)
        if name in ("is_nan", "is_finite", "is_infinite"):
            return call(name, BOOLEAN, *args)
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor",
                    "bitwise_not", "bitwise_left_shift",
                    "bitwise_right_shift",
                    "bitwise_arithmetic_shift_right", "width_bucket"):
            return call(name, BIGINT, *args)
        if name == "infinity":
            return ConstantExpression(float("inf"), DOUBLE)
        if name == "nan":
            return ConstantExpression(float("nan"), DOUBLE)
        # -- dates (DateTimeFunctions.java) -------------------------------
        if name in ("day_of_week", "dow"):
            return call("day_of_week", BIGINT, *args)
        if name in ("day_of_year", "doy"):
            return call("day_of_year", BIGINT, *args)
        if name in ("week", "week_of_year"):
            return call("week", BIGINT, *args)
        if name == "date_trunc":
            return call("date_trunc", args[1].type, *args)
        if name == "date_add":
            return call("date_add", args[2].type, *args)
        if name == "date_diff":
            return call("date_diff", BIGINT, *args)
        raise PlanningError(f"unknown function {name!r}")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _schema_sf(schema: str) -> float:
    s = schema.lower().lstrip("sf")
    try:
        return float(s)
    except ValueError:
        return {"tiny": 0.01}.get(schema, 1.0)


def _used_columns(query: A.Query, table: str, alias: str) -> Optional[set]:
    """Column names the query may reference on this relation, for scan
    pruning.  Returns None (= keep all) when a bare/qualified star appears."""
    used: set = set()
    star = [False]

    def walk(n):
        if isinstance(n, A.Star):
            if n.qualifier is None or n.qualifier.lower() == alias:
                star[0] = True
            return
        if isinstance(n, A.Ident):
            if len(n.parts) == 1:
                used.add(n.parts[0].lower())
            elif n.parts[-2].lower() == alias:
                used.add(n.parts[-1].lower())
            return
        if isinstance(n, A.Query):
            # subqueries may reference outer columns only when correlated,
            # which we don't support yet — but be conservative and collect
            for item in n.select_items:
                walk(item.expr)
            for r in n.relations:
                walk(r)
            for e in (n.where, n.having):
                if e is not None:
                    walk(e)
            for g in n.group_by:
                walk(g)
            for oi in n.order_by:
                walk(oi.expr)
            return
        if isinstance(n, A.Node):
            for f in vars(n).values():
                if isinstance(f, A.Node):
                    walk(f)
                elif isinstance(f, list):
                    for x in f:
                        if isinstance(x, A.Node):
                            walk(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, A.Node):
                                    walk(y)

    walk(query)
    return None if star[0] else used


def _extract_common_predicates(e):
    """Factor conjuncts common to every OR branch out of the OR:
    (A AND x) OR (A AND y) -> A AND (x OR y), recursively — the
    reference's LogicalExpressionRewriter extract-common-predicates
    identity.  Lets correlation equalities buried under ORs (TPC-DS q41)
    classify as plain equi-correlations."""
    if not (isinstance(e, A.BinaryOp) and e.op == "or"):
        return e
    left = _extract_common_predicates(e.left)
    right = _extract_common_predicates(e.right)
    lc = _conjuncts(left)
    rc = _conjuncts(right)
    lkeys = {_canon(x): x for x in lc}
    rkeys = {_canon(x) for x in rc}
    common = [x for k, x in lkeys.items() if k in rkeys]
    if not common:
        return A.BinaryOp("or", left, right)
    ckeys = {_canon(x) for x in common}
    rest_l = [x for x in lc if _canon(x) not in ckeys]
    rest_r = [x for x in rc if _canon(x) not in ckeys]
    if not rest_l or not rest_r:
        # absorption: A OR (A AND y) == A
        return _and_ast(common)
    return _and_ast(common + [A.BinaryOp("or", _and_ast(rest_l),
                                         _and_ast(rest_r))])


def _conjuncts(e: Optional[A.Node]) -> List[A.Node]:
    if e is None:
        return []
    if isinstance(e, A.BinaryOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _disjuncts(e: A.Node) -> List[A.Node]:
    if isinstance(e, A.BinaryOp) and e.op == "or":
        return _disjuncts(e.left) + _disjuncts(e.right)
    return [e]


def _and_ast(conjs: List[A.Node]) -> Optional[A.Node]:
    if not conjs:
        return None
    out = conjs[0]
    for c in conjs[1:]:
        out = A.BinaryOp("and", out, c)
    return out


def _or_ast(disjs: List[A.Node]) -> A.Node:
    out = disjs[0]
    for d in disjs[1:]:
        out = A.BinaryOp("or", out, d)
    return out


def _normalize_conjuncts(conjs: List[A.Node]) -> List[A.Node]:
    """Hoist conjuncts common to every OR branch (reference
    PredicatePushDown tryExtractCommonPredicates): Q19's
    `(p=l and A) or (p=l and B)` exposes its join criterion `p=l` to the
    equi-join extractor instead of forcing a cross join."""
    out: List[A.Node] = []
    for c in conjs:
        if not (isinstance(c, A.BinaryOp) and c.op == "or"):
            out.append(c)
            continue
        branch_lists = [_conjuncts(d) for d in _disjuncts(c)]
        canon_maps = [{_canon(x): x for x in l} for l in branch_lists]
        common = set(canon_maps[0])
        for m in canon_maps[1:]:
            common &= set(m)
        if not common:
            out.append(c)
            continue
        for k in sorted(common):
            out.append(canon_maps[0][k])
        rests = []
        degenerate = False
        for l in branch_lists:
            rest = [x for x in l if _canon(x) not in common]
            if not rest:
                degenerate = True  # one branch reduced to TRUE: OR is TRUE
                break
            rests.append(_and_ast(rest))
        if not degenerate:
            out.append(_or_ast(rests))
    return out


def _has_subquery(n: A.Node) -> bool:
    if isinstance(n, (A.InSubquery, A.Exists, A.ScalarSubquery)):
        return True
    for f in vars(n).values() if isinstance(n, A.Node) else []:
        if isinstance(f, A.Node) and _has_subquery(f):
            return True
        if isinstance(f, list):
            for x in f:
                if isinstance(x, A.Node) and _has_subquery(x):
                    return True
                if isinstance(x, tuple) and any(
                        isinstance(y, A.Node) and _has_subquery(y)
                        for y in x):
                    return True
    return False


def _idents(n: A.Node) -> List[A.Ident]:
    """Identifiers in an expression, not descending into nested subqueries
    (their names belong to the nested scope)."""
    out: List[A.Ident] = []

    def walk(x):
        if isinstance(x, A.Ident):
            out.append(x)
            return
        if isinstance(x, (A.InSubquery, A.Exists, A.ScalarSubquery)):
            if isinstance(x, A.InSubquery):
                walk(x.value)
            return
        if isinstance(x, A.Query):
            return
        for f in vars(x).values() if isinstance(x, A.Node) else []:
            if isinstance(f, A.Node):
                walk(f)
            elif isinstance(f, list):
                for y in f:
                    if isinstance(y, A.Node):
                        walk(y)
                    elif isinstance(y, tuple):
                        for z in y:
                            if isinstance(z, A.Node):
                                walk(z)

    walk(n)
    return out


def _flatten_relations(relations: List[A.Node]) -> List[A.Node]:
    flat: List[A.Node] = []

    def rec(rel):
        if isinstance(rel, A.JoinRel):
            rec(rel.left)
            rec(rel.right)
        else:
            flat.append(rel)

    for r in relations:
        rec(r)
    return flat


def _select_names(q) -> List[str]:
    if isinstance(q, A.SetOp):
        return _select_names(q.left)   # set-op output names come from the
    out = []                           # first branch (SQL rule)
    for item in q.select_items:
        if isinstance(item.expr, A.Star):
            continue
        out.append(item.alias or _default_name(item.expr))
    return out


def _collect_vars(e: RowExpression, out: set) -> None:
    if isinstance(e, VariableReferenceExpression):
        out.add(e.name)
    args = getattr(e, "arguments", None)
    if args:
        for a in args:
            _collect_vars(a, out)


def _resolvable(planner: Planner, e: A.Node, scope: Scope) -> bool:
    try:
        planner.plan_expr(e, scope)
        return True
    except PlanningError:
        return False


def _scope_vars(scope: Scope) -> List[VariableReferenceExpression]:
    out, seen = [], set()
    for r in scope.relations:
        for v in r.columns.values():
            if v.name not in seen:
                seen.add(v.name)
                out.append(v)
    return out


def _collect_window_calls(query: A.Query) -> List[A.WindowCall]:
    out: List[A.WindowCall] = []
    seen = set()

    def walk(n):
        if isinstance(n, (A.InSubquery, A.Exists, A.ScalarSubquery)):
            return
        if isinstance(n, A.WindowCall):
            key = _canon(n)
            if key not in seen:
                seen.add(key)
                out.append(n)
            return
        for f in vars(n).values() if isinstance(n, A.Node) else []:
            if isinstance(f, A.Node):
                walk(f)
            elif isinstance(f, list):
                for x in f:
                    if isinstance(x, A.Node):
                        walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, A.Node):
                                walk(y)

    for item in query.select_items:
        if not isinstance(item.expr, A.Star):
            walk(item.expr)
    for oi in query.order_by:
        walk(oi.expr)
    return out


def _walk_ast_fields(n, visit) -> None:
    """Visit every AST child of n (dataclass fields holding Nodes, lists
    of Nodes, or tuples containing Nodes) — the shared traversal for
    subquery discovery walkers."""
    for f in (vars(n).values() if isinstance(n, A.Node) else []):
        if isinstance(f, A.Node):
            visit(f)
        elif isinstance(f, list):
            for x in f:
                if isinstance(x, A.Node):
                    visit(x)
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, A.Node):
                            visit(y)


def _collect_agg_calls(query: A.Query) -> List[A.FuncCall]:
    out: List[A.FuncCall] = []
    seen = set()

    def walk(n):
        if isinstance(n, (A.InSubquery, A.Exists, A.ScalarSubquery)):
            return  # subquery aggregates belong to the subquery's own scope
        if isinstance(n, A.WindowCall):
            # the window call itself is not a group aggregate, but its
            # argument / spec may contain ones (sum(sum(x)) over (...))
            for a in n.func.args:
                walk(a)
            for p in n.partition_by:
                walk(p)
            for oi in n.order_by:
                walk(oi.expr)
            return
        if isinstance(n, A.FuncCall) and n.name in AGG_FUNCS:
            key = _canon(n)
            if key not in seen:
                seen.add(key)
                out.append(n)
            return  # don't descend into agg args
        for f in vars(n).values() if isinstance(n, A.Node) else []:
            if isinstance(f, A.Node):
                walk(f)
            elif isinstance(f, list):
                for x in f:
                    if isinstance(x, A.Node):
                        walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, A.Node):
                                walk(y)

    for item in query.select_items:
        walk(item.expr)
    if query.having is not None:
        walk(query.having)
    for oi in query.order_by:
        walk(oi.expr)
    return out


def _canon(e: A.Node, scope: Optional[Scope] = None) -> str:
    """Canonical text of an AST expression, for matching group keys/aggs.

    With a scope, identifiers canonicalize to their resolved (globally
    unique) variable, so `l.x` and bare `x` match while `a.x` and `b.x`
    stay distinct; without one, to their fully qualified text."""
    if isinstance(e, A.Ident):
        if scope is not None:
            try:
                return "var:" + scope.resolve(e.parts).name
            except PlanningError:
                pass
        return ".".join(p.lower() for p in e.parts)
    if isinstance(e, A.NumberLit):
        return e.text
    if isinstance(e, A.StringLit):
        return f"'{e.value}'"
    if isinstance(e, A.BoolLit):
        return str(e.value).lower()
    if isinstance(e, A.DateLit):
        return f"date'{e.value}'"
    c = lambda x: _canon(x, scope)  # noqa: E731
    if isinstance(e, A.BinaryOp):
        return f"({c(e.left)}{e.op}{c(e.right)})"
    if isinstance(e, A.UnaryOp):
        return f"({e.op} {c(e.operand)})"
    if isinstance(e, A.FuncCall):
        d = "distinct " if e.distinct else ""
        return f"{e.name}({d}{','.join(c(a) for a in e.args)})"
    if isinstance(e, A.WindowCall):
        parts = [c(p) for p in e.partition_by]
        orders = [f"{c(oi.expr)}:{oi.ascending}:{oi.nulls_first}"
                  for oi in e.order_by]
        if e.frame is not None:
            f = e.frame
            frame = (f" {f.frame_type} {f.start_kind}:{f.start_offset}"
                     f"..{f.end_kind}:{f.end_offset}")
        else:
            frame = ""
        return (f"{c(e.func)} over (partition by {','.join(parts)} "
                f"order by {','.join(orders)}{frame})")
    if isinstance(e, A.CastExpr):
        return f"cast({c(e.operand)} as {e.type_name})"
    if isinstance(e, A.Between):
        return f"({c(e.value)} between {c(e.low)} and {c(e.high)})"
    if isinstance(e, A.Case):
        parts = [f"when {c(w)} then {c(r)}" for w, r in e.whens]
        base = c(e.operand) if e.operand is not None else ""
        dflt = f" else {c(e.default)}" if e.default is not None else ""
        return f"case {base} {' '.join(parts)}{dflt} end"
    if isinstance(e, A.ExtractExpr):
        return f"extract({e.part} from {c(e.operand)})"
    if isinstance(e, A.IsNull):
        return f"({c(e.value)} is {'not ' if e.negated else ''}null)"
    if isinstance(e, A.Like):
        return f"({c(e.value)} like {c(e.pattern)})"
    if isinstance(e, A.InList):
        return f"({c(e.value)} in ({','.join(c(i) for i in e.items)}))"
    return repr(e)


def _default_name(e: A.Node) -> str:
    if isinstance(e, A.Ident):
        return e.parts[-1].lower()
    if isinstance(e, A.FuncCall):
        return "_col_" + e.name
    return "_col"


def _parse_date_str(text: str) -> str:
    """Normalize 'yyyy-m-d' to zero-padded ISO before np.datetime64
    (Presto accepts non-padded date literals; numpy does not)."""
    parts = str(text).strip().split("-")
    if len(parts) == 3:
        y, m, d = parts
        text = f"{int(y):04d}-{int(m):02d}-{int(d):02d}"
    return str(np.datetime64(text, "D"))


def _number_literal(text: str) -> ConstantExpression:
    if "." in text:
        digits = text.replace(".", "").lstrip("0") or "0"
        scale = len(text.split(".")[1])
        precision = max(len(digits), scale)
        from decimal import Decimal
        return constant(Decimal(text), DecimalType(precision, scale))
    v = int(text)
    if -2**31 <= v < 2**31:
        return constant(v, INTEGER)
    return constant(v, BIGINT)


def _negate_const(c: ConstantExpression) -> ConstantExpression:
    return constant(-c.value, c.type)


def _to_boolean(e: RowExpression) -> RowExpression:
    return e  # type analysis already guarantees boolean predicates


def _is_decimal(t):
    return isinstance(t, DecimalType)


def _arith_type(op: str, t1: Type, t2: Type) -> Type:
    if isinstance(t1, (DoubleType, RealType)) or isinstance(t2, (DoubleType, RealType)):
        return DOUBLE
    if isinstance(t1, DateType) or isinstance(t2, DateType):
        return DATE  # date ± int days
    if _is_decimal(t1) or _is_decimal(t2):
        d1 = t1 if _is_decimal(t1) else DecimalType(19, 0)
        d2 = t2 if _is_decimal(t2) else DecimalType(19, 0)
        p1, s1 = d1.precision, d1.scale
        p2, s2 = d2.precision, d2.scale
        # reference DecimalOperators precision/scale rules
        if op in ("+", "-"):
            s = max(s1, s2)
            p = min(38, max(p1 - s1, p2 - s2) + s + 1)
            return DecimalType(p, s)
        if op == "*":
            return DecimalType(min(38, p1 + p2), s1 + s2)
        if op == "/":
            s = max(s1, s2)
            p = min(38, p1 + s2 + max(0, s2 - s1))
            return DecimalType(max(p, s + 1), s)
        if op == "%":
            return DecimalType(min(p1, p2), max(s1, s2))
    if isinstance(t1, BigintType) or isinstance(t2, BigintType):
        return BIGINT
    return INTEGER if isinstance(t1, IntegerType) and isinstance(t2, IntegerType) else BIGINT


def _unify_comparison(left: RowExpression, right: RowExpression):
    """Coerce literal types toward the column side for comparisons (e.g.
    decimal column vs integer literal)."""
    lt, rt = left.type, right.type
    if isinstance(left, ConstantExpression) and not isinstance(right, ConstantExpression):
        r, l = _unify_comparison(right, left)
        return l, r
    if isinstance(right, ConstantExpression):
        if _is_decimal(lt) and isinstance(rt, (IntegerType, BigintType)):
            from decimal import Decimal
            return left, ConstantExpression(Decimal(right.value),
                                            DecimalType(38, lt.scale),
                                            origin=right.origin)
        if _is_decimal(lt) and _is_decimal(rt):
            return left, right
        if isinstance(lt, DateType) and isinstance(rt, (VarcharType, CharType)):
            return left, ConstantExpression(right.value, DATE,
                                            origin=right.origin)
    return left, right


def _common_result_type(t1: Type, t2: Type) -> Type:
    """Common super type for CASE/COALESCE branch unification."""
    if t1.signature == t2.signature:
        return t1
    if t1.signature == "unknown":
        return t2
    if t2.signature == "unknown":
        return t1
    numeric = (DoubleType, RealType, DecimalType, IntegerType, BigintType)
    if isinstance(t1, numeric) and isinstance(t2, numeric):
        if isinstance(t1, (DoubleType, RealType)) \
                or isinstance(t2, (DoubleType, RealType)):
            return DOUBLE
        if _is_decimal(t1) or _is_decimal(t2):
            d1 = t1 if _is_decimal(t1) else DecimalType(19, 0)
            d2 = t2 if _is_decimal(t2) else DecimalType(19, 0)
            s = max(d1.scale, d2.scale)
            p = min(38, max(d1.precision - d1.scale,
                            d2.precision - d2.scale) + s)
            return DecimalType(max(p, s + 1), s)
        return BIGINT
    if isinstance(t1, (VarcharType, CharType)) \
            and isinstance(t2, (VarcharType, CharType)):
        return VarcharType(max(getattr(t1, "length", 0) or 0,
                               getattr(t2, "length", 0) or 0))
    raise PlanningError(f"no common type for {t1.signature}/{t2.signature}")


def _coerce_to(e: RowExpression, target: Type) -> RowExpression:
    if e.type.signature == target.signature:
        return e
    if isinstance(e, ConstantExpression):
        if e.value is None:
            return constant(None, target)
        if isinstance(target, DecimalType) and isinstance(
                e.value, int) and not isinstance(e.value, bool):
            from decimal import Decimal
            return ConstantExpression(Decimal(e.value), target,
                                      origin=e.origin)
    return call("cast", target, e)


def _agg_output_type(fname: str, input_type: Type) -> Type:
    if fname == "count":
        return BIGINT
    if fname == "sum":
        if isinstance(input_type, DecimalType):
            return DecimalType(38, input_type.scale)
        if isinstance(input_type, (DoubleType, RealType)):
            return DOUBLE
        return BIGINT
    if fname == "avg":
        if isinstance(input_type, DecimalType):
            return input_type
        return DOUBLE
    if fname in ("stddev", "stddev_pop", "stddev_samp", "variance",
                 "var_pop", "var_samp", "corr", "covar_pop",
                 "covar_samp"):
        return DOUBLE
    if fname == "approx_distinct":
        return BIGINT
    if fname == "approx_percentile":
        return input_type
    # min / max preserve type
    return input_type
