"""Distribution planning: exchange insertion + plan fragmentation.

The TPU analog of the reference's distribution passes and fragmenter
(presto-main-base/.../sql/planner/optimizations/AddExchanges.java:161,
PlanFragmenter.java:49, createSubPlans :73).  The single-task logical plan the
planner emits is rewritten so that:

- aggregations split into PARTIAL (runs where the data is) + a REMOTE
  repartition-by-group-keys exchange (or gather, for global aggs) + FINAL
  (the reference's PushPartialAggregationThroughExchange rule);
- joins pick a distribution: REPLICATED (broadcast the build side, the
  reference's join_distribution_type=BROADCAST) when the build estimate is
  under the threshold, else PARTITIONED (both sides repartitioned on the
  join keys, FIXED_HASH_DISTRIBUTION);
- sort/topN/limit split into partial (distributed) + final (after a gather);
- the root gets a GATHER exchange (the coordinator's result pump reads a
  SINGLE-distribution root stage, Query.java:116).

`fragment_plan` then cuts the plan at REMOTE exchanges into a SubPlan tree of
PlanFragments with RemoteSourceNode leaves, exactly where the reference's
coordinator would hand each fragment to a stage.

avg() is rewritten at the split (partial sum+count, final sums, then a
projection dividing them) so the engine only ever executes decomposable
aggregates — the reference does the same via its intermediate "avg state"
row type; a projection keeps the TPU pipeline in plain columns instead.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..common.types import BIGINT, DOUBLE, DecimalType, DoubleType, RealType, Type
from ..spi import plan as P
from ..spi.expr import (CallExpression, RowExpression,
                        VariableReferenceExpression)

Variable = VariableReferenceExpression


@dataclass
class FragmenterConfig:
    # broadcast the join build side when its estimated rows fall below this
    # (reference: join_distribution_type AUTOMATIC + JoinSwappingRules)
    broadcast_threshold: int = 600_000


# ---------------------------------------------------------------------------
# cardinality estimation (the skeleton of the reference's StatsCalculator)
# ---------------------------------------------------------------------------

# connector id -> (TableHandle -> Optional[row count])
CONNECTOR_STATS: Dict[str, Callable[[P.TableHandle], Optional[float]]] = {}


def register_connector_stats(connector_id: str, fn) -> None:
    CONNECTOR_STATS[connector_id] = fn


def _connector_stats_fn(connector_id: str):
    if connector_id not in CONNECTOR_STATS \
            and connector_id in ("tpch", "tpcds"):
        # built-in connectors: load on demand so estimates don't silently
        # depend on unrelated import order
        from ..connectors import tpch, tpcds  # noqa: F401 (self-register)
    return CONNECTOR_STATS.get(connector_id)


def estimate_rows(node: P.PlanNode, calc=None) -> Optional[float]:
    """Output-cardinality estimate: the stats module's selectivity-aware
    estimator (sql/stats.py, the StatsCalculator analog) first, falling
    back to the original coarse heuristics when stats are unavailable.
    Pass a shared StatsCalculator (`calc`) when estimating many nodes of
    one plan — its memo makes the pass O(nodes) instead of O(nodes^2)."""
    from .stats import StatsCalculator
    calc = calc or StatsCalculator()
    est = calc.rows(node)
    if est is not None:
        return est
    return _estimate_rows_heuristic(node, calc)


def _estimate_rows_heuristic(node: P.PlanNode, calc) -> Optional[float]:
    if isinstance(node, P.TableScanNode):
        fn = _connector_stats_fn(node.table.connector_id)
        return fn(node.table) if fn else None
    if isinstance(node, P.FilterNode):
        c = estimate_rows(node.source, calc)
        return None if c is None else c * 0.5
    if isinstance(node, (P.ProjectNode, P.OutputNode, P.SortNode,
                         P.MarkDistinctNode, P.AssignUniqueIdNode,
                         P.EnforceSingleRowNode, P.WindowNode)):
        return estimate_rows(node.sources[0], calc)
    if isinstance(node, (P.LimitNode, P.TopNNode, P.DistinctLimitNode)):
        c = estimate_rows(node.sources[0], calc)
        return node.count if c is None else min(float(node.count), c)
    if isinstance(node, P.AggregationNode):
        c = estimate_rows(node.source, calc)
        if not node.grouping_keys:
            return 1.0
        return None if c is None else max(1.0, c * 0.1)
    if isinstance(node, P.JoinNode):
        l, r = estimate_rows(node.left, calc), estimate_rows(node.right, calc)
        if l is None or r is None:
            return None
        return max(l, r)
    if isinstance(node, P.SemiJoinNode):
        return estimate_rows(node.source, calc)
    if isinstance(node, P.ValuesNode):
        return float(len(node.rows))
    if isinstance(node, (P.ExchangeNode, P.UnionNode)):
        ests = [estimate_rows(s, calc) for s in node.sources]
        if any(e is None for e in ests):
            return None
        return sum(ests)
    if isinstance(node, P.RemoteSourceNode):
        return None
    srcs = node.sources
    return estimate_rows(srcs[0], calc) if srcs else None


# ---------------------------------------------------------------------------
# exchange insertion
# ---------------------------------------------------------------------------

SINGLE = "single"          # all rows on one task
SOURCE = "source"          # split-partitioned leaf (scan-driven)
HASHED = "hashed"          # hash-partitioned on keys


@dataclass
class _Placed:
    node: P.PlanNode
    dist: str                       # SINGLE / SOURCE / HASHED
    hash_keys: Tuple[str, ...] = ()


class ExchangeInserter:
    def __init__(self, config: Optional[FragmenterConfig] = None):
        from .stats import StatsCalculator
        self.config = config or FragmenterConfig()
        self._counter = 0
        # shared memoized estimator for the whole pass (O(nodes))
        self._calc = StatsCalculator()

    # -- helpers ----------------------------------------------------------
    def _id(self, hint: str) -> str:
        self._counter += 1
        return f"x_{hint}_{self._counter}"

    def _var(self, hint: str, typ: Type) -> Variable:
        self._counter += 1
        return Variable(f"{hint}_x{self._counter}", typ)

    def _gather(self, child: P.PlanNode) -> P.PlanNode:
        layout = list(child.output_variables)
        return P.ExchangeNode(
            self._id("gather"), P.GATHER, P.REMOTE,
            P.PartitioningScheme(P.SINGLE_DISTRIBUTION, [], layout),
            [child], [layout])

    def _repartition(self, child: P.PlanNode, keys: List[Variable]) -> P.PlanNode:
        layout = list(child.output_variables)
        return P.ExchangeNode(
            self._id("repart"), P.REPARTITION, P.REMOTE,
            P.PartitioningScheme(P.FIXED_HASH_DISTRIBUTION, list(keys), layout),
            [child], [layout])

    def _broadcast(self, child: P.PlanNode) -> P.PlanNode:
        layout = list(child.output_variables)
        return P.ExchangeNode(
            self._id("bcast"), P.REPLICATE, P.REMOTE,
            P.PartitioningScheme(P.FIXED_BROADCAST_DISTRIBUTION, [], layout),
            [child], [layout])

    # -- entry ------------------------------------------------------------
    def rewrite(self, root: P.PlanNode) -> P.PlanNode:
        placed = self._visit(root)
        return placed.node

    # -- dispatch ---------------------------------------------------------
    def _visit(self, node: P.PlanNode) -> _Placed:
        m = getattr(self, "_visit_" + type(node).__name__, None)
        if m is not None:
            return m(node)
        # default: single-source passthrough keeps the child's distribution
        srcs = node.sources
        if len(srcs) == 1:
            child = self._visit(srcs[0])
            _set_source(node, child.node)
            return _Placed(node, child.dist, child.hash_keys)
        if not srcs:
            return _Placed(node, SINGLE)
        raise NotImplementedError(
            f"exchange insertion for {type(node).__name__}")

    # -- leaves -----------------------------------------------------------
    def _visit_TableScanNode(self, node: P.TableScanNode) -> _Placed:
        return _Placed(node, SOURCE)

    def _visit_ValuesNode(self, node: P.ValuesNode) -> _Placed:
        return _Placed(node, SINGLE)

    # -- structural -------------------------------------------------------
    def _visit_OutputNode(self, node: P.OutputNode) -> _Placed:
        child = self._visit(node.source)
        if child.dist != SINGLE:
            node.source = self._gather(child.node)
        else:
            node.source = child.node
        return _Placed(node, SINGLE)

    def _visit_AggregationNode(self, node: P.AggregationNode) -> _Placed:
        child = self._visit(node.source)
        node.source = child.node
        if child.dist == SINGLE:
            return _Placed(node, SINGLE)
        # distributed input: already partitioned on a subset of the grouping
        # keys -> grouping is partition-local, run SINGLE-step in place
        key_names = tuple(v.name for v in node.grouping_keys)
        if child.dist == HASHED and child.hash_keys and \
                set(child.hash_keys) <= set(key_names):
            return _Placed(node, HASHED, child.hash_keys)
        if any(a.distinct or a.mask for a in node.aggregations.values()):
            # non-decomposable: gather everything to one task
            node.source = self._gather(child.node)
            return _Placed(node, SINGLE)
        return self._split_aggregation(node, child)

    def _split_aggregation(self, node: P.AggregationNode,
                           child: _Placed) -> _Placed:
        """SINGLE agg -> PARTIAL + exchange + FINAL (+ avg projection)."""
        partial_aggs: Dict[Variable, P.Aggregation] = {}
        final_aggs: Dict[Variable, P.Aggregation] = {}
        # final output var -> expression over final agg outputs (avg division)
        post: Dict[Variable, RowExpression] = {}
        needs_post = False

        for v, agg in node.aggregations.items():
            fname = agg.call.display_name.lower().split(".")[-1]
            args = agg.call.arguments
            if fname == "avg":
                arg = args[0]
                sum_t = _sum_type(arg.type)
                psum = self._var(v.name + "_psum", sum_t)
                pcnt = self._var(v.name + "_pcnt", BIGINT)
                partial_aggs[psum] = P.Aggregation(
                    CallExpression("sum", sum_t, [arg]))
                partial_aggs[pcnt] = P.Aggregation(
                    CallExpression("count", BIGINT, [arg]))
                fsum = self._var(v.name + "_fsum", sum_t)
                fcnt = self._var(v.name + "_fcnt", BIGINT)
                final_aggs[fsum] = P.Aggregation(
                    CallExpression("sum", sum_t, [psum]))
                final_aggs[fcnt] = P.Aggregation(
                    CallExpression("sum", BIGINT, [pcnt]))
                post[v] = CallExpression("$operator$divide", v.type,
                                         [fsum, fcnt])
                needs_post = True
            elif fname in ("count",):
                pv = self._var(v.name + "_p", BIGINT)
                partial_aggs[pv] = agg
                final_aggs[v] = P.Aggregation(
                    CallExpression("sum", BIGINT, [pv]))
                post[v] = v
            elif fname in ("sum", "min", "max"):
                pv = self._var(v.name + "_p", v.type)
                partial_aggs[pv] = agg
                final_aggs[v] = P.Aggregation(
                    CallExpression(fname, v.type, [pv]))
                post[v] = v
            else:
                # unknown aggregate: bail out to single-node execution
                node.source = self._gather(child.node)
                return _Placed(node, SINGLE)

        keys = list(node.grouping_keys)
        partial = P.AggregationNode(node.id + "_partial", child.node,
                                    partial_aggs, keys, P.PARTIAL)
        if keys:
            ex = self._repartition(partial, keys)
            dist, hkeys = HASHED, tuple(v.name for v in keys)
        else:
            ex = self._gather(partial)
            dist, hkeys = SINGLE, ()
        final = P.AggregationNode(node.id, ex, final_aggs, keys, P.FINAL)
        out: P.PlanNode = final
        if needs_post:
            assignments: Dict[Variable, RowExpression] = {}
            for k in keys:
                assignments[k] = k
            for v in node.aggregations:
                assignments[v] = post[v]
            out = P.ProjectNode(node.id + "_avgdiv", final, assignments)
        return _Placed(out, dist, hkeys)

    def _visit_JoinNode(self, node: P.JoinNode) -> _Placed:
        left = self._visit(node.left)
        right = self._visit(node.right)
        node.left, node.right = left.node, right.node
        if left.dist == SINGLE and right.dist == SINGLE:
            return _Placed(node, SINGLE)

        lest = estimate_rows(node.left, self._calc)
        rest = estimate_rows(node.right, self._calc)
        # INNER joins may swap sides so the smaller relation is built
        if node.join_type == P.INNER and lest is not None and rest is not None \
                and lest < rest:
            node.left, node.right = node.right, node.left
            node.criteria = [(r, l) for l, r in node.criteria]
            left, right = right, left
            lest, rest = rest, lest

        # record the planner's build-side assumption so the scheduler can
        # compare it against observed rows at the stage boundary and flip
        # the exchange strategy (exec/adaptive.decide_exchange)
        node.planned_build_rows = int(rest) if rest is not None else None
        broadcast = (rest is not None
                     and rest <= self.config.broadcast_threshold
                     and node.join_type in (P.INNER, P.LEFT))
        if broadcast:
            node.distribution = P.REPLICATED
            if right.dist != SINGLE or left.dist != SINGLE:
                node.right = self._broadcast(node.right)
            return _Placed(node, left.dist, left.hash_keys)

        node.distribution = P.PARTITIONED
        lkeys = [l for l, _ in node.criteria]
        rkeys = [r for _, r in node.criteria]
        lnames = tuple(v.name for v in lkeys)
        rnames = tuple(v.name for v in rkeys)
        if not (left.dist == HASHED and left.hash_keys == lnames):
            node.left = self._repartition(node.left, lkeys)
        if not (right.dist == HASHED and right.hash_keys == rnames):
            node.right = self._repartition(node.right, rkeys)
        return _Placed(node, HASHED, lnames)

    def _visit_SemiJoinNode(self, node: P.SemiJoinNode) -> _Placed:
        src = self._visit(node.source)
        filt = self._visit(node.filtering_source)
        node.source, node.filtering_source = src.node, filt.node
        if src.dist == SINGLE and filt.dist == SINGLE:
            return _Placed(node, SINGLE)
        fest = estimate_rows(node.filtering_source, self._calc)
        if fest is not None and fest <= self.config.broadcast_threshold:
            if filt.dist != SINGLE or src.dist != SINGLE:
                node.filtering_source = self._broadcast(node.filtering_source)
            return _Placed(node, src.dist, src.hash_keys)
        skey, fkey = node.source_join_variable, node.filtering_source_join_variable
        if not (src.dist == HASHED and src.hash_keys == (skey.name,)):
            node.source = self._repartition(node.source, [skey])
        if not (filt.dist == HASHED and filt.hash_keys == (fkey.name,)):
            node.filtering_source = self._repartition(
                node.filtering_source, [fkey])
        return _Placed(node, HASHED, (skey.name,))

    def _visit_SortNode(self, node: P.SortNode) -> _Placed:
        child = self._visit(node.source)
        if child.dist == SINGLE:
            node.source = child.node
        else:
            node.source = self._gather(child.node)
        return _Placed(node, SINGLE)

    def _visit_TopNNode(self, node: P.TopNNode) -> _Placed:
        child = self._visit(node.source)
        if child.dist == SINGLE:
            node.source = child.node
            return _Placed(node, SINGLE)
        partial = P.TopNNode(node.id + "_partial", child.node, node.count,
                             node.ordering_scheme, P.PARTIAL)
        node.source = self._gather(partial)
        node.step = P.FINAL
        return _Placed(node, SINGLE)

    def _visit_LimitNode(self, node: P.LimitNode) -> _Placed:
        child = self._visit(node.source)
        if child.dist == SINGLE:
            node.source = child.node
            return _Placed(node, SINGLE)
        partial = P.LimitNode(node.id + "_partial", child.node, node.count,
                              P.PARTIAL)
        node.source = self._gather(partial)
        node.step = P.FINAL
        return _Placed(node, SINGLE)

    def _visit_DistinctLimitNode(self, node: P.DistinctLimitNode) -> _Placed:
        child = self._visit(node.source)
        if child.dist == SINGLE:
            node.source = child.node
            return _Placed(node, SINGLE)
        partial = P.DistinctLimitNode(node.id + "_partial", child.node,
                                      node.count, node.distinct_variables)
        node.source = self._gather(partial)
        return _Placed(node, SINGLE)

    def _visit_UnionNode(self, node: P.UnionNode) -> _Placed:
        """UNION ALL runs on one task; each distributed branch is gathered
        (the reference instead collapses union into the exchange — same
        wire shape, one stage per branch)."""
        new_inputs = []
        for s in node.inputs:
            child = self._visit(s)
            new_inputs.append(child.node if child.dist == SINGLE
                              else self._gather(child.node))
        node.inputs = new_inputs
        return _Placed(node, SINGLE)

    def _visit_WindowNode(self, node: P.WindowNode) -> _Placed:
        child = self._visit(node.source)
        if child.dist == SINGLE:
            node.source = child.node
            return _Placed(node, SINGLE)
        if node.partition_by:
            node.source = self._repartition(child.node,
                                            list(node.partition_by))
            return _Placed(node, HASHED,
                           tuple(v.name for v in node.partition_by))
        node.source = self._gather(child.node)
        return _Placed(node, SINGLE)

    def _visit_EnforceSingleRowNode(self, node) -> _Placed:
        child = self._visit(node.source)
        if child.dist == SINGLE:
            node.source = child.node
        else:
            node.source = self._gather(child.node)
        return _Placed(node, SINGLE)


def _set_source(node: P.PlanNode, new_source: P.PlanNode) -> None:
    if hasattr(node, "source"):
        node.source = new_source
    else:
        raise NotImplementedError(
            f"cannot replace source of {type(node).__name__}")


def _sum_type(input_type: Type) -> Type:
    if isinstance(input_type, (DoubleType, RealType)):
        return DOUBLE
    if isinstance(input_type, DecimalType):
        return DecimalType(38, input_type.scale)
    return BIGINT


# ---------------------------------------------------------------------------
# fragmentation
# ---------------------------------------------------------------------------

class Fragmenter:
    """Cuts a plan with REMOTE exchanges into a SubPlan tree
    (reference PlanFragmenter.createSubPlans :73)."""

    def __init__(self):
        self._next_id = 0

    def fragment(self, root: P.PlanNode) -> P.SubPlan:
        root_scheme = P.PartitioningScheme(
            P.SINGLE_DISTRIBUTION, [], list(root.output_variables))
        return self._make_fragment(root, root_scheme)

    def _make_fragment(self, root: P.PlanNode,
                       output_scheme: P.PartitioningScheme) -> P.SubPlan:
        fid = str(self._next_id)
        self._next_id += 1
        children: List[P.SubPlan] = []
        props = {"has_scan": False, "scan_ids": [], "consumed": []}
        new_root = self._rewrite(root, children, props)
        if props["has_scan"]:
            partitioning = P.SOURCE_DISTRIBUTION
        elif P.REPARTITION in props["consumed"]:
            partitioning = P.FIXED_HASH_DISTRIBUTION
        else:
            partitioning = P.SINGLE_DISTRIBUTION
        fragment = P.PlanFragment(fid, new_root, partitioning, output_scheme,
                                  props["scan_ids"])
        return P.SubPlan(fragment, children)

    def _rewrite(self, node: P.PlanNode, children: List[P.SubPlan],
                 props: dict) -> P.PlanNode:
        if isinstance(node, P.ExchangeNode) and node.scope == P.REMOTE:
            props["consumed"].append(node.exchange_type)
            ids = []
            for src in node.exchange_sources:
                sub = self._make_fragment(src, node.partitioning_scheme)
                children.append(sub)
                ids.append(sub.fragment.fragment_id)
            return P.RemoteSourceNode(
                node.id, ids, list(node.partitioning_scheme.output_layout))
        if isinstance(node, P.TableScanNode):
            props["has_scan"] = True
            props["scan_ids"].append(node.id)
            return node
        for attr in ("source", "left", "right", "filtering_source"):
            if hasattr(node, attr):
                setattr(node, attr,
                        self._rewrite(getattr(node, attr), children, props))
        if isinstance(node, P.ExchangeNode):  # LOCAL exchange
            node.exchange_sources = [
                self._rewrite(s, children, props)
                for s in node.exchange_sources]
        if isinstance(node, P.UnionNode):
            # branches carry their own REMOTE gathers (ExchangeInserter
            # _visit_UnionNode); skipping them left whole distributed
            # branches — scans included — inlined in the consuming
            # fragment (caught by the FRAGMENT_BOUNDARY checker)
            node.inputs = [self._rewrite(s, children, props)
                           for s in node.inputs]
        return node


def annotate_dynamic_filter_sources(subplan: P.SubPlan) -> P.SubPlan:
    """Stamp `PlanFragment.dynamic_filter_sources` (producer output column
    name -> dynamic filter id) on every child fragment whose output feeds
    the SOURCE side of an annotated join in its consumer fragment.

    The optimizer's `plan_dynamic_filters` keys `dynamic_filters` by the
    RECEIVING variable; the summarized domain comes from the opposite
    side (INNER: build/right, LEFT: probe/left, semi: filtering source).
    When fragmentation cut that side behind a RemoteSourceNode, the
    producing stage is where the key column's min/max/value-set summary
    must be built (exec/adaptive.summarize_key_column) — this pass tells
    each producer WHICH of its output columns feed filters, so the
    scheduler / worker tasks summarize them as pages stream out."""
    def source_sides(node) -> List[Tuple[P.PlanNode, str, str]]:
        """(source subtree, source variable name, filter id) triples.

        For INNER joins the receiving var may sit on EITHER side — the
        exchange inserter's build-side swap flips criteria after the
        optimizer annotated — and both directions are sound (neither
        side is preserved).  LEFT joins receive on the build (right)
        side only; semi joins on the probe source."""
        out: List[Tuple[P.PlanNode, str, str]] = []
        if isinstance(node, P.JoinNode) and node.dynamic_filters:
            for l, r in node.criteria:
                if l.name in node.dynamic_filters \
                        and node.join_type == P.INNER:
                    out.append((node.right, r.name,
                                node.dynamic_filters[l.name]))
                elif r.name in node.dynamic_filters:
                    out.append((node.left, l.name,
                                node.dynamic_filters[r.name]))
        elif isinstance(node, P.SemiJoinNode) \
                and getattr(node, "dynamic_filters", None):
            skey = node.source_join_variable.name
            if skey in node.dynamic_filters:
                out.append((node.filtering_source,
                            node.filtering_source_join_variable.name,
                            node.dynamic_filters[skey]))
        return out

    def side_remote(side) -> Optional[P.RemoteSourceNode]:
        """The RemoteSourceNode feeding a join side, if the fragment cut
        landed directly there (the common shape: repartition/broadcast
        exchanges become fragment boundaries)."""
        while isinstance(side, P.FilterNode):
            side = side.source
        return side if isinstance(side, P.RemoteSourceNode) else None

    def visit(sp: P.SubPlan) -> None:
        by_fid = {c.fragment.fragment_id: c for c in sp.children}
        for node in P.walk_plan(sp.fragment.root):
            for side, var_name, fid in source_sides(node):
                remote = side_remote(side)
                if remote is None:
                    continue
                out_names = [v.name for v in remote.outputs]
                if var_name not in out_names:
                    continue
                j = out_names.index(var_name)
                for cfid in remote.source_fragment_ids:
                    child = by_fid.get(cfid)
                    if child is None:
                        continue
                    layout = child.fragment.output_partitioning_scheme \
                        .output_layout
                    if j < len(layout):
                        child.fragment.dynamic_filter_sources[
                            layout[j].name] = fid
        for c in sp.children:
            visit(c)

    visit(subplan)
    return subplan


def plan_distributed(root: P.OutputNode,
                     config: Optional[FragmenterConfig] = None,
                     exec_config=None) -> P.SubPlan:
    """Full distribution pipeline: exchange insertion then fragmentation,
    then the final sanity pass (per-fragment tree checks + fragment
    boundary / partitioning / grouped-execution checks).  `exec_config`
    feeds the grouped-execution eligibility predicate; None uses the
    default ExecutionConfig."""
    rewritten = ExchangeInserter(config).rewrite(root)
    sub = Fragmenter().fragment(rewritten)
    annotate_dynamic_filter_sources(sub)
    from ..analysis import validate_subplan
    validate_subplan(sub, "post-fragment", exec_config=exec_config)
    return sub


def annotate_exchange_fabrics(subplan: P.SubPlan, exec_config=None,
                              mesh_size: int = 0,
                              batch_mode: bool = False) -> P.SubPlan:
    """Annotate every remote-exchange edge (each child fragment's output
    partitioning scheme) with its resolved fabric ("http" | "ici",
    parallel/fabric.py) for the given mesh.  The scheduler re-derives the
    same resolution when choosing task counts; annotating the plan makes
    the choice visible to EXPLAIN and checkable by the EXCHANGE_FABRIC
    validation pass.  A RemoteSourceNode reading several child fragments
    (union) must see ONE fabric across them — the device reader consumes
    all-device or nothing — so mixed resolutions demote to http."""
    from ..parallel.fabric import FABRIC_HTTP, FABRIC_ICI, resolve_fabric
    requested = getattr(exec_config, "exchange_fabric", None)

    def visit(sp: P.SubPlan) -> None:
        frag = sp.fragment
        by_fid = {c.fragment.fragment_id: c for c in sp.children}
        for node in P.walk_plan(frag.root):
            if not isinstance(node, P.RemoteSourceNode):
                continue
            resolved = []
            for fid in node.source_fragment_ids:
                child = by_fid.get(fid)
                if child is None:
                    continue
                scheme = child.fragment.output_partitioning_scheme
                fabric, _why = resolve_fabric(
                    scheme.fabric or requested, handle=scheme.handle,
                    producer_partitioning=child.fragment.partitioning,
                    consumer_partitioning=frag.partitioning,
                    mesh_size=mesh_size, batch_mode=batch_mode)
                resolved.append((scheme, fabric))
            mixed = len({f for _, f in resolved}) > 1
            for scheme, fabric in resolved:
                scheme.fabric = FABRIC_HTTP if mixed else fabric
        for c in sp.children:
            visit(c)

    visit(subplan)
    return subplan
