// Native host-side kernels for the presto-tpu worker data plane.
//
// The reference worker's shell is C++ (presto-native-execution/presto_cpp);
// the TPU worker keeps JAX/XLA for device compute and uses this library for
// the host-side per-row hot loops that sit outside jit: SQL LIKE matching
// (reference LikeFunctions semantics: only % and _ are wildcards, optional
// escape character) and dictionary encoding of substrings over packed string
// buffers.  Strings arrive as one contiguous byte buffer plus an int64
// offsets array of length n+1 (Arrow-style layout); all semantics are
// byte-wise, callers guarantee ASCII (the Python wrapper falls back to the
// pure-Python matcher otherwise).
//
// C ABI only: loaded via ctypes, no pybind11 dependency.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

enum TokKind : uint8_t { LIT = 0, ANY = 1, STAR = 2 };

struct Tok {
  TokKind kind;
  char c;
};

// Compile a LIKE pattern into tokens; escape < 0 means no escape character.
std::vector<Tok> compile_pattern(const char* pattern, int64_t len,
                                 int escape) {
  std::vector<Tok> toks;
  toks.reserve(static_cast<size_t>(len));
  for (int64_t i = 0; i < len; ++i) {
    char ch = pattern[i];
    if (escape >= 0 && ch == static_cast<char>(escape) && i + 1 < len) {
      toks.push_back({LIT, pattern[++i]});
    } else if (ch == '%') {
      if (toks.empty() || toks.back().kind != STAR) toks.push_back({STAR, 0});
    } else if (ch == '_') {
      toks.push_back({ANY, 0});
    } else {
      toks.push_back({LIT, ch});
    }
  }
  return toks;
}

// Greedy wildcard match with backtracking over the last '%'.
bool match_one(const char* s, int64_t slen, const Tok* toks, int64_t ntoks) {
  int64_t si = 0, ti = 0, star_ti = -1, star_si = 0;
  while (si < slen) {
    if (ti < ntoks && (toks[ti].kind == ANY ||
                       (toks[ti].kind == LIT && toks[ti].c == s[si]))) {
      ++ti;
      ++si;
    } else if (ti < ntoks && toks[ti].kind == STAR) {
      star_ti = ti++;
      star_si = si;
    } else if (star_ti >= 0) {
      ti = star_ti + 1;
      si = ++star_si;
    } else {
      return false;
    }
  }
  while (ti < ntoks && toks[ti].kind == STAR) ++ti;
  return ti == ntoks;
}

// Binary search `needle` in a packed sorted dictionary; -1 if absent.
int32_t dict_find(const char* dict_data, const int64_t* dict_offsets,
                  int32_t dict_n, const char* needle, int64_t nlen) {
  int32_t lo = 0, hi = dict_n - 1;
  while (lo <= hi) {
    int32_t mid = lo + (hi - lo) / 2;
    const char* e = dict_data + dict_offsets[mid];
    int64_t elen = dict_offsets[mid + 1] - dict_offsets[mid];
    int64_t common = elen < nlen ? elen : nlen;
    int cmp = std::memcmp(e, needle, static_cast<size_t>(common));
    if (cmp == 0) cmp = (elen > nlen) - (elen < nlen);
    if (cmp == 0) return mid;
    if (cmp < 0)
      lo = mid + 1;
    else
      hi = mid - 1;
  }
  return -1;
}

}  // namespace

extern "C" {

// out[i] = 1 iff strings[i] matches the LIKE pattern.
void ptn_like(const char* data, const int64_t* offsets, int64_t n,
              const char* pattern, int64_t pattern_len, int escape,
              uint8_t* out) {
  std::vector<Tok> toks = compile_pattern(pattern, pattern_len, escape);
  const Tok* t = toks.data();
  int64_t nt = static_cast<int64_t>(toks.size());
  for (int64_t i = 0; i < n; ++i) {
    out[i] = match_one(data + offsets[i], offsets[i + 1] - offsets[i], t, nt)
                 ? 1
                 : 0;
  }
}

// SQL substr(s, start, length) of each input (1-based start; negative start
// counts from the end; length < 0 means "to the end"), then encode against a
// packed SORTED dictionary.  Returns the number of values not found in the
// dictionary (their codes are set to -1).
int64_t ptn_substr_dict_encode(const char* data, const int64_t* offsets,
                               int64_t n, int64_t start, int64_t length,
                               const char* dict_data,
                               const int64_t* dict_offsets, int32_t dict_n,
                               int32_t* out_codes) {
  int64_t missing = 0;
  for (int64_t i = 0; i < n; ++i) {
    const char* s = data + offsets[i];
    int64_t slen = offsets[i + 1] - offsets[i];
    // mirror the Python oracle (_py_substr) exactly, including Python slice
    // semantics when the adjusted start is still negative: s[b0:e0] re-bases
    // negative bounds off the end and clamps to [0, slen]
    int64_t b0 = start > 0 ? start - 1 : slen + start;
    int64_t e0 = length < 0 ? slen : b0 + length;
    int64_t b = b0 >= 0 ? (b0 < slen ? b0 : slen)
                        : (slen + b0 > 0 ? slen + b0 : 0);
    int64_t e = e0 >= 0 ? (e0 < slen ? e0 : slen)
                        : (slen + e0 > 0 ? slen + e0 : 0);
    if (e < b) e = b;
    int32_t code = dict_find(dict_data, dict_offsets, dict_n, s + b, e - b);
    out_codes[i] = code;
    if (code < 0) ++missing;
  }
  return missing;
}

// Combined splitmix64 hash of an int64 column into an accumulator array,
// matching exec/operators.py splitmix64 / hash_columns (h = mix(h*31 + mix(v))).
void ptn_hash_combine(const int64_t* values, const uint8_t* nulls, int64_t n,
                      uint64_t* inout) {
  const uint64_t GOLDEN = 0x9E3779B97F4A7C15ULL;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t x = static_cast<uint64_t>(values[i]);
    x += GOLDEN;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    x = x ^ (x >> 31);
    if (nulls != nullptr && nulls[i]) x = GOLDEN;
    uint64_t h = inout[i] * 31ULL + x;
    h += GOLDEN;
    h = (h ^ (h >> 30)) * 0xBF58476D1CE4E5B9ULL;
    h = (h ^ (h >> 27)) * 0x94D049BB133111EBULL;
    inout[i] = h ^ (h >> 31);
  }
}

}  // extern "C"
