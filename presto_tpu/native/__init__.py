"""Native (C++) host-side kernels, loaded via ctypes.

Build-on-first-use with g++ (the image's native toolchain); every entry point
has a pure-Python fallback, so the package works — just slower — when no
compiler is available.  The C++ side mirrors the role of the reference's
native worker shell (presto-native-execution/presto_cpp): host data-plane
loops stay native while device compute stays in XLA.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import List, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "kernels.cpp")
_SO = os.path.join(_HERE, "_kernels.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> Optional[str]:
    """Compile kernels.cpp -> _kernels.so (atomic replace; safe under
    concurrent builders)."""
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def load():
    """The loaded library, or None when native kernels are unavailable."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _SO
        if not os.path.exists(path) or \
                os.path.getmtime(path) < os.path.getmtime(_SRC):
            path = _build()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        lib.ptn_like.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8)]
        lib.ptn_like.restype = None
        lib.ptn_substr_dict_encode.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32)]
        lib.ptn_substr_dict_encode.restype = ctypes.c_int64
        lib.ptn_hash_combine.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64)]
        lib.ptn_hash_combine.restype = None
        _lib = lib
        return _lib


def pack_strings(strings: List[str]
                 ) -> Optional[Tuple[bytes, np.ndarray]]:
    """list[str] -> (utf-8 buffer, int64 offsets[n+1]), or None when any
    string is non-ASCII (byte-wise kernels would miscount characters)."""
    n = len(strings)
    offsets = np.zeros(n + 1, dtype=np.int64)
    lens = np.fromiter((len(s) for s in strings), dtype=np.int64, count=n)
    np.cumsum(lens, out=offsets[1:])
    data = "".join(strings).encode("utf-8")
    if len(data) != int(offsets[-1]):
        return None  # non-ASCII: char count != byte count
    return data, offsets


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def like_match(strings: List[str], pattern: str,
               escape: Optional[str] = None) -> Optional[np.ndarray]:
    """Vectorized SQL LIKE over a string list; None -> caller falls back to
    the Python matcher (no native lib, or non-ASCII input)."""
    lib = load()
    if lib is None:
        return None
    packed = pack_strings(strings)
    if packed is None:
        return None
    try:
        pat = pattern.encode("ascii")
    except UnicodeEncodeError:
        return None
    data, offsets = packed
    out = np.zeros(len(strings), dtype=np.uint8)
    esc = ord(escape) if escape else -1
    lib.ptn_like(data, _i64p(offsets), len(strings), pat, len(pat), esc,
                 out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out.astype(bool)


def substr_dict_encode(strings: List[str], start: int, length: Optional[int],
                       dictionary: Tuple[str, ...]) -> Optional[np.ndarray]:
    """codes[i] = index of substr(strings[i], start, length) in the sorted
    dictionary.  None -> fall back to Python.  Raises KeyError when a value
    is missing from the dictionary (callers build exhaustive dictionaries)."""
    lib = load()
    if lib is None:
        return None
    packed = pack_strings(strings)
    dpacked = pack_strings(list(dictionary))
    if packed is None or dpacked is None:
        return None
    data, offsets = packed
    ddata, doffsets = dpacked
    out = np.zeros(len(strings), dtype=np.int32)
    missing = lib.ptn_substr_dict_encode(
        data, _i64p(offsets), len(strings), start,
        -1 if length is None else length,
        ddata, _i64p(doffsets), len(dictionary),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if missing:
        raise KeyError(f"{missing} values missing from dictionary")
    return out
