"""TPC-H macro-benchmark driver (the presto-benchmark-driver /
benchto-suite analog, SURVEY.md §2.11 + §6: per-query wall-clock with
prewarm runs over the full q1-q22 suite).

    python -m presto_tpu.benchmarks.driver [--sf 1] [--runs 3]
        [--queries 1,6,3] [--distributed N] [--json out.json]

Prints one JSON object per query: {"query", "sf", "best_s", "runs_s",
"rows"} and a trailing suite summary; mirrors the benchto harness shape
(6 runs / 2 prewarm in the reference's tpch.yaml — defaults here are
smaller because compile warmup is the dominant first-run cost on TPU).
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="presto-tpu-bench-driver")
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--prewarm", type=int, default=1)
    ap.add_argument("--queries", default=None,
                    help="comma-separated query numbers (default: all 22)")
    ap.add_argument("--distributed", type=int, default=0, metavar="N",
                    help="run through the in-process distributed scheduler "
                         "with N tasks per stage")
    ap.add_argument("--batch-rows", type=int, default=1 << 20)
    ap.add_argument("--grouped-lifespans", type=int, default=0,
                    help="0=auto, 1=off, N>=2 force N bucket lifespans")
    ap.add_argument("--grouped-prefetch-depth", type=int, default=1,
                    help="lifespans staged ahead of the one computing "
                         "(0 = strictly serial bucket loop)")
    ap.add_argument("--grouped-stats", action="store_true",
                    help="attach per-query grouped bucket gen/compute/run "
                         "walls from runtime stats to each record")
    ap.add_argument("--json", default=None, help="write results file")
    args = ap.parse_args(argv)

    from .tpch_queries import queries_for_sf
    from ..exec.pipeline import ExecutionConfig
    from ..exec.runner import DistributedQueryRunner, LocalQueryRunner

    suite = queries_for_sf(args.sf)
    nums = (sorted(int(x) for x in args.queries.split(","))
            if args.queries else sorted(suite))
    cfg = ExecutionConfig(batch_rows=args.batch_rows,
                          join_out_capacity=1 << 21,
                          grouped_lifespans=args.grouped_lifespans,
                          grouped_prefetch_depth=args.grouped_prefetch_depth)
    schema = f"sf{args.sf:g}"
    if args.distributed:
        runner = DistributedQueryRunner(schema, config=cfg,
                                        n_tasks=args.distributed)
    else:
        runner = LocalQueryRunner(schema, config=cfg)

    results = []
    for qnum in nums:
        try:
            sql = suite[qnum]
            for _ in range(args.prewarm):
                runner.execute(sql)
            runs = []
            rows = 0
            for _ in range(args.runs):
                t0 = time.perf_counter()
                r = runner.execute(sql)
                runs.append(round(time.perf_counter() - t0, 4))
                rows = len(r.rows)
            rec = {"query": f"q{qnum:02d}", "sf": args.sf,
                   "best_s": min(runs), "runs_s": runs, "rows": rows}
            if args.grouped_stats:
                stats = getattr(r, "runtime_stats", None) or {}
                rec["grouped_stats"] = {
                    k: v for k, v in stats.items()
                    if k.startswith("grouped")}
        except Exception as e:   # noqa: BLE001 — record and continue
            rec = {"query": f"q{qnum:02d}", "sf": args.sf,
                   "error": f"{type(e).__name__}: {e}"}
        results.append(rec)
        print(json.dumps(rec), flush=True)
        if args.json:   # incremental: a killed run keeps prior results
            with open(args.json, "w") as f:
                json.dump({"results": results}, f, indent=1)

    ok = [r for r in results if "best_s" in r]
    summary = {"suite": "tpch", "sf": args.sf,
               "queries_ok": len(ok), "queries_failed":
               len(results) - len(ok),
               "total_best_s": round(sum(r["best_s"] for r in ok), 3)}
    print(json.dumps(summary), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"results": results, "summary": summary}, f,
                      indent=1)
    return 0 if len(ok) == len(results) else 1


if __name__ == "__main__":
    sys.exit(main())
