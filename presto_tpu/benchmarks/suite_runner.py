"""Run the TPC-H suite one query per subprocess with a wall-clock timeout
(the benchto-style black-box runner: a hung query must not sink the suite).

    python -m presto_tpu.benchmarks.suite_runner [--sf 0.1] [--runs 2]
        [--timeout 300] [--json results.json]
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="presto-tpu-suite-runner")
    ap.add_argument("--sf", default="0.1")
    ap.add_argument("--runs", default="2")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)

    out = []
    for q in range(1, 23):
        try:
            p = subprocess.run(
                [sys.executable, "-m", "presto_tpu.benchmarks.driver",
                 "--sf", args.sf, "--runs", args.runs,
                 "--queries", str(q)],
                capture_output=True, text=True, timeout=args.timeout)
            lines = p.stdout.strip().splitlines()
            rec = (json.loads(lines[0]) if lines
                   else {"query": f"q{q:02d}", "sf": float(args.sf),
                         "error": (p.stderr or "no output")[-200:]})
        except subprocess.TimeoutExpired:
            rec = {"query": f"q{q:02d}", "sf": float(args.sf),
                   "error": f"timeout >{args.timeout:g}s"}
        out.append(rec)
        print(json.dumps(rec), flush=True)
        if args.json:
            with open(args.json, "w") as f:
                json.dump({"results": out}, f, indent=1)
    ok = [r for r in out if "best_s" in r]
    print(json.dumps({"suite": "tpch", "sf": float(args.sf),
                      "queries_ok": len(ok),
                      "queries_failed": len(out) - len(ok),
                      "total_best_s": round(sum(r["best_s"]
                                                for r in ok), 3)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
