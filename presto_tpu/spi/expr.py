"""RowExpression IR (reference presto-spi/.../spi/relation/RowExpression.java).

JSON shape follows the reference Jackson bindings: polymorphic on "@type" with
names "call" / "special" / "lambda" / "input" / "variable" / "constant"
(RowExpression.java:31-36), types carried as signature strings.

Constant values are held as python objects in their logical form (int for
integral, decimal.Decimal for decimals, float for double, str for varchar,
bool, None).  JSON has no decimal type, so Decimal constants serialize as
strings and from_dict re-parses them by the carried type signature.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from decimal import Decimal
from typing import Any, List, Optional, Tuple

from ..common.types import DecimalType, Type, parse_type


class RowExpression:
    type: Type

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "RowExpression":
        kind = d["@type"]
        if kind == "constant":
            typ = parse_type(d["type"])
            if "valueBlock" in d:
                value = d["valueBlock"]
            else:
                value = d.get("value")
                # JSON has no decimal: decimals travel as strings (to_dict)
                if isinstance(typ, DecimalType) and isinstance(value, str):
                    value = Decimal(value)
            return ConstantExpression(value, typ)
        if kind == "variable":
            return VariableReferenceExpression(d["name"], parse_type(d["type"]))
        if kind == "call":
            return CallExpression(
                d.get("displayName", d.get("functionHandle", "?")),
                parse_type(d["returnType"]),
                [RowExpression.from_dict(a) for a in d["arguments"]],
                function_handle=d.get("functionHandle"))
        if kind == "special":
            return SpecialFormExpression(
                d["form"], parse_type(d["returnType"]),
                [RowExpression.from_dict(a) for a in d["arguments"]])
        if kind == "lambda":
            return LambdaExpression(
                [a for a in d["argumentTypes"]],
                d["arguments"], RowExpression.from_dict(d["body"]))
        if kind == "input":
            return InputReferenceExpression(d["field"], parse_type(d["type"]))
        if kind == "parameter":
            return BoundParameterExpression(d["index"], parse_type(d["type"]))
        raise ValueError(f"unknown RowExpression @type {kind!r}")


@dataclass
class ConstantExpression(RowExpression):
    value: Any
    type: Type
    # Provenance for prepared-statement binding: which `?` slot (by ordinal)
    # this literal came from.  Deliberately excluded from equality, repr and
    # to_dict so it can never leak into structural keys or serialized plans;
    # a folded constant simply loses its origin and stays a fixed literal.
    origin: Optional[int] = field(default=None, compare=False, repr=False)

    def to_dict(self):
        value = self.value
        if isinstance(value, Decimal):
            value = str(value)  # JSON-safe; from_dict re-parses by type
        return {"@type": "constant", "value": value,
                "type": self.type.signature}

    def __str__(self):
        return f"{self.value!r}:{self.type}"


@dataclass
class VariableReferenceExpression(RowExpression):
    name: str
    type: Type

    def to_dict(self):
        return {"@type": "variable", "name": self.name,
                "type": self.type.signature}

    def __hash__(self):
        return hash((self.name, self.type.signature))

    def __eq__(self, other):
        return (isinstance(other, VariableReferenceExpression)
                and self.name == other.name
                and self.type.signature == other.type.signature)

    def __str__(self):
        return self.name


@dataclass
class CallExpression(RowExpression):
    """Function call.  `display_name` is the engine-facing function name (e.g.
    "$operator$add", "sum", "lower"); lowering resolves it in the registry."""

    display_name: str
    type: Type
    arguments: List[RowExpression]
    function_handle: Optional[str] = None

    def to_dict(self):
        return {"@type": "call", "displayName": self.display_name,
                "functionHandle": self.function_handle or self.display_name,
                "returnType": self.type.signature,
                "arguments": [a.to_dict() for a in self.arguments]}

    def __str__(self):
        return f"{self.display_name}({', '.join(map(str, self.arguments))})"


# Reference SpecialFormExpression.Form values
SPECIAL_FORMS = (
    "IF", "NULL_IF", "SWITCH", "WHEN", "IS_NULL", "COALESCE", "IN",
    "AND", "OR", "DEREFERENCE", "ROW_CONSTRUCTOR", "BIND",
)


@dataclass
class SpecialFormExpression(RowExpression):
    form: str
    type: Type
    arguments: List[RowExpression]

    def __post_init__(self):
        if self.form not in SPECIAL_FORMS:
            raise ValueError(f"unknown special form {self.form!r}")

    def to_dict(self):
        return {"@type": "special", "form": self.form,
                "returnType": self.type.signature,
                "arguments": [a.to_dict() for a in self.arguments]}

    def __str__(self):
        return f"{self.form}({', '.join(map(str, self.arguments))})"


@dataclass
class LambdaExpression(RowExpression):
    argument_types: List[str]
    arguments: List[str]
    body: RowExpression

    @property
    def type(self):  # function type; not used for block layout
        return self.body.type

    def to_dict(self):
        return {"@type": "lambda", "argumentTypes": self.argument_types,
                "arguments": self.arguments, "body": self.body.to_dict()}


@dataclass
class InputReferenceExpression(RowExpression):
    field: int
    type: Type

    def to_dict(self):
        return {"@type": "input", "field": self.field,
                "type": self.type.signature}


@dataclass
class BoundParameterExpression(RowExpression):
    """A literal extracted into the bound-parameter vector by the serving
    tier's plan canonicalizer (sql/canonical.py).  Not a ConstantExpression
    subclass on purpose: constant folding, hoisting, trivial-filter removal
    and scan pushdown all test `isinstance(_, ConstantExpression)` and must
    treat a parameter as opaque.  Lowering reads `batch.params[index]`."""

    index: int
    type: Type

    def to_dict(self):
        return {"@type": "parameter", "index": self.index,
                "type": self.type.signature}

    def __str__(self):
        return f"?{self.index}:{self.type}"


# ---------------------------------------------------------------------------
# convenience builders used by the planner / tests
# ---------------------------------------------------------------------------

def variable(name: str, typ: Type) -> VariableReferenceExpression:
    return VariableReferenceExpression(name, typ)


def constant(value, typ: Type) -> ConstantExpression:
    return ConstantExpression(value, typ)


def call(name: str, return_type: Type, *args: RowExpression) -> CallExpression:
    return CallExpression(name, return_type, list(args))


def special(form: str, return_type: Type, *args: RowExpression) -> SpecialFormExpression:
    return SpecialFormExpression(form, return_type, list(args))


def and_(*args: RowExpression) -> RowExpression:
    from ..common.types import BOOLEAN
    args = [a for a in args if a is not None]
    if not args:
        return constant(True, BOOLEAN)
    if len(args) == 1:
        return args[0]
    out = args[0]
    for a in args[1:]:
        out = special("AND", BOOLEAN, out, a)
    return out


def free_variables(expr: RowExpression) -> List[VariableReferenceExpression]:
    out: List[VariableReferenceExpression] = []
    seen = set()

    def walk(e: RowExpression):
        if isinstance(e, VariableReferenceExpression):
            if e.name not in seen:
                seen.add(e.name)
                out.append(e)
        elif isinstance(e, CallExpression) or isinstance(e, SpecialFormExpression):
            for a in e.arguments:
                walk(a)
        elif isinstance(e, LambdaExpression):
            walk(e.body)

    walk(expr)
    return out
