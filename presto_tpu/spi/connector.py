"""The connector SPI: formal interfaces between the engine and storage.

The analog of the reference plugin surface —
presto-spi/.../spi/Plugin.java:42 (getConnectorFactories),
spi/connector/ConnectorFactory.java, Connector.java,
ConnectorMetadata.java:73 (tables/columns/statistics),
ConnectorSplitManager.java:23 (splits),
ConnectorPageSourceProvider.java:26 / ConnectorPageSource.java:23
(page streams per split).

Two adapters bridge to the engine's registry (connectors/catalog.py),
whose built-ins predate this surface and are module-shaped:

  * module_connector(cid, module) — view any registered duck-typed
    connector module THROUGH these interfaces (metadata, splits, page
    sources), so SPI consumers see one shape for every catalog.
  * register_plugin(plugin, ...) — register third-party connectors
    written AGAINST these interfaces: each factory's Connector is
    wrapped in a module-shaped shim the engine's scan/metadata layers
    consume, giving plugin authors the reference contract (implement
    ConnectorMetadata + ConnectorSplitManager + PageSourceProvider and
    every engine path — planner, pipeline, oracle, worker protocol —
    just works).
"""
from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..common.page import Page
from ..common.types import Type


# ---------------------------------------------------------------------------
# interfaces
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SchemaTableName:
    schema: str
    table: str


class ConnectorMetadata(abc.ABC):
    """Table/column metadata (ConnectorMetadata.java:73)."""

    @abc.abstractmethod
    def list_tables(self) -> List[str]:
        ...

    @abc.abstractmethod
    def get_columns(self, table: str) -> List[Tuple[str, Type]]:
        """Ordered (column name, type) pairs; KeyError for unknown."""
        ...

    def get_table_statistics(self, table: str, column: str,
                             scale_factor: float):
        """ColumnStats or None (getTableStatistics analog)."""
        return None


class ConnectorSplit(abc.ABC):
    """An addressable shard of a table (ConnectorSplit); row-range splits
    carry (start, end)."""


@dataclass(frozen=True)
class RowRangeSplit(ConnectorSplit):
    table: str
    start: int
    end: int


class ConnectorSplitManager(abc.ABC):
    """ConnectorSplitManager.java:23."""

    @abc.abstractmethod
    def get_splits(self, table: str, scale_factor: float,
                   desired_splits: int) -> List[ConnectorSplit]:
        ...


class ConnectorPageSource(abc.ABC):
    """A finite stream of Pages for one split
    (ConnectorPageSource.java:23)."""

    @abc.abstractmethod
    def pages(self) -> Iterator[Page]:
        ...


class ConnectorPageSourceProvider(abc.ABC):
    """ConnectorPageSourceProvider.java:26."""

    @abc.abstractmethod
    def create_page_source(self, split: ConnectorSplit,
                           columns: Optional[Sequence[str]],
                           scale_factor: float) -> ConnectorPageSource:
        ...


class Connector(abc.ABC):
    """One catalog's services (Connector.java)."""

    @abc.abstractmethod
    def get_metadata(self) -> ConnectorMetadata:
        ...

    @abc.abstractmethod
    def get_split_manager(self) -> ConnectorSplitManager:
        ...

    @abc.abstractmethod
    def get_page_source_provider(self) -> ConnectorPageSourceProvider:
        ...


class ConnectorFactory(abc.ABC):
    """ConnectorFactory: name + create(config) -> Connector."""

    @property
    @abc.abstractmethod
    def name(self) -> str:
        ...

    @abc.abstractmethod
    def create(self, catalog_name: str, config: Dict[str, str]) -> Connector:
        ...


class Plugin:
    """Plugin.java:42 — the unit third parties ship."""

    def get_connector_factories(self) -> List[ConnectorFactory]:
        return []


# ---------------------------------------------------------------------------
# adapter: duck-typed registered module -> SPI view
# ---------------------------------------------------------------------------

class _ModuleMetadata(ConnectorMetadata):
    def __init__(self, module):
        self._m = module

    def list_tables(self):
        return sorted(self._m.SCHEMAS)

    def get_columns(self, table):
        return list(self._m.SCHEMAS[table])

    def get_table_statistics(self, table, column, scale_factor):
        fn = getattr(self._m, "column_stats", None)
        return None if fn is None else fn(table, column, scale_factor)


class _ModuleSplitManager(ConnectorSplitManager):
    def __init__(self, module):
        self._m = module

    def get_splits(self, table, scale_factor, desired_splits):
        total = self._m.table_row_count(table, scale_factor)
        per = max(1, (total + desired_splits - 1) // max(1, desired_splits))
        return [RowRangeSplit(table, lo, min(lo + per, total))
                for lo in range(0, total, per)]


class _ModulePageSource(ConnectorPageSource):
    def __init__(self, module, split: RowRangeSplit, columns, sf,
                 page_rows: int = 1 << 16):
        self._m, self._split = module, split
        self._columns, self._sf, self._page_rows = columns, sf, page_rows

    def pages(self):
        from ..common.block import block_from_values
        from ..connectors.catalog import HostColumn
        m, s = self._m, self._split
        cols = self._columns or [c for c, _t in m.SCHEMAS[s.table]]
        pos = s.start
        while pos < s.end:
            n = min(self._page_rows, s.end - pos)
            if hasattr(m, "generate_page"):
                yield m.generate_page(s.table, self._sf, pos, n, cols)
            else:
                blocks = []
                for c in cols:
                    typ = m.column_type(s.table, c)
                    raw = m.generate_column(s.table, c, self._sf, pos, n)
                    if isinstance(raw, HostColumn):
                        raw = raw.values
                    if isinstance(raw, tuple):
                        codes, values = raw
                        blocks.append(block_from_values(
                            typ, [values[k] for k in codes]))
                    elif isinstance(raw, list):
                        blocks.append(block_from_values(typ, raw))
                    else:
                        blocks.append(block_from_values(
                            typ, np.asarray(raw).tolist()))
                yield Page(blocks, n)
            pos += n


class _ModulePageSourceProvider(ConnectorPageSourceProvider):
    def __init__(self, module):
        self._m = module

    def create_page_source(self, split, columns, scale_factor):
        return _ModulePageSource(self._m, split, columns, scale_factor)


class ModuleConnector(Connector):
    """SPI view over a duck-typed registered connector module."""

    def __init__(self, connector_id: str, module):
        self.connector_id = connector_id
        self._module = module

    def get_metadata(self):
        return _ModuleMetadata(self._module)

    def get_split_manager(self):
        return _ModuleSplitManager(self._module)

    def get_page_source_provider(self):
        return _ModulePageSourceProvider(self._module)


def module_connector(connector_id: str) -> ModuleConnector:
    """SPI view of a connector registered in the engine catalog."""
    from ..connectors import catalog
    return ModuleConnector(connector_id, catalog.module(connector_id))


# ---------------------------------------------------------------------------
# adapter: SPI Connector -> duck-typed module shim (register_plugin)
# ---------------------------------------------------------------------------

class _ConnectorModuleShim:
    """Presents an SPI Connector as the module surface the engine's
    catalog/scan layers consume — the inverse adapter, so connectors
    written against the reference-shaped interfaces run end to end."""

    def __init__(self, connector: Connector):
        self._c = connector
        meta = connector.get_metadata()
        self.SCHEMAS = {t: list(meta.get_columns(t))
                        for t in meta.list_tables()}
        self.PREFIXES = {t: "" for t in self.SCHEMAS}
        self.OPEN_DOMAIN = set()
        self.ROWID_ORDERED = set()
        self.ROWID_DISTINCT = set()
        # engine operators assume a TABLE-STABLE dictionary per string
        # column (codes comparable across batches/splits), so the shim
        # builds one dictionary over the whole column and reuses it for
        # every range (the hive connector's table-wide-dictionary rule)
        self._dicts: Dict[Tuple[str, str, float], list] = {}

    def column_type(self, table, column):
        for c, t in self.SCHEMAS[table]:
            if c == column:
                return t
        raise KeyError(f"{table}.{column}")

    def table_row_count(self, table, sf):
        # one maximal split describes the table extent
        splits = self._c.get_split_manager().get_splits(table, sf, 1)
        return max((s.end for s in splits
                    if isinstance(s, RowRangeSplit)), default=0)

    def column_stats(self, table, column, sf):
        return self._c.get_metadata().get_table_statistics(table, column,
                                                           sf)

    def _read(self, table, columns, sf, start, count):
        from ..common.block import block_to_values
        provider = self._c.get_page_source_provider()
        src = provider.create_page_source(
            RowRangeSplit(table, start, start + count), columns, sf)
        out = {c: [] for c in columns}
        for page in src.pages():
            for c, block in zip(columns, page.blocks):
                out[c].extend(block_to_values(
                    self.column_type(table, c), block))
        return out

    def generate_column(self, table, column, sf, start, count):
        from ..connectors.catalog import HostColumn
        vals = self._read(table, [column], sf, start, count)[column]
        typ = self.column_type(table, column)
        nulls = np.array([v is None for v in vals], dtype=bool)
        if typ.signature.startswith(("varchar", "char")):
            # dictionary-encode against the TABLE-STABLE dictionary: the
            # scan's host path consumes (codes, values) pairs and engine
            # operators compare codes across batches
            key = (table, column, sf)
            uniq = self._dicts.get(key)
            if uniq is None:
                total = self.table_row_count(table, sf)
                allv = self._read(table, [column], sf, 0, total)[column]
                uniq = sorted({v for v in allv if v is not None}) or [""]
                self._dicts[key] = uniq
            index = {v: i for i, v in enumerate(uniq)}
            codes = np.array([0 if v is None else index[v] for v in vals],
                             dtype=np.int32)
            return HostColumn((codes, uniq),
                              nulls if nulls.any() else None)
        from ..common.types import (BooleanType, DateType, DecimalType,
                                    DoubleType, RealType)
        if isinstance(typ, DecimalType):
            arr = np.array([0 if v is None else int(v * 10 ** typ.scale)
                            for v in vals], dtype=np.int64)
        elif isinstance(typ, (DoubleType, RealType)):
            arr = np.array([0.0 if v is None else float(v) for v in vals],
                           dtype=np.float64)
        elif isinstance(typ, BooleanType):
            arr = np.array([bool(v) for v in vals], dtype=bool)
        elif isinstance(typ, DateType):
            arr = np.array([0 if v is None
                            else int(np.datetime64(v, "D").astype(np.int64))
                            for v in vals], dtype=np.int64)
        else:
            arr = np.array([0 if v is None else int(v) for v in vals],
                           dtype=np.int64)
        return HostColumn(arr, nulls if nulls.any() else None)

    def generate_values_at(self, table, column, sf, ids):
        # coalesce contiguous id runs into one ranged _read each: lazy
        # row-id gathers come in mostly-sequential batches, and a
        # storage connector's per-call overhead (seek, page decode)
        # dwarfs the cost of the extra rows in a run
        ids = np.asarray(ids, dtype=np.int64)
        out = []
        i, n = 0, len(ids)
        while i < n:
            j = i + 1
            while j < n and ids[j] == ids[j - 1] + 1:
                j += 1
            out.extend(self._read(table, [column], sf, int(ids[i]),
                                  j - i)[column])
            i = j
        return out


def register_plugin(plugin: Plugin,
                    config: Optional[Dict[str, str]] = None,
                    catalog_prefix: str = "") -> List[str]:
    """Install every connector factory a plugin ships (the PluginManager
    analog).  Each factory registers under catalog_prefix + factory.name;
    returns the registered catalog names."""
    from ..connectors import catalog
    registered = []
    for factory in plugin.get_connector_factories():
        name = catalog_prefix + factory.name
        conn = factory.create(name, dict(config or {}))
        catalog.register_connector(name, _ConnectorModuleShim(conn))
        registered.append(name)
    return registered
