"""Plan IR (reference presto-spi/.../spi/plan/*.java + presto-main-base
sql/planner/plan/*.java).

Node set covers what the reference fragmenter can send to a leaf/intermediate
worker for the TPC-H / TPC-DS vocabulary.  JSON uses the reference's Jackson
MINIMAL_CLASS discriminator style ("@type": ".FilterNode").
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..common.types import Type, parse_type
from .expr import (CallExpression, RowExpression, VariableReferenceExpression)

Variable = VariableReferenceExpression


# ---------------------------------------------------------------------------
# handles
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ColumnHandle:
    """Connector column reference (reference spi/ColumnHandle)."""
    name: str
    type: Type

    def to_dict(self):
        return {"name": self.name, "type": self.type.signature}

    @staticmethod
    def from_dict(d):
        return ColumnHandle(d["name"], parse_type(d["type"]))


@dataclass(frozen=True)
class TableHandle:
    """Connector table reference (reference spi/TableHandle)."""
    connector_id: str
    schema_name: str
    table_name: str
    # connector-specific payload, e.g. {"scaleFactor": 1.0} for tpch
    extra: Tuple[Tuple[str, Any], ...] = ()

    def to_dict(self):
        return {"connectorId": self.connector_id, "schema": self.schema_name,
                "table": self.table_name, "extra": dict(self.extra)}

    @staticmethod
    def from_dict(d):
        return TableHandle(d["connectorId"], d["schema"], d["table"],
                           tuple(sorted(d.get("extra", {}).items())))


# Sort orders (reference spi/block/SortOrder.java)
ASC_NULLS_FIRST = "ASC_NULLS_FIRST"
ASC_NULLS_LAST = "ASC_NULLS_LAST"
DESC_NULLS_FIRST = "DESC_NULLS_FIRST"
DESC_NULLS_LAST = "DESC_NULLS_LAST"


@dataclass
class OrderingScheme:
    orderings: List[Tuple[Variable, str]]  # (variable, sort order)

    def to_dict(self):
        return {"orderBy": [{"variable": v.to_dict(), "sortOrder": o}
                            for v, o in self.orderings]}

    @staticmethod
    def from_dict(d):
        return OrderingScheme([
            (RowExpression.from_dict(e["variable"]), e["sortOrder"])
            for e in d["orderBy"]])


# Partitioning handles (reference SystemPartitioningHandle.java:62-68)
SINGLE_DISTRIBUTION = "SINGLE"
FIXED_HASH_DISTRIBUTION = "FIXED_HASH"
FIXED_ARBITRARY_DISTRIBUTION = "FIXED_ARBITRARY"
FIXED_BROADCAST_DISTRIBUTION = "FIXED_BROADCAST"
SOURCE_DISTRIBUTION = "SOURCE"
SCALED_WRITER_DISTRIBUTION = "SCALED_WRITER"


@dataclass
class PartitioningScheme:
    handle: str                      # one of the *_DISTRIBUTION constants
    arguments: List[Variable]        # partitioning columns (hash)
    output_layout: List[Variable]
    # resolved exchange fabric of the remote edge this scheme describes
    # ("http" | "ici", parallel/fabric.py), annotated post-fragmentation
    # by the fragmenter/scheduler; None = unannotated (local exchanges,
    # plans never fragmented).  Emitted in serde only when set so golden
    # plan JSON and structural keys of unannotated plans are unchanged
    fabric: Optional[str] = None

    def to_dict(self):
        d = {"partitioning": {"handle": self.handle,
                              "arguments": [a.to_dict() for a in self.arguments]},
             "outputLayout": [v.to_dict() for v in self.output_layout]}
        if self.fabric is not None:
            d["fabric"] = self.fabric
        return d

    @staticmethod
    def from_dict(d):
        return PartitioningScheme(
            d["partitioning"]["handle"],
            [RowExpression.from_dict(a) for a in d["partitioning"]["arguments"]],
            [RowExpression.from_dict(v) for v in d["outputLayout"]],
            d.get("fabric"))


# ---------------------------------------------------------------------------
# plan nodes
# ---------------------------------------------------------------------------

_NODE_REGISTRY: Dict[str, type] = {}


def _node(cls):
    _NODE_REGISTRY["." + cls.__name__] = cls
    return cls


@dataclass
class PlanNode:
    id: str

    @property
    def sources(self) -> List["PlanNode"]:
        return []

    @property
    def output_variables(self) -> List[Variable]:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = self._to_dict()
        d["@type"] = "." + type(self).__name__
        d["id"] = self.id
        return d

    @staticmethod
    def from_dict(d: dict) -> "PlanNode":
        cls = _NODE_REGISTRY[d["@type"]]
        return cls._from_dict(d)


def _vars_to_dict(vs):
    return [v.to_dict() for v in vs]


def _vars_from_dict(ds):
    return [RowExpression.from_dict(x) for x in ds]


@_node
@dataclass
class TableScanNode(PlanNode):
    table: TableHandle
    outputs: List[Variable] = field(default_factory=list)
    assignments: Dict[Variable, ColumnHandle] = field(default_factory=dict)
    # range/equality conjuncts pushed down from the parent FilterNode by
    # sql/optimizer.plan_scan_pushdown: [{"column", "op", "value"}, ...]
    # with op in storage.pushdown.PUSHDOWN_OPS.  ADVISORY — consumed for
    # zone-map chunk skipping; the filter itself stays in the plan.
    # Validated by analysis/checker.py (SCAN_PUSHDOWN).
    pushdown: List[dict] = field(default_factory=list)
    # runtime dynamic filters this scan may consume, planned by
    # sql/optimizer.plan_runtime_filter_pushdown:
    # [{"id": filter_id, "column": column_name}, ...].  Each entry also
    # appends ["dyn", id, bound] marker rows to `pushdown`, resolved at
    # prune time from summaries a completed build stage published.
    runtime_filters: List[dict] = field(default_factory=list)

    @property
    def output_variables(self):
        return self.outputs

    def _to_dict(self):
        d = {"table": self.table.to_dict(),
             "outputVariables": _vars_to_dict(self.outputs),
             "assignments": [{"variable": v.to_dict(), "column": c.to_dict()}
                             for v, c in self.assignments.items()]}
        if self.pushdown:
            # emitted only when present: golden plan JSON stays stable
            d["pushdown"] = [dict(e) for e in self.pushdown]
        if self.runtime_filters:
            d["runtimeFilters"] = [dict(e) for e in self.runtime_filters]
        return d

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], TableHandle.from_dict(d["table"]),
                   _vars_from_dict(d["outputVariables"]),
                   {RowExpression.from_dict(e["variable"]): ColumnHandle.from_dict(e["column"])
                    for e in d["assignments"]},
                   [dict(e) for e in d.get("pushdown", [])],
                   [dict(e) for e in d.get("runtimeFilters", [])])


@_node
@dataclass
class FilterNode(PlanNode):
    source: PlanNode
    predicate: RowExpression

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.source.output_variables

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "predicate": self.predicate.to_dict()}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   RowExpression.from_dict(d["predicate"]))


@_node
@dataclass
class ProjectNode(PlanNode):
    source: PlanNode
    assignments: Dict[Variable, RowExpression]

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return list(self.assignments.keys())

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "assignments": [{"variable": v.to_dict(), "expression": e.to_dict()}
                                for v, e in self.assignments.items()]}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   {RowExpression.from_dict(e["variable"]): RowExpression.from_dict(e["expression"])
                    for e in d["assignments"]})


# Aggregation steps (reference AggregationNode.Step)
PARTIAL = "PARTIAL"
FINAL = "FINAL"
INTERMEDIATE = "INTERMEDIATE"
SINGLE = "SINGLE"


@dataclass
class Aggregation:
    """One aggregate: call like sum(x), optional filter/mask, distinct flag."""
    call: CallExpression
    distinct: bool = False
    mask: Optional[Variable] = None

    def to_dict(self):
        return {"call": self.call.to_dict(), "distinct": self.distinct,
                "mask": self.mask.to_dict() if self.mask else None}

    @staticmethod
    def from_dict(d):
        return Aggregation(
            RowExpression.from_dict(d["call"]), d.get("distinct", False),
            RowExpression.from_dict(d["mask"]) if d.get("mask") else None)


@_node
@dataclass
class AggregationNode(PlanNode):
    source: PlanNode
    aggregations: Dict[Variable, Aggregation]
    grouping_keys: List[Variable]
    step: str = SINGLE

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return list(self.grouping_keys) + list(self.aggregations.keys())

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "aggregations": [{"variable": v.to_dict(), "aggregation": a.to_dict()}
                                 for v, a in self.aggregations.items()],
                "groupingKeys": _vars_to_dict(self.grouping_keys),
                "step": self.step}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   {RowExpression.from_dict(e["variable"]): Aggregation.from_dict(e["aggregation"])
                    for e in d["aggregations"]},
                   _vars_from_dict(d["groupingKeys"]), d["step"])


# Join types (reference spi/plan/JoinType.java)
INNER = "INNER"
LEFT = "LEFT"
RIGHT = "RIGHT"
FULL = "FULL"

PARTITIONED = "PARTITIONED"
REPLICATED = "REPLICATED"


@_node
@dataclass
class JoinNode(PlanNode):
    join_type: str
    left: PlanNode
    right: PlanNode
    criteria: List[Tuple[Variable, Variable]]  # left var == right var
    outputs: List[Variable]
    filter: Optional[RowExpression] = None
    distribution: Optional[str] = None  # PARTITIONED / REPLICATED
    # dynamic filter id per RECEIVING key variable (reference
    # JoinNode.dynamicFilters / DynamicFilterSourceOperator).  Direction
    # depends on join type — the filter may only drop rows from a
    # NON-PRESERVED side: INNER keys are probe (left) variables narrowed
    # by the build domain; LEFT keys are build (right) variables narrowed
    # by the probe domain (the probe is preserved and must never shrink).
    dynamic_filters: Dict[str, str] = field(default_factory=dict)
    # the fragmenter's build-side row estimate at exchange-decision time;
    # exec/adaptive.decide_exchange compares it against the observed
    # count at the stage boundary
    planned_build_rows: Optional[int] = None

    @property
    def sources(self):
        return [self.left, self.right]

    @property
    def output_variables(self):
        return self.outputs

    def _to_dict(self):
        d = {"type": self.join_type, "left": self.left.to_dict(),
             "right": self.right.to_dict(),
             "criteria": [{"left": l.to_dict(), "right": r.to_dict()}
                          for l, r in self.criteria],
             "outputVariables": _vars_to_dict(self.outputs),
             "filter": self.filter.to_dict() if self.filter else None,
             "distributionType": self.distribution,
             "dynamicFilters": dict(self.dynamic_filters)}
        if self.planned_build_rows is not None:
            d["plannedBuildRows"] = self.planned_build_rows
        return d

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], d["type"], PlanNode.from_dict(d["left"]),
                   PlanNode.from_dict(d["right"]),
                   [(RowExpression.from_dict(c["left"]), RowExpression.from_dict(c["right"]))
                    for c in d["criteria"]],
                   _vars_from_dict(d["outputVariables"]),
                   RowExpression.from_dict(d["filter"]) if d.get("filter") else None,
                   d.get("distributionType"),
                   d.get("dynamicFilters", {}),
                   d.get("plannedBuildRows"))


@_node
@dataclass
class SemiJoinNode(PlanNode):
    source: PlanNode
    filtering_source: PlanNode
    source_join_variable: Variable
    filtering_source_join_variable: Variable
    semi_join_output: Variable
    # dynamic filter id keyed by the SOURCE join variable, set only when
    # the membership marker is consumed as a positive filter conjunct
    # (so source rows outside the filtering-source domain are droppable)
    dynamic_filters: Dict[str, str] = field(default_factory=dict)

    @property
    def sources(self):
        return [self.source, self.filtering_source]

    @property
    def output_variables(self):
        return self.source.output_variables + [self.semi_join_output]

    def _to_dict(self):
        d = {"source": self.source.to_dict(),
             "filteringSource": self.filtering_source.to_dict(),
             "sourceJoinVariable": self.source_join_variable.to_dict(),
             "filteringSourceJoinVariable": self.filtering_source_join_variable.to_dict(),
             "semiJoinOutput": self.semi_join_output.to_dict()}
        if self.dynamic_filters:
            # emitted only when present: golden plan JSON stays stable
            d["dynamicFilters"] = dict(self.dynamic_filters)
        return d

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   PlanNode.from_dict(d["filteringSource"]),
                   RowExpression.from_dict(d["sourceJoinVariable"]),
                   RowExpression.from_dict(d["filteringSourceJoinVariable"]),
                   RowExpression.from_dict(d["semiJoinOutput"]),
                   d.get("dynamicFilters", {}))


# Exchange (reference sql/planner/plan/ExchangeNode.java)
GATHER = "GATHER"
REPARTITION = "REPARTITION"
REPLICATE = "REPLICATE"
LOCAL = "LOCAL"
REMOTE = "REMOTE"


@_node
@dataclass
class ExchangeNode(PlanNode):
    exchange_type: str                  # GATHER / REPARTITION / REPLICATE
    scope: str                          # LOCAL / REMOTE
    partitioning_scheme: PartitioningScheme
    exchange_sources: List[PlanNode]
    # inputs[i][j]: variable of sources[i] feeding output_layout[j]
    inputs: List[List[Variable]] = field(default_factory=list)

    @property
    def sources(self):
        return self.exchange_sources

    @property
    def output_variables(self):
        return self.partitioning_scheme.output_layout

    def _to_dict(self):
        return {"exchangeType": self.exchange_type, "scope": self.scope,
                "partitioningScheme": self.partitioning_scheme.to_dict(),
                "sources": [s.to_dict() for s in self.exchange_sources],
                "inputs": [_vars_to_dict(row) for row in self.inputs]}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], d["exchangeType"], d["scope"],
                   PartitioningScheme.from_dict(d["partitioningScheme"]),
                   [PlanNode.from_dict(s) for s in d["sources"]],
                   [_vars_from_dict(row) for row in d.get("inputs", [])])


@_node
@dataclass
class RemoteSourceNode(PlanNode):
    """Leaf in a fragment: reads the output of other fragments
    (reference sql/planner/plan/RemoteSourceNode.java)."""
    source_fragment_ids: List[str]
    outputs: List[Variable]
    ensure_source_ordering: bool = False
    ordering_scheme: Optional[OrderingScheme] = None

    @property
    def output_variables(self):
        return self.outputs

    def _to_dict(self):
        return {"sourceFragmentIds": self.source_fragment_ids,
                "outputVariables": _vars_to_dict(self.outputs),
                "ensureSourceOrdering": self.ensure_source_ordering,
                "orderingScheme": self.ordering_scheme.to_dict() if self.ordering_scheme else None}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], d["sourceFragmentIds"],
                   _vars_from_dict(d["outputVariables"]),
                   d.get("ensureSourceOrdering", False),
                   OrderingScheme.from_dict(d["orderingScheme"]) if d.get("orderingScheme") else None)


@_node
@dataclass
class SortNode(PlanNode):
    source: PlanNode
    ordering_scheme: OrderingScheme
    is_partial: bool = False

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.source.output_variables

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "orderingScheme": self.ordering_scheme.to_dict(),
                "isPartial": self.is_partial}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   OrderingScheme.from_dict(d["orderingScheme"]),
                   d.get("isPartial", False))


@_node
@dataclass
class TopNNode(PlanNode):
    source: PlanNode
    count: int
    ordering_scheme: OrderingScheme
    step: str = SINGLE  # SINGLE / PARTIAL / FINAL

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.source.output_variables

    def _to_dict(self):
        return {"source": self.source.to_dict(), "count": self.count,
                "orderingScheme": self.ordering_scheme.to_dict(),
                "step": self.step}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]), d["count"],
                   OrderingScheme.from_dict(d["orderingScheme"]),
                   d.get("step", SINGLE))


@_node
@dataclass
class LimitNode(PlanNode):
    source: PlanNode
    count: int
    step: str = SINGLE  # PARTIAL / FINAL

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.source.output_variables

    def _to_dict(self):
        return {"source": self.source.to_dict(), "count": self.count,
                "step": self.step}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]), d["count"],
                   d.get("step", SINGLE))


@_node
@dataclass
class DistinctLimitNode(PlanNode):
    source: PlanNode
    count: int
    distinct_variables: List[Variable] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.distinct_variables

    def _to_dict(self):
        return {"source": self.source.to_dict(), "count": self.count,
                "distinctVariables": _vars_to_dict(self.distinct_variables)}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]), d["count"],
                   _vars_from_dict(d["distinctVariables"]))


@_node
@dataclass
class ValuesNode(PlanNode):
    outputs: List[Variable]
    rows: List[List[RowExpression]] = field(default_factory=list)

    @property
    def output_variables(self):
        return self.outputs

    def _to_dict(self):
        return {"outputVariables": _vars_to_dict(self.outputs),
                "rows": [[e.to_dict() for e in row] for row in self.rows]}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], _vars_from_dict(d["outputVariables"]),
                   [[RowExpression.from_dict(e) for e in row] for row in d["rows"]])


@_node
@dataclass
class OutputNode(PlanNode):
    source: PlanNode
    column_names: List[str]
    outputs: List[Variable] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.outputs

    def _to_dict(self):
        return {"source": self.source.to_dict(), "columnNames": self.column_names,
                "outputVariables": _vars_to_dict(self.outputs)}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]), d["columnNames"],
                   _vars_from_dict(d["outputVariables"]))


@_node
@dataclass
class MarkDistinctNode(PlanNode):
    source: PlanNode
    marker: Variable
    distinct_variables: List[Variable] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.source.output_variables + [self.marker]

    def _to_dict(self):
        return {"source": self.source.to_dict(), "marker": self.marker.to_dict(),
                "distinctVariables": _vars_to_dict(self.distinct_variables)}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   RowExpression.from_dict(d["marker"]),
                   _vars_from_dict(d["distinctVariables"]))


@_node
@dataclass
class GroupIdNode(PlanNode):
    """Grouping-set row expansion (reference GroupIdNode,
    presto_protocol_core.h:1340-1349, executed by GroupIdOperator.java):
    each input row is replicated once per grouping set with the grouping
    columns absent from that set null-filled and `group_id_variable` set to
    the set's ordinal.  The AggregationNode above groups by
    (grouping columns..., group_id)."""
    source: PlanNode
    grouping_sets: List[List[Variable]]           # per-set OUTPUT columns
    grouping_columns: Dict[Variable, Variable]    # output -> input column
    aggregation_arguments: List[Variable] = field(default_factory=list)
    group_id_variable: Variable = None

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return (list(self.grouping_columns) + self.aggregation_arguments
                + [self.group_id_variable])

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "groupingSets": [_vars_to_dict(s)
                                 for s in self.grouping_sets],
                "groupingColumns": [{"output": o.to_dict(),
                                     "input": i.to_dict()}
                                    for o, i in
                                    self.grouping_columns.items()],
                "aggregationArguments":
                    _vars_to_dict(self.aggregation_arguments),
                "groupIdVariable": self.group_id_variable.to_dict()}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   [_vars_from_dict(s) for s in d["groupingSets"]],
                   {RowExpression.from_dict(e["output"]):
                    RowExpression.from_dict(e["input"])
                    for e in d["groupingColumns"]},
                   _vars_from_dict(d["aggregationArguments"]),
                   RowExpression.from_dict(d["groupIdVariable"]))


@_node
@dataclass
class EnforceSingleRowNode(PlanNode):
    source: PlanNode

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.source.output_variables

    def _to_dict(self):
        return {"source": self.source.to_dict()}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]))


@_node
@dataclass
class AssignUniqueIdNode(PlanNode):
    source: PlanNode
    id_variable: Variable = None

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.source.output_variables + [self.id_variable]

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "idVariable": self.id_variable.to_dict()}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   RowExpression.from_dict(d["idVariable"]))


@dataclass
class WindowFunction:
    call: CallExpression
    frame: Optional[dict] = None  # frame spec; None == default RANGE UNBOUNDED..CURRENT

    def to_dict(self):
        return {"call": self.call.to_dict(), "frame": self.frame}

    @staticmethod
    def from_dict(d):
        return WindowFunction(RowExpression.from_dict(d["call"]), d.get("frame"))


@_node
@dataclass
class WindowNode(PlanNode):
    source: PlanNode
    partition_by: List[Variable]
    ordering_scheme: Optional[OrderingScheme]
    window_functions: Dict[Variable, WindowFunction] = field(default_factory=dict)

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return self.source.output_variables + list(self.window_functions.keys())

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "partitionBy": _vars_to_dict(self.partition_by),
                "orderingScheme": self.ordering_scheme.to_dict() if self.ordering_scheme else None,
                "windowFunctions": [{"variable": v.to_dict(), "function": f.to_dict()}
                                    for v, f in self.window_functions.items()]}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   _vars_from_dict(d["partitionBy"]),
                   OrderingScheme.from_dict(d["orderingScheme"]) if d.get("orderingScheme") else None,
                   {RowExpression.from_dict(e["variable"]): WindowFunction.from_dict(e["function"])
                    for e in d["windowFunctions"]})


@_node
@dataclass
class UnionNode(PlanNode):
    """UNION ALL of N sources (reference UnionNode / SetOperationNode).
    The planner projects every source to the same output variables, so no
    per-source variable mapping is needed; DISTINCT and INTERSECT/EXCEPT
    are lowered to UnionNode + aggregation (the reference's
    ImplementIntersectAsUnion / ImplementExceptAsUnion rules)."""
    inputs: List[PlanNode]
    outputs: List[Variable] = field(default_factory=list)

    @property
    def sources(self):
        return list(self.inputs)

    @property
    def output_variables(self):
        return list(self.outputs)

    def _to_dict(self):
        return {"sources": [s.to_dict() for s in self.inputs],
                "outputs": _vars_to_dict(self.outputs)}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], [PlanNode.from_dict(s) for s in d["sources"]],
                   _vars_from_dict(d["outputs"]))


@_node
@dataclass
class UnnestNode(PlanNode):
    source: PlanNode
    replicate_variables: List[Variable]
    unnest_variables: List[Tuple[Variable, List[Variable]]]  # array var -> element vars
    # WITH ORDINALITY output (reference UnnestNode.ordinalityVariable)
    ordinality_variable: Optional[Variable] = None

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        out = list(self.replicate_variables)
        for _, elems in self.unnest_variables:
            out.extend(elems)
        if self.ordinality_variable is not None:
            out.append(self.ordinality_variable)
        return out

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "replicateVariables": _vars_to_dict(self.replicate_variables),
                "unnestVariables": [{"variable": v.to_dict(),
                                     "elements": _vars_to_dict(elems)}
                                    for v, elems in self.unnest_variables],
                "ordinalityVariable":
                    None if self.ordinality_variable is None
                    else self.ordinality_variable.to_dict()}

    @classmethod
    def _from_dict(cls, d):
        ov = d.get("ordinalityVariable")
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   _vars_from_dict(d["replicateVariables"]),
                   [(RowExpression.from_dict(e["variable"]), _vars_from_dict(e["elements"]))
                    for e in d["unnestVariables"]],
                   None if ov is None else RowExpression.from_dict(ov))


# ---------------------------------------------------------------------------
# fragments
# ---------------------------------------------------------------------------

@dataclass
class PlanFragment:
    """A scheduling unit cut at exchange boundaries
    (reference sql/planner/PlanFragment.java:46)."""
    fragment_id: str
    root: PlanNode
    partitioning: str                       # how this fragment's tasks are distributed
    output_partitioning_scheme: PartitioningScheme
    # table-scan node ids in this fragment that receive splits
    partitioned_sources: List[str] = field(default_factory=list)
    # output column name -> dynamic filter id: this fragment's output is
    # a dynamic-filter SOURCE, so its tasks summarize the named column's
    # domain on completion (sql/fragmenter.plan_dynamic_filter_sources)
    dynamic_filter_sources: Dict[str, str] = field(default_factory=dict)

    def to_dict(self):
        d = {"id": self.fragment_id, "root": self.root.to_dict(),
             "partitioning": self.partitioning,
             "outputPartitioningScheme": self.output_partitioning_scheme.to_dict(),
             "partitionedSources": self.partitioned_sources}
        if self.dynamic_filter_sources:
            d["dynamicFilterSources"] = dict(self.dynamic_filter_sources)
        return d

    @staticmethod
    def from_dict(d):
        return PlanFragment(
            d["id"], PlanNode.from_dict(d["root"]), d["partitioning"],
            PartitioningScheme.from_dict(d["outputPartitioningScheme"]),
            d.get("partitionedSources", []),
            d.get("dynamicFilterSources", {}))


@_node
@dataclass
class TableWriterNode(PlanNode):
    """Write the source's rows into a connector table (reference
    TableWriterOperator.java:78).  Emits one row per task:
    (rows BIGINT, fragment VARCHAR) where `fragment` is the connector's
    staging token, committed by TableFinishNode."""
    source: PlanNode
    connector_id: str
    table_name: str
    column_names: List[str] = field(default_factory=list)
    outputs: List[Variable] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return list(self.outputs)

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "connectorId": self.connector_id, "table": self.table_name,
                "columnNames": self.column_names,
                "outputs": _vars_to_dict(self.outputs)}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   d["connectorId"], d["table"], d["columnNames"],
                   _vars_from_dict(d["outputs"]))


@_node
@dataclass
class TableFinishNode(PlanNode):
    """Commit staged table writes and emit the total row count (reference
    TableFinishOperator.java: gathers writer fragments, runs the connector
    commit, outputs rows)."""
    source: PlanNode
    connector_id: str
    table_name: str
    outputs: List[Variable] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]

    @property
    def output_variables(self):
        return list(self.outputs)

    def _to_dict(self):
        return {"source": self.source.to_dict(),
                "connectorId": self.connector_id, "table": self.table_name,
                "outputs": _vars_to_dict(self.outputs)}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["id"], PlanNode.from_dict(d["source"]),
                   d["connectorId"], d["table"],
                   _vars_from_dict(d["outputs"]))


@dataclass
class SubPlan:
    """Tree of fragments (reference sql/planner/SubPlan.java)."""
    fragment: PlanFragment
    children: List["SubPlan"] = field(default_factory=list)

    def all_fragments(self) -> List[PlanFragment]:
        out = [self.fragment]
        for c in self.children:
            out.extend(c.all_fragments())
        return out


def walk_plan(node: PlanNode):
    """Pre-order traversal."""
    yield node
    for s in node.sources:
        yield from walk_plan(s)


def structural_key(node: PlanNode, canonical_params: bool = False) -> str:
    """Canonical text of a subtree that is identical for structurally
    equal plans regardless of node ids or variable names — node ids are
    blanked and variables renamed by first occurrence in a deterministic
    (sorted-key) traversal.  Lets execution-layer result caches recognize
    REPLAYED subtrees (scalar-subquery re-plans, decorrelated deep copies)
    whose node ids differ; a false mismatch only costs a cache miss, and
    structural equality implies identical output data (generated connector
    data is immutable and AssignUniqueId ids are deterministic).

    `canonical_params=True` additionally renames bound-parameter slot
    indices by first occurrence (both `{"@type": "parameter", "index": N}`
    expressions and scan-pushdown `["param", N]` markers share one
    mapping).  The serving tier's parameterizer gives every literal
    occurrence its own global slot, so decorrelated deep copies of the
    same source subtree (a CTE referenced by two subqueries) carry
    different indices while remaining structurally the same plan.  The
    DUPLICATE_NODE_ID checker compares plans under this mode; execution
    result caches must NOT — two subtrees bound to different slots of the
    same execution can carry different values, and params_fingerprint
    (whole-vector) would not disambiguate them."""
    rename: Dict[str, str] = {}
    param_rename: Dict[int, int] = {}

    def pidx(i: int) -> int:
        if i not in param_rename:
            param_rename[i] = len(param_rename)
        return param_rename[i]

    def canon(x):
        if isinstance(x, dict):
            if x.get("@type") == "variable" and "name" in x:
                nm = x["name"]
                if nm not in rename:
                    rename[nm] = f"v{len(rename)}"
                return {"@type": "variable", "name": rename[nm],
                        "type": x.get("type")}
            if (canonical_params and x.get("@type") == "parameter"
                    and isinstance(x.get("index"), int)):
                return {"@type": "parameter", "index": pidx(x["index"]),
                        "type": x.get("type")}
            out = {}
            for k in sorted(x):
                v = x[k]
                if k == "id":
                    out[k] = ""
                elif k == "dynamicFilters" and isinstance(v, dict):
                    # keys are probe variable names (renamed like any other
                    # variable); values are planner-counter filter ids,
                    # blanked like node ids — two decorrelated copies
                    # differing only in filter numbering are the same plan
                    out[k] = sorted(rename.get(n, n) for n in v)
                elif k == "runtimeFilters" and isinstance(v, list):
                    # filter ids blanked like node ids; columns are
                    # physical names, kept as-is
                    out[k] = sorted(
                        (e.get("column"), "") for e in v if isinstance(e, dict))
                else:
                    out[k] = canon(v)
            return out
        if isinstance(x, list):
            if (canonical_params and len(x) == 2 and x[0] == "param"
                    and isinstance(x[1], int)):
                return ["param", pidx(x[1])]
            if len(x) == 3 and x[0] == "dyn":
                # runtime-filter pushdown marker: the planner-counter
                # filter id is blanked like node ids
                return ["dyn", "", x[2]]
            return [canon(i) for i in x]
        return x

    import json as _json
    return _json.dumps(canon(node.to_dict()), sort_keys=True, default=str)
