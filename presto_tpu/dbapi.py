"""PEP 249 (DB-API 2.0) driver over the statement protocol.

The python-ecosystem analog of the reference's JDBC driver (presto-jdbc,
presto-jdbc/src/main/java/com/facebook/presto/jdbc/): the standard database
interface of the host language implemented purely on the public client
protocol, so any DB-API tooling (pandas.read_sql, SQLAlchemy dialects,
ORMs) can talk to a presto-tpu coordinator.

    import presto_tpu.dbapi as dbapi
    conn = dbapi.connect("http://127.0.0.1:8080", schema="sf1")
    cur = conn.cursor()
    cur.execute("SELECT returnflag, count(*) FROM lineitem GROUP BY 1")
    cur.fetchall()
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .client import QueryError, StatementClient

apilevel = "2.0"
threadsafety = 1          # threads may share the module, not connections
paramstyle = "qmark"      # positional '?' substitution


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class ProgrammingError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


def connect(uri: str, user: str = "user", catalog: str = "tpch",
            schema: str = "sf0.01",
            session: Optional[Dict[str, str]] = None,
            server_side_binding: bool = True) -> "Connection":
    """`server_side_binding=False` falls back to the legacy client-side
    textual '?' substitution; the default binds parameters on the server
    through EXECUTE ... USING, which lets the coordinator's canonical plan
    cache reuse one compiled executable across parameter values."""
    return Connection(uri, user, catalog, schema, session,
                      server_side_binding)


class Connection:
    def __init__(self, uri: str, user: str, catalog: str, schema: str,
                 session: Optional[Dict[str, str]],
                 server_side_binding: bool = True):
        self._client = StatementClient(uri, user=user, catalog=catalog,
                                       schema=schema, session=session,
                                       source="presto-tpu-dbapi")
        self.server_side_binding = server_side_binding
        self._closed = False

    def cursor(self) -> "Cursor":
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self._client, self.server_side_binding)

    def close(self) -> None:
        self._closed = True

    def commit(self) -> None:
        pass              # autocommit (like the reference JDBC driver)

    def rollback(self) -> None:
        raise OperationalError("transactions are not supported")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _split_placeholders(sql: str) -> List[str]:
    """Split on '?' placeholders OUTSIDE single-quoted string literals
    (a '?' inside 'a?b' is data, not a parameter)."""
    parts, buf, in_str = [], [], False
    i = 0
    while i < len(sql):
        ch = sql[i]
        if in_str:
            buf.append(ch)
            if ch == "'":
                if i + 1 < len(sql) and sql[i + 1] == "'":
                    buf.append("'")
                    i += 1       # escaped quote stays inside the literal
                else:
                    in_str = False
        elif ch == "'":
            in_str = True
            buf.append(ch)
        elif ch == "?":
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
        i += 1
    parts.append("".join(buf))
    return parts


def _quote(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return repr(v)
    s = str(v).replace("'", "''")
    return f"'{s}'"


class Cursor:
    arraysize = 1

    def __init__(self, client: StatementClient,
                 server_side_binding: bool = True):
        self._client = client
        self._server_side_binding = server_side_binding
        self._rows: List[Sequence] = []
        self._pos = 0
        self.description = None
        self.rowcount = -1
        self._closed = False

    # -- execution --------------------------------------------------------
    def execute(self, sql: str, parameters: Optional[Sequence] = None):
        if self._closed:
            raise InterfaceError("cursor is closed")
        if parameters:
            parts = _split_placeholders(sql)
            if len(parts) != len(parameters) + 1:
                raise ProgrammingError(
                    f"statement has {len(parts) - 1} placeholders but "
                    f"{len(parameters)} parameters were given")
            if self._server_side_binding:
                # register the '?' template in the client's prepared map
                # (replayed as a header each request — no PREPARE round
                # trip needed) and bind values server-side so the
                # coordinator's canonical plan cache reuses one compiled
                # executable across parameter values
                import hashlib
                name = "stmt_" + hashlib.sha1(
                    sql.encode()).hexdigest()[:12]
                self._client.prepared[name] = sql
                sql = (f"EXECUTE {name} USING "
                       + ", ".join(_quote(v) for v in parameters))
            else:
                sql = "".join(
                    p + (_quote(v) if i < len(parameters) else "")
                    for i, (p, v) in enumerate(
                        zip(parts, list(parameters) + [None])))
        try:
            result = self._client.execute(sql)
        except QueryError as e:
            raise ProgrammingError(str(e)) from e
        except OSError as e:
            raise OperationalError(str(e)) from e
        # description: 7-tuples (name, type_code, None x5) per PEP 249
        self.description = [(c["name"], c["type"], None, None, None, None,
                             None) for c in result.columns] or None
        self._rows = result.rows
        self._pos = 0
        self.rowcount = len(result.rows)
        return self

    def executemany(self, sql: str, seq_of_parameters):
        for p in seq_of_parameters:
            self.execute(sql, p)
        return self

    # -- fetching ---------------------------------------------------------
    def fetchone(self):
        if self._pos >= len(self._rows):
            return None
        row = tuple(self._rows[self._pos])
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None):
        size = size or self.arraysize
        out = [tuple(r) for r in self._rows[self._pos:self._pos + size]]
        self._pos += len(out)
        return out

    def fetchall(self):
        out = [tuple(r) for r in self._rows[self._pos:]]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    # -- misc -------------------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def setinputsizes(self, sizes) -> None:
        pass

    def setoutputsize(self, size, column=None) -> None:
        pass
