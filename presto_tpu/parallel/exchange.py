"""Partitioned exchange over ICI: the TPU-native replacement for the
reference's HTTP pull shuffle between hash-partitioned stages
(PartitionedOutputOperator.java:58 -> ExchangeClient.java:72; SURVEY.md §5.8).

Where both producer and consumer stages run on chips of the same pod slice,
the shuffle is a jitted `all_to_all` under shard_map: each device buckets
its rows by target partition (hash of the partition keys mod the worker
count), pads buckets to a fixed quota (static shapes for XLA), and the
collective transposes the bucket axis across the mesh.  Bucket overflow is
detected on device and surfaced to the host driver, which splits the batch
and retries — same recovery discipline as the join's output capacity.

The scheduler's chunked mode (exec/scheduler.py _ici_exchange,
exchange.ici-chunk-rows) calls the exchange once per fixed-size row chunk
with quota == chunk rows: a chunk of C rows can never put more than C rows
in one bucket, so overflow is STATICALLY impossible and the driver
dispatches every chunk's collective back-to-back with no host sync — chunk
k+1 rides the wire while the consumer computes on chunk k (JAX async
dispatch), and the fixed chunk shape means one compiled exchange program
(and its donated input staging buffers) is reused across chunks and
stages instead of re-padding to a fresh per-stage global max.

Cross-pod edges and TPU<->Java edges keep the HTTP exchange (worker/);
fabric selection lives in parallel/fabric.py.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
try:                                    # moved out of experimental in 0.6
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ..exec.batch import Batch, Column
from ..exec.operators import hash_columns
from .mesh import WORKER_AXIS


def _bucket_locally(batch: Batch, key_names: List[str], n_parts: int,
                    quota: int, salt: int):
    """Reorder local rows into n_parts buckets of `quota` rows each.

    Returns (bucketed columns dict name->(n_parts*quota,) arrays,
    bucketed mask, overflow flag)."""
    if key_names:
        h = hash_columns([batch.columns[k] for k in key_names], salt)
        target = (h % jnp.uint64(n_parts)).astype(jnp.int32)
    else:
        # round robin
        target = (jnp.cumsum(batch.mask) - 1).astype(jnp.int32) % n_parts
    target = jnp.where(batch.mask, target, n_parts)  # padding sorts last

    order = jnp.argsort(target, stable=True)          # rows grouped by target
    sorted_target = target[order]
    # position of each row within its bucket
    ranks = jnp.arange(batch.capacity) - jnp.searchsorted(
        sorted_target, sorted_target, side="left")
    dest = sorted_target * quota + ranks              # slot in bucketed layout
    valid = (sorted_target < n_parts) & (ranks < quota)
    counts = jnp.zeros(n_parts + 1, dtype=jnp.int32).at[sorted_target].add(
        jnp.where(sorted_target < n_parts, 1, 0), mode="drop")
    overflow = jnp.any(counts[:n_parts] > quota)
    dest = jnp.where(valid, dest, n_parts * quota)    # drop overflow rows

    out_cols = {}
    for name, col in batch.columns.items():
        src = col.values[order]
        buf = jnp.zeros(n_parts * quota, dtype=col.values.dtype)
        buf = buf.at[dest].set(src, mode="drop")
        nulls = None
        if col.nulls is not None:
            nbuf = jnp.zeros(n_parts * quota, dtype=bool)
            nulls = nbuf.at[dest].set(col.nulls[order], mode="drop")
        out_cols[name] = Column(buf, nulls, col.dictionary, col.lazy)
    mask = jnp.zeros(n_parts * quota, dtype=bool).at[dest].set(
        valid, mode="drop")
    return out_cols, mask, overflow


def exchange_step(batch: Batch, key_names: Tuple[str, ...], n_parts: int,
                  quota: int, salt: int = 0):
    """Device-local portion of the shuffle, to be called INSIDE shard_map.

    Returns (exchanged Batch with capacity n_parts*quota, overflow flag).
    After all_to_all, device d holds every device's bucket d."""
    cols, mask, overflow = _bucket_locally(batch, list(key_names), n_parts,
                                           quota, salt)

    def a2a(x):
        # (n_parts*quota, ...) -> (n_parts, quota, ...) -> transpose partitions
        shaped = x.reshape((n_parts, quota) + x.shape[1:])
        out = jax.lax.all_to_all(shaped, WORKER_AXIS, split_axis=0,
                                 concat_axis=0, tiled=False)
        return out.reshape((n_parts * quota,) + x.shape[1:])

    out_cols = {}
    for name, col in cols.items():
        values = a2a(col.values)
        nulls = a2a(col.nulls) if col.nulls is not None else None
        out_cols[name] = Column(values, nulls, col.dictionary, col.lazy)
    new_mask = a2a(mask)
    # overflow anywhere must stop everyone
    any_overflow = jax.lax.pmax(overflow.astype(jnp.int32), WORKER_AXIS) > 0
    return Batch(out_cols, new_mask), any_overflow


def make_partitioned_exchange(mesh, key_names: Tuple[str, ...],
                              quota: int, salt: int = 0,
                              donate: bool = False):
    """Build a jitted shard_map shuffle: Batch (row-sharded) -> Batch
    (row-sharded, rows placed on their hash-target device).

    donate=True marks the input batch's buffers donatable (the chunked
    caller's per-chunk staging slices are dead after the collective, so
    XLA may reuse their memory for the bucketed layout / output where
    layouts permit)."""
    n_parts = mesh.shape[WORKER_AXIS]

    def fn(batch: Batch):
        return exchange_step(batch, key_names, n_parts, quota, salt)

    spec = P(WORKER_AXIS)
    shmapped = shard_map(fn, mesh=mesh, in_specs=(spec,),
                         out_specs=(spec, P()))
    return jax.jit(shmapped, donate_argnums=(0,) if donate else ())
