"""Device mesh management for multi-chip execution.

The TPU worker maps Presto's FIXED_HASH task distribution
(SystemPartitioningHandle.java:64, NodePartitioningManager bucket->node
mapping) onto a 1-D `jax.sharding.Mesh` over the pod slice: task partition i
== mesh position i, and the partitioned exchange between stages rides ICI
all-to-all instead of the reference's HTTP pull shuffle (SURVEY.md §5.8).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

WORKER_AXIS = "workers"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (WORKER_AXIS,))


def mesh_size(mesh: Optional[Mesh]) -> int:
    """Worker count of a scheduler mesh (0 when no mesh is configured) —
    the task count the scheduler pins 1:1 to devices for ICI-fabric
    stages (parallel/fabric.py resolve_fabric)."""
    return 0 if mesh is None else mesh.shape[WORKER_AXIS]


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 (rows) across workers."""
    return NamedSharding(mesh, PartitionSpec(WORKER_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
