"""Exchange fabric selection + per-fabric shuffle metrics.

A remote-exchange edge between two fragments can ride one of two
fabrics (SURVEY.md §5.8, the PAPER.md "partitioned-exchange shuffles
over ICI" north star):

  http  the PR 4 ExchangeClient pull shuffle: producer tasks serialize
        pages into output buffers, consumers pull over HTTP.  Works
        across hosts/pods and for every partitioning handle.
  ici   a jitted all_to_all over the device mesh
        (parallel/exchange.py): rows never leave HBM.  Requires a
        hash-partitioned edge whose producer AND consumer stages are
        co-located on one mesh with tasks pinned 1:1 to devices.

`exchange.fabric` (ExecutionConfig.exchange_fabric, session property
`exchange_fabric`) requests `auto | http | ici` per query; `auto` picks
ICI wherever the edge is eligible and the scheduler can CHOOSE task
counts equal to the mesh size, falling back to HTTP otherwise — so one
plan may mix fabrics (intra-mesh edges on ICI, gather / broadcast /
cross-host edges on HTTP).

This module is import-light (no jax): the fragmenter, scheduler,
checker, and EXPLAIN all share `resolve_fabric` so plan annotation,
runtime selection, and validation cannot drift.
"""
from __future__ import annotations

from typing import Optional, Tuple

from ..common.locks import OrderedLock

FABRIC_AUTO = "auto"
FABRIC_HTTP = "http"
FABRIC_ICI = "ici"
FABRICS = (FABRIC_AUTO, FABRIC_HTTP, FABRIC_ICI)

# fragment partitionings an ICI endpoint stage may have (spi/plan.py
# *_DISTRIBUTION values): its task count must be the scheduler's to
# choose, and SINGLE fragments are pinned to one task (values /
# enforce-single-row / final gather semantics)
_MULTI_TASK = ("SOURCE", "FIXED_HASH")


def resolve_fabric(requested: Optional[str], *, handle: str,
                   producer_partitioning: str,
                   consumer_partitioning: str,
                   mesh_size: int,
                   batch_mode: bool = False) -> Tuple[str, str]:
    """Resolve one remote-exchange edge to a concrete fabric.

    Returns (fabric, reason); fabric is FABRIC_HTTP or FABRIC_ICI, the
    reason says why (surfaced in EXPLAIN / fallback stats).  `requested`
    is the edge annotation or config value (None == auto).
    """
    req = requested or FABRIC_AUTO
    if req == FABRIC_HTTP:
        return FABRIC_HTTP, "requested"
    if handle != "FIXED_HASH":
        return FABRIC_HTTP, f"{handle} edge (ICI is hash-only)"
    if mesh_size < 2:
        return FABRIC_HTTP, "no mesh"
    if batch_mode:
        return FABRIC_HTTP, "batch mode needs durable shuffle files"
    if producer_partitioning not in _MULTI_TASK:
        return FABRIC_HTTP, (f"{producer_partitioning} producer cannot "
                             f"pin {mesh_size} tasks to the mesh")
    if consumer_partitioning not in _MULTI_TASK:
        return FABRIC_HTTP, (f"{consumer_partitioning} consumer cannot "
                             f"pin {mesh_size} tasks to the mesh")
    return FABRIC_ICI, ("requested" if req == FABRIC_ICI
                        else "mesh-eligible hash edge")


class FabricMetrics:
    """Process-wide per-fabric shuffle counters — the stats-parity
    surface of the ICI path next to worker/exchange.py ExchangeMetrics
    (which meters the HTTP client).  Snapshot keys per fabric:

      exchanges        completed exchange edges (stage executions)
      chunks           collective dispatches (== exchanges for the
                       unchunked page path)
      bytes_moved      payload bytes through the fabric (wire bytes for
                       http, device shard bytes for ici)
      host_bytes       bytes that crossed device->host or host->host —
                       the ICI win: ~0, vs everything for http
      exchange_wall_s  producer-side shuffle wall (dispatch for ici,
                       partition+split for the in-process page path)
      compute_wall_s   consumer-side drain wall (first read ->
                       exhaustion, compute between chunks included)
      wait_wall_s      consumer-side time blocked on data not yet ready
      fallbacks        edges demoted to http (ineligible / metadata
                       mismatch / forced)
    """

    _FIELDS = ("exchanges", "chunks", "bytes_moved", "host_bytes",
               "exchange_wall_s", "compute_wall_s", "wait_wall_s",
               "fallbacks")

    def __init__(self):
        # rank 100: metrics registries are leaf locks
        self._lock = OrderedLock("metrics:fabric", 100)  # lint: guarded-by(_lock)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._by_fabric = {
                FABRIC_HTTP: {f: 0.0 for f in self._FIELDS},
                FABRIC_ICI: {f: 0.0 for f in self._FIELDS},
            }

    def record(self, fabric: str, **deltas) -> None:
        with self._lock:
            m = self._by_fabric[fabric]
            for k, v in deltas.items():
                m[k] += v

    def overlap_fraction(self, fabric: str) -> float:
        """1 - wait/compute: the share of consumer drain time the
        collective (or pull) was hidden behind compute — same shape as
        bench.py's HTTP overlap_fraction."""
        with self._lock:
            m = self._by_fabric[fabric]
            if m["compute_wall_s"] <= 0:
                return 0.0
            return max(0.0, 1.0 - m["wait_wall_s"] / m["compute_wall_s"])

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for fabric, m in self._by_fabric.items():
                d = dict(m)
                for k in ("exchanges", "chunks", "bytes_moved",
                          "host_bytes", "fallbacks"):
                    d[k] = int(d[k])
                d["overlap_fraction"] = (
                    max(0.0, 1.0 - m["wait_wall_s"] / m["compute_wall_s"])
                    if m["compute_wall_s"] > 0 else 0.0)
                out[fabric] = d
            return out

    def byte_rates(self) -> dict:
        """bytes/s through each fabric while it was actually moving data
        (bytes_moved over exchange wall) — the /v1/cluster analog of the
        reference ClusterStatsResource input/output byte rates."""
        with self._lock:
            out = {}
            for fabric, m in self._by_fabric.items():
                wall = m["exchange_wall_s"]
                out[fabric] = (m["bytes_moved"] / wall) if wall > 0 else 0.0
            return out


FABRIC_METRICS = FabricMetrics()


class IciChunkTuner:
    """Feedback controller for the chunked ICI exchange granularity.

    When `exchange.ici-chunk-rows` is left unset (ExecutionConfig value
    0), the scheduler asks this tuner for each run's chunk size and
    feeds back the observed compute/collective `overlap_fraction` from
    FABRIC_METRICS after the exchange completes.  Simple multiplicative
    feedback, clamped:

      overlap < LOW    the consumer spent a large share of its drain
                       wall BLOCKED on collectives -> halve the chunk:
                       finer chunks start compute sooner and give the
                       pipeline more in-flight collectives to hide
      overlap > HIGH   collectives are already hidden behind compute ->
                       double the chunk to amortize per-chunk dispatch
                       (fewer all_to_all launches for the same rows)

    Hysteresis between LOW and HIGH holds the size steady.  Explicit
    config values bypass the tuner entirely (properties layer rejects
    explicit values < 1, so 0 is only reachable as the default)."""

    LOW = 0.5
    HIGH = 0.9
    MIN_ROWS = 1 << 10
    MAX_ROWS = 1 << 16
    DEFAULT_ROWS = 1 << 12

    def __init__(self):
        self._lock = OrderedLock("metrics:ici-tuner", 100)  # lint: guarded-by(_lock)
        self._rows = self.DEFAULT_ROWS

    def chunk_rows(self) -> int:
        with self._lock:
            return self._rows

    def observe(self, overlap_fraction: float) -> None:
        with self._lock:
            if overlap_fraction < self.LOW:
                self._rows = max(self.MIN_ROWS, self._rows // 2)
            elif overlap_fraction > self.HIGH:
                self._rows = min(self.MAX_ROWS, self._rows * 2)

    def reset(self) -> None:
        with self._lock:
            self._rows = self.DEFAULT_ROWS


ICI_CHUNK_TUNER = IciChunkTuner()
