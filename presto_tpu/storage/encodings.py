"""Lightweight columnar encodings for HBM-resident table columns.

A `ResidentColumn` is the device half of the resident storage layer
(store.py): one whole-table column materialized ONCE into HBM in an
encoded physical form, decoded per scan chunk INSIDE the fused kernel.
The point is bandwidth: a fused Q1 scan is HBM-bound, and what streams
out of HBM is the *encoded* bytes — dictionary codes are int8/int16
where the logical column is 8 bytes wide, so the same query reads a
fraction of the traffic.  Decode (a small-table gather, or a
searchsorted over run starts) happens in vector registers after the
chunk's `dynamic_slice`, which is the classic late-materialization
trade: spend VPU cycles, save HBM bytes.

Three encodings, mirroring the engine's host Block hierarchy
(common/block.py DictionaryBlock / RunLengthBlock / FixedWidthBlock):

- ``plain``  — the padded device array as-is.
- ``dict``   — sorted distinct values + per-row codes (int8 when the
  cardinality fits in 7 bits, else int16).  Exact: decode is
  ``values[codes]``.
- ``rle``    — run values + run start offsets for sorted/monotone
  columns (tpcds ``ws_order_number``-style co-bucket layouts).  Decode
  is ``values[searchsorted(starts, row) - 1]`` — log2(runs) gathers per
  element, so it is only selected when runs compress heavily (the run
  table then lives in cache) or a connector hint forces it.

Zone maps (per-zone min/max/null-count at a fixed row granularity) are
built HERE, from the exact decoded values, on device, and brought to
the host once at build time — query-time chunk pruning
(pushdown.prune_chunks) is then pure host numpy and never syncs.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# dictionary codes wider than int16 would erase most of the byte win
DICT_MAX_NDV = 1 << 15
# cheap cardinality probe before paying a full-column jnp.unique sort
DICT_PROBE_ROWS = 1 << 18
# without a connector hint, RLE must compress >= this factor: decode
# pays log2(runs) gathers per element, so the run table must be small
# enough to stay cache-resident
RLE_MIN_COMPRESSION = 16.0
# with a connector "rle" hint (known-monotone layout), accept >= 2x
RLE_HINT_COMPRESSION = 2.0


class ResidentColumn:
    """One whole-table encoded column, traceable as a jit argument.

    Registered as a pytree: the device arrays are children (resident
    columns ride jit argument lists — closing over them would inline
    hundreds of MB as XLA literal constants), the encoding shape is
    static aux data (so the jit cache keys on it).
    """

    def __init__(self, kind: str, arrays: Tuple, n_rows: int):
        self.kind = kind          # "plain" | "dict" | "rle"
        self.arrays = tuple(arrays)
        self.n_rows = int(n_rows)

    # -- chunk decode (traceable; pos may be a tracer) --------------------
    def slice_decode(self, pos, cap: int):
        """Decode rows [pos, pos+cap) to logical values.  Arrays are
        tail-padded past n_rows at build time so the dynamic_slice never
        clamp-shifts at the table edge."""
        if self.kind == "plain":
            (data,) = self.arrays
            return jax.lax.dynamic_slice(data, (pos,), (cap,))
        if self.kind == "dict":
            codes, values = self.arrays
            c = jax.lax.dynamic_slice(codes, (pos,), (cap,))
            return values[c.astype(jnp.int32)]
        run_values, run_starts = self.arrays
        idx = pos + jnp.arange(cap, dtype=jnp.int64)
        ri = jnp.searchsorted(run_starts, idx, side="right") - 1
        ri = jnp.clip(ri, 0, run_values.shape[0] - 1)
        return run_values[ri]

    def decode_full(self):
        """The full padded logical array (tests / zone-map building)."""
        if self.kind == "plain":
            return self.arrays[0]
        if self.kind == "dict":
            codes, values = self.arrays
            return values[codes.astype(jnp.int32)]
        return self.slice_decode(jnp.int64(0), self.n_rows)

    # -- accounting -------------------------------------------------------
    @property
    def nbytes(self) -> int:
        """Resident (encoded) device bytes — what HBM actually holds."""
        return int(sum(a.nbytes for a in self.arrays))

    @property
    def logical_nbytes(self) -> int:
        """Bytes a plain encoding of the same column would hold."""
        if self.kind == "plain":
            return int(self.arrays[0].nbytes)
        if self.kind == "dict":
            codes, values = self.arrays
            return int(codes.shape[0] * values.dtype.itemsize)
        run_values, _run_starts = self.arrays
        return self.n_rows * run_values.dtype.itemsize

    @property
    def dtype(self):
        if self.kind == "dict":
            return self.arrays[1].dtype
        return self.arrays[0].dtype

    def __repr__(self):
        return (f"ResidentColumn({self.kind}, rows={self.n_rows}, "
                f"bytes={self.nbytes})")


def _rescol_flatten(rc: ResidentColumn):
    return rc.arrays, (rc.kind, rc.n_rows)


def _rescol_unflatten(aux, children):
    kind, n_rows = aux
    return ResidentColumn(kind, tuple(children), n_rows)


jax.tree_util.register_pytree_node(
    ResidentColumn, _rescol_flatten, _rescol_unflatten)


# ---------------------------------------------------------------------------
# encoder selection
# ---------------------------------------------------------------------------

def encode_column(arr, n_rows: int, encodings: bool = True,
                  hint: Optional[str] = None,
                  host: Optional[np.ndarray] = None) -> ResidentColumn:
    """Pick an encoding for a fully built padded device array.

    `arr` holds n_rows logical rows plus zero tail padding.  Selection
    stats (run count, cardinality) are device reductions pulled to the
    host ONCE at build time; the resulting ResidentColumn never syncs.
    When the caller already holds the padded column on the host
    (`host`), selection AND encoding run in numpy — small tables pay
    one transfer instead of a dozen tiny device programs.
    """
    if not encodings or n_rows < 2 or hint == "plain":
        return ResidentColumn("plain", (arr,), n_rows)
    if host is not None:
        return _encode_column_host(arr, host, n_rows, hint)
    body = arr[:n_rows]
    itemsize = arr.dtype.itemsize

    # --- RLE: runs of equal adjacent values -----------------------------
    changes = body[1:] != body[:-1]
    # build-time stat, one sync per column per process
    nruns = 1 + int(jax.device_get(changes.sum()))  # lint: allow-host-sync
    plain_bytes = n_rows * itemsize
    rle_bytes = nruns * (itemsize + 8)
    want = RLE_HINT_COMPRESSION if hint == "rle" else RLE_MIN_COMPRESSION
    if rle_bytes * want <= plain_bytes:
        change_mask = jnp.concatenate(
            [jnp.ones(1, dtype=bool), changes])
        starts = jnp.nonzero(change_mask, size=nruns,
                             fill_value=n_rows - 1)[0].astype(jnp.int64)
        run_values = body[starts]
        # sentinel run: zero-valued tail padding, so any in-capacity row
        # index decodes without clamping surprises
        run_starts = jnp.concatenate(
            [starts, jnp.asarray([n_rows], dtype=jnp.int64)])
        run_values = jnp.concatenate(
            [run_values, jnp.zeros(1, dtype=body.dtype)])
        return ResidentColumn("rle", (run_values, run_starts), n_rows)

    # --- dictionary: low-cardinality columns ----------------------------
    probe = jnp.unique(body[:DICT_PROBE_ROWS])
    if hint == "dict" or probe.shape[0] <= DICT_MAX_NDV:
        values = jnp.unique(body)
        ndv = int(values.shape[0])
        if ndv <= DICT_MAX_NDV:
            code_dtype = jnp.int8 if ndv <= 127 else jnp.int16
            dict_bytes = (arr.shape[0] * np.dtype(code_dtype).itemsize
                          + ndv * itemsize)
            # the values table is resident too: near-unique columns on a
            # small table pass the NDV cap yet net MORE bytes than plain
            if np.dtype(code_dtype).itemsize < itemsize \
                    and dict_bytes < plain_bytes:
                # pad rows code to an arbitrary slot (dead rows are
                # masked by the scan's live predicate); clip keeps the
                # decode gather in-bounds either way
                codes = jnp.clip(
                    jnp.searchsorted(values, arr), 0, ndv - 1
                ).astype(code_dtype)
                return ResidentColumn("dict", (codes, values), n_rows)
    return ResidentColumn("plain", (arr,), n_rows)


def _encode_column_host(arr, host: np.ndarray, n_rows: int,
                        hint: Optional[str]) -> ResidentColumn:
    """Numpy twin of the device selection path, same thresholds and
    same physical layout; only the encoded arrays go back to device."""
    body = host[:n_rows]
    itemsize = body.dtype.itemsize
    changes = body[1:] != body[:-1]
    nruns = 1 + int(np.count_nonzero(changes))
    plain_bytes = n_rows * itemsize
    rle_bytes = nruns * (itemsize + 8)
    want = RLE_HINT_COMPRESSION if hint == "rle" else RLE_MIN_COMPRESSION
    if rle_bytes * want <= plain_bytes:
        starts = np.flatnonzero(
            np.concatenate([np.ones(1, dtype=bool), changes]))
        run_values = jnp.asarray(np.concatenate(
            [body[starts], np.zeros(1, dtype=body.dtype)]))
        run_starts = jnp.asarray(np.concatenate(
            [starts, [n_rows]]).astype(np.int64))
        return ResidentColumn("rle", (run_values, run_starts), n_rows)

    values_h = np.unique(body[:DICT_PROBE_ROWS])
    if hint == "dict" or values_h.shape[0] <= DICT_MAX_NDV:
        values_h = np.unique(body)
        ndv = int(values_h.shape[0])
        if ndv <= DICT_MAX_NDV:
            code_dtype = np.int8 if ndv <= 127 else np.int16
            dict_bytes = (host.shape[0] * np.dtype(code_dtype).itemsize
                          + ndv * itemsize)
            if np.dtype(code_dtype).itemsize < itemsize \
                    and dict_bytes < plain_bytes:
                codes_h = np.clip(
                    np.searchsorted(values_h, host), 0, ndv - 1
                ).astype(code_dtype)
                return ResidentColumn(
                    "dict", (jnp.asarray(codes_h), jnp.asarray(values_h)),
                    n_rows)
    return ResidentColumn("plain", (arr,), n_rows)


# ---------------------------------------------------------------------------
# zone maps
# ---------------------------------------------------------------------------

class ZoneMaps:
    """Host-side per-zone min/max/null-count at a fixed row granularity.

    Built once from the exact column values; consulted by
    pushdown.prune_chunks with pure numpy — pruning never touches the
    device."""

    __slots__ = ("zmin", "zmax", "null_count", "zone_rows")

    def __init__(self, zmin: np.ndarray, zmax: np.ndarray,
                 null_count: np.ndarray, zone_rows: int):
        self.zmin = zmin
        self.zmax = zmax
        self.null_count = null_count
        self.zone_rows = int(zone_rows)

    def chunk_bounds(self, pos: int, count: int):
        """Aggregate (min, max) over the zones covering [pos, pos+count)."""
        z0 = pos // self.zone_rows
        z1 = (pos + count - 1) // self.zone_rows
        z1 = min(z1, len(self.zmin) - 1)
        if z0 > z1:
            return None
        return self.zmin[z0:z1 + 1].min(), self.zmax[z0:z1 + 1].max()


def _reduce_identities(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf, -jnp.inf
    if dtype == jnp.bool_:
        return True, False
    info = jnp.iinfo(dtype)
    return info.max, info.min


def build_zone_maps(arr, n_rows: int, zone_rows: int,
                    nulls=None, host: Optional[np.ndarray] = None
                    ) -> ZoneMaps:
    """Device reshape+reduce over the UNPADDED rows, one host pull.

    The ragged last zone is padded with reduction identities so zero
    tail padding never leaks into a zone's min.  With `host` (the
    padded column already on the host) the reduce is pure numpy."""
    if host is not None and nulls is None:
        return _build_zone_maps_host(host, n_rows, zone_rows)
    body = arr[:n_rows]
    nz = -(-n_rows // zone_rows)
    pad = nz * zone_rows - n_rows
    ident_min, ident_max = _reduce_identities(body.dtype)
    pmin = jnp.concatenate(
        [body, jnp.full(pad, ident_min, dtype=body.dtype)]) if pad \
        else body
    pmax = jnp.concatenate(
        [body, jnp.full(pad, ident_max, dtype=body.dtype)]) if pad \
        else body
    zmin = pmin.reshape(nz, zone_rows).min(axis=1)
    zmax = pmax.reshape(nz, zone_rows).max(axis=1)
    if nulls is not None:
        nbody = nulls[:n_rows]
        if pad:
            nbody = jnp.concatenate([nbody, jnp.zeros(pad, dtype=bool)])
        ncnt = nbody.reshape(nz, zone_rows).sum(axis=1)
    else:
        ncnt = jnp.zeros(nz, dtype=jnp.int32)
    # build-time stat transfer: one sync per column per process
    zmin, zmax, ncnt = jax.device_get((zmin, zmax, ncnt))  # lint: allow-host-sync
    return ZoneMaps(np.asarray(zmin), np.asarray(zmax),
                    np.asarray(ncnt), zone_rows)


def _build_zone_maps_host(host: np.ndarray, n_rows: int,
                          zone_rows: int) -> ZoneMaps:
    body = host[:n_rows]
    nz = -(-n_rows // zone_rows)
    pad = nz * zone_rows - n_rows
    ident_min, ident_max = _reduce_identities(body.dtype)
    pmin = np.concatenate(
        [body, np.full(pad, ident_min, dtype=body.dtype)]) if pad \
        else body
    pmax = np.concatenate(
        [body, np.full(pad, ident_max, dtype=body.dtype)]) if pad \
        else body
    return ZoneMaps(pmin.reshape(nz, zone_rows).min(axis=1),
                    pmax.reshape(nz, zone_rows).max(axis=1),
                    np.zeros(nz, dtype=np.int32), zone_rows)
