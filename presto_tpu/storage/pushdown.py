"""Scan predicate pushdown metadata and zone-map chunk pruning.

`plan_scan_pushdown` (sql/optimizer.py) records on each TableScanNode
the conjuncts of its parent FilterNode that are range/equality-shaped
(``col <op> literal`` with op in eq/lt/lte/gt/gte, or BETWEEN) as plain
``{"column", "op", "value"}`` dicts — serializable, checker-visible
(analysis/checker.py SCAN_PUSHDOWN), and consumed at execution by
`prune_chunks` to skip whole scan chunks whose zone-map [min, max]
cannot satisfy the conjunction.

Pruning is ADVISORY: the FilterNode stays in the plan and re-filters
every surviving row exactly, so over-inclusion is harmless and the only
correctness obligation here is conservatism — a chunk is skipped ONLY
when no value in its zone range can pass.  All decisions are host-side
numpy over stats captured at build time (encodings.build_zone_maps);
nothing here touches the device.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

PUSHDOWN_OPS = ("eq", "lt", "lte", "gt", "gte")

_CMP_ALIASES = {
    "lt": "lt", "less_than": "lt",
    "lte": "lte", "less_than_or_equal": "lte",
    "gt": "gt", "greater_than": "gt",
    "gte": "gte", "greater_than_or_equal": "gte",
    "eq": "eq", "equal": "eq",
}
_FLIP = {"lt": "gt", "lte": "gte", "gt": "lt", "gte": "lte", "eq": "eq"}


def _literal(expr, var) -> Optional[float]:
    """The constant's numeric value in STORED-column units, or None.

    Mirrors exec/lowering.constant_device_value, which is what the
    residual filter itself compares against, but only when the units
    provably line up with the column `var` is bound to:

    - decimal constants are unscaled ints at the constant's scale;
      accepted only against a decimal column of the SAME scale (decimal
      device columns are stored unscaled at their declared scale);
    - date constants become epoch-day ints, accepted against date
      columns (stored as epoch-day i32);
    - plain int/float constants are accepted against non-decimal
      columns (a raw int against an unscaled decimal column would be
      off by 10^scale and make pruning unsound).
    """
    from ..common.types import DateType, DecimalType
    from ..spi.expr import ConstantExpression
    if not isinstance(expr, ConstantExpression) or expr.value is None:
        return None
    vt = getattr(var, "type", None)
    if isinstance(expr.type, (DecimalType, DateType)):
        if isinstance(expr.type, DecimalType) and not (
                isinstance(vt, DecimalType)
                and vt.scale == expr.type.scale
                # a float typed decimal would be truncated, not scaled
                and not isinstance(expr.value, float)):
            return None
        if isinstance(expr.type, DateType) and not isinstance(vt, DateType):
            return None
        from ..exec.lowering import constant_device_value
        v = constant_device_value(expr.value, expr.type)
        return v if isinstance(v, int) else None
    v = expr.value
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if isinstance(vt, DecimalType):
        return None
    return v


def _param_marker(expr, var) -> Optional[list]:
    """``["param", index]`` when a bound-parameter operand's plan type
    lines up with the column's stored units, else None.

    Same unit gates as `_literal`; the VALUE is resolved at prune time
    from the execution's parameter fingerprint, which already carries
    device-unit host scalars (decimals unscaled at the plan scale, dates
    as epoch days — see sql/canonical.device_params).  The marker is a
    list, not a tuple, so it survives the TableScanNode JSON round trip
    unchanged and the checker's re-derivation equality keeps holding.
    """
    from ..common.types import BooleanType, DateType, DecimalType
    from ..spi.expr import BoundParameterExpression
    if not isinstance(expr, BoundParameterExpression):
        return None
    vt = getattr(var, "type", None)
    if isinstance(expr.type, DecimalType):
        if not (isinstance(vt, DecimalType)
                and vt.scale == expr.type.scale):
            return None
    elif isinstance(expr.type, DateType):
        if not isinstance(vt, DateType):
            return None
    elif isinstance(expr.type, BooleanType) or isinstance(vt, DecimalType):
        return None
    return ["param", expr.index]


def _operand_value(expr, var):
    """Pushdown value for one comparison operand: a plain number (plan
    constant), a ``["param", index]`` marker, or None (not pushable)."""
    v = _literal(expr, var)
    if v is not None:
        return v
    return _param_marker(expr, var)


def split_conjuncts(expr) -> List:
    """Flatten an AND tree into its conjuncts."""
    from ..spi.expr import SpecialFormExpression
    if isinstance(expr, SpecialFormExpression) and expr.form == "AND":
        out: List = []
        for a in expr.arguments:
            out.extend(split_conjuncts(a))
        return out
    return [expr]


def conjunct_to_entries(expr, var_to_col: Dict[str, str]) -> List[dict]:
    """Pushdown entries for ONE conjunct ([] when it isn't range-shaped)."""
    from ..exec.lowering import canonical_name
    from ..spi.expr import (BoundParameterExpression, CallExpression,
                            ConstantExpression,
                            VariableReferenceExpression)
    if not isinstance(expr, CallExpression):
        return []
    name = canonical_name(expr.display_name)
    args = expr.arguments
    if name == "between" and len(args) == 3 \
            and isinstance(args[0], VariableReferenceExpression):
        col = var_to_col.get(args[0].name)
        lo = _operand_value(args[1], args[0])
        hi = _operand_value(args[2], args[0])
        if col is None or lo is None or hi is None:
            return []
        return [{"column": col, "op": "gte", "value": lo},
                {"column": col, "op": "lte", "value": hi}]
    op = _CMP_ALIASES.get(name)
    if op is None or len(args) != 2:
        return []
    a, b = args
    if isinstance(a, (ConstantExpression, BoundParameterExpression)) \
            and isinstance(b, VariableReferenceExpression):
        a, b = b, a
        op = _FLIP[op]
    if not isinstance(a, VariableReferenceExpression):
        return []
    col = var_to_col.get(a.name)
    v = _operand_value(b, a)
    if col is None or v is None:
        return []
    return [{"column": col, "op": op, "value": v}]


def extract_pushdown(predicate, var_to_col: Dict[str, str]) -> List[dict]:
    """All range/equality-shaped conjuncts of `predicate`, as entries."""
    out: List[dict] = []
    for c in split_conjuncts(predicate):
        out.extend(conjunct_to_entries(c, var_to_col))
    return out


# ---------------------------------------------------------------------------
# chunk pruning
# ---------------------------------------------------------------------------

def entry_unsatisfiable(op: str, value, zmin, zmax) -> bool:
    """True when NO value in [zmin, zmax] can satisfy ``col <op> value``.

    Empty zones carry reduction-identity bounds (zmin > zmax), which is
    unsatisfiable for every op — correct, since a zone with no values
    has no row that can pass.

    A TUPLE value with op "eq" is IN-set semantics (a runtime dynamic
    filter's exact small-domain value set): satisfiable as long as any
    member falls inside the zone range."""
    if zmin > zmax:
        return True
    if isinstance(value, tuple):
        if op != "eq":
            return False
        return all(v < zmin or v > zmax for v in value)
    if op == "eq":
        return value < zmin or value > zmax
    if op == "lt":
        return zmin >= value
    if op == "lte":
        return zmin > value
    if op == "gt":
        return zmax <= value
    if op == "gte":
        return zmax < value
    return False


def is_dyn_marker(value) -> bool:
    """``["dyn", filter_id, "min"|"max"|"set"]`` runtime-filter marker
    (sql/optimizer.plan_runtime_filter_pushdown)."""
    return isinstance(value, (list, tuple)) and len(value) == 3 \
        and value[0] == "dyn"


def resolve_entry_value(value, params, dynamic: Optional[Dict] = None):
    """A pushdown entry's comparison value for pruning: plain numbers
    pass through; ``["param", index]`` markers resolve from the
    execution's parameter fingerprint (device-unit host scalars);
    ``["dyn", fid, bound]`` runtime-filter markers resolve from the
    collected summaries in `dynamic` (fid -> DynamicFilterSummary wire
    dict) — "min"/"max" give ints, "set" gives the exact value tuple.
    Returns None when the marker cannot be resolved — the caller must
    then keep the chunk (conservatism over cleverness)."""
    if isinstance(value, (list, tuple)):
        if len(value) == 2 and value[0] == "param" and params is not None \
                and isinstance(value[1], int) and 0 <= value[1] < len(params):
            v = params[value[1]]
            if not isinstance(v, bool) and isinstance(v, (int, float)):
                return v
        if is_dyn_marker(value) and dynamic is not None:
            s = dynamic.get(value[1])
            if isinstance(s, dict) and int(s.get("rowCount", 0)) > 0:
                bound = value[2]
                if bound in ("min", "max"):
                    v = s.get(bound)
                    return v if isinstance(v, int) \
                        and not isinstance(v, bool) else None
                if bound == "set" and s.get("values") is not None:
                    return tuple(s["values"])
        return None
    return value


def prune_chunks(chunks: List[Tuple[int, int]], zone_maps: Dict,
                 pushdown: List[dict], params: Optional[Tuple] = None,
                 dynamic: Optional[Dict] = None,
                 detail: Optional[dict] = None,
                 keep_one: bool = True):
    """Drop chunks no pushed-down conjunct combination can satisfy.

    Returns (kept_chunks, skipped_count).  A conjunction skips a chunk
    when ANY single conjunct is unsatisfiable over the chunk's
    aggregated zone bounds.  With `keep_one` (the default) at least one
    chunk is always kept: fused consumers bake len(chunks) into
    compiled fori_loop programs and a zero-chunk scan would leave them
    nothing to fold over (the residual filter turns the survivor into
    zero rows anyway).  Streaming scans that prune split-by-split pass
    keep_one=False — an empty split simply yields no batches, and the
    per-call floor would otherwise make a single-chunk split immune to
    pruning.

    `params` is the execution's host-side parameter fingerprint;
    `dynamic` the runtime dynamic-filter summaries (fid -> wire dict).
    Marker entries resolve against them and prune nothing when absent.
    Static entries order before dyn markers in planned pushdown lists,
    so a chunk skip attributed to a dyn entry is one static pushdown
    could NOT have made — counted separately (the adaptive registry's
    `filter_chunks_skipped`).

    `detail`, when given, is filled with {"dyn_engaged": did any dyn
    marker resolve, "rows_in": total rows considered, "dyn_rows_pruned":
    rows in dyn-attributed skipped chunks} — callers that own per-
    execution metering (fused chains bypass the row-level runtime
    filter) read it instead of re-deriving attribution."""
    from .store import STORAGE_METRICS
    kept: List[Tuple[int, int]] = []
    dyn_skipped: List[Tuple[int, int]] = []
    dyn_engaged = False
    for pos, count in chunks:
        skip = skip_dyn = False
        for e in pushdown:
            zm = zone_maps.get(e["column"])
            if zm is None:
                continue
            value = resolve_entry_value(e["value"], params, dynamic)
            if value is None:
                continue
            if is_dyn_marker(e["value"]):
                dyn_engaged = True
            bounds = zm.chunk_bounds(pos, count)
            if bounds is None:
                continue
            if entry_unsatisfiable(e["op"], value, *bounds):
                skip = True
                skip_dyn = is_dyn_marker(e["value"])
                break
        if not skip:
            kept.append((pos, count))
        elif skip_dyn:
            dyn_skipped.append((pos, count))
    if not kept and chunks and keep_one:
        kept = [chunks[0]]
        if chunks[0] in dyn_skipped:
            dyn_skipped.remove(chunks[0])
    skipped = len(chunks) - len(kept)
    STORAGE_METRICS.incr("chunks_total", len(chunks))
    STORAGE_METRICS.incr("chunks_skipped", skipped)
    if dyn_skipped and detail is None:
        # callers that pass `detail` own adaptive metering themselves
        # (fused chains recompute chunk lists more than once per
        # execution and must count each skip exactly once)
        from ..exec.adaptive import ADAPTIVE_METRICS
        ADAPTIVE_METRICS.incr("filter_chunks_skipped",
                              min(len(dyn_skipped), skipped))
    if detail is not None:
        detail["dyn_engaged"] = dyn_engaged
        detail["rows_in"] = sum(c for _, c in chunks)
        detail["dyn_chunks_pruned"] = len(dyn_skipped)
        detail["dyn_rows_pruned"] = sum(c for _, c in dyn_skipped)
    return kept, skipped
