"""Scan predicate pushdown metadata and zone-map chunk pruning.

`plan_scan_pushdown` (sql/optimizer.py) records on each TableScanNode
the conjuncts of its parent FilterNode that are range/equality-shaped
(``col <op> literal`` with op in eq/lt/lte/gt/gte, or BETWEEN) as plain
``{"column", "op", "value"}`` dicts — serializable, checker-visible
(analysis/checker.py SCAN_PUSHDOWN), and consumed at execution by
`prune_chunks` to skip whole scan chunks whose zone-map [min, max]
cannot satisfy the conjunction.

Pruning is ADVISORY: the FilterNode stays in the plan and re-filters
every surviving row exactly, so over-inclusion is harmless and the only
correctness obligation here is conservatism — a chunk is skipped ONLY
when no value in its zone range can pass.  All decisions are host-side
numpy over stats captured at build time (encodings.build_zone_maps);
nothing here touches the device.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

PUSHDOWN_OPS = ("eq", "lt", "lte", "gt", "gte")

_CMP_ALIASES = {
    "lt": "lt", "less_than": "lt",
    "lte": "lte", "less_than_or_equal": "lte",
    "gt": "gt", "greater_than": "gt",
    "gte": "gte", "greater_than_or_equal": "gte",
    "eq": "eq", "equal": "eq",
}
_FLIP = {"lt": "gt", "lte": "gte", "gt": "lt", "gte": "lte", "eq": "eq"}


def _literal(expr, var) -> Optional[float]:
    """The constant's numeric value in STORED-column units, or None.

    Mirrors exec/lowering.constant_device_value, which is what the
    residual filter itself compares against, but only when the units
    provably line up with the column `var` is bound to:

    - decimal constants are unscaled ints at the constant's scale;
      accepted only against a decimal column of the SAME scale (decimal
      device columns are stored unscaled at their declared scale);
    - date constants become epoch-day ints, accepted against date
      columns (stored as epoch-day i32);
    - plain int/float constants are accepted against non-decimal
      columns (a raw int against an unscaled decimal column would be
      off by 10^scale and make pruning unsound).
    """
    from ..common.types import DateType, DecimalType
    from ..spi.expr import ConstantExpression
    if not isinstance(expr, ConstantExpression) or expr.value is None:
        return None
    vt = getattr(var, "type", None)
    if isinstance(expr.type, (DecimalType, DateType)):
        if isinstance(expr.type, DecimalType) and not (
                isinstance(vt, DecimalType)
                and vt.scale == expr.type.scale
                # a float typed decimal would be truncated, not scaled
                and not isinstance(expr.value, float)):
            return None
        if isinstance(expr.type, DateType) and not isinstance(vt, DateType):
            return None
        from ..exec.lowering import constant_device_value
        v = constant_device_value(expr.value, expr.type)
        return v if isinstance(v, int) else None
    v = expr.value
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if isinstance(vt, DecimalType):
        return None
    return v


def _param_marker(expr, var) -> Optional[list]:
    """``["param", index]`` when a bound-parameter operand's plan type
    lines up with the column's stored units, else None.

    Same unit gates as `_literal`; the VALUE is resolved at prune time
    from the execution's parameter fingerprint, which already carries
    device-unit host scalars (decimals unscaled at the plan scale, dates
    as epoch days — see sql/canonical.device_params).  The marker is a
    list, not a tuple, so it survives the TableScanNode JSON round trip
    unchanged and the checker's re-derivation equality keeps holding.
    """
    from ..common.types import BooleanType, DateType, DecimalType
    from ..spi.expr import BoundParameterExpression
    if not isinstance(expr, BoundParameterExpression):
        return None
    vt = getattr(var, "type", None)
    if isinstance(expr.type, DecimalType):
        if not (isinstance(vt, DecimalType)
                and vt.scale == expr.type.scale):
            return None
    elif isinstance(expr.type, DateType):
        if not isinstance(vt, DateType):
            return None
    elif isinstance(expr.type, BooleanType) or isinstance(vt, DecimalType):
        return None
    return ["param", expr.index]


def _operand_value(expr, var):
    """Pushdown value for one comparison operand: a plain number (plan
    constant), a ``["param", index]`` marker, or None (not pushable)."""
    v = _literal(expr, var)
    if v is not None:
        return v
    return _param_marker(expr, var)


def split_conjuncts(expr) -> List:
    """Flatten an AND tree into its conjuncts."""
    from ..spi.expr import SpecialFormExpression
    if isinstance(expr, SpecialFormExpression) and expr.form == "AND":
        out: List = []
        for a in expr.arguments:
            out.extend(split_conjuncts(a))
        return out
    return [expr]


def conjunct_to_entries(expr, var_to_col: Dict[str, str]) -> List[dict]:
    """Pushdown entries for ONE conjunct ([] when it isn't range-shaped)."""
    from ..exec.lowering import canonical_name
    from ..spi.expr import (BoundParameterExpression, CallExpression,
                            ConstantExpression,
                            VariableReferenceExpression)
    if not isinstance(expr, CallExpression):
        return []
    name = canonical_name(expr.display_name)
    args = expr.arguments
    if name == "between" and len(args) == 3 \
            and isinstance(args[0], VariableReferenceExpression):
        col = var_to_col.get(args[0].name)
        lo = _operand_value(args[1], args[0])
        hi = _operand_value(args[2], args[0])
        if col is None or lo is None or hi is None:
            return []
        return [{"column": col, "op": "gte", "value": lo},
                {"column": col, "op": "lte", "value": hi}]
    op = _CMP_ALIASES.get(name)
    if op is None or len(args) != 2:
        return []
    a, b = args
    if isinstance(a, (ConstantExpression, BoundParameterExpression)) \
            and isinstance(b, VariableReferenceExpression):
        a, b = b, a
        op = _FLIP[op]
    if not isinstance(a, VariableReferenceExpression):
        return []
    col = var_to_col.get(a.name)
    v = _operand_value(b, a)
    if col is None or v is None:
        return []
    return [{"column": col, "op": op, "value": v}]


def extract_pushdown(predicate, var_to_col: Dict[str, str]) -> List[dict]:
    """All range/equality-shaped conjuncts of `predicate`, as entries."""
    out: List[dict] = []
    for c in split_conjuncts(predicate):
        out.extend(conjunct_to_entries(c, var_to_col))
    return out


# ---------------------------------------------------------------------------
# chunk pruning
# ---------------------------------------------------------------------------

def entry_unsatisfiable(op: str, value, zmin, zmax) -> bool:
    """True when NO value in [zmin, zmax] can satisfy ``col <op> value``.

    Empty zones carry reduction-identity bounds (zmin > zmax), which is
    unsatisfiable for every op — correct, since a zone with no values
    has no row that can pass."""
    if zmin > zmax:
        return True
    if op == "eq":
        return value < zmin or value > zmax
    if op == "lt":
        return zmin >= value
    if op == "lte":
        return zmin > value
    if op == "gt":
        return zmax <= value
    if op == "gte":
        return zmax < value
    return False


def resolve_entry_value(value, params):
    """A pushdown entry's comparison value for pruning: plain numbers
    pass through; ``["param", index]`` markers resolve from the
    execution's parameter fingerprint (device-unit host scalars).
    Returns None when the marker cannot be resolved — the caller must
    then keep the chunk (conservatism over cleverness)."""
    if isinstance(value, (list, tuple)):
        if len(value) == 2 and value[0] == "param" and params is not None \
                and isinstance(value[1], int) and 0 <= value[1] < len(params):
            v = params[value[1]]
            if not isinstance(v, bool) and isinstance(v, (int, float)):
                return v
        return None
    return value


def prune_chunks(chunks: List[Tuple[int, int]], zone_maps: Dict,
                 pushdown: List[dict], params: Optional[Tuple] = None):
    """Drop chunks no pushed-down conjunct combination can satisfy.

    Returns (kept_chunks, skipped_count).  A conjunction skips a chunk
    when ANY single conjunct is unsatisfiable over the chunk's
    aggregated zone bounds.  At least one chunk is always kept: fused
    consumers bake len(chunks) into compiled fori_loop programs and a
    zero-chunk scan would leave them nothing to fold over (the residual
    filter turns the survivor into zero rows anyway).

    `params` is the execution's host-side parameter fingerprint; entries
    whose value is a ``["param", index]`` marker resolve against it and
    prune nothing when it is absent.
    """
    from .store import STORAGE_METRICS
    kept: List[Tuple[int, int]] = []
    for pos, count in chunks:
        skip = False
        for e in pushdown:
            zm = zone_maps.get(e["column"])
            if zm is None:
                continue
            value = resolve_entry_value(e["value"], params)
            if value is None:
                continue
            bounds = zm.chunk_bounds(pos, count)
            if bounds is None:
                continue
            if entry_unsatisfiable(e["op"], value, *bounds):
                skip = True
                break
        if not skip:
            kept.append((pos, count))
    if not kept and chunks:
        kept = [chunks[0]]
    skipped = len(chunks) - len(kept)
    STORAGE_METRICS.incr("chunks_total", len(chunks))
    STORAGE_METRICS.incr("chunks_skipped", skipped)
    return kept, skipped
