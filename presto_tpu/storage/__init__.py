"""HBM-resident encoded columnar storage (see store.py for the design).

Public surface:
- ResidentColumn / encode_column / ZoneMaps / build_zone_maps (encodings)
- ResidentStore / get_store / STORAGE_METRICS / reset_storage_metrics
- extract_pushdown / prune_chunks / PUSHDOWN_OPS (pushdown)
"""
from .encodings import (DICT_MAX_NDV, ResidentColumn, ZoneMaps,
                        build_zone_maps, encode_column)
from .pushdown import (PUSHDOWN_OPS, entry_unsatisfiable, extract_pushdown,
                       prune_chunks, split_conjuncts)
from .store import (DEFAULT_MAX_COLUMN_BYTES, DEFAULT_STORAGE_BUDGET,
                    DEFAULT_ZONE_ROWS, STORAGE_METRICS, ResidentEntry,
                    ResidentStore, get_store, reset_storage_metrics)

__all__ = [
    "DICT_MAX_NDV", "ResidentColumn", "ZoneMaps", "build_zone_maps",
    "encode_column", "PUSHDOWN_OPS", "entry_unsatisfiable",
    "extract_pushdown", "prune_chunks", "split_conjuncts",
    "DEFAULT_MAX_COLUMN_BYTES", "DEFAULT_STORAGE_BUDGET",
    "DEFAULT_ZONE_ROWS", "STORAGE_METRICS", "ResidentEntry",
    "ResidentStore", "get_store", "reset_storage_metrics",
]
