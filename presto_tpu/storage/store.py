"""Process-wide HBM-resident columnar store with LRU eviction.

Generating a connector column is a uint64 splitmix hash per row —
64-bit integer multiplies are EMULATED on the TPU vector unit and
dominate fused-scan wall clock (measured at SF10: shipdate generation
alone cost 3x the whole aggregation).  Generated connector data is
immutable, so whole-table columns are materialized into HBM ONCE,
encoded (encodings.py), zone-mapped, and every scan chunk becomes a
`slice_decode` — the reference analog is Velox reading an in-memory
columnar table instead of recomputing it.

Residency is charged to an `exec.memory.MemoryPool` (the same
accounting type task execution uses, so the cache composes with memory
arbitration/spill work):

- insertion evicts least-recently-used entries until the new column's
  encoded bytes fit the `storage` budget;
- a column that cannot fit even alone is simply NOT cached — the scan
  falls back to on-the-fly generation.  The budget degrades throughput,
  never correctness, and never raises MemoryExceededError.

Eviction releases the store's reference and accounting immediately;
the arrays themselves leave HBM when the last compiled plan holding
them is dropped (plans receive resident columns as traced arguments,
not closures, so nothing is baked into executables).
"""
from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..common.locks import OrderedLock
from ..exec.memory import MemoryPool
from .encodings import (ResidentColumn, ZoneMaps, build_zone_maps,
                        encode_column)

DEFAULT_STORAGE_BUDGET = 6 << 30
# building a column transiently holds ~2x its plain bytes (chunk parts
# + concatenated result), so multi-GB columns (SF100 lineitem) must stay
# on-the-fly or the build itself OOMs HBM
DEFAULT_MAX_COLUMN_BYTES = 1 << 30
DEFAULT_ZONE_ROWS = 1 << 16
# columns at or under this row count take the host-side stats path at
# build time (one device_get, numpy selection); larger columns keep all
# probes on device so a SF10+ build never round-trips gigabytes
HOST_STATS_ROWS = 1 << 20

# process-wide observability counters, consumed by bench.py and tests;
# chunks_total/chunks_skipped are bumped by pushdown.prune_chunks every
# time a chunk list is enumerated, so the skip FRACTION stays exact even
# though repeated enumerations inflate both counters proportionally
_STORAGE_COUNTERS = ("cache_hits", "cache_misses", "columns_built",
                     "build_rejected", "evictions", "resident_bytes",
                     "encoded_bytes", "plain_bytes",
                     "chunks_total", "chunks_skipped")


class StorageMetrics:
    """Locked storage-counter registry.  Replaces the bare module dict:
    concurrent scan threads bumping `d[k] += 1` lose increments, and
    /v1/metrics could read a half-updated view mid-build.  Keeps the
    dict-like read surface (`m[k]`, `sorted(m)`, `dict(m)`, `.items()`)
    the existing consumers and tests use."""

    def __init__(self):
        # rank 100: metrics registries are leaf locks
        self._lock = OrderedLock("metrics:storage", 100)  # lint: guarded-by(_lock)
        self._values: Dict[str, int] = {k: 0 for k in _STORAGE_COUNTERS}

    def reset(self) -> None:
        with self._lock:
            for k in _STORAGE_COUNTERS:
                self._values[k] = 0

    def incr(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._values[name] += delta

    def __getitem__(self, name: str) -> int:
        with self._lock:
            return self._values[name]

    def __setitem__(self, name: str, value: int) -> None:
        with self._lock:
            self._values[name] = value

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._values

    def __iter__(self):
        return iter(self.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._values)

    def keys(self):
        with self._lock:
            return list(self._values)

    def items(self):
        return self.snapshot().items()

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._values)


STORAGE_METRICS = StorageMetrics()


def reset_storage_metrics() -> None:
    STORAGE_METRICS.reset()


class ResidentEntry:
    """One cached column: encoded device arrays + host-side zone maps."""

    __slots__ = ("column", "zones", "nbytes", "pad")

    def __init__(self, column: ResidentColumn, zones: ZoneMaps,
                 pad: int):
        self.column = column
        self.zones = zones
        self.nbytes = column.nbytes
        self.pad = pad


class ResidentStore:
    """LRU cache of ResidentEntry keyed (connector, table, column, sf,
    as_i32), charged to its own MemoryPool."""

    def __init__(self, budget: Optional[int] = DEFAULT_STORAGE_BUDGET,
                 max_column_bytes: int = DEFAULT_MAX_COLUMN_BYTES):
        self.pool = MemoryPool(budget)
        self.max_column_bytes = max_column_bytes
        self.entries: "OrderedDict[tuple, ResidentEntry]" = OrderedDict()

    # -- lookup / build ---------------------------------------------------
    def get_or_build(self, cid: str, table: str, colname: str, sf: float,
                     n_rows: int, pad: int, as_i32: bool,
                     zone_rows: int = DEFAULT_ZONE_ROWS,
                     encodings: bool = True) -> Optional[ResidentEntry]:
        key = (cid, table, colname, float(sf), bool(as_i32))
        ent = self.entries.get(key)
        if ent is not None:
            if ent.pad >= pad and ent.zones.zone_rows <= zone_rows:
                self.entries.move_to_end(key)
                STORAGE_METRICS.incr("cache_hits")
                return ent
            # built under a smaller batch capacity (chunk slices must
            # never clamp) or coarser zone maps (a session asking for
            # finer storage_zone_rows must actually get the pruning
            # granularity it asked for): rebuild.  A finer-than-requested
            # cached entry is kept — extra zones only sharpen pruning.
            self._evict(key)
        STORAGE_METRICS.incr("cache_misses")
        itemsize = 4 if as_i32 else 8
        if (n_rows + pad) * itemsize > self.max_column_bytes:
            STORAGE_METRICS.incr("build_rejected")
            return None
        arr = _build_full(cid, table, colname, sf, n_rows, pad, as_i32)
        from ..connectors import device_gen
        hint = device_gen.encoding_hint(cid, table, colname)
        # for small columns, pull the padded column to the host once and
        # run encoding selection + zone reduction in numpy — dozens of
        # tiny per-column device programs collapse into one transfer
        host = None
        if n_rows <= HOST_STATS_ROWS:
            # build-time stat transfer, once per column per process
            host = jax.device_get(arr)  # lint: allow-host-sync
        col = encode_column(arr, n_rows, encodings=encodings, hint=hint,
                            host=host)
        zones = build_zone_maps(arr, n_rows, zone_rows, host=host)
        del arr, host
        ent = ResidentEntry(col, zones, pad)
        while not self.pool.try_reserve(ent.nbytes):
            if not self.entries:
                STORAGE_METRICS.incr("build_rejected")
                return None
            oldest = next(iter(self.entries))
            self._evict(oldest)
        self.entries[key] = ent
        STORAGE_METRICS.incr("columns_built")
        STORAGE_METRICS.incr("encoded_bytes", ent.nbytes)
        STORAGE_METRICS.incr("plain_bytes", col.logical_nbytes)
        STORAGE_METRICS["resident_bytes"] = self.pool.reserved
        return ent

    def _evict(self, key: tuple) -> None:
        ent = self.entries.pop(key)
        self.pool.free(ent.nbytes)
        STORAGE_METRICS.incr("evictions")
        STORAGE_METRICS["resident_bytes"] = self.pool.reserved

    def clear(self) -> None:
        for key in list(self.entries):
            ent = self.entries.pop(key)
            self.pool.free(ent.nbytes)
        STORAGE_METRICS["resident_bytes"] = self.pool.reserved


@functools.lru_cache(maxsize=None)
def _gen_fn(cid: str, table: str, colname: str, sf: float, chunk: int,
            as_i32: bool):
    """Jitted whole-chunk generator, cached so pad-growth rebuilds and
    differently-budgeted stores reuse the compiled executable."""
    from ..connectors import device_gen

    @jax.jit
    def gen_chunk(pos):
        idx = pos + jnp.arange(chunk, dtype=jnp.int64)
        v = device_gen.column(cid, table, colname, sf, idx)
        return v.astype(jnp.int32) if as_i32 and v.dtype == jnp.int64 \
            else v

    return gen_chunk


def _build_full(cid: str, table: str, colname: str, sf: float,
                n_rows: int, pad: int, as_i32: bool):
    """Materialize one whole column on device via the jitted counter-hash
    generator, zero tail padding appended (chunk slices never clamp-shift
    at the table edge — dynamic_slice clamping would silently misalign
    live rows).  The chunk is the next power of two covering the table
    (capped at 4M rows): tiny catalog tables don't pay a 4M-row hash,
    and pow2 bucketing keeps compile-cache reuse across similar sizes."""
    chunk = 1 << max(10, min(22, (max(n_rows, 1) - 1).bit_length()))
    gen_chunk = _gen_fn(cid, table, colname, float(sf), chunk, bool(as_i32))
    parts = [gen_chunk(jnp.int64(p)) for p in range(0, n_rows, chunk)]
    arr = jnp.concatenate(parts)[:n_rows]
    return jnp.concatenate([arr, jnp.zeros(pad, dtype=arr.dtype)])


# ---------------------------------------------------------------------------
# store registry: one store per (budget, max_column_bytes) configuration,
# so a test running under a deliberately tiny budget never pollutes (or
# borrows from) the default 6 GiB process store
# ---------------------------------------------------------------------------

_STORES: Dict[tuple, ResidentStore] = {}


def get_store(budget: Optional[int] = DEFAULT_STORAGE_BUDGET,
              max_column_bytes: int = DEFAULT_MAX_COLUMN_BYTES
              ) -> ResidentStore:
    key = (budget, max_column_bytes)
    st = _STORES.get(key)
    if st is None:
        st = _STORES[key] = ResidentStore(budget, max_column_bytes)
    return st
