"""RuntimeStats + tracer SPI.

The analog of the reference's fine-grained engine profiling (§5.1):

  * RuntimeStats (presto-common/.../common/RuntimeStats.java): a
    thread-safe name -> {sum, count, min, max, unit} metric map threaded
    through query execution; phases are recorded with
    `record_wall(name)` the way SqlQueryExecution.java:556-614 wraps
    analysis/optimization/fragmentation in recordWallAndCpuTime, and the
    map is mergeable (task stats roll up into query stats).

  * Tracer SPI (TracerProviderManager / SimpleTracer,
    presto-main-base/.../tracing/): pluggable `TracerProvider`; the
    in-tree SimpleTracer records per-query trace points with wall-clock
    timestamps, queryable for tests/ops.  NoopTracer is the default.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

NANO = 1_000_000_000


@dataclass
class Metric:
    unit: str = "NANO"      # NANO | BYTE | NONE (RuntimeUnit analog)
    sum: float = 0.0
    count: int = 0
    min: float = float("inf")
    max: float = float("-inf")

    def add(self, value: float) -> None:
        self.sum += value
        self.count += 1
        self.min = min(self.min, value)
        self.max = max(self.max, value)

    def merge(self, other: "Metric") -> None:
        self.sum += other.sum
        self.count += other.count
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    def to_dict(self) -> dict:
        return {"unit": self.unit, "sum": self.sum, "count": self.count,
                "min": self.min if self.count else 0,
                "max": self.max if self.count else 0}


class RuntimeStats:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def add(self, name: str, value: float, unit: str = "NONE") -> None:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Metric(unit)
            m.add(value)

    @contextmanager
    def record_wall(self, name: str):
        """recordWallAndCpuTime analog (wall only; CPU time is not
        meaningful for device-side work)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name + "WallNanos",
                     (time.perf_counter() - t0) * NANO, "NANO")

    def merge(self, other: "RuntimeStats") -> None:
        with other._lock:
            items = list(other._metrics.items())
        with self._lock:
            for name, m in items:
                mine = self._metrics.get(name)
                if mine is None:
                    mine = self._metrics[name] = Metric(m.unit)
                mine.merge(m)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def to_dict(self) -> Dict[str, dict]:
        with self._lock:
            return {n: m.to_dict() for n, m in sorted(self._metrics.items())}


# ---------------------------------------------------------------------------
# tracer SPI
# ---------------------------------------------------------------------------

@dataclass
class TracePoint:
    annotation: str
    at: float = field(default_factory=time.time)


@dataclass
class Span:
    """One named interval in the query's span tree (query -> fragment ->
    task -> operator).  `parent` is the parent span's name ("" = root)."""
    name: str
    parent: str = ""
    start: float = 0.0
    end: float = 0.0
    attributes: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "parent": self.parent,
                "start": self.start, "end": self.end,
                "attributes": dict(self.attributes)}


class Tracer:
    """SPI (presto-spi tracing.Tracer analog)."""

    def add_point(self, annotation: str) -> None:
        raise NotImplementedError

    @contextmanager
    def span(self, name: str, parent: str = "", **attributes):
        """Nested interval recording; no-op in the base/Noop tracers.
        `parent` names the enclosing span explicitly so spans opened on
        worker threads (stage tasks) attach to the right parent."""
        yield name

    def end_trace(self, annotation: str = "trace ended") -> None:
        self.add_point(annotation)


class NoopTracer(Tracer):
    def add_point(self, annotation: str) -> None:
        pass


class SimpleTracer(Tracer):
    """In-memory recording tracer (tracing/SimpleTracer.java), extended
    with a span tree for tests/ops."""

    def __init__(self, trace_token: str = ""):
        self.trace_token = trace_token
        self.points: List[TracePoint] = []
        self.spans: List[Span] = []
        self._lock = threading.Lock()

    def add_point(self, annotation: str) -> None:
        with self._lock:
            self.points.append(TracePoint(annotation))

    @contextmanager
    def span(self, name: str, parent: str = "", **attributes):
        s = Span(name, parent, start=time.time(), attributes=attributes)
        with self._lock:
            self.spans.append(s)
        try:
            yield name
        finally:
            s.end = time.time()

    def annotations(self) -> List[str]:
        with self._lock:
            return [p.annotation for p in self.points]

    def span_children(self, parent: str = "") -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.parent == parent]

    def span_tree(self) -> List[dict]:
        """Nested {name, attributes, children} forest rooted at parent=""."""
        def build(parent: str) -> List[dict]:
            return [{"name": s.name, "attributes": dict(s.attributes),
                     "children": build(s.name)}
                    for s in self.span_children(parent)]
        return build("")


class TracerProvider:
    """Selected once per process (TracerProviderManager analog)."""

    def __init__(self, kind: str = "noop"):
        self.kind = kind
        self._traces: Dict[str, SimpleTracer] = {}
        self._lock = threading.Lock()

    def new_tracer(self, trace_token: str) -> Tracer:
        if self.kind != "simple":
            return NoopTracer()
        t = SimpleTracer(trace_token)
        with self._lock:
            self._traces[trace_token] = t
        return t

    def get_trace(self, trace_token: str) -> Optional[SimpleTracer]:
        with self._lock:
            return self._traces.get(trace_token)

    def pop_trace(self, trace_token: str) -> Optional[SimpleTracer]:
        """Detach a finished trace (export pipelines take ownership so
        long-lived providers do not accumulate span trees forever)."""
        with self._lock:
            return self._traces.pop(trace_token, None)
