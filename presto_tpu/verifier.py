"""Result verifier: checksum-based A/B comparison of two engines.

The analog of presto-verifier (presto-verifier/.../framework/
AbstractVerification.java:74 + checksum/): each query runs on a *control*
runner and a *test* runner and the result sets are compared by per-column
checksums — order-insensitive, with floating point compared by count /
null-count / bounded-error mean rather than exact bits, exactly the
strategy the reference's ChecksumValidator family implements.

Typical pairings here: numpy reference interpreter vs the TPU engine,
unconstrained engine vs forced-spill engine, local vs distributed runner.
"""
from __future__ import annotations

import hashlib
import math
from dataclasses import dataclass, field
from decimal import Decimal
from typing import Callable, Dict, List, Optional

MATCH = "MATCH"
MISMATCH = "MISMATCH"
CONTROL_ERROR = "CONTROL_ERROR"
TEST_ERROR = "TEST_ERROR"


@dataclass
class ColumnChecksum:
    count: int = 0
    nulls: int = 0
    # exact types: order-insensitive sum of value hashes (mod 2^64)
    hash_sum: int = 0
    # floats: compared by aggregates with tolerance
    float_sum: float = 0.0
    float_nan: int = 0

    def add(self, value, is_float: bool) -> None:
        self.count += 1
        if value is None:
            self.nulls += 1
            return
        if is_float:
            f = float(value)
            if math.isnan(f):
                self.float_nan += 1
            else:
                self.float_sum += f
            return
        h = hashlib.blake2b(repr(value).encode(), digest_size=8).digest()
        self.hash_sum = (self.hash_sum
                         + int.from_bytes(h, "little")) % (1 << 64)

    def matches(self, other: "ColumnChecksum",
                rel_tol: float = 1e-9) -> bool:
        if (self.count, self.nulls, self.float_nan) != \
                (other.count, other.nulls, other.float_nan):
            return False
        if self.hash_sum != other.hash_sum:
            return False
        scale = max(abs(self.float_sum), abs(other.float_sum), 1.0)
        return abs(self.float_sum - other.float_sum) <= rel_tol * scale


@dataclass
class VerificationResult:
    query: str
    status: str
    detail: str = ""
    control_checksums: List[ColumnChecksum] = field(default_factory=list)
    test_checksums: List[ColumnChecksum] = field(default_factory=list)


def checksum_result(result) -> List[ColumnChecksum]:
    """QueryResult -> per-column checksums, POSITIONAL (duplicate column
    names are common — 'select count(*), count(*)' — and must not
    collapse)."""
    from .common.types import DoubleType, RealType
    sums = [ColumnChecksum() for _ in result.column_names]
    flts = [isinstance(t, (DoubleType, RealType))
            for t in result.column_types]
    for row in result.rows:
        for cs, v, isf in zip(sums, row, flts):
            cs.add(_canonical(v), isf)
    return sums


def _canonical(v):
    if isinstance(v, Decimal):
        return str(v.normalize())
    if isinstance(v, bool):
        return int(v)
    return v


def verify(control: Callable[[str], object], test: Callable[[str], object],
           queries: List[str]) -> List[VerificationResult]:
    """Run every query through both engines and compare checksums.
    control/test: callables sql -> QueryResult."""
    out = []
    for sql in queries:
        try:
            c = control(sql)
        except Exception as e:  # noqa: BLE001 — verifier reports, not raises
            out.append(VerificationResult(sql, CONTROL_ERROR, repr(e)))
            continue
        try:
            t = test(sql)
        except Exception as e:  # noqa: BLE001
            out.append(VerificationResult(sql, TEST_ERROR, repr(e)))
            continue
        cc, tc = checksum_result(c), checksum_result(t)
        if c.column_names != t.column_names:
            out.append(VerificationResult(
                sql, MISMATCH,
                f"column sets differ: {c.column_names} vs {t.column_names}",
                cc, tc))
            continue
        bad = [f"{c.column_names[i]}#{i}" for i in range(len(cc))
               if not cc[i].matches(tc[i])]
        if bad:
            out.append(VerificationResult(
                sql, MISMATCH, f"checksum mismatch in columns {bad}", cc, tc))
        else:
            out.append(VerificationResult(sql, MATCH, "", cc, tc))
    return out
