"""Pallas TPU kernel: direct (small-domain) grouped aggregation as one MXU pass.

The TPU-native hot path for HashAggregationOperator.java:56-style grouped
aggregation when the group domain is small (TPC-H Q1: 6 groups) or global
(Q6: 1 group).  The XLA fallback (operators.agg_direct_update) materializes a
G x N boolean grid and does masked VPU reductions per aggregate; this kernel
instead expresses the whole multi-aggregate update as a single systolic-array
matmul per input tile:

    planes (P, T) f32  @  one_hot (T, 128) f32  ->  (P, 128) f32

where `planes` stacks, per aggregate input column, eight 8-bit limb planes of
the int64 values plus one validity plane, and `one_hot` encodes each row's
group code (mask folded in).  All in-kernel arithmetic is int32/f32 - native
VPU/MXU dtypes - so the kernel never touches the 32-bit-ALU emulation that
int64 math costs on TPU.  Exactness:

  * limbs are < 2^8, a tile has T = 2048 rows, so every matmul partial
    product/accumulation stays < 2^19 - exactly representable in f32;
  * per-block f32 limb sums are combined outside the kernel in uint64 as
    sum_k 2^(8k) * limb_sum_k, i.e. the column sum **mod 2^64** - identical
    to int64 wraparound semantics of the engine's accumulators.

Grid iterates over row tiles; each block writes its own (P, 128) partial so
cross-block combination happens in XLA at int64 width (no in-kernel overflow).

On non-TPU backends the kernel runs under the Pallas interpreter (tests).
Routing is opt-in: ExecutionConfig.pallas_agg=True (exec/pipeline.py) sends
eligible direct aggregations here on both the streaming and fused paths;
the default stays on the XLA masked-reduction path, which profiles at parity
on current hardware (the kernel exists to own this seam for shapes where
XLA's reduction strategy degrades: many aggregates x many groups).
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

TILE_ROWS = 2048          # T: rows per grid step
LANES = 128               # one-hot width (>= DIRECT_AGG_MAX_GROUPS)
LIMBS = 8                 # 8-bit limbs covering int64


def _kernel(codes_ref, mask_ref, lo_ref, hi_ref, valid_ref, out_ref, *, C, P):
    """One grid step: build limb planes for T rows, matmul against one-hot.

    codes_ref (1,T) i32; mask_ref (1,T) f32; lo/hi_ref (C,T) i32 (bitcast
    halves of the int64 values); valid_ref (C,T) f32 (mask & not-null);
    out_ref (1,P,128) f32 where P = 9C+1 padded to a multiple of 8.
    """
    codes = codes_ref[0, :]
    onehot = (codes[:, None] ==
              jax.lax.broadcasted_iota(jnp.int32, (TILE_ROWS, LANES), 1))
    onehot = onehot.astype(jnp.float32) * mask_ref[0, :][:, None]

    planes = []
    for c in range(C):
        lo = lo_ref[c, :]
        hi = hi_ref[c, :]
        valid = valid_ref[c, :]
        for k in range(4):
            limb = ((lo >> (8 * k)) & 255).astype(jnp.float32) * valid
            planes.append(limb)
        for k in range(4):
            limb = ((hi >> (8 * k)) & 255).astype(jnp.float32) * valid
            planes.append(limb)
        planes.append(valid)                      # non-null count plane
    planes.append(mask_ref[0, :])                 # group-count plane
    while len(planes) < P:
        planes.append(jnp.zeros((TILE_ROWS,), jnp.float32))
    stacked = jnp.stack(planes, axis=0)           # (P, T)

    out_ref[0, :, :] = jax.lax.dot(
        stacked, onehot, preferred_element_type=jnp.float32)


@partial(jax.jit, static_argnames=("G", "interpret"))
def _grouped_sums_padded(lo, hi, valid, codes, mask, G: int, interpret: bool):
    """lo/hi (C, N) i32, valid (C, N) f32, codes (1, N) i32, mask (1, N) f32;
    N a multiple of TILE_ROWS.  Returns (sums u64 (C,G), counts i64 (C,G),
    gcount i64 (G,))."""
    C, N = lo.shape
    P = -(-(9 * C + 1) // 8) * 8
    nblocks = N // TILE_ROWS

    out = pl.pallas_call(
        partial(_kernel, C=C, P=P),
        out_shape=jax.ShapeDtypeStruct((nblocks, P, LANES), jnp.float32),
        grid=(nblocks,),
        in_specs=[
            # NOTE: constants via np.int32 — under jax_enable_x64 a bare 0
            # becomes an i64 the Mosaic index-map lowering can't legalize
            pl.BlockSpec((1, TILE_ROWS), lambda i: (np.int32(0), i)),  # codes
            pl.BlockSpec((1, TILE_ROWS), lambda i: (np.int32(0), i)),  # mask
            pl.BlockSpec((C, TILE_ROWS), lambda i: (np.int32(0), i)),  # lo
            pl.BlockSpec((C, TILE_ROWS), lambda i: (np.int32(0), i)),  # hi
            pl.BlockSpec((C, TILE_ROWS), lambda i: (np.int32(0), i)),  # valid
        ],
        out_specs=pl.BlockSpec((1, P, LANES),
                               lambda i: (i, np.int32(0), np.int32(0))),
        interpret=interpret,
    )(codes, mask, lo, hi, valid)

    # cross-block combine at integer width (per-block entries < 2^19 exact)
    tot = out.astype(jnp.int64).sum(axis=0)       # (P, 128)
    tot = tot[:, :G]
    sums = jnp.zeros((C, G), dtype=jnp.uint64)
    counts = jnp.zeros((C, G), dtype=jnp.int64)
    for c in range(C):
        s = jnp.zeros((G,), dtype=jnp.uint64)
        for k in range(LIMBS):
            s = s + (tot[9 * c + k].astype(jnp.uint64) << jnp.uint64(8 * k))
        sums = sums.at[c].set(s)
        counts = counts.at[c].set(tot[9 * c + 8])
    gcount = tot[9 * C]
    return sums, counts, gcount


def grouped_sums(cols: List[Tuple[jnp.ndarray, Optional[jnp.ndarray]]],
                 codes, mask, G: int,
                 interpret: Optional[bool] = None):
    """Masked, null-aware per-group sums of int64 columns.

    cols: list of (values int64 (N,), nulls bool (N,) or None).
    codes: per-row group code in [0, G); mask: live-row mask.
    Returns (sums int64 (C, G) - mod-2^64 like the int64 accumulators,
    counts int64 (C, G) non-null live counts, gcount int64 (G,) live counts).
    Traceable (use inside jit); G static.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    N = mask.shape[0]
    npad = -(-N // TILE_ROWS) * TILE_ROWS - N

    maskf = mask.astype(jnp.float32)
    codes32 = codes.astype(jnp.int32)
    los, his, valids = [], [], []
    for values, nulls in cols:
        v = values.astype(jnp.int64)
        u = v.astype(jnp.uint64)
        los.append((u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
                   .astype(jnp.int32))
        his.append((u >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32))
        val = maskf if nulls is None else maskf * (~nulls).astype(jnp.float32)
        valids.append(val)
    lo = jnp.stack(los, axis=0)
    hi = jnp.stack(his, axis=0)
    valid = jnp.stack(valids, axis=0)
    if npad:
        lo = jnp.pad(lo, ((0, 0), (0, npad)))
        hi = jnp.pad(hi, ((0, 0), (0, npad)))
        valid = jnp.pad(valid, ((0, 0), (0, npad)))
        codes32 = jnp.pad(codes32, (0, npad))
        maskf = jnp.pad(maskf, (0, npad))
    sums, counts, gcount = _grouped_sums_padded(
        lo, hi, valid, codes32[None, :], maskf[None, :], G, interpret)
    return sums.astype(jnp.int64), counts, gcount
