"""PlanChecker: pluggable sanity & type validation over PlanNode trees and
SubPlan/PlanFragment graphs.

The reference runs a fixed list of checkers at three pipeline stages
(sql/planner/sanity/PlanChecker.java: intermediatePlanSanityChecker after
planning and optimization, finalPlanSanityChecker / fragment checks after
fragmentation).  Each check here walks the plan and emits typed
``PlanDiagnostic``s (check code, node path, severity); the wiring in
sql/planner.py, sql/rules.py, and sql/fragmenter.py raises ERROR
diagnostics as fail-fast ``PlanValidationError`` (common/errors.py,
``PLAN_VALIDATION``: non-retryable — retrying a malformed plan cannot
help).

Check codes
-----------
- ``DANGLING_VARIABLE``   (ValidateDependenciesChecker): a node references
  a variable none of its sources produce, or a node's declared outputs are
  not grounded in its sources.
- ``DUPLICATE_NODE_ID``   (NoDuplicatePlanNodeIdsChecker): two structurally
  DIFFERENT nodes share a plan-node id.  Structurally identical copies
  sharing an id are this engine's decorrelation contract (sql/rules.py
  node-identity note) and are allowed.
- ``TYPE_MISMATCH``       (TypeValidator): an expression's output type does
  not match the declared variable type — project assignments, filter
  predicates (must be boolean), scan column assignments, aggregation
  call/output and intermediate (PARTIAL/FINAL) types.
- ``JOIN_KEY_TYPE``       (TypeValidator equi-clause check): join /
  semi-join key pairs with incompatible types.
- ``EXCHANGE_LAYOUT``     exchange/union column alignment: each input row
  of an ExchangeNode (and each UnionNode branch) must supply every output
  column with a matching type.
- ``PARTITIONING``        PartitioningScheme consistency: partitioning
  arguments and output layout must exist in the producing node's outputs
  with matching types.
- ``FRAGMENT_BOUNDARY``   RemoteSourceNode fragment ids must name real
  child fragments of the consuming fragment, every child fragment must
  have a consumer, and the producer's output layout must align with the
  consumer's declared columns (name AND type).
- ``GROUPED_EXECUTION``   a fragment claiming grouped lifespan sharding
  (exec/grouped.py stage_shards_lifespans) must actually be the shape the
  scheduler assumes: SOURCE-distributed with its single scan receiving
  splits.
- ``EXCHANGE_FABRIC``     a remote-exchange edge annotated with a fabric
  (parallel/fabric.py) must be a shape that fabric can carry: ICI edges
  hash-partitioned between multi-taskable stages (the scheduler pins
  tasks 1:1 to mesh devices), and no RemoteSourceNode mixing ici and
  http sources (an HTTP edge must not feed a device-resident read).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..common.types import Type
from ..spi import plan as P
from ..spi.expr import VariableReferenceExpression, free_variables

CHECK_DANGLING_VARIABLE = "DANGLING_VARIABLE"
CHECK_DUPLICATE_NODE_ID = "DUPLICATE_NODE_ID"
CHECK_TYPE_MISMATCH = "TYPE_MISMATCH"
CHECK_JOIN_KEY_TYPE = "JOIN_KEY_TYPE"
CHECK_EXCHANGE_LAYOUT = "EXCHANGE_LAYOUT"
CHECK_PARTITIONING = "PARTITIONING"
CHECK_FRAGMENT_BOUNDARY = "FRAGMENT_BOUNDARY"
CHECK_GROUPED_EXECUTION = "GROUPED_EXECUTION"
CHECK_SCAN_PUSHDOWN = "SCAN_PUSHDOWN"
CHECK_EXCHANGE_FABRIC = "EXCHANGE_FABRIC"

ALL_CHECK_CODES = (
    CHECK_DANGLING_VARIABLE, CHECK_DUPLICATE_NODE_ID, CHECK_TYPE_MISMATCH,
    CHECK_JOIN_KEY_TYPE, CHECK_EXCHANGE_LAYOUT, CHECK_PARTITIONING,
    CHECK_FRAGMENT_BOUNDARY, CHECK_GROUPED_EXECUTION, CHECK_SCAN_PUSHDOWN,
    CHECK_EXCHANGE_FABRIC,
)

ERROR = "ERROR"
WARNING = "WARNING"


@dataclass(frozen=True)
class PlanDiagnostic:
    code: str
    severity: str
    node_id: str
    path: str           # root-to-node chain of node kinds, "/"-separated
    message: str
    stage: str = ""     # post-plan | post-optimize | post-fragment | rule:<n>

    def __str__(self):
        stage = f" [{self.stage}]" if self.stage else ""
        return (f"{self.severity} {self.code}{stage} at {self.path} "
                f"(id={self.node_id}): {self.message}")


# ---------------------------------------------------------------------------
# type compatibility
# ---------------------------------------------------------------------------

_INT_FAMILY = {"tinyint", "smallint", "integer", "bigint"}
_FLOAT_FAMILY = {"real", "double"}
_CHARISH = {"varchar", "char"}


def _base(sig: str) -> str:
    return sig.split("(", 1)[0]


def types_compatible(a: Type, b: Type) -> bool:
    """Physical compatibility, not equality: the engine freely widens
    within the integer and float families and tolerates varchar/char and
    decimal-precision drift (blocks carry their own widths), but a
    cross-family mismatch means a rewrite dropped or retyped a column."""
    sa, sb = a.signature, b.signature
    if sa == sb:
        return True
    ba, bb = _base(sa), _base(sb)
    if ba in _INT_FAMILY and bb in _INT_FAMILY:
        return True
    if ba in _FLOAT_FAMILY and bb in _FLOAT_FAMILY:
        return True
    if ba in _CHARISH and bb in _CHARISH:
        return True
    if ba == "decimal" and bb == "decimal":
        # precision drift is layout-safe; a scale change rescales values
        from ..common.types import DecimalType
        if isinstance(a, DecimalType) and isinstance(b, DecimalType):
            return a.scale == b.scale
        return True
    if ba == "unknown" or bb == "unknown":
        return True     # NULL literal: coerces to any type
    return False


# ---------------------------------------------------------------------------
# check context
# ---------------------------------------------------------------------------

@dataclass
class _Ctx:
    stage: str = ""
    diags: List[PlanDiagnostic] = field(default_factory=list)

    def add(self, code: str, node: P.PlanNode, path: str, message: str,
            severity: str = ERROR) -> None:
        self.diags.append(PlanDiagnostic(
            code, severity, getattr(node, "id", "?"), path, message,
            self.stage))


def _kind(node: P.PlanNode) -> str:
    return type(node).__name__.replace("Node", "")


# ---------------------------------------------------------------------------
# individual checks (each pluggable into PlanChecker)
# ---------------------------------------------------------------------------

class Check:
    """One sanity pass over a plan tree."""
    code: str = "?"

    def run(self, root: P.PlanNode, ctx: _Ctx) -> None:
        raise NotImplementedError


class NoDuplicatePlanNodeIds(Check):
    """Same id on two structurally DIFFERENT nodes.  Decorrelated deep
    copies deliberately share ids (the pipeline compiler memoizes per id,
    sql/rules.py); those copies are structurally identical, so equality of
    ``structural_key`` separates the contract from the bug."""
    code = CHECK_DUPLICATE_NODE_ID

    def run(self, root, ctx):
        by_id: Dict[str, List[P.PlanNode]] = {}
        seen_objs: Set[int] = set()

        def walk(node):
            if id(node) in seen_objs:   # DAG share: one node, not a dup
                return
            seen_objs.add(id(node))
            by_id.setdefault(node.id, []).append(node)
            for s in node.sources:
                walk(s)

        walk(root)
        for nid, nodes in by_id.items():
            if len(nodes) < 2:
                continue
            # canonical_params: the serving tier's parameterizer gives each
            # literal occurrence its own global slot, so decorrelated deep
            # copies of one source subtree differ only in slot indices —
            # still the same plan for the id-sharing contract
            keys = {P.structural_key(n, canonical_params=True)
                    for n in nodes}
            if len(keys) > 1:
                kinds = ", ".join(sorted({_kind(n) for n in nodes}))
                ctx.add(self.code, nodes[0], kinds,
                        f"plan-node id {nid!r} is shared by "
                        f"{len(nodes)} structurally different nodes "
                        f"({kinds})")


class ValidateDependencies(Check):
    """Every variable a node references must be produced by its sources
    (scoped per side for joins), and every declared output must be
    grounded — the reference's ValidateDependenciesChecker."""
    code = CHECK_DANGLING_VARIABLE

    def run(self, root, ctx):
        _walk_scoped(root, ctx, self._visit)

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _produced(*nodes: P.PlanNode) -> Dict[str, Type]:
        out: Dict[str, Type] = {}
        for n in nodes:
            for v in n.output_variables:
                out[v.name] = v.type
        return out

    def _require(self, ctx, node, path, scope: Dict[str, Type],
                 vars_: Iterable[VariableReferenceExpression],
                 what: str) -> None:
        for v in vars_:
            if v.name not in scope:
                ctx.add(self.code, node, path,
                        f"{what} references {v.name!r} which no source "
                        f"produces")
            elif not types_compatible(v.type, scope[v.name]):
                ctx.add(CHECK_TYPE_MISMATCH, node, path,
                        f"{what} reads {v.name!r} as {v.type.signature} "
                        f"but the source produces "
                        f"{scope[v.name].signature}")

    def _require_exprs(self, ctx, node, path, scope, exprs, what):
        for e in exprs:
            if e is None:
                continue
            self._require(ctx, node, path, scope, free_variables(e), what)

    # -- node dispatch ----------------------------------------------------
    def _visit(self, node: P.PlanNode, path: str, ctx: _Ctx) -> None:
        t = type(node).__name__
        m = getattr(self, "_visit_" + t, None)
        if m is not None:
            m(node, path, ctx)
            return
        # default: declared outputs must come from the (single) source
        if node.sources:
            scope = self._produced(*node.sources)
            self._require(ctx, node, path, scope,
                          self._own_outputs(node), "output")

    @staticmethod
    def _own_outputs(node: P.PlanNode):
        """Outputs the node passes through (excluding ones it mints)."""
        minted = set()
        for attr in ("marker", "id_variable", "semi_join_output",
                     "group_id_variable"):
            v = getattr(node, attr, None)
            if v is not None:
                minted.add(v.name)
        return [v for v in node.output_variables if v.name not in minted]

    def _visit_TableScanNode(self, node, path, ctx):
        # match by name: Variable hashes on (name, type), so a type-drifted
        # output would miss a keyed lookup and misreport as unassigned
        by_name = {v.name: ch for v, ch in node.assignments.items()}
        for v in node.outputs:
            ch = by_name.get(v.name)
            if ch is None:
                ctx.add(self.code, node, path,
                        f"scan output {v.name!r} has no column assignment")
            elif not types_compatible(v.type, ch.type):
                ctx.add(CHECK_TYPE_MISMATCH, node, path,
                        f"scan output {v.name!r} declared "
                        f"{v.type.signature} but column {ch.name!r} is "
                        f"{ch.type.signature}")

    def _visit_FilterNode(self, node, path, ctx):
        scope = self._produced(node.source)
        self._require_exprs(ctx, node, path, scope, [node.predicate],
                            "predicate")
        if _base(node.predicate.type.signature) not in ("boolean", "unknown"):
            ctx.add(CHECK_TYPE_MISMATCH, node, path,
                    f"filter predicate has type "
                    f"{node.predicate.type.signature}, expected boolean")

    def _visit_ProjectNode(self, node, path, ctx):
        scope = self._produced(node.source)
        for v, e in node.assignments.items():
            self._require_exprs(ctx, node, path, scope, [e],
                                f"assignment {v.name!r}")
            if not types_compatible(v.type, e.type):
                ctx.add(CHECK_TYPE_MISMATCH, node, path,
                        f"projection {v.name!r} declared "
                        f"{v.type.signature} but expression produces "
                        f"{e.type.signature}")

    def _visit_AggregationNode(self, node, path, ctx):
        scope = self._produced(node.source)
        self._require(ctx, node, path, scope, node.grouping_keys,
                      "grouping key")
        for v, agg in node.aggregations.items():
            self._require_exprs(ctx, node, path, scope,
                                list(agg.call.arguments), f"aggregate "
                                f"{v.name!r}")
            if agg.mask is not None:
                self._require(ctx, node, path, scope, [agg.mask],
                              f"aggregate mask of {v.name!r}")
            if not types_compatible(v.type, agg.call.type):
                ctx.add(CHECK_TYPE_MISMATCH, node, path,
                        f"aggregate {v.name!r} declared "
                        f"{v.type.signature} but call "
                        f"{agg.call.display_name} returns "
                        f"{agg.call.type.signature}")
            self._check_agg_call(node, path, ctx, v, agg)

    def _check_agg_call(self, node, path, ctx, v, agg):
        """Intermediate/final type rules for the decomposable aggregates
        the fragmenter splits (sum/count/min/max; avg is rewritten away at
        the split).  count is always bigint; min/max preserve their input
        type; sum widens within its family (int->bigint, real->double,
        decimal(p,s)->decimal(38,s))."""
        from ..common.types import BigintType
        name = agg.call.display_name.lower().split(".")[-1]
        args = agg.call.arguments
        if name == "count":
            if not isinstance(agg.call.type, BigintType):
                ctx.add(CHECK_TYPE_MISMATCH, node, path,
                        f"count aggregate {v.name!r} must be bigint, got "
                        f"{agg.call.type.signature}")
        elif name in ("min", "max") and args:
            if not types_compatible(agg.call.type, args[0].type):
                ctx.add(CHECK_TYPE_MISMATCH, node, path,
                        f"{name} aggregate {v.name!r} returns "
                        f"{agg.call.type.signature} from a "
                        f"{args[0].type.signature} input")
        elif name == "sum" and args:
            rb = _base(agg.call.type.signature)
            ab = _base(args[0].type.signature)
            ok = (rb == ab
                  or (rb in _INT_FAMILY and ab in _INT_FAMILY)
                  or (rb in _FLOAT_FAMILY and ab in _FLOAT_FAMILY)
                  or (rb == "decimal" and ab == "decimal")
                  or ab == "unknown")
            if not ok:
                ctx.add(CHECK_TYPE_MISMATCH, node, path,
                        f"sum aggregate {v.name!r} returns "
                        f"{agg.call.type.signature} from a "
                        f"{args[0].type.signature} input (cross-family)")

    def _visit_JoinNode(self, node, path, ctx):
        lscope = self._produced(node.left)
        rscope = self._produced(node.right)
        both = dict(rscope)
        both.update(lscope)
        for l, r in node.criteria:
            self._require(ctx, node, path, lscope, [l],
                          "join criteria (left)")
            self._require(ctx, node, path, rscope, [r],
                          "join criteria (right)")
            if not types_compatible(l.type, r.type):
                ctx.add(CHECK_JOIN_KEY_TYPE, node, path,
                        f"equi-join key types differ: {l.name} is "
                        f"{l.type.signature}, {r.name} is "
                        f"{r.type.signature}")
        self._require_exprs(ctx, node, path, both, [node.filter],
                            "join filter")
        self._require(ctx, node, path, both, node.outputs, "join output")
        # the receiving side is the NON-PRESERVED one: probe (left) for
        # INNER, build (right) for LEFT — see plan_dynamic_filters
        recv_scope, recv_side = ((rscope, "build (right)")
                                 if node.join_type == P.LEFT
                                 else (lscope, "probe (left)"))
        for recv_name in node.dynamic_filters:
            if recv_name not in recv_scope:
                ctx.add(self.code, node, path,
                        f"dynamic filter receiving column {recv_name!r} is "
                        f"not produced by the {recv_side} side")

    def _visit_SemiJoinNode(self, node, path, ctx):
        sscope = self._produced(node.source)
        fscope = self._produced(node.filtering_source)
        self._require(ctx, node, path, sscope,
                      [node.source_join_variable], "semi-join source key")
        self._require(ctx, node, path, fscope,
                      [node.filtering_source_join_variable],
                      "semi-join filtering key")
        if not types_compatible(node.source_join_variable.type,
                                node.filtering_source_join_variable.type):
            ctx.add(CHECK_JOIN_KEY_TYPE, node, path,
                    f"semi-join key types differ: "
                    f"{node.source_join_variable.name} is "
                    f"{node.source_join_variable.type.signature}, "
                    f"{node.filtering_source_join_variable.name} is "
                    f"{node.filtering_source_join_variable.type.signature}")
        if _base(node.semi_join_output.type.signature) != "boolean":
            ctx.add(CHECK_TYPE_MISMATCH, node, path,
                    f"semi-join output {node.semi_join_output.name!r} "
                    f"must be boolean, got "
                    f"{node.semi_join_output.type.signature}")

    def _visit_SortNode(self, node, path, ctx):
        self._require(ctx, node, path, self._produced(node.source),
                      [v for v, _o in node.ordering_scheme.orderings],
                      "sort key")

    _visit_TopNNode = _visit_SortNode

    def _visit_DistinctLimitNode(self, node, path, ctx):
        self._require(ctx, node, path, self._produced(node.source),
                      node.distinct_variables, "distinct key")

    def _visit_MarkDistinctNode(self, node, path, ctx):
        self._require(ctx, node, path, self._produced(node.source),
                      node.distinct_variables, "distinct key")

    def _visit_OutputNode(self, node, path, ctx):
        scope = self._produced(node.source)
        self._require(ctx, node, path, scope, node.outputs, "output column")
        if len(node.column_names) != len(node.outputs):
            ctx.add(self.code, node, path,
                    f"output has {len(node.column_names)} column names "
                    f"for {len(node.outputs)} variables")

    def _visit_WindowNode(self, node, path, ctx):
        scope = self._produced(node.source)
        self._require(ctx, node, path, scope, node.partition_by,
                      "window partition key")
        if node.ordering_scheme:
            self._require(ctx, node, path, scope,
                          [v for v, _o in node.ordering_scheme.orderings],
                          "window order key")
        for v, wf in node.window_functions.items():
            self._require_exprs(ctx, node, path, scope, [wf.call],
                                f"window function {v.name!r}")
            if not types_compatible(v.type, wf.call.type):
                ctx.add(CHECK_TYPE_MISMATCH, node, path,
                        f"window function {v.name!r} declared "
                        f"{v.type.signature} but call returns "
                        f"{wf.call.type.signature}")

    def _visit_GroupIdNode(self, node, path, ctx):
        scope = self._produced(node.source)
        self._require(ctx, node, path, scope,
                      list(node.grouping_columns.values()),
                      "grouping input column")
        self._require(ctx, node, path, scope, node.aggregation_arguments,
                      "aggregation argument")
        out_names = {v.name for v in node.grouping_columns}
        for s in node.grouping_sets:
            for v in s:
                if v.name not in out_names:
                    ctx.add(self.code, node, path,
                            f"grouping set references {v.name!r} which is "
                            f"not a grouping output column")

    def _visit_UnnestNode(self, node, path, ctx):
        scope = self._produced(node.source)
        self._require(ctx, node, path, scope, node.replicate_variables,
                      "replicate column")
        self._require(ctx, node, path, scope,
                      [v for v, _e in node.unnest_variables],
                      "unnest input")

    def _visit_UnionNode(self, node, path, ctx):
        for i, src in enumerate(node.inputs):
            scope = self._produced(src)
            for v in node.outputs:
                if v.name not in scope:
                    ctx.add(CHECK_EXCHANGE_LAYOUT, node, path,
                            f"union branch {i} does not produce output "
                            f"column {v.name!r}")
                elif not types_compatible(v.type, scope[v.name]):
                    ctx.add(CHECK_EXCHANGE_LAYOUT, node, path,
                            f"union branch {i} produces {v.name!r} as "
                            f"{scope[v.name].signature}, union declares "
                            f"{v.type.signature}")

    def _visit_ExchangeNode(self, node, path, ctx):
        layout = node.partitioning_scheme.output_layout
        if node.inputs and len(node.inputs) != len(node.exchange_sources):
            ctx.add(CHECK_EXCHANGE_LAYOUT, node, path,
                    f"exchange has {len(node.exchange_sources)} sources "
                    f"but {len(node.inputs)} input rows")
        for i, src in enumerate(node.exchange_sources):
            scope = self._produced(src)
            row = node.inputs[i] if i < len(node.inputs) else None
            if row is None:
                continue
            if len(row) != len(layout):
                ctx.add(CHECK_EXCHANGE_LAYOUT, node, path,
                        f"exchange input row {i} has {len(row)} columns "
                        f"for a {len(layout)}-column output layout")
                continue
            for j, (iv, ov) in enumerate(zip(row, layout)):
                if iv.name not in scope:
                    ctx.add(self.code, node, path,
                            f"exchange input {iv.name!r} (row {i}, col "
                            f"{j}) is not produced by source {i}")
                elif not types_compatible(iv.type, ov.type):
                    ctx.add(CHECK_EXCHANGE_LAYOUT, node, path,
                            f"exchange column {j}: input {iv.name!r} is "
                            f"{iv.type.signature} but layout declares "
                            f"{ov.name!r} {ov.type.signature}")
        _check_partitioning_scheme(node.partitioning_scheme, node, path, ctx)

    def _visit_ValuesNode(self, node, path, ctx):
        for r, row in enumerate(node.rows):
            if len(row) != len(node.outputs):
                ctx.add(self.code, node, path,
                        f"values row {r} has {len(row)} expressions for "
                        f"{len(node.outputs)} outputs")
                continue
            for v, e in zip(node.outputs, row):
                if not types_compatible(v.type, e.type):
                    ctx.add(CHECK_TYPE_MISMATCH, node, path,
                            f"values column {v.name!r} declared "
                            f"{v.type.signature} but row {r} supplies "
                            f"{e.type.signature}")

    def _visit_RemoteSourceNode(self, node, path, ctx):
        pass    # fragment-boundary checks own remote sources

    def _visit_TableWriterNode(self, node, path, ctx):
        pass    # writer mints its (rows, fragment) outputs

    _visit_TableFinishNode = _visit_TableWriterNode


def _check_partitioning_scheme(scheme: P.PartitioningScheme,
                               node: P.PlanNode, path: str,
                               ctx: _Ctx) -> None:
    layout = {v.name: v.type for v in scheme.output_layout}
    for a in scheme.arguments:
        if a.name not in layout:
            ctx.add(CHECK_PARTITIONING, node, path,
                    f"partitioning column {a.name!r} is not in the "
                    f"output layout")
        elif not types_compatible(a.type, layout[a.name]):
            ctx.add(CHECK_PARTITIONING, node, path,
                    f"partitioning column {a.name!r} is "
                    f"{a.type.signature} but the layout carries "
                    f"{layout[a.name].signature}")
    if scheme.handle == P.FIXED_HASH_DISTRIBUTION and not scheme.arguments:
        ctx.add(CHECK_PARTITIONING, node, path,
                "FIXED_HASH partitioning with no partitioning columns")


def _walk_scoped(root: P.PlanNode, ctx: _Ctx, visit) -> None:
    """Pre-order walk carrying the root-to-node kind path; DAG-shared
    subtrees (decorrelated copies materialized as one object) visit once."""
    seen: Set[int] = set()

    def walk(node: P.PlanNode, path: str) -> None:
        here = f"{path}/{_kind(node)}" if path else _kind(node)
        if id(node) in seen:
            return
        seen.add(id(node))
        visit(node, here, ctx)
        for s in node.sources:
            walk(s, here)

    walk(root, "")


# ---------------------------------------------------------------------------
# fragment-graph checks
# ---------------------------------------------------------------------------

class FragmentCheck:
    code: str = "?"

    def run(self, subplan: P.SubPlan, ctx: _Ctx, exec_config=None) -> None:
        raise NotImplementedError


class ValidateFragmentBoundaries(FragmentCheck):
    """Every RemoteSourceNode must name real child fragments, every child
    fragment must have a consumer (its output buffer would otherwise fill
    and stall), and the producer's output partitioning layout must align
    column-for-column with the consumer's declared outputs."""
    code = CHECK_FRAGMENT_BOUNDARY

    def run(self, subplan, ctx, exec_config=None):
        self._visit(subplan, ctx)

    def _visit(self, sp: P.SubPlan, ctx: _Ctx) -> None:
        frag = sp.fragment
        children = {c.fragment.fragment_id: c.fragment for c in sp.children}
        consumed: Set[str] = set()
        path = f"Fragment[{frag.fragment_id}]"
        for node in P.walk_plan(frag.root):
            if isinstance(node, P.ExchangeNode) and node.scope == P.REMOTE:
                ctx.add(self.code, node, f"{path}/{_kind(node)}",
                        "REMOTE exchange survived fragmentation")
            if not isinstance(node, P.RemoteSourceNode):
                continue
            for fid in node.source_fragment_ids:
                child = children.get(fid)
                if child is None:
                    ctx.add(self.code, node, f"{path}/RemoteSource",
                            f"remote source names fragment {fid!r} which "
                            f"is not a child of fragment "
                            f"{frag.fragment_id!r}")
                    continue
                consumed.add(fid)
                self._check_layout(node, child, path, ctx)
        for fid in children:
            if fid not in consumed:
                ctx.add(self.code, sp.children[0].fragment.root
                        if sp.children else frag.root, path,
                        f"child fragment {fid!r} has no consuming remote "
                        f"source in fragment {frag.fragment_id!r}")
        for c in sp.children:
            self._visit(c, ctx)

    @staticmethod
    def _check_layout(node: P.RemoteSourceNode, child: P.PlanFragment,
                      path: str, ctx: _Ctx) -> None:
        produced = child.output_partitioning_scheme.output_layout
        if len(produced) != len(node.outputs):
            ctx.add(CHECK_FRAGMENT_BOUNDARY, node, f"{path}/RemoteSource",
                    f"fragment {child.fragment_id!r} produces "
                    f"{len(produced)} columns but the consumer declares "
                    f"{len(node.outputs)}")
            return
        for j, (pv, cv) in enumerate(zip(produced, node.outputs)):
            if pv.name != cv.name:
                ctx.add(CHECK_FRAGMENT_BOUNDARY, node,
                        f"{path}/RemoteSource",
                        f"fragment boundary column {j} is {pv.name!r} on "
                        f"the producer but {cv.name!r} on the consumer "
                        f"(column-order drift)")
            elif not types_compatible(pv.type, cv.type):
                ctx.add(CHECK_FRAGMENT_BOUNDARY, node,
                        f"{path}/RemoteSource",
                        f"fragment boundary column {j} ({pv.name!r}) is "
                        f"{pv.type.signature} on the producer but "
                        f"{cv.type.signature} on the consumer")
        # the producer fragment's root must actually yield that layout
        root_out = {v.name: v.type
                    for v in child.root.output_variables}
        for pv in produced:
            if pv.name not in root_out:
                ctx.add(CHECK_FRAGMENT_BOUNDARY, node,
                        f"{path}/RemoteSource",
                        f"fragment {child.fragment_id!r} declares output "
                        f"{pv.name!r} its root does not produce")


class ValidateFragmentPartitioning(FragmentCheck):
    """A fragment's declared partitioning must match its body: scans only
    in SOURCE-distributed fragments, partitioned_sources listing exactly
    the scan node ids, and the output partitioning columns grounded in the
    root's outputs."""
    code = CHECK_PARTITIONING

    def run(self, subplan, ctx, exec_config=None):
        for sp in self._walk(subplan):
            frag = sp.fragment
            path = f"Fragment[{frag.fragment_id}]"
            scan_ids = [n.id for n in P.walk_plan(frag.root)
                        if isinstance(n, P.TableScanNode)]
            if scan_ids and frag.partitioning != P.SOURCE_DISTRIBUTION:
                ctx.add(self.code, frag.root, path,
                        f"fragment contains table scans but is "
                        f"{frag.partitioning}-partitioned")
            if sorted(scan_ids) != sorted(frag.partitioned_sources):
                ctx.add(self.code, frag.root, path,
                        f"partitioned_sources {frag.partitioned_sources} "
                        f"do not match the fragment's scan ids {scan_ids}")
            _check_partitioning_scheme(
                frag.output_partitioning_scheme, frag.root, path, ctx)
            root_out = {v.name for v in frag.root.output_variables}
            for v in frag.output_partitioning_scheme.output_layout:
                if v.name not in root_out:
                    ctx.add(self.code, frag.root, path,
                            f"output layout column {v.name!r} is not "
                            f"produced by the fragment root")

    @staticmethod
    def _walk(sp: P.SubPlan):
        yield sp
        for c in sp.children:
            yield from ValidateFragmentPartitioning._walk(c)


class ValidateGroupedExecution(FragmentCheck):
    """If the scheduler's plan-time predicate (exec/grouped.py
    stage_shards_lifespans) claims a fragment may shard lifespans, the
    fragment must be the shape that claim assumes: SOURCE-distributed with
    exactly one scan, and that scan registered to receive splits.  A
    mispredicted claim on a non-SOURCE fragment would hand disjoint
    lifespan subsets to tasks that never see splits."""
    code = CHECK_GROUPED_EXECUTION

    def run(self, subplan, ctx, exec_config=None):
        if exec_config is None:
            from ..exec.pipeline import ExecutionConfig
            exec_config = ExecutionConfig()
        from ..exec.grouped import stage_shards_lifespans
        for sp in ValidateFragmentPartitioning._walk(subplan):
            frag = sp.fragment
            try:
                claims = stage_shards_lifespans(frag.root, exec_config)
            except Exception as e:  # predicate must never throw at plan time
                ctx.add(self.code, frag.root,
                        f"Fragment[{frag.fragment_id}]",
                        f"stage_shards_lifespans raised "
                        f"{type(e).__name__}: {e}")
                continue
            if not claims:
                continue
            path = f"Fragment[{frag.fragment_id}]"
            scans = [n for n in P.walk_plan(frag.root)
                     if isinstance(n, P.TableScanNode)]
            if frag.partitioning != P.SOURCE_DISTRIBUTION:
                ctx.add(self.code, frag.root, path,
                        f"fragment claims grouped lifespan sharding but "
                        f"is {frag.partitioning}-partitioned")
            if len(scans) != 1:
                ctx.add(self.code, frag.root, path,
                        f"fragment claims grouped lifespan sharding with "
                        f"{len(scans)} scans (needs exactly 1)")
            elif scans[0].id not in frag.partitioned_sources:
                ctx.add(self.code, frag.root, path,
                        f"grouped-sharded scan {scans[0].id!r} is not in "
                        f"partitioned_sources")


class ValidateExchangeFabric(FragmentCheck):
    """A remote-exchange edge annotated with a fabric (fragmenter
    annotate_exchange_fabrics / scheduler _plan_fabrics writing
    PartitioningScheme.fabric) must be a shape the fabric can carry.
    ICI rides a hash all_to_all between stages whose tasks the
    scheduler pins 1:1 to mesh devices, so an ici edge must be
    FIXED_HASH-partitioned and both endpoint fragments multi-taskable
    (SOURCE or FIXED_HASH distribution); and a RemoteSourceNode's
    source set must not mix ici with http — the device reader consumes
    all-device or nothing, so an http edge feeding it would drop rows.
    Un-annotated edges (fabric None) are out of scope: annotation is
    optional and runtime resolution re-derives it."""
    code = CHECK_EXCHANGE_FABRIC

    _MULTI_TASK = (P.SOURCE_DISTRIBUTION, P.FIXED_HASH_DISTRIBUTION)

    def run(self, subplan, ctx, exec_config=None):
        from ..parallel.fabric import FABRIC_ICI, FABRICS
        for sp in ValidateFragmentPartitioning._walk(subplan):
            frag = sp.fragment
            path = f"Fragment[{frag.fragment_id}]"
            children = {c.fragment.fragment_id: c.fragment
                        for c in sp.children}
            for node in P.walk_plan(frag.root):
                if not isinstance(node, P.RemoteSourceNode):
                    continue
                fabrics = set()
                for fid in node.source_fragment_ids:
                    child = children.get(fid)
                    if child is None:
                        continue    # FRAGMENT_BOUNDARY owns that diag
                    scheme = child.output_partitioning_scheme
                    fabric = getattr(scheme, "fabric", None)
                    if fabric is None:
                        continue
                    fabrics.add(fabric)
                    if fabric not in FABRICS or fabric == "auto":
                        ctx.add(self.code, node, f"{path}/RemoteSource",
                                f"fragment {fid!r} output annotated with "
                                f"unknown fabric {fabric!r}")
                        continue
                    if fabric != FABRIC_ICI:
                        continue
                    if scheme.handle != P.FIXED_HASH_DISTRIBUTION:
                        ctx.add(self.code, node, f"{path}/RemoteSource",
                                f"ici fabric on a {scheme.handle} edge "
                                f"from fragment {fid!r} (the all_to_all "
                                f"carries only hash partitioning)")
                    if child.partitioning not in self._MULTI_TASK:
                        ctx.add(self.code, node, f"{path}/RemoteSource",
                                f"ici fabric from a {child.partitioning}"
                                f"-partitioned producer fragment {fid!r} "
                                f"(tasks cannot pin 1:1 to mesh devices)")
                    if frag.partitioning not in self._MULTI_TASK:
                        ctx.add(self.code, node, f"{path}/RemoteSource",
                                f"ici fabric into a {frag.partitioning}"
                                f"-partitioned consumer fragment "
                                f"{frag.fragment_id!r} (tasks cannot pin "
                                f"1:1 to mesh devices)")
                known = fabrics - {None}
                if len(known) > 1:
                    ctx.add(self.code, node, f"{path}/RemoteSource",
                            f"remote source mixes fabrics {sorted(known)}"
                            f": an http edge must not feed the "
                            f"device-resident (ici) read path")


class ValidateScanPushdown(Check):
    """A scan claiming pushed-down predicates must be able to prove the
    claim: every entry must be range/equality-shaped over a column the
    scan actually assigns with a plain-numeric literal, and — because the
    storage layer skips whole chunks on the strength of these entries
    while relying on the residual filter for exactness — the entry must
    re-derive from a conjunct of the scan's DIRECT parent FilterNode.  A
    claim with no parent filter (or not re-derivable from it) means some
    rewrite moved/edited the filter after plan_scan_pushdown ran, and
    chunk skipping would silently drop rows."""
    code = CHECK_SCAN_PUSHDOWN

    def run(self, root, ctx):
        seen: Set[int] = set()

        def walk(node, path, parent):
            if id(node) in seen:
                return
            seen.add(id(node))
            here = f"{path}/{_kind(node)}" if path else _kind(node)
            if isinstance(node, P.TableScanNode) \
                    and getattr(node, "pushdown", None):
                self._check_scan(node, here, parent, ctx)
            for s in node.sources:
                walk(s, here, node)

        walk(root, "", None)

    # bound -> the op plan_runtime_filter_pushdown pairs it with
    _DYN_OPS = {"min": "gte", "max": "lte", "set": "eq"}

    def _dyn_entry_ok(self, e, scan) -> bool:
        """A runtime-filter marker entry re-derives from the scan's own
        dynamic-filter annotation instead of a parent FilterNode: the
        join that produced the filter id supplies the residual exactness,
        so the entry only needs a matching (id, column, bound-op) triple
        among scan.runtime_filters."""
        from ..storage.pushdown import is_dyn_marker
        val = e.get("value")
        if not is_dyn_marker(val):
            return False
        _tag, fid, bound = val
        if e.get("op") != self._DYN_OPS.get(bound):
            return False
        return any(rf.get("id") == fid
                   and rf.get("column") == e.get("column")
                   for rf in getattr(scan, "runtime_filters", None) or [])

    def _check_scan(self, scan, path, parent, ctx):
        from ..storage.pushdown import (PUSHDOWN_OPS, extract_pushdown,
                                        is_dyn_marker)
        assigned = {c.name for c in scan.assignments.values()}
        static = []
        for e in scan.pushdown:
            col = e.get("column") if isinstance(e, dict) else None
            op = e.get("op") if isinstance(e, dict) else None
            val = e.get("value") if isinstance(e, dict) else None
            if col not in assigned:
                ctx.add(self.code, scan, path,
                        f"pushed-down predicate names column {col!r} "
                        f"which the scan does not assign")
                continue
            if op not in PUSHDOWN_OPS:
                ctx.add(self.code, scan, path,
                        f"pushed-down predicate on {col!r} has op {op!r} "
                        f"(not range/equality-shaped: {PUSHDOWN_OPS})")
                continue
            if isinstance(e, dict) and is_dyn_marker(val):
                if not self._dyn_entry_ok(e, scan):
                    ctx.add(self.code, scan, path,
                            f"runtime-filter marker {e!r} does not "
                            f"re-derive from the scan's dynamic-filter "
                            f"annotation (runtime_filters)")
                continue        # dyn marker, resolved at prune time
            static.append(e)
            if isinstance(val, (list, tuple)) and len(val) == 2 \
                    and val[0] == "param" and isinstance(val[1], int) \
                    and not isinstance(val[1], bool) and val[1] >= 0:
                continue        # bound-parameter marker, resolved at prune
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                ctx.add(self.code, scan, path,
                        f"pushed-down predicate on {col!r} has "
                        f"non-numeric literal {val!r}")
        if not static:
            return              # only runtime-filter markers: no residual
        if not isinstance(parent, P.FilterNode):
            ctx.add(self.code, scan, path,
                    f"scan claims {len(static)} pushed-down "
                    f"predicate(s) but its parent is "
                    f"{_kind(parent) if parent is not None else 'the root'}"
                    f", not a Filter — the residual filter that makes "
                    f"chunk skipping safe is missing")
            return
        var_to_col = {v.name: c.name for v, c in scan.assignments.items()}
        derivable = extract_pushdown(parent.predicate, var_to_col)
        for e in static:
            if isinstance(e, dict) and e not in derivable:
                ctx.add(self.code, scan, path,
                        f"pushed-down predicate {e!r} does not appear "
                        f"among the parent filter's range/equality "
                        f"conjuncts")


# ---------------------------------------------------------------------------
# the pluggable checker
# ---------------------------------------------------------------------------

DEFAULT_CHECKS: Tuple[Check, ...] = (
    NoDuplicatePlanNodeIds(),
    ValidateDependencies(),
    ValidateScanPushdown(),
)

DEFAULT_FRAGMENT_CHECKS: Tuple[FragmentCheck, ...] = (
    ValidateFragmentBoundaries(),
    ValidateFragmentPartitioning(),
    ValidateGroupedExecution(),
    ValidateExchangeFabric(),
)


class PlanChecker:
    """Runs a pluggable list of checks over a plan tree (post-plan /
    post-optimize) or a fragment graph (post-fragment: per-fragment tree
    checks plus boundary checks)."""

    def __init__(self, checks: Optional[Iterable[Check]] = None,
                 fragment_checks: Optional[
                     Iterable[FragmentCheck]] = None):
        self.checks = tuple(checks) if checks is not None \
            else DEFAULT_CHECKS
        self.fragment_checks = tuple(fragment_checks) \
            if fragment_checks is not None else DEFAULT_FRAGMENT_CHECKS

    def check_plan(self, root: P.PlanNode,
                   stage: str = "") -> List[PlanDiagnostic]:
        ctx = _Ctx(stage)
        for check in self.checks:
            check.run(root, ctx)
        return ctx.diags

    def check_subplan(self, subplan: P.SubPlan, stage: str = "",
                      exec_config=None) -> List[PlanDiagnostic]:
        ctx = _Ctx(stage)
        for sp in ValidateFragmentPartitioning._walk(subplan):
            inner = _Ctx(stage)
            for check in self.checks:
                check.run(sp.fragment.root, inner)
            fid = sp.fragment.fragment_id
            ctx.diags.extend(PlanDiagnostic(
                d.code, d.severity, d.node_id,
                f"Fragment[{fid}]/{d.path}", d.message, d.stage)
                for d in inner.diags)
        for check in self.fragment_checks:
            check.run(subplan, ctx, exec_config=exec_config)
        return ctx.diags


_DEFAULT = PlanChecker()


def check_plan(root: P.PlanNode, stage: str = "") -> List[PlanDiagnostic]:
    return _DEFAULT.check_plan(root, stage)


def check_subplan(subplan: P.SubPlan, stage: str = "",
                  exec_config=None) -> List[PlanDiagnostic]:
    return _DEFAULT.check_subplan(subplan, stage, exec_config=exec_config)


def _raise_if_errors(diags: List[PlanDiagnostic], stage: str) -> None:
    errors = [d for d in diags if d.severity == ERROR]
    if not errors:
        return
    from ..common.errors import PlanValidationError
    head = "; ".join(str(d) for d in errors[:5])
    more = f" (+{len(errors) - 5} more)" if len(errors) > 5 else ""
    raise PlanValidationError(
        f"plan validation failed at {stage}: {head}{more}",
        diagnostics=errors)


def validate_plan(root: P.PlanNode, stage: str) -> None:
    """Check and raise PlanValidationError on ERROR diagnostics; honors
    the thread-local validation mode (off -> no-op)."""
    from . import VALIDATION_OFF, validation_mode
    if validation_mode() == VALIDATION_OFF:
        return
    _raise_if_errors(check_plan(root, stage), stage)


def validate_subplan(subplan: P.SubPlan, stage: str = "post-fragment",
                     exec_config=None) -> None:
    from . import VALIDATION_OFF, validation_mode
    if validation_mode() == VALIDATION_OFF:
        return
    _raise_if_errors(check_subplan(subplan, stage,
                                   exec_config=exec_config), stage)
