"""AST thread-safety pass: guarded-by checking, blocking-under-lock,
non-blocking callbacks, and the static lock-order graph.

The worker's threaded subsystems (exchange pullers, spill staging,
telemetry flush, heartbeat detector, task reaper, spool flush
callbacks) coordinate through per-class locks whose discipline so far
lived only in comments and reviewer memory.  This pass — the static
half of `common/locks.py`'s runtime validation — walks Python source
with `ast` at CLASS granularity and flags four hazard shapes:

  LOCK001  a mutable attribute of a lock-owning class written outside
           the lock that guards it.  Guarding is DECLARED with a
           `# lint: guarded-by(<lockattr>)` annotation: on the line
           initialising `self.attr` it guards that one attribute; on
           the line declaring the lock itself it guards the whole
           class (every `self.*` write must then sit in an allowed
           context).  For the unannotated single-lock common case the
           guard is INFERRED: an attribute written in >= 2 methods,
           at least once under `with self.<lock>` and at least once
           outside, is assumed guarded and the outside writes flagged.
           Allowed contexts: `__init__`/`__new__`, lexically inside
           `with self.<lock>`, a method whose name ends `_locked`
           (runs under the caller's lock by convention), a method that
           manually acquires the lock (`self.<lock>.acquire(...)` —
           the try/finally and timed-decline shapes), or the
           `# lint: allow-unguarded` pragma on the write.
  LOCK002  a blocking call made while a lock is held (lexically inside
           `with self.<lock>`): urllib requests, an untimed zero-arg
           `.get()` / `.join()` / `.wait()` (queue pulls, thread
           joins, event waits), or a device sync (`jax.device_get`,
           `.item()`, `.block_until_ready()`).  Holding a mutex across
           an unbounded wait turns one stalled peer into a stalled
           subsystem.  `cond.wait()` ON the held condition itself is
           the sanctioned condition-variable shape and is exempt.
           Escape: `# lint: allow-blocking-under-lock`.
  LOCK003  a lock acquisition inside a callback registered as
           non-blocking.  The PR 15 arbitrator runs revoke callbacks
           while other operators wait on memory; a callback that
           blocks on a contended lock stalls arbitration for everyone
           — the implemented discipline (TaskSpool._revoke,
           PageBuffer._revoke) is a TIMED acquire that declines the
           pass.  Callback methods are found by registration
           (`self.<meth>` passed to a `register_revocable(...)` call)
           or marked explicitly with `# lint: non-blocking-callback`
           on the def line.  Inside one, a `with self.<lock>:` or an
           unbounded `self.<lock>.acquire()` is flagged; an acquire
           bounded by `timeout=` / `blocking=False` complies.
           Escape: `# lint: allow-lock-in-callback`.
  LOCK004  a cycle or rank inversion in the statically-extracted
           lock-order graph.  Lexically nested `with self.<lock>`
           blocks (and manual acquires under a held `with`) contribute
           directed edges outer->inner; locks declared as
           `OrderedLock`/`OrderedCondition` resolve to their declared
           (name, rank).  An edge from rank r to rank <= r, a
           non-reentrant self-edge, or any directed cycle across
           classes is flagged — the same inversions
           `debug.lock-validation=on` raises at runtime, caught in CI
           without needing the interleaving to happen.
           Escape: `# lint: allow-lock-order` on the inner acquisition.

Like `analysis/lint.py` the pass is a tripwire tuned to zero false
positives on the shipped tree, not a race detector: it sees lexical
structure, so a lock taken behind a method call is invisible to
LOCK004 (the runtime half covers those), and guarded-by inference
deliberately requires evidence (one guarded write) before it trusts
itself.

Run as a module (exits nonzero when any finding survives the pragmas):

    python -m presto_tpu.analysis.concurrency presto_tpu
"""
from __future__ import annotations

import ast
import io
import re
import sys
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .lint import LintFinding, _dotted

PRAGMA_UNGUARDED = "lint: allow-unguarded"
PRAGMA_BLOCKING = "lint: allow-blocking-under-lock"
PRAGMA_CALLBACK_MARK = "lint: non-blocking-callback"
PRAGMA_CALLBACK_ALLOW = "lint: allow-lock-in-callback"
PRAGMA_LOCK_ORDER = "lint: allow-lock-order"
_GUARDED_BY = re.compile(r"lint:\s*guarded-by\(\s*([A-Za-z_]\w*)\s*\)")

LOCK_UNGUARDED = "LOCK001"
LOCK_BLOCKING_HELD = "LOCK002"
LOCK_IN_CALLBACK = "LOCK003"
LOCK_ORDER = "LOCK004"

ALL_CONCURRENCY_CODES = (LOCK_UNGUARDED, LOCK_BLOCKING_HELD,
                         LOCK_IN_CALLBACK, LOCK_ORDER)

# constructors whose result is a mutex / condition (raw or ordered)
_LOCK_CTORS = {"Lock", "RLock", "Condition",
               "OrderedLock", "OrderedCondition"}
_REENTRANT_CTORS = {"RLock", "Condition", "OrderedCondition"}
# callback registration entry points whose function arguments must not
# block (the PR 15 arbitrator contract)
_NONBLOCKING_REGISTRARS = ("register_revocable",)
# blocking network entry points (same family as lint's SYNC005/NET001)
_BLOCKING_NET_CALLS = {"urllib.request.urlopen", "urllib.request.urlretrieve",
                       "request.urlopen", "urlopen"}
# zero-arg method calls that park the calling thread until someone else
# acts: queue pulls, thread joins, event/condition waits
_BLOCKING_METHODS = ("get", "join", "wait")
# device syncs (lint flags them on the query path; HERE the hazard is
# holding a mutex across the device round trip)
_DEVICE_SYNC_METHODS = ("item", "block_until_ready")
_DEVICE_SYNC_CALLS = {"jax.device_get"}
_LIFECYCLE_METHODS = ("__init__", "__new__", "__post_init__")


def _pragma_lines(source: str) -> Tuple[Dict[str, Set[int]],
                                        Dict[int, str]]:
    """(per-pragma line sets, guarded-by line -> lock attr).  Pragmas
    are NOT interchangeable across codes — each check consults only its
    own set, so an allow-unguarded can't silence a lock-order edge."""
    allowed: Dict[str, Set[int]] = {
        PRAGMA_UNGUARDED: set(), PRAGMA_BLOCKING: set(),
        PRAGMA_CALLBACK_MARK: set(), PRAGMA_CALLBACK_ALLOW: set(),
        PRAGMA_LOCK_ORDER: set()}
    guarded_by: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            for pragma, lines in allowed.items():
                if pragma in tok.string:
                    lines.add(tok.start[0])
            m = _GUARDED_BY.search(tok.string)
            if m:
                guarded_by[tok.start[0]] = m.group(1)
    except tokenize.TokenizeError:
        pass
    return allowed, guarded_by


def _stmt_lines(node: ast.AST) -> range:
    first = getattr(node, "lineno", 0)
    last = getattr(node, "end_lineno", first) or first
    return range(first, last + 1)


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a plain `self.x` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _LockDecl:
    """One lock attribute of one class: its constructor kind and, when
    declared as OrderedLock/OrderedCondition, its (name, rank)."""

    __slots__ = ("cls", "attr", "name", "rank", "reentrant")

    def __init__(self, cls: str, attr: str, name: Optional[str],
                 rank: Optional[int], reentrant: bool):
        self.cls = cls
        self.attr = attr
        self.name = name
        self.rank = rank
        self.reentrant = reentrant

    def node_id(self) -> str:
        """Graph node identity: the declared lock NAME when ranked (so
        the same logical lock matches across classes), else the
        class-qualified attribute (so anonymous `self._lock`s in
        different classes never alias)."""
        return self.name if self.name else f"{self.cls}.{self.attr}"


def _parse_lock_ctor(cls: str, attr: str,
                     value: ast.AST) -> Optional[_LockDecl]:
    """A `self.attr = <lock ctor>(...)` (or dataclass
    `attr: T = field(default_factory=<ctor>)`) -> _LockDecl, else None."""
    if not isinstance(value, ast.Call):
        return None
    ctor = _dotted(value.func).rsplit(".", 1)[-1]
    if ctor == "field":
        for kw in value.keywords:
            if kw.arg == "default_factory":
                inner = _dotted(kw.value).rsplit(".", 1)[-1]
                if inner in _LOCK_CTORS:
                    return _LockDecl(cls, attr, None, None,
                                     inner in _REENTRANT_CTORS)
        return None
    if ctor not in _LOCK_CTORS:
        return None
    name = rank = None
    reentrant = ctor in _REENTRANT_CTORS
    if ctor in ("OrderedLock", "OrderedCondition"):
        args = list(value.args)
        if args and isinstance(args[0], ast.Constant) \
                and isinstance(args[0].value, str):
            name = args[0].value
        if len(args) > 1 and isinstance(args[1], ast.Constant) \
                and isinstance(args[1].value, int):
            rank = args[1].value
        for kw in value.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                name = kw.value.value
            elif kw.arg == "rank" and isinstance(kw.value, ast.Constant):
                rank = kw.value.value
            elif kw.arg == "reentrant" \
                    and isinstance(kw.value, ast.Constant):
                reentrant = bool(kw.value.value) \
                    or ctor == "OrderedCondition"
    return _LockDecl(cls, attr, name, rank, reentrant)


class _Edge:
    """One lock-order edge outer->inner extracted from a lexically
    nested acquisition."""

    __slots__ = ("outer", "inner", "path", "line", "allowed")

    def __init__(self, outer: _LockDecl, inner: _LockDecl, path: str,
                 line: int, allowed: bool):
        self.outer = outer
        self.inner = inner
        self.path = path
        self.line = line
        self.allowed = allowed


class _Write:
    __slots__ = ("attr", "node", "held", "method")

    def __init__(self, attr: str, node: ast.AST, held: Tuple[str, ...],
                 method: str):
        self.attr = attr
        self.node = node
        self.held = held
        self.method = method


class _ClassScan:
    """Everything one pass over a ClassDef collects: lock declarations,
    guarded-by annotations, attribute writes with their held-lock
    context, blocking calls under locks, callback registrations, and
    lock-order edges."""

    def __init__(self, module: "_ModuleScan", node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.locks: Dict[str, _LockDecl] = {}
        self.guarded: Dict[str, str] = {}      # attr -> guarding lock attr
        self.class_guard: Optional[str] = None  # whole-class guard attr
        self.writes: List[_Write] = []
        self.acquires: Dict[str, Set[str]] = {}  # method -> lock attrs
        self.callback_methods: Set[str] = set()
        self.methods: Dict[str, ast.FunctionDef] = {}

    # -- pass A: declarations ------------------------------------------------
    def collect_declarations(self) -> None:
        for stmt in self.node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[stmt.name] = stmt
                if self._marked_callback(stmt):
                    self.callback_methods.add(stmt.name)
                for sub in ast.walk(stmt):
                    self._note_assignment(sub)
                    self._note_registration(sub)
            else:
                self._note_assignment(stmt)
        # a guarded-by on the lock's own declaration line guards the
        # whole class
        for attr, guard in list(self.guarded.items()):
            if attr == guard and attr in self.locks:
                self.class_guard = guard
                del self.guarded[attr]

    def _marked_callback(self, fn) -> bool:
        first = fn.body[0].lineno if fn.body else fn.lineno
        marks = self.module.allowed[PRAGMA_CALLBACK_MARK]
        return any(ln in marks for ln in range(fn.lineno, first + 1))

    def _note_assignment(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            return
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is None and isinstance(tgt, ast.Name) \
                    and stmt in self.node.body:
                attr = tgt.id     # dataclass-style class-level field
            if attr is None:
                continue
            decl = _parse_lock_ctor(self.name, attr, value)
            if decl is not None:
                self.locks[attr] = decl
            for ln in _stmt_lines(stmt):
                if ln in self.module.guarded_lines:
                    self.guarded[attr] = self.module.guarded_lines[ln]
                    break

    def _note_registration(self, node: ast.AST) -> None:
        if not isinstance(node, ast.Call):
            return
        fn = _dotted(node.func).rsplit(".", 1)[-1]
        if fn not in _NONBLOCKING_REGISTRARS:
            return
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            meth = _self_attr(arg)
            if meth is not None:
                self.callback_methods.add(meth)

    # -- pass B: method bodies ------------------------------------------------
    def scan_methods(self) -> None:
        for name, fn in self.methods.items():
            self.acquires.setdefault(name, set())
            self._walk(fn.body, name, held=[])

    def _walk(self, stmts: Sequence[ast.stmt], method: str,
              held: List[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                pushed = 0
                for item in stmt.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr in self.locks:
                        self._note_edge(held, attr, item.context_expr)
                        held.append(attr)
                        pushed += 1
                    else:
                        self._scan_expr(item.context_expr, method, held)
                self._walk(stmt.body, method, held)
                for _ in range(pushed):
                    held.pop()
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def's body runs later, not under these locks
                self._walk(stmt.body, method, held=[])
            elif isinstance(stmt, ast.ClassDef):
                continue
            else:
                for tgt, kind in self._write_targets(stmt):
                    self.writes.append(
                        _Write(tgt, stmt, tuple(held), method))
                for sub_body in self._nested_bodies(stmt):
                    self._walk(sub_body, method, held)
                self._scan_stmt_exprs(stmt, method, held)

    @staticmethod
    def _nested_bodies(stmt: ast.stmt) -> List[Sequence[ast.stmt]]:
        bodies = []
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                bodies.append(sub)
        for handler in getattr(stmt, "handlers", ()) or ():
            bodies.append(handler.body)
        return bodies

    def _write_targets(self, stmt: ast.stmt) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []

        def _target(tgt: ast.AST, kind: str) -> None:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                for elt in tgt.elts:
                    _target(elt, kind)
                return
            if isinstance(tgt, ast.Starred):
                _target(tgt.value, kind)
                return
            if isinstance(tgt, ast.Subscript):
                tgt = tgt.value
            attr = _self_attr(tgt)
            if attr is not None:
                out.append((attr, kind))

        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                _target(tgt, "assign")
        elif isinstance(stmt, ast.AugAssign):
            _target(stmt.target, "augassign")
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            _target(stmt.target, "assign")
        return out

    def _scan_stmt_exprs(self, stmt: ast.stmt, method: str,
                         held: List[str]) -> None:
        """Scan the expressions hanging off one statement (not its
        nested statement bodies, which _walk recurses into itself)."""
        for field, value in ast.iter_fields(stmt):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.expr):
                self._scan_expr(value, method, held)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        self._scan_expr(v, method, held)

    def _scan_expr(self, expr: ast.AST, method: str,
                   held: List[str]) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                self._check_call(sub, method, held)

    # -- hazards at a call site ------------------------------------------------
    def _check_call(self, node: ast.Call, method: str,
                    held: List[str]) -> None:
        name = _dotted(node.func)
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else ""
        receiver = _self_attr(node.func.value) \
            if isinstance(node.func, ast.Attribute) else None

        # manual acquire: record for LOCK001's decline-pattern exemption,
        # LOCK004 edges, and LOCK003's bounded-acquire check
        if attr == "acquire" and receiver in self.locks:
            self.acquires.setdefault(method, set()).add(receiver)
            if held:
                self._note_edge(held, receiver, node)
            if method in self.callback_methods \
                    and not self._acquire_is_bounded(node):
                self._flag(node, LOCK_IN_CALLBACK,
                           f"{self.name}.{method} is registered as a "
                           f"non-blocking callback but acquires "
                           f"self.{receiver} without a bound; use "
                           f"acquire(timeout=...) and decline the pass "
                           f"on contention, or mark "
                           f"`# {PRAGMA_CALLBACK_ALLOW}`",
                           PRAGMA_CALLBACK_ALLOW)
            return

        if not held:
            return

        # LOCK002: blocking shapes while lexically under a lock
        if name in _BLOCKING_NET_CALLS or name in _DEVICE_SYNC_CALLS:
            self._flag(node, LOCK_BLOCKING_HELD,
                       f"{name}() while holding self.{held[-1]} stalls "
                       f"every thread contending for the lock; move the "
                       f"call outside the critical section or mark "
                       f"`# {PRAGMA_BLOCKING}`", PRAGMA_BLOCKING)
        elif attr in _BLOCKING_METHODS and not node.args \
                and not node.keywords:
            if attr == "wait" and receiver in held:
                return          # cond.wait() on the held condition
            self._flag(node, LOCK_BLOCKING_HELD,
                       f".{attr}() with no timeout while holding "
                       f"self.{held[-1]} can park the thread forever "
                       f"inside the critical section; bound the wait or "
                       f"mark `# {PRAGMA_BLOCKING}`", PRAGMA_BLOCKING)
        elif attr in _DEVICE_SYNC_METHODS and not node.args:
            self._flag(node, LOCK_BLOCKING_HELD,
                       f".{attr}() is a device sync while holding "
                       f"self.{held[-1]}; sync first, then take the "
                       f"lock, or mark `# {PRAGMA_BLOCKING}`",
                       PRAGMA_BLOCKING)

    @staticmethod
    def _acquire_is_bounded(node: ast.Call) -> bool:
        if any(kw.arg in ("timeout", None) for kw in node.keywords):
            return True
        for kw in node.keywords:
            if kw.arg == "blocking" \
                    and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True     # acquire(blocking=False)
        if len(node.args) >= 2:
            return True         # acquire(blocking, timeout)
        if node.args and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value is False:
            return True         # acquire(False): non-blocking probe
        return False

    # -- lock-order edges --------------------------------------------------
    def _note_edge(self, held: List[str], inner_attr: str,
                   site: ast.AST) -> None:
        if not held:
            return
        outer = self.locks.get(held[-1])
        inner = self.locks.get(inner_attr)
        if outer is None or inner is None:
            return
        allowed = any(
            ln in self.module.allowed[PRAGMA_LOCK_ORDER]
            for ln in _stmt_lines(site))
        self.module.edges.append(_Edge(
            outer, inner, self.module.path,
            getattr(site, "lineno", 0), allowed))

    # -- LOCK003: with-blocks inside callbacks --------------------------------
    def check_callbacks(self) -> None:
        for meth in self.callback_methods:
            fn = self.methods.get(meth)
            if fn is None:
                continue
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.With):
                    continue
                for item in sub.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.locks:
                        self._flag(
                            item.context_expr, LOCK_IN_CALLBACK,
                            f"{self.name}.{meth} is registered as a "
                            f"non-blocking callback but takes "
                            f"`with self.{attr}` (unbounded); use "
                            f"acquire(timeout=...) and decline the "
                            f"pass on contention, or mark "
                            f"`# {PRAGMA_CALLBACK_ALLOW}`",
                            PRAGMA_CALLBACK_ALLOW)

    # -- LOCK001 -------------------------------------------------------------
    def check_guarded(self) -> None:
        if not self.locks:
            return
        guards: Dict[str, str] = dict(self.guarded)
        if self.class_guard is not None:
            for w in self.writes:
                if w.attr not in self.locks and w.attr not in guards:
                    guards.setdefault(w.attr, self.class_guard)
        inferred = self._inferred_guards()
        for attr, guard in inferred.items():
            guards.setdefault(attr, guard)
        declared = set(self.guarded) | (
            set(guards) if self.class_guard else set())
        for w in self.writes:
            guard = guards.get(w.attr)
            if guard is None:
                continue
            if self._write_is_allowed(w, guard):
                continue
            how = "declared" if w.attr in declared else "inferred"
            self._flag(w.node, LOCK_UNGUARDED,
                       f"{self.name}.{w.attr} is guarded by "
                       f"self.{guard} ({how}) but written in "
                       f"{w.method}() outside it; take the lock, "
                       f"rename the method `*_locked`, or mark "
                       f"`# {PRAGMA_UNGUARDED}`", PRAGMA_UNGUARDED)

    def _write_is_allowed(self, w: _Write, guard: str) -> bool:
        if w.method in _LIFECYCLE_METHODS:
            return True
        if w.method.endswith("_locked"):
            return True
        if guard in w.held:
            return True
        if guard in self.acquires.get(w.method, ()):
            return True
        allowed = self.module.allowed[PRAGMA_UNGUARDED]
        return any(ln in allowed for ln in _stmt_lines(w.node))

    def _inferred_guards(self) -> Dict[str, str]:
        """Single-lock inference: a class with exactly one lock whose
        attribute is written in >= 2 methods, at least once under the
        lock, is assumed to guard that attribute."""
        if len(self.locks) != 1 or self.class_guard:
            return {}
        guard = next(iter(self.locks))
        by_attr: Dict[str, List[_Write]] = {}
        for w in self.writes:
            if w.attr in self.locks or w.attr in self.guarded:
                continue
            if w.method in _LIFECYCLE_METHODS:
                continue
            by_attr.setdefault(w.attr, []).append(w)
        out: Dict[str, str] = {}
        for attr, ws in by_attr.items():
            methods = {w.method for w in ws}
            if len(methods) < 2:
                continue
            evidence = any(
                guard in w.held or w.method.endswith("_locked")
                or guard in self.acquires.get(w.method, ())
                for w in ws)
            if evidence:
                out[attr] = guard
        return out

    # -- reporting --------------------------------------------------------
    def _flag(self, node: ast.AST, code: str, message: str,
              pragma: str) -> None:
        allowed = self.module.allowed[pragma]
        if any(ln in allowed for ln in _stmt_lines(node)):
            return
        self.module.findings.append(LintFinding(
            self.module.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), code, message))


class _ModuleScan:
    """One parsed module: per-class scans plus the pragma line sets and
    the lock-order edges it contributes to the global graph."""

    def __init__(self, source: str, path: str):
        self.path = path
        self.findings: List[LintFinding] = []
        self.edges: List[_Edge] = []
        self.allowed, self.guarded_lines = _pragma_lines(source)
        self.classes: List[_ClassScan] = []
        self.parse_error: Optional[LintFinding] = None
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = LintFinding(
                path, e.lineno or 0, e.offset or 0, "SYNTAX",
                f"cannot parse: {e.msg}")
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self.classes.append(_ClassScan(self, node))

    def run(self) -> None:
        if self.parse_error is not None:
            self.findings.append(self.parse_error)
            return
        for cls in self.classes:
            cls.collect_declarations()
            cls.scan_methods()
            cls.check_guarded()
            cls.check_callbacks()


def _check_lock_order(modules: Sequence[_ModuleScan]) -> List[LintFinding]:
    """LOCK004 over the combined edge set: rank inversions, reentrancy
    violations, and directed cycles (Tarjan SCCs)."""
    findings: List[LintFinding] = []
    graph: Dict[str, Set[str]] = {}
    edges: List[_Edge] = []
    for mod in modules:
        for e in mod.edges:
            if e.allowed:
                continue
            edges.append(e)
            o, i = e.outer.node_id(), e.inner.node_id()
            if o != i:
                graph.setdefault(o, set()).add(i)
                graph.setdefault(i, set())

    for e in edges:
        o, i = e.outer.node_id(), e.inner.node_id()
        if o == i:
            if not e.inner.reentrant:
                findings.append(LintFinding(
                    e.path, e.line, 0, LOCK_ORDER,
                    f"'{i}' re-acquired while already held and is not "
                    f"reentrant: self-deadlock; make it reentrant or "
                    f"mark `# {PRAGMA_LOCK_ORDER}`"))
            continue
        if e.outer.rank is not None and e.inner.rank is not None \
                and e.outer.rank >= e.inner.rank:
            findings.append(LintFinding(
                e.path, e.line, 0, LOCK_ORDER,
                f"rank inversion: '{i}' (rank {e.inner.rank}) acquired "
                f"under '{o}' (rank {e.outer.rank}); ranks must be "
                f"strictly increasing — reorder the acquisitions or "
                f"re-rank (see common/locks.py), or mark "
                f"`# {PRAGMA_LOCK_ORDER}`"))

    # Tarjan strongly-connected components; every edge inside an SCC of
    # size > 1 participates in some cycle.
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    scc_of: Dict[str, int] = {}
    counter = [0]
    scc_id = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    for w in comp:
                        scc_of[w] = scc_id[0]
                    scc_id[0] += 1

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    seen: Set[Tuple[str, str]] = set()
    for e in edges:
        o, i = e.outer.node_id(), e.inner.node_id()
        if o == i or (o, i) in seen:
            continue
        if o in scc_of and scc_of.get(i) == scc_of[o]:
            seen.add((o, i))
            members = sorted(n for n, s in scc_of.items()
                             if s == scc_of[o])
            findings.append(LintFinding(
                e.path, e.line, 0, LOCK_ORDER,
                f"lock-order cycle through {{{', '.join(members)}}}: "
                f"'{o}' -> '{i}' closes a loop another thread can "
                f"traverse in the opposite order (deadlock); break the "
                f"cycle or mark `# {PRAGMA_LOCK_ORDER}`"))
    return findings


def check_source(source: str, path: str = "<string>") -> List[LintFinding]:
    """Check one module's source (lock-order graph is local to it)."""
    mod = _ModuleScan(source, path)
    mod.run()
    findings = mod.findings + _check_lock_order([mod])
    return sorted(findings, key=lambda f: (f.line, f.col, f.code))


def check_file(path: str) -> List[LintFinding]:
    text = Path(path).read_text(encoding="utf-8")
    return check_source(text, str(path))


def check_paths(paths: Iterable[str]) -> List[LintFinding]:
    """Check files and directory trees; LOCK004 runs over the COMBINED
    lock-order graph so cross-module cycles are visible."""
    modules: List[_ModuleScan] = []
    for p in paths:
        path = Path(p)
        files = sorted(path.rglob("*.py")) if path.is_dir() else [path]
        for f in files:
            mod = _ModuleScan(f.read_text(encoding="utf-8"), str(f))
            mod.run()
            modules.append(mod)
    findings = [f for m in modules for f in m.findings]
    findings.extend(_check_lock_order(modules))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code))


def check_or_raise(paths: Iterable[str]) -> None:
    """Programmatic gate: raise the same non-retryable PLAN_VALIDATION
    error the plan checker and lint use."""
    findings = check_paths(paths)
    if findings:
        from ..common.errors import PlanValidationError
        head = "; ".join(str(f) for f in findings[:5])
        more = f" (+{len(findings) - 5} more)" if len(findings) > 5 else ""
        raise PlanValidationError(
            f"concurrency check failed: {head}{more}", diagnostics=findings)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print("usage: python -m presto_tpu.analysis.concurrency "
              "<path> [path ...]", file=sys.stderr)
        return 2
    findings = check_paths(args)
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} concurrency hazard(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
