"""One-shot static-analysis gate: `python -m presto_tpu.analysis.ci`.

The CI entry point that runs every static pass this package owns over a
clean tree and the TPC-H planning corpus, then emits one JSON report:

  1. host-sync lint (analysis/lint.py) over the engine sources;
  2. the class-granular thread-safety pass (analysis/concurrency.py,
     LOCK001-LOCK004) over the same tree — including the globally
     combined lock-order graph;
  3. the PlanChecker sweep: every TPC-H suite query is planned,
     optimized, and fragmented with validation diagnostics collected at
     all three wired stages (post-plan / post-optimize / post-fragment),
     the same recipe the conformance tests run per query.

Exit 0 means the tree is clean (no lint finding, no concurrency finding,
no plan diagnostic); anything else exits 1 with the findings both
printed and embedded in the JSON report.  `--json <path>` writes the
report to a file (default: stdout only), `--max-plans N` bounds the
TPC-H sweep for quick pre-commit runs (the bound is recorded in the
report — a capped sweep is not a clean-tree claim for the skipped
queries).
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time
from typing import Dict, List

_ENGINE_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _count_py_files(paths: List[str]) -> int:
    n = 0
    for p in paths:
        path = pathlib.Path(p)
        n += sum(1 for _ in path.rglob("*.py")) if path.is_dir() else 1
    return n


def _finding_dicts(findings) -> List[dict]:
    return [{"path": f.path, "line": f.line, "code": f.code,
             "message": f.message} for f in findings]


def _count_codes(counts: Dict[str, int], codes) -> None:
    for code in codes:
        counts[code] = counts.get(code, 0) + 1


def run_plan_sweep(max_plans: int = 0) -> dict:
    """Plan+optimize+fragment every TPC-H suite query, collecting
    validation diagnostics at the three wired stages (the PlanChecker
    conformance recipe) instead of raising on the first."""
    import dataclasses

    from ..benchmarks.tpch_queries import ALL as TPCH_QUERIES
    from ..spi import plan as P
    from ..sql import parser as A
    from ..sql.fragmenter import plan_distributed
    from ..sql.optimizer import optimize
    from ..sql.planner import Planner
    from . import check_plan, check_subplan

    qids = sorted(TPCH_QUERIES)
    skipped = 0
    if max_plans > 0 and len(qids) > max_plans:
        skipped = len(qids) - max_plans
        qids = qids[:max_plans]
    diagnostics: List[dict] = []
    errors: List[dict] = []
    for qid in qids:
        try:
            planner = Planner("sf0.01", "tpch")
            node, names, out_vars = planner.plan_query_any(
                A.parse_sql(TPCH_QUERIES[qid]))
            out = P.OutputNode(planner.new_id("output"), node, names,
                               out_vars)
            for diag in check_plan(out, "post-plan"):
                diagnostics.append(
                    {"query": qid, **dataclasses.asdict(diag)})
            out = optimize(out)
            for diag in check_plan(out, "post-optimize"):
                diagnostics.append(
                    {"query": qid, **dataclasses.asdict(diag)})
            sub = plan_distributed(out)
            for diag in check_subplan(sub, "post-fragment"):
                diagnostics.append(
                    {"query": qid, **dataclasses.asdict(diag)})
        except Exception as e:  # noqa: BLE001 — a crash IS a CI failure
            errors.append({"query": qid,
                           "error": f"{type(e).__name__}: {e}"})
    return {"queries": len(qids), "skipped": skipped,
            "diagnostics": diagnostics, "errors": errors}


def run(paths: List[str], max_plans: int = 0) -> dict:
    from .concurrency import check_paths as concurrency_paths
    from .lint import lint_paths

    t0 = time.perf_counter()
    report: dict = {"paths": [str(p) for p in paths],
                    "files_scanned": _count_py_files(paths)}
    counts: Dict[str, int] = {}

    lint_findings = lint_paths(paths)
    _count_codes(counts, (f.code for f in lint_findings))
    report["lint"] = {"findings": _finding_dicts(lint_findings)}

    conc_findings = concurrency_paths(paths)
    _count_codes(counts, (f.code for f in conc_findings))
    report["concurrency"] = {"findings": _finding_dicts(conc_findings)}

    sweep = run_plan_sweep(max_plans)
    _count_codes(counts, (d.get("code", "PLAN_ERROR")
                          for d in sweep["diagnostics"]))
    for _ in sweep["errors"]:
        counts["PLAN_CRASH"] = counts.get("PLAN_CRASH", 0) + 1
    report["plan_sweep"] = sweep

    report["counts_by_code"] = dict(sorted(counts.items()))
    report["total_findings"] = sum(counts.values())
    report["wall_seconds"] = round(time.perf_counter() - t0, 3)
    report["clean"] = report["total_findings"] == 0
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m presto_tpu.analysis.ci",
        description="run lint + concurrency + the TPC-H PlanChecker "
                    "sweep; exit 0 only on a clean tree")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to scan (default: the presto_tpu "
                         "package)")
    ap.add_argument("--json", dest="json_path", default="",
                    help="also write the JSON report to this path")
    ap.add_argument("--max-plans", type=int, default=0,
                    help="bound the TPC-H sweep to N queries (0 = all)")
    args = ap.parse_args(argv)
    paths = args.paths or [str(_ENGINE_ROOT)]

    report = run(paths, max_plans=args.max_plans)

    for section in ("lint", "concurrency"):
        for f in report[section]["findings"]:
            print(f"{f['path']}:{f['line']}: {f['code']} {f['message']}")
    for d in report["plan_sweep"]["diagnostics"]:
        print(f"plan[{d['query']}]: {d}")
    for e in report["plan_sweep"]["errors"]:
        print(f"plan[{e['query']}] crashed: {e['error']}")

    out = json.dumps(report, indent=2, default=str)
    if args.json_path:
        pathlib.Path(args.json_path).write_text(out + "\n")
    print(out)
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
