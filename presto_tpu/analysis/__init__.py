"""Static plan analysis: sanity / type validation passes + host-sync lint.

The analog of the reference coordinator's plan sanity framework
(presto-main-base/.../sql/planner/sanity/PlanChecker.java, which runs
ValidateDependenciesChecker, NoDuplicatePlanNodeIdsChecker, TypeValidator
and friends after planning, after optimization, and after fragmentation).
A buggy optimizer rule or fragmenter rewrite surfaces here as a typed
diagnostic instead of a wrong answer only a TPC-H oracle diff can catch.

Validation is gated by the ``plan_validation`` session property /
``task.plan-validation`` config key:

- ``on`` (default): validate after planning, after the whole optimizer
  run, and after fragmentation; ERROR diagnostics raise
  ``PlanValidationError`` (non-retryable ``PLAN_VALIDATION``).
- ``strict``: additionally validate after EVERY iterative-rule firing,
  attributing the violation to the rule that introduced it.
- ``off``: no validation.

The mode is carried in a thread-local (planning has no config object in
scope); runners seed it from ``ExecutionConfig.plan_validation``.
"""
from __future__ import annotations

import contextlib
import threading

VALIDATION_ON = "on"
VALIDATION_STRICT = "strict"
VALIDATION_OFF = "off"
VALIDATION_MODES = (VALIDATION_ON, VALIDATION_STRICT, VALIDATION_OFF)

_state = threading.local()


def validation_mode() -> str:
    return getattr(_state, "mode", VALIDATION_ON)


@contextlib.contextmanager
def use_validation_mode(mode: str):
    """Scope the plan-validation mode for the current thread (the planner
    and optimizer run synchronously on the planning thread)."""
    if mode not in VALIDATION_MODES:
        raise ValueError(
            f"plan_validation must be one of {VALIDATION_MODES}, "
            f"got {mode!r}")
    prev = getattr(_state, "mode", None)
    _state.mode = mode
    try:
        yield
    finally:
        if prev is None:
            del _state.mode
        else:
            _state.mode = prev


from .checker import (  # noqa: E402
    ALL_CHECK_CODES, CHECK_DANGLING_VARIABLE, CHECK_DUPLICATE_NODE_ID,
    CHECK_EXCHANGE_LAYOUT, CHECK_FRAGMENT_BOUNDARY, CHECK_GROUPED_EXECUTION,
    CHECK_JOIN_KEY_TYPE, CHECK_PARTITIONING, CHECK_SCAN_PUSHDOWN,
    CHECK_TYPE_MISMATCH, PlanChecker, PlanDiagnostic, check_plan,
    check_subplan, validate_plan, validate_subplan)

__all__ = [
    "ALL_CHECK_CODES", "CHECK_DANGLING_VARIABLE", "CHECK_DUPLICATE_NODE_ID",
    "CHECK_EXCHANGE_LAYOUT", "CHECK_FRAGMENT_BOUNDARY",
    "CHECK_GROUPED_EXECUTION", "CHECK_JOIN_KEY_TYPE", "CHECK_PARTITIONING",
    "CHECK_SCAN_PUSHDOWN", "CHECK_TYPE_MISMATCH", "PlanChecker",
    "PlanDiagnostic",
    "VALIDATION_MODES", "VALIDATION_OFF", "VALIDATION_ON",
    "VALIDATION_STRICT", "check_plan", "check_subplan", "use_validation_mode",
    "validate_plan", "validate_subplan", "validation_mode",
]
