"""AST lint for host-device synchronisation hazards in JAX execution code.

The TPU execution paper's premise is that operator pipelines stay on
device: every implicit device->host transfer (a `.item()`, an `int()`
of a traced scalar, a Python `if` on a device boolean) inserts a
blocking round trip that serialises the pipeline exactly where the
paper's overlap comes from.  This lint walks Python source with `ast`
and flags the hazard shapes:

  SYNC001  explicit host sync: `jax.device_get(...)`, `.item()`,
           `.block_until_ready()`.  These are sometimes *required*
           (adaptive re-plans, duplicate-key probes) but each site must
           be acknowledged with the allowlist pragma so new ones can't
           creep in silently.
  SYNC002  `int()` / `float()` / `bool()` applied to a device value —
           an implicit transfer hidden inside a cast.
  SYNC003  `np.asarray()` / `np.array()` applied to a device value —
           an implicit transfer hidden inside a conversion.
  SYNC004  Python `if` / `while` branching on a device boolean — forces
           the trace to materialise the predicate on host.
  SYNC005  blocking network I/O (`urllib.request.urlopen` and friends)
           called from a pipeline compute module (`exec/`, `common/`,
           `ops/`, `connectors/`) — a synchronous HTTP round trip in
           operator code serialises the pipeline worse than any device
           sync.  Network I/O belongs in the worker layer; the exchange
           client (worker/exchange.py) is the sanctioned home and is
           allow-listed.
  SYNC006  un-metered wall-clock reads (`time.time()` /
           `time.perf_counter()` / `time.perf_counter_ns()`) in `exec/`.
           Every wall-clock sample in the execution layer must feed a
           stats surface (RuntimeStats, operator stats, driver walls) —
           ad-hoc timing that goes nowhere rots into dead measurement
           and hides where walls are ACTUALLY recorded.  Sanctioned
           metering sites carry `# lint: allow-wall-clock`.
  KERNEL001  an `interpret=True` literal (keyword or kwargs-dict store)
           anywhere outside `exec/kernels/shim.py`.  Interpret mode is
           the CPU test fallback; a stray literal in kernel or call-site
           code would make a TPU build silently run Pallas kernels in
           the Python interpreter.  There is NO pragma escape — the shim
           is the one sanctioned site.
  TELEM001 an unbounded queue (`queue.Queue()` with no / zero maxsize,
           or `queue.SimpleQueue()`) in `presto_tpu/telemetry/`.  The
           telemetry export pipeline sits BESIDE the query path: if its
           sink stalls, buffering must saturate a bound and drop (with
           the drop metered) rather than grow until the process OOMs.
           There is NO pragma escape — pass an explicit positive
           maxsize.
  NET001   a blocking `urllib` request in the worker or telemetry layer
           (`worker/`, `telemetry/`) without an explicit `timeout=`
           keyword.  The fault-tolerant control plane (task updates,
           exchange pulls, heartbeats, graceful drain) depends on every
           HTTP call having a bounded wait: one default-timeout socket
           to a dead peer wedges its calling thread forever and turns a
           single worker loss into a hung query.  Sites that bound the
           wait elsewhere carry `# lint: allow-no-timeout`.
  MEM001   an unbounded host-side STAGING collection in `exec/` or
           `worker/`: a class initializes a staging-named attribute
           (`*bucket*`, `*page*`, `*staged*`, `*collected*`,
           `*pending*`, `*chunk*`, `*spill*`) to an empty list/dict but
           nowhere references the memory-charging API (try_reserve /
           register_revocable / note_spill / batch_bytes / a memory
           context).  Host collections that grow with input size are
           exactly what made PR 2's retained buffers invisible to every
           pool; new ones must either charge a memory context or carry
           `# lint: allow-uncharged-staging` on the initializer
           acknowledging why their growth is bounded elsewhere.

"Device value" is tracked with a deliberately shallow per-scope
dataflow: names assigned from `jnp.*` / `lax.*` calls (or expressions
over such names) are device; `jax.device_get(...)` results are host.
The tracking is heuristic — the lint is a tripwire for review, not a
type system — so precision is tuned to zero false positives on the
shipped tree rather than completeness.

Legitimate sync points carry the pragma on any line of the statement:

    kmax = int(jax.device_get(_max_run(table)))  # lint: allow-host-sync

Run as a module (exits nonzero when any finding survives the pragmas):

    python -m presto_tpu.analysis.lint presto_tpu
"""
from __future__ import annotations

import ast
import io
import sys
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set

PRAGMA = "lint: allow-host-sync"
WALL_PRAGMA = "lint: allow-wall-clock"
MEM_PRAGMA = "lint: allow-uncharged-staging"
NET_PRAGMA = "lint: allow-no-timeout"

SYNC_EXPLICIT = "SYNC001"
SYNC_CAST = "SYNC002"
SYNC_ASARRAY = "SYNC003"
SYNC_BRANCH = "SYNC004"
SYNC_NETWORK = "SYNC005"
SYNC_WALLCLOCK = "SYNC006"
KERNEL_INTERPRET = "KERNEL001"
TELEM_UNBOUNDED_QUEUE = "TELEM001"
MEM_UNCHARGED_STAGING = "MEM001"
NET_NO_TIMEOUT = "NET001"

ALL_LINT_CODES = (SYNC_EXPLICIT, SYNC_CAST, SYNC_ASARRAY, SYNC_BRANCH,
                  SYNC_NETWORK, SYNC_WALLCLOCK, KERNEL_INTERPRET,
                  TELEM_UNBOUNDED_QUEUE, MEM_UNCHARGED_STAGING,
                  NET_NO_TIMEOUT)

# KERNEL001 scope: everywhere.  The shim is the ONE file that may select
# Pallas interpret mode (it gates on the backend); no pragma overrides.
_INTERPRET_ALLOWLIST = ("presto_tpu/exec/kernels/shim.py",)

# SYNC005 scope: pipeline compute packages where a blocking HTTP round
# trip would serialise operator execution.  Matching is on path markers,
# not imports: `urllib.parse` / `urllib.error` usage is metadata and
# stays legal everywhere — only the blocking CALLS below are hazards.
_NETWORK_PATH_MARKERS = ("presto_tpu/exec/", "presto_tpu/common/",
                         "presto_tpu/ops/", "presto_tpu/parallel/",
                         "presto_tpu/connectors/", "presto_tpu/storage/",
                         "presto_tpu/serving/", "presto_tpu/telemetry/")
# the worker exchange client is THE sanctioned network home; everything
# else in the marked packages must stay network-free by construction.
# telemetry/export.py is sanctioned too: its OTLP HTTP POSTs run on the
# exporter's background flush thread, never the query path.
_NETWORK_ALLOWLIST = ("presto_tpu/worker/exchange.py",
                      "presto_tpu/telemetry/export.py")
_NETWORK_CALLS = {"urllib.request.urlopen", "urllib.request.urlretrieve",
                  "request.urlopen", "urlopen", "urlopen_internal"}

# NET001 scope: the layers that talk HTTP on purpose.  Every blocking
# urllib request there must pass an explicit `timeout=` keyword — a
# default-timeout socket to a dead peer wedges its thread forever, which
# is exactly the failure mode the fault-tolerant mode exists to survive.
_NET_TIMEOUT_PATH_MARKERS = ("presto_tpu/worker/", "presto_tpu/telemetry/")

# SYNC006 scope: the execution layer proper.  Wall-clock reads there must
# feed a stats surface (RuntimeStats / operator stats / driver walls);
# sanctioned metering sites carry `# lint: allow-wall-clock`.  `_time.*`
# covers the `import time as _time` idiom used by several exec modules.
_WALL_PATH_MARKER = "presto_tpu/exec/"
_WALL_CALLS = {"time.time", "_time.time",
               "time.perf_counter", "_time.perf_counter",
               "time.perf_counter_ns", "_time.perf_counter_ns",
               "time.monotonic", "_time.monotonic"}

# MEM001 scope: the packages whose host-side collections stage QUERY
# data (rows, pages, spill chunks) and therefore grow with input size.
# Granularity is the CLASS: a class that references any charging marker
# is assumed to account for its staging somewhere (the lint is a
# tripwire, not a flow analysis); one that references none must either
# start charging or acknowledge each initializer with the pragma.
_MEM_PATH_MARKERS = ("presto_tpu/exec/", "presto_tpu/worker/")
import re as _re
_MEM_STAGING_NAME = _re.compile(
    r"bucket|page|stag|collect|pending|chunk|spill", _re.IGNORECASE)
_MEM_CHARGE_MARKERS = {"try_reserve", "reserve", "register_revocable",
                       "note_spill", "batch_bytes", "MemoryContext",
                       "MemoryPool", "memory_context"}
_MEM_EMPTY_CTORS = {"list", "dict", "deque", "defaultdict"}

# TELEM001 scope: the telemetry export package.  A backpressure stall in
# a sink must hit a bounded queue (metered drop), never unbounded growth.
_TELEM_PATH_MARKER = "presto_tpu/telemetry/"
_QUEUE_CALLS = {"queue.Queue", "Queue", "queue.LifoQueue", "LifoQueue",
                "queue.PriorityQueue", "PriorityQueue"}
_SIMPLE_QUEUE_CALLS = {"queue.SimpleQueue", "SimpleQueue"}

# Call prefixes whose results live on device.  `jax.` alone is NOT in the
# list: most of the jax namespace (jit, vmap, tree_util) returns host
# objects; the array-producing submodules are named explicitly.
_DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")
# Calls that move a value to host (their result is safe to branch on).
_HOST_CALLS = {"jax.device_get"}
# numpy conversion entry points that force a device->host copy when fed
# a device array.
_NUMPY_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray",
                     "numpy.array"}
# Attribute reads on a device array that are host metadata, not data.
_HOST_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes"}
# jnp/lax functions that return host metadata (Python bools, dtype
# objects, iinfo records), not device arrays.
_METADATA_FUNCS = {"issubdtype", "isdtype", "iinfo", "finfo", "dtype",
                   "result_type", "promote_types", "shape", "ndim", "size"}


@dataclass(frozen=True)
class LintFinding:
    path: str
    line: int
    col: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col} {self.code} {self.message}"


def _dotted(node: ast.AST) -> str:
    """`a.b.c` for a Name/Attribute chain, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _allowed_lines(source: str) -> Dict[str, Set[int]]:
    """Per-pragma sets of line numbers carrying an allowlist comment.

    The two pragmas are deliberately NOT interchangeable: a host-sync
    acknowledgement must not silence a wall-clock finding on the same
    statement (and vice versa), so each code checks only its own set."""
    allowed: Dict[str, Set[int]] = {PRAGMA: set(), WALL_PRAGMA: set(),
                                    MEM_PRAGMA: set(), NET_PRAGMA: set()}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            for pragma, lines in allowed.items():
                if pragma in tok.string:
                    lines.add(tok.start[0])
    except tokenize.TokenizeError:
        pass
    return allowed


class _Linter(ast.NodeVisitor):
    """One pass over a module; `_device` is a stack of per-scope sets of
    names currently bound to device values (function scopes copy their
    enclosing scope so closures over device arrays stay tracked)."""

    def __init__(self, path: str, allowed: Dict[str, Set[int]]):
        self.path = path
        self.allowed = allowed.get(PRAGMA, set())
        self.wall_allowed = allowed.get(WALL_PRAGMA, set())
        self.mem_allowed = allowed.get(MEM_PRAGMA, set())
        self.net_allowed = allowed.get(NET_PRAGMA, set())
        self.findings: List[LintFinding] = []
        self._device: List[Set[str]] = [set()]
        import os
        norm = path.replace(os.sep, "/")
        self._network_scoped = (
            any(m in norm for m in _NETWORK_PATH_MARKERS)
            and not any(norm.endswith(a) for a in _NETWORK_ALLOWLIST))
        self._wall_scoped = _WALL_PATH_MARKER in norm
        self._net_timeout_scoped = any(
            m in norm for m in _NET_TIMEOUT_PATH_MARKERS)
        self._telem_scoped = _TELEM_PATH_MARKER in norm
        self._mem_scoped = any(m in norm for m in _MEM_PATH_MARKERS)
        self._interpret_exempt = any(
            norm.endswith(a) for a in _INTERPRET_ALLOWLIST)

    # -- reporting --------------------------------------------------------
    def _flag(self, node: ast.AST, code: str, message: str,
              allowed: Optional[Set[int]] = None) -> None:
        allowed = self.allowed if allowed is None else allowed
        first = getattr(node, "lineno", 0)
        last = getattr(node, "end_lineno", first) or first
        if any(ln in allowed for ln in range(first, last + 1)):
            return
        self.findings.append(LintFinding(
            self.path, first, getattr(node, "col_offset", 0), code, message))

    # -- device-value dataflow --------------------------------------------
    def _scope(self) -> Set[str]:
        return self._device[-1]

    def _is_device(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self._scope()
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in _HOST_CALLS:
                return False
            if name.startswith(_DEVICE_PREFIXES):
                return name.rsplit(".", 1)[-1] not in _METADATA_FUNCS
            # method call on a device value (x.sum(), x.astype(...))
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in ("item", "tolist", "block_until_ready"):
                    return False  # those syncs are flagged where they occur
                return self._is_device(node.func.value)
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _HOST_ATTRS:
                return False
            return self._is_device(node.value)
        if isinstance(node, ast.Subscript):
            return self._is_device(node.value)
        if isinstance(node, ast.BinOp):
            return self._is_device(node.left) or self._is_device(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._is_device(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._is_device(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return (self._is_device(node.left)
                    or any(self._is_device(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return self._is_device(node.body) or self._is_device(node.orelse)
        return False

    def _bind(self, target: ast.AST, device: bool) -> None:
        if isinstance(target, ast.Name):
            (self._scope().add if device
             else self._scope().discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, device)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, device)

    # -- scopes ------------------------------------------------------------
    def _visit_function(self, node) -> None:
        self._device.append(set(self._scope()))
        for arg_default in node.args.defaults + node.args.kw_defaults:
            if arg_default is not None:
                self.visit(arg_default)
        for stmt in node.body:
            self.visit(stmt)
        self._device.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    # -- bindings ----------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        if not self._interpret_exempt:
            # the kwargs-dict store form of the same hazard:
            # kwargs["interpret"] = True
            for tgt in node.targets:
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Constant)
                        and tgt.slice.value == "interpret"
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is True):
                    self._flag(node, KERNEL_INTERPRET,
                               "interpret=True outside exec/kernels/shim.py "
                               "would make TPU builds run Pallas kernels in "
                               "the Python interpreter; route the call "
                               "through the shim (no pragma escape)",
                               allowed=set())
        self.visit(node.value)
        if (isinstance(node.value, ast.Tuple)
                and len(node.targets) == 1
                and isinstance(node.targets[0], (ast.Tuple, ast.List))
                and len(node.targets[0].elts) == len(node.value.elts)):
            for tgt, val in zip(node.targets[0].elts, node.value.elts):
                self._bind(tgt, self._is_device(val))
        else:
            device = self._is_device(node.value)
            for tgt in node.targets:
                self._bind(tgt, device)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
            self._bind(node.target, self._is_device(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if self._is_device(node.value):
            self._bind(node.target, True)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        # iterating a device array yields device rows
        self._bind(node.target, self._is_device(node.iter))
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self.visit(node.iter)
        self._bind(node.target, self._is_device(node.iter))
        for cond in node.ifs:
            self.visit(cond)

    # -- memory accounting (MEM001) ----------------------------------------
    def _mem_is_empty_collection(self, value: ast.AST) -> bool:
        if isinstance(value, ast.List) and not value.elts:
            return True
        if isinstance(value, ast.Dict) and not value.keys:
            return True
        if isinstance(value, ast.Call):
            name = _dotted(value.func).rsplit(".", 1)[-1]
            if name not in _MEM_EMPTY_CTORS:
                return False
            if name == "deque":
                # deque(maxlen=N) is bounded: not a staging hazard
                return not any(kw.arg == "maxlen" for kw in value.keywords)
            if name == "defaultdict":
                return True  # defaultdict(list) grows per key: unbounded
            return not value.args  # list(xs)/dict(xs) copy, not staging
        return False

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if self._mem_scoped:
            mentioned: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute):
                    mentioned.add(sub.attr)
                elif isinstance(sub, ast.Name):
                    mentioned.add(sub.id)
            if not mentioned & _MEM_CHARGE_MARKERS:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        targets, value = sub.targets, sub.value
                    elif (isinstance(sub, ast.AnnAssign)
                          and sub.value is not None):
                        targets, value = [sub.target], sub.value
                    else:
                        continue
                    for tgt in targets:
                        if not (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            continue
                        if not _MEM_STAGING_NAME.search(tgt.attr):
                            continue
                        if self._mem_is_empty_collection(value):
                            self._flag(
                                sub, MEM_UNCHARGED_STAGING,
                                f"class {node.name} stages rows in "
                                f"self.{tgt.attr} but never charges a "
                                "memory context (no try_reserve/"
                                "register_revocable/MemoryContext "
                                "reference); account the bytes or mark "
                                f"`# {MEM_PRAGMA}`",
                                allowed=self.mem_allowed)
        self.generic_visit(node)

    # -- hazards -----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        if name in _HOST_CALLS:
            self._flag(node, SYNC_EXPLICIT,
                       f"{name}() is an explicit device->host transfer; "
                       f"acknowledge with `# {PRAGMA}` if intended")
        elif isinstance(node.func, ast.Attribute) and not node.args:
            if node.func.attr == "item":
                self._flag(node, SYNC_EXPLICIT,
                           ".item() blocks on a device->host copy; "
                           f"acknowledge with `# {PRAGMA}` if intended")
            elif node.func.attr == "block_until_ready":
                self._flag(node, SYNC_EXPLICIT,
                           ".block_until_ready() stalls the host; "
                           f"acknowledge with `# {PRAGMA}` if intended")
        if (name in ("int", "float", "bool") and len(node.args) == 1
                and not node.keywords and self._is_device(node.args[0])):
            self._flag(node, SYNC_CAST,
                       f"{name}() on a device value forces a blocking "
                       f"transfer; device_get first (with the pragma) or "
                       f"keep the value on device")
        if (name in _NUMPY_CONVERTERS and node.args
                and self._is_device(node.args[0])):
            self._flag(node, SYNC_ASARRAY,
                       f"{name}() on a device array copies to host; use "
                       f"jnp.asarray to stay on device or device_get "
                       f"explicitly")
        if self._network_scoped and name in _NETWORK_CALLS:
            self._flag(node, SYNC_NETWORK,
                       f"{name}() is blocking network I/O in a pipeline "
                       f"compute module; route it through the worker "
                       f"exchange client (worker/exchange.py) or "
                       f"acknowledge with `# {PRAGMA}`")
        if self._net_timeout_scoped and name in _NETWORK_CALLS:
            # an explicit timeout= keyword (or a **kwargs splat the
            # caller is trusted to bound) is the compliance signal;
            # positional timeouts don't read as deliberate at review
            bounded = any(kw.arg == "timeout" or kw.arg is None
                          for kw in node.keywords)
            if not bounded:
                self._flag(node, NET_NO_TIMEOUT,
                           f"{name}() without an explicit timeout= can "
                           f"block its thread forever on a dead peer; "
                           f"pass timeout= or mark the site with "
                           f"`# {NET_PRAGMA}`",
                           allowed=self.net_allowed)
        if self._wall_scoped and name in _WALL_CALLS:
            self._flag(node, SYNC_WALLCLOCK,
                       f"{name}() is an un-metered wall-clock read in the "
                       f"execution layer; feed it into RuntimeStats / "
                       f"operator stats, or mark the sanctioned metering "
                       f"site with `# {WALL_PRAGMA}`",
                       allowed=self.wall_allowed)
        if self._telem_scoped:
            self._check_telemetry_queue(node, name)
        if not self._interpret_exempt:
            for kw in node.keywords:
                if kw.arg == "interpret" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    self._flag(kw.value, KERNEL_INTERPRET,
                               "interpret=True outside exec/kernels/shim.py "
                               "would make TPU builds run Pallas kernels in "
                               "the Python interpreter; route the call "
                               "through the shim (no pragma escape)",
                               allowed=set())
        self.generic_visit(node)

    def _check_telemetry_queue(self, node: ast.Call, name: str) -> None:
        """TELEM001: every queue constructed in presto_tpu/telemetry/
        must carry an explicit nonzero maxsize (queue.Queue treats
        maxsize<=0 as infinite; SimpleQueue is always unbounded)."""
        if name in _SIMPLE_QUEUE_CALLS:
            self._flag(node, TELEM_UNBOUNDED_QUEUE,
                       f"{name}() is always unbounded; the telemetry "
                       f"pipeline must use queue.Queue(maxsize=N) so a "
                       f"stalled sink drops (metered) instead of growing "
                       f"without bound (no pragma escape)",
                       allowed=set())
            return
        if name not in _QUEUE_CALLS:
            return
        def _zeroish(v: ast.AST) -> bool:
            return isinstance(v, ast.Constant) and not v.value
        bounded = bool(node.args) and not _zeroish(node.args[0])
        for kw in node.keywords:
            if kw.arg == "maxsize":
                bounded = not _zeroish(kw.value)
            elif kw.arg is None:
                bounded = True      # **kwargs: assume the caller bounds it
        if not bounded:
            self._flag(node, TELEM_UNBOUNDED_QUEUE,
                       f"{name}() without a positive maxsize is an "
                       f"unbounded buffer in the telemetry pipeline; a "
                       f"stalled sink must drop (metered) at a bound, "
                       f"not grow until OOM (no pragma escape)",
                       allowed=set())

    def visit_If(self, node: ast.If) -> None:
        if self._is_device(node.test):
            self._flag(node.test, SYNC_BRANCH,
                       "Python branch on a device boolean blocks until the "
                       "value is on host; use lax.cond / jnp.where, or "
                       "device_get with the pragma")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self._is_device(node.test):
            self._flag(node.test, SYNC_BRANCH,
                       "Python loop condition on a device value blocks every "
                       "iteration; use lax.while_loop, or device_get with "
                       "the pragma")
        self.generic_visit(node)


def lint_source(source: str, path: str = "<string>") -> List[LintFinding]:
    """Lint one module's source; returns surviving findings."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [LintFinding(path, e.lineno or 0, e.offset or 0,
                            "SYNTAX", f"cannot parse: {e.msg}")]
    linter = _Linter(path, _allowed_lines(source))
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.line, f.col))


def lint_file(path: str) -> List[LintFinding]:
    text = Path(path).read_text(encoding="utf-8")
    return lint_source(text, str(path))


def lint_paths(paths: Iterable[str]) -> List[LintFinding]:
    """Lint files and directory trees (``*.py``, recursively)."""
    findings: List[LintFinding] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            for f in sorted(path.rglob("*.py")):
                findings.extend(lint_file(str(f)))
        else:
            findings.extend(lint_file(str(p)))
    return findings


def lint_or_raise(paths: Iterable[str]) -> None:
    """Programmatic gate: raise the same non-retryable PLAN_VALIDATION
    error the plan checker uses, so a build step embedding the lint
    fails through the one typed channel."""
    findings = lint_paths(paths)
    if findings:
        from ..common.errors import PlanValidationError
        head = "; ".join(str(f) for f in findings[:5])
        more = f" (+{len(findings) - 5} more)" if len(findings) > 5 else ""
        raise PlanValidationError(
            f"host-sync lint failed: {head}{more}", diagnostics=findings)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print("usage: python -m presto_tpu.analysis.lint <path> [path ...]",
              file=sys.stderr)
        return 2
    findings = lint_paths(args)
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} host-sync hazard(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
