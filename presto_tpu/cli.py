"""Interactive SQL console over the statement protocol (the presto-cli
analog, Console.java:68 / :179 runConsole).

    python -m presto_tpu.cli --server http://127.0.0.1:8080 [--schema sf1]
    python -m presto_tpu.cli --server ... -e "SELECT 1 x"   # batch mode

Statements end with `;` in interactive mode; `quit`/`exit` leaves."""
from __future__ import annotations

import argparse
import sys
import time
from typing import List

from .client import QueryError, StatementClient


def format_table(columns: List[str], rows: list) -> str:
    """Aligned text table like the reference CLI's ALIGNED output."""
    cells = [[("NULL" if v is None else str(v)) for v in row]
             for row in rows]
    widths = [len(c) for c in columns]
    for row in cells:
        for i, v in enumerate(row):
            widths[i] = max(widths[i], len(v))
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(c.ljust(w) for c, w in zip(columns, widths)), sep]
    for row in cells:
        out.append(" | ".join(v.ljust(w) for v, w in zip(row, widths)))
    return "\n".join(out)


def run_statement(client: StatementClient, sql: str,
                  out=sys.stdout) -> bool:
    t0 = time.time()
    try:
        result = client.execute(sql)
    except QueryError as e:
        print(f"Query failed: {e}", file=out)
        return False
    if result.columns:
        print(format_table(result.column_names, result.rows), file=out)
    print(f"({len(result.rows)} row{'s' if len(result.rows) != 1 else ''}, "
          f"{time.time() - t0:.2f}s)", file=out)
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="presto-tpu-cli")
    ap.add_argument("--server", required=True,
                    help="coordinator URI, e.g. http://127.0.0.1:8080")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="sf0.01")
    ap.add_argument("--user", default="user")
    ap.add_argument("--session", action="append", default=[],
                    metavar="K=V", help="session property (repeatable)")
    ap.add_argument("-e", "--execute", help="run one statement and exit")
    args = ap.parse_args(argv)

    session = dict(kv.split("=", 1) for kv in args.session)
    client = StatementClient(args.server, user=args.user,
                             catalog=args.catalog, schema=args.schema,
                             session=session)
    if args.execute:
        return 0 if run_statement(client, args.execute) else 1

    buf = []
    while True:
        try:
            line = input("presto-tpu> " if not buf else "        ... ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if not buf and line.strip().lower() in ("quit", "exit", "\\q"):
            return 0
        buf.append(line)
        if line.rstrip().endswith(";"):
            sql = "\n".join(buf).strip().rstrip(";")
            buf = []
            if sql:
                run_statement(client, sql)


if __name__ == "__main__":
    sys.exit(main())
