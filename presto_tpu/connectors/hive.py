"""File-based warehouse connector over Parquet ("hive" analog).

The storage-backed counterpart of the generated tpch/tpcds connectors — the
slim analog of the reference's presto-hive connector + presto-parquet and
presto-orc readers/writers (presto-hive/.../HiveConnector,
presto-parquet/.../reader/ParquetReader.java:95,
presto-orc/.../OrcReader.java:64 — both formats ride pyarrow here, the
way the reference rides its own columnar readers)
with the table-write commit protocol of TableWriterOperator.java:78 /
TableFinishOperator.java (stage part files in a hidden temp dir, atomic
rename on finish).

Layout: `<warehouse>/<table>/part-*.{parquet,orc}` (hive.storage-format
selects the written format; reads accept either).  Each part file stores columns
in the engine's device representation (decimals as scaled int64, dates as
int32 days, varchars as strings) with the Presto type recorded in parquet
field metadata (`presto_type`), so round-trips are exact; external parquet
files without the metadata are mapped from their arrow types (decimal128 is
converted to scaled int64 on read).

The connector implements the same duck-typed surface the catalog dispatches
over (SCHEMAS / PREFIXES / OPEN_DOMAIN / ROWID_* / table_row_count /
generate_column / generate_values_at / column_type — see catalog.py), which
is what lets every engine layer (planner, device pipeline, numpy reference
interpreter, distributed scheduler) read hive tables with no special cases:
a split is a row range, and `generate_column` serves it from row groups.

String columns are served as codes into a TABLE-WIDE dictionary built on
first access: jitted consumers require one stable dictionary per column
across batches (exec/pipeline.py caches resolution on the first batch).
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.types import (BigintType, BooleanType, CharType, DateType,
                            DecimalType, DoubleType, IntegerType, RealType,
                            SmallintType, TinyintType, Type, VarcharType,
                            parse_type)

OPEN_DOMAIN: set = set()
ROWID_ORDERED: set = set()
ROWID_DISTINCT: set = set()


def _arrow():
    import pyarrow
    import pyarrow.parquet
    return pyarrow


def _type_from_arrow(field) -> Type:
    """Arrow field -> Presto type (field metadata wins when present)."""
    import pyarrow as pa
    md = field.metadata or {}
    pt = md.get(b"presto_type")
    if pt:
        return parse_type(pt.decode())
    t = field.type
    if pa.types.is_boolean(t):
        return BooleanType()
    if pa.types.is_int8(t):
        return TinyintType()
    if pa.types.is_int16(t):
        return SmallintType()
    if pa.types.is_int32(t):
        return IntegerType()
    if pa.types.is_int64(t):
        return BigintType()
    if pa.types.is_float32(t):
        return RealType()
    if pa.types.is_float64(t):
        return DoubleType()
    if pa.types.is_date32(t):
        return DateType()
    if pa.types.is_decimal(t):
        return DecimalType(t.precision, t.scale)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return VarcharType(None)
    raise NotImplementedError(f"unsupported parquet type {t}")


def _stat_float(v) -> float:
    """Parquet row-group statistic -> float in logical units (pyarrow hands
    back datetime.date for date32 columns, Decimal for decimal128)."""
    import datetime
    if isinstance(v, datetime.date):
        return float((v - datetime.date(1970, 1, 1)).days)
    return float(v)


def _np_dtype_for(typ: Type):
    if isinstance(typ, BooleanType):
        return np.bool_
    if isinstance(typ, (IntegerType, DateType)):
        return np.int32
    if isinstance(typ, (TinyintType, SmallintType)):
        return np.int32
    if isinstance(typ, (DoubleType, RealType)):
        return np.float64
    return np.int64


class _OrcPart:
    """ORC part file with the slice of the ParquetFile surface the table
    reader uses (presto-orc's OrcReader role; pyarrow's ORC reader
    underneath).  ORC footers expose no per-stripe min/max through
    pyarrow, so column_stats counts rows only for ORC parts."""

    def __init__(self, path: str):
        from pyarrow import orc
        self._f = orc.ORCFile(path)
        self.schema_arrow = self._f.schema
        self.num_rows = self._f.nrows

    def read(self, columns=None):
        return self._f.read(columns=columns)


class _Table:
    """One on-disk table: parquet parts + lazily built per-column state."""

    def __init__(self, path: str):
        self.path = path
        self.name = os.path.basename(path)
        self._lock = threading.Lock()
        self._files: Optional[List] = None       # ParquetFile handles
        self._offsets: Optional[List[int]] = None  # cumulative row starts
        self._schema: Optional[List[Tuple[str, Type]]] = None
        self._dicts: Dict[str, Tuple[Tuple[str, ...], Dict[str, int]]] = {}
        self._col_cache: Dict[str, Tuple] = {}    # column -> (values, nulls)
        self._stats_cache: Dict[str, object] = {}

    def _parts(self) -> List[str]:
        return sorted(os.path.join(self.path, f)
                      for f in os.listdir(self.path)
                      if f.endswith(".parquet") or f.endswith(".orc"))

    def _open(self):
        import pyarrow.parquet as pq
        with self._lock:
            if self._files is None:
                self._files = [
                    _OrcPart(p) if p.endswith(".orc")
                    else pq.ParquetFile(p) for p in self._parts()]
                self._offsets = [0]
                for f in self._files:
                    n = (f.num_rows if isinstance(f, _OrcPart)
                         else f.metadata.num_rows)
                    self._offsets.append(self._offsets[-1] + n)
                if self._files:
                    sch = self._files[0].schema_arrow
                    self._schema = [(f.name, _type_from_arrow(f))
                                    for f in sch]
                else:
                    self._schema = []
        return self._files

    def invalidate(self):
        with self._lock:
            self._files = None
            self._offsets = None
            self._schema = None
            self._dicts.clear()
            self._col_cache.clear()
            self._stats_cache.clear()

    @property
    def schema(self) -> List[Tuple[str, Type]]:
        self._open()
        return self._schema

    def row_count(self) -> int:
        self._open()
        return self._offsets[-1]

    def column_type(self, column: str) -> Type:
        for n, t in self.schema:
            if n == column:
                return t
        raise KeyError(f"{self.name}.{column}")

    # -- column read ------------------------------------------------------

    def _read_full_column(self, column: str):
        """Whole column as (numpy values in device repr, nulls or None).
        Cached: hive tables are read-mostly and column-cached reads make
        row-range splits O(slice) — the analog of the reference's data cache
        (presto-cache)."""
        got = self._col_cache.get(column)
        if got is not None:
            return got
        import pyarrow as pa
        typ = self.column_type(column)
        # decode PER PART: a table may mix parquet parts (decimals as
        # scaled int64 + field metadata, dates as int32) with ORC parts
        # (decimal128, date32) — each part normalizes to the device
        # representation before the numpy concat, so mixed-format tables
        # read correctly (pa.concat_arrays would reject the mixed types)
        if isinstance(typ, (VarcharType, CharType)):
            vals: list = []
            null_chunks = []
            for f in self._open():
                arr = f.read(columns=[column]).column(0)
                vals.extend(arr.to_pylist())
            uniq, index = self._dictionary(column, vals)
            codes = np.zeros(len(vals), dtype=np.int32)
            nm = np.zeros(len(vals), dtype=bool)
            for i, sv in enumerate(vals):
                if sv is None:
                    nm[i] = True
                else:
                    codes[i] = index[sv]
            nulls = nm if nm.any() else None
            out = (codes, uniq)
            self._col_cache[column] = (out, nulls)
            return (out, nulls)
        val_chunks = []
        null_chunks = []
        any_nulls = False
        for f in self._open():
            arr = f.read(columns=[column]).column(0)
            if hasattr(arr, "combine_chunks"):
                arr = arr.combine_chunks()
            if arr.null_count:
                any_nulls = True
                null_chunks.append(np.asarray(arr.is_null()))
            else:
                null_chunks.append(np.zeros(len(arr), dtype=bool))
            if pa.types.is_decimal(arr.type):
                scale = arr.type.scale
                py = arr.to_pylist()
                v = np.asarray(
                    [0 if x is None else int(x.scaleb(scale)) for x in py],
                    dtype=np.int64)
            else:
                if pa.types.is_date32(arr.type):
                    arr = arr.cast(_arrow().int32())
                v = np.asarray(arr.fill_null(0)
                               if arr.null_count else arr)
                v = v.astype(_np_dtype_for(typ), copy=False)
            val_chunks.append(v)
        values = (np.concatenate(val_chunks) if val_chunks
                  else np.zeros(0, dtype=_np_dtype_for(typ)))
        nulls = np.concatenate(null_chunks) if any_nulls else None
        self._col_cache[column] = (values, nulls)
        return (values, nulls)

    def _dictionary(self, column: str, vals=None):
        got = self._dicts.get(column)
        if got is None:
            assert vals is not None
            uniq = tuple(sorted({v for v in vals if v is not None}))
            got = (uniq, {s: i for i, s in enumerate(uniq)})
            self._dicts[column] = got
        return got

    def column_stats(self, column: str):
        """Column stats from parquet row-group metadata (the analog of the
        reference's HiveMetadata.getTableStatistics over file footers).
        Physical min/max are mapped back to logical units; results are
        cached per column — footers are re-read only after invalidate()."""
        import pyarrow as pa
        from ..sql.stats import ColumnStats
        cached = self._stats_cache.get(column)
        if cached is not None:
            return cached
        try:
            typ = self.column_type(column)
        except KeyError:
            return None
        lo = hi = None
        nulls = 0
        total = 0
        for f in self._open():
            if isinstance(f, _OrcPart):
                # no stripe min/max via pyarrow; nulls counted from the
                # column data (stats are cached, tables read-mostly) so
                # null_fraction stays truthful for ORC parts
                total += f.num_rows
                try:
                    nulls += f.read(columns=[column]).column(0).null_count
                except (KeyError, pa.lib.ArrowInvalid):
                    return None
                continue
            md = f.metadata
            try:
                field = f.schema_arrow.field(column)
                ci = [md.schema.column(i).name
                      for i in range(md.num_columns)].index(column)
            except (KeyError, ValueError):
                return None
            # physical type is PER FILE: a table can mix engine-written
            # parts (decimals as scaled int64) and external decimal128
            # parts — convert each file's min/max to logical units before
            # folding into the running lo/hi
            descale = 1.0
            if isinstance(typ, DecimalType) \
                    and not pa.types.is_decimal(field.type):
                descale = 10.0 ** typ.scale
            for rg in range(md.num_row_groups):
                col = md.row_group(rg).column(ci)
                total += col.num_values
                st = col.statistics
                if st is None:
                    continue
                if st.null_count is not None:
                    nulls += st.null_count
                if st.has_min_max and not isinstance(
                        typ, (VarcharType, CharType)):
                    try:
                        mn, mx = (_stat_float(st.min) / descale,
                                  _stat_float(st.max) / descale)
                    except (TypeError, ValueError):
                        continue
                    lo = mn if lo is None else min(lo, mn)
                    hi = mx if hi is None else max(hi, mx)
        ndv = None
        dcached = self._dicts.get(column)
        if dcached is not None:
            ndv = float(len(dcached[0]))
        out = ColumnStats(
            low=lo, high=hi, ndv=ndv,
            null_fraction=(nulls / total) if total else 0.0)
        self._stats_cache[column] = out
        return out

    def read_range(self, column: str, start: int, count: int):
        """Rows [start, start+count) of one column ->
        values | (codes, dict-tuple) | HostColumn-with-nulls (see catalog)."""
        from .catalog import HostColumn
        values, nulls = self._read_full_column(column)
        if isinstance(values, tuple):
            codes, uniq = values
            out_vals: object = (codes[start:start + count], list(uniq))
        else:
            out_vals = values[start:start + count]
        if nulls is not None:
            return HostColumn(out_vals, nulls[start:start + count])
        return out_vals

    def values_at(self, column: str, ids) -> list:
        values, nulls = self._read_full_column(column)
        ids = np.asarray(ids)
        if isinstance(values, tuple):
            codes, uniq = values
            out = [uniq[c] for c in codes[ids]]
        else:
            out = list(values[ids])
        if nulls is not None:
            nm = nulls[ids]
            out = [None if n else v for v, n in zip(out, nm)]
        return out


class _WriteHandle:
    """Staged write of one part file set (TableWriterOperator analog).

    Pages are appended to `<warehouse>/.staging-<id>/part-N.parquet`; commit
    atomically renames the staged files into the table directory (CTAS
    creates it, INSERT appends), mirroring the reference's rename-based
    commit in TableFinishOperator + metastore."""

    def __init__(self, conn: "HiveConnector", table: str,
                 names: List[str], types: List[Type],
                 storage_format: str = "PARQUET"):
        self.conn = conn
        self.table = table
        self.names = names
        self.types = types
        self.storage_format = storage_format
        self.staging_id = uuid.uuid4().hex[:12]
        self.staging_dir = os.path.join(conn.warehouse,
                                        f".staging-{self.staging_id}")
        os.makedirs(self.staging_dir, exist_ok=True)
        self._part = 0
        self.rows = 0
        conn._staged[self.staging_id] = self

    def write_page(self, page) -> int:
        import pyarrow as pa
        import pyarrow.parquet as pq
        from ..common.block import decode_to_flat
        cols, fields = [], []
        for name, typ, block in zip(self.names, self.types, page.blocks):
            flat = decode_to_flat(block)
            nulls = flat.null_mask()
            mask = pa.array(np.asarray(nulls, dtype=bool)) \
                if nulls is not None and np.any(nulls) else None
            if isinstance(typ, (VarcharType, CharType)):
                arr = pa.array([None if v is None else str(v)
                                for v in flat.to_pylist()], type=pa.string())
            elif isinstance(typ, BooleanType):
                arr = pa.array(np.asarray(flat.values, dtype=bool),
                               type=pa.bool_(), mask=mask)
            elif isinstance(typ, DoubleType):
                v = flat.values
                v = v.view(np.float64) if v.dtype != np.float64 else v
                arr = pa.array(v, type=pa.float64(), mask=mask)
            elif isinstance(typ, RealType):
                v = flat.values
                v = v.view(np.float32) if v.dtype != np.float32 else v
                arr = pa.array(v.astype(np.float64), type=pa.float64(),
                               mask=mask)
            elif isinstance(typ, (IntegerType, DateType)):
                arr = pa.array(np.asarray(flat.values, dtype=np.int32),
                               type=pa.int32(), mask=mask)
            elif isinstance(typ, DecimalType):
                ints = flat.to_pylist()
                if self.storage_format == "ORC":
                    # ORC keeps no arrow field metadata, so decimals must
                    # carry their LOGICAL type (decimal128) in-band
                    from decimal import Decimal
                    arr = pa.array(
                        [None if v is None
                         else Decimal(int(v)).scaleb(-typ.scale)
                         for v in ints],
                        type=pa.decimal128(typ.precision, typ.scale))
                else:
                    # parquet: scaled-integer device representation with
                    # the Presto type in field metadata; exact round-trip
                    # (long decimals beyond int64 are rejected)
                    arr = pa.array([None if v is None else int(v)
                                    for v in ints], type=pa.int64())
            else:
                arr = pa.array(np.asarray(flat.values, dtype=np.int64),
                               type=pa.int64(), mask=mask)
            if self.storage_format == "ORC":
                # ORC discards arrow field metadata: the LOGICAL type must
                # ride in-band (date32 / decimal128 / exact int widths);
                # CHAR reads back as VARCHAR (width metadata lost)
                if isinstance(typ, DateType):
                    arr = arr.cast(pa.date32())
                elif isinstance(typ, TinyintType):
                    arr = arr.cast(pa.int8())
                elif isinstance(typ, SmallintType):
                    arr = arr.cast(pa.int16())
                fields.append(pa.field(name, arr.type))
            else:
                fields.append(pa.field(name, arr.type,
                                       metadata={"presto_type": str(typ)}))
            cols.append(arr)
        table = pa.Table.from_arrays(cols, schema=pa.schema(fields))
        if self.storage_format == "ORC":
            from pyarrow import orc as pa_orc
            path = os.path.join(self.staging_dir,
                                f"part-{self._part}.orc")
            pa_orc.write_table(table, path)
        else:
            path = os.path.join(self.staging_dir,
                                f"part-{self._part}.parquet")
            pq.write_table(table, path)
        self._part += 1
        self.rows += page.position_count
        return page.position_count

    def commit(self) -> int:
        dest = os.path.join(self.conn.warehouse, self.table)
        os.makedirs(dest, exist_ok=True)
        prefix = uuid.uuid4().hex[:8]
        for f in sorted(os.listdir(self.staging_dir)):
            os.rename(os.path.join(self.staging_dir, f),
                      os.path.join(dest, f"part-{prefix}-{f.split('-')[1]}"))
        shutil.rmtree(self.staging_dir, ignore_errors=True)
        self.conn._staged.pop(self.staging_id, None)
        self.conn.refresh()
        return self.rows

    def abort(self):
        shutil.rmtree(self.staging_dir, ignore_errors=True)
        self.conn._staged.pop(self.staging_id, None)


class HiveConnector:
    """Duck-typed connector module over a warehouse directory."""

    OPEN_DOMAIN = OPEN_DOMAIN
    ROWID_ORDERED = ROWID_ORDERED
    ROWID_DISTINCT = ROWID_DISTINCT

    def __init__(self, warehouse: str, storage_format: str = "PARQUET"):
        if storage_format not in ("PARQUET", "ORC"):
            raise ValueError(
                f"unsupported hive.storage-format {storage_format!r}")
        self.storage_format = storage_format
        self.warehouse = os.path.abspath(warehouse)
        os.makedirs(self.warehouse, exist_ok=True)
        self._tables: Dict[str, _Table] = {}
        self._staged: Dict[str, _WriteHandle] = {}
        self.refresh()

    # -- metadata (ConnectorMetadata analog) ------------------------------

    def refresh(self):
        found = {}
        for entry in sorted(os.listdir(self.warehouse)):
            path = os.path.join(self.warehouse, entry)
            if entry.startswith(".") or not os.path.isdir(path):
                continue
            t = self._tables.get(entry)
            if t is None:
                t = _Table(path)
            else:
                t.invalidate()
            found[entry] = t
        self._tables = found

    @property
    def SCHEMAS(self) -> Dict[str, List[Tuple[str, Type]]]:
        return {name: t.schema for name, t in self._tables.items()}

    @property
    def PREFIXES(self) -> Dict[str, str]:
        return {name: "" for name in self._tables}

    def column_type(self, table: str, column: str) -> Type:
        return self._tables[table].column_type(column)

    def table_row_count(self, table: str, sf: float) -> int:
        return self._tables[table].row_count()

    # -- reads (ConnectorPageSource analog; splits are row ranges) --------

    def generate_column(self, table: str, column: str, sf: float,
                        start: int, count: int):
        return self._tables[table].read_range(column, start, count)

    def column_stats(self, table: str, column: str, sf: float):
        t = self._tables.get(table)
        return None if t is None else t.column_stats(column)

    def generate_values_at(self, table: str, column: str, sf: float, ids):
        return self._tables[table].values_at(column, ids)

    # -- writes (ConnectorPageSink analog) --------------------------------

    def begin_write(self, table: str, names: List[str],
                    types: List[Type]) -> _WriteHandle:
        # an INSERT into an existing table keeps that table's part format
        # (mixed-format tables read fine, but staying uniform keeps the
        # footer-stats path and external readers simple)
        fmt = self.storage_format
        t = self._tables.get(table)
        if t is not None:
            parts = t._parts()
            if parts:
                fmt = "ORC" if parts[0].endswith(".orc") else "PARQUET"
        return _WriteHandle(self, table, names, types, storage_format=fmt)

    def staged(self, staging_id: str) -> _WriteHandle:
        return self._staged[staging_id]

    def drop_table(self, table: str):
        t = self._tables.pop(table, None)
        if t is None:
            raise KeyError(f"unknown table {table!r}")
        shutil.rmtree(t.path, ignore_errors=True)
