"""TPC-H connector: deterministic in-memory columnar data generator.

Plays the role of the reference's presto-tpch connector
(presto-tpch/.../TpchConnectorFactory.java:32, TpchRecordSet, TpchSplitManager):
a storage-free, deterministic data source that all conformance suites and
benchmarks run on.  Unlike the reference (which wraps io.airlift.tpch, a port
of dbgen), this generator is counter-hash based: every cell is a pure function
of (table, column, row index, scale factor), so any row range can be produced
independently — splits need no shared state, and workers can generate their
own shards directly into device memory.

Row counts match the TPC-H spec per scale factor (6M lineitem / 1.5M orders /
200k part / 800k partsupp / 150k customer / 10k supplier per SF; fixed 25
nations / 5 regions).  Value domains and formulas follow the public TPC-H
specification (retail price formula, date ranges, flag rules); text columns
use the spec's value lists.  The data is NOT bit-identical to dbgen — parity
testing is differential (TPU engine vs the numpy reference executor on the
same generated data), mirroring how the reference tests Presto vs H2
(presto-tests/.../QueryAssertions.java:52).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..common.types import (BIGINT, DATE, DOUBLE, INTEGER, Type, DecimalType,
                            VarcharType)
from ..common.block import (DictionaryBlock, FixedWidthBlock,
                            VariableWidthBlock)
from ..common.page import Page

# ---------------------------------------------------------------------------
# counter-based hashing (splitmix64), vectorized
# ---------------------------------------------------------------------------

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        z = (x + _GOLDEN).astype(np.uint64)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


_SEED_CACHE: Dict[Tuple[str, str], np.uint64] = {}


def _stream_seed(table: str, column: str) -> np.uint64:
    """Process-independent seed (builtin hash() is randomized per process,
    which would make workers generate different data for the same rows)."""
    key = (table, column)
    seed = _SEED_CACHE.get(key)
    if seed is None:
        import hashlib
        digest = hashlib.blake2b(f"{table}.{column}".encode(),
                                 digest_size=8).digest()
        seed = np.uint64(int.from_bytes(digest, "little"))
        _SEED_CACHE[key] = seed
    return seed


def _cell_hash(table: str, column: str, idx: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit hash per row for a (table, column) stream."""
    seed = _stream_seed(table, column)
    with np.errstate(over="ignore"):
        return _splitmix64(idx.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15) + seed)


def _uniform(table, column, idx, lo, hi):
    """Uniform integer in [lo, hi] inclusive."""
    h = _cell_hash(table, column, idx)
    span = np.uint64(hi - lo + 1)
    return (h % span).astype(np.int64) + lo


# ---------------------------------------------------------------------------
# dates
# ---------------------------------------------------------------------------

def _days(datestr: str) -> int:
    return int(np.datetime64(datestr, "D").astype(np.int64))


MIN_ORDER_DATE = _days("1992-01-01")
MAX_ORDER_DATE = _days("1998-08-02") - 151
CURRENT_DATE = _days("1995-06-17")

# ---------------------------------------------------------------------------
# value lists (TPC-H spec §4.2.2.13)
# ---------------------------------------------------------------------------

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
NATIONS = [  # (name, regionkey)
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
TYPE_SYLL1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_SYLL2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_SYLL3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_SYLL1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_SYLL2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
RETURN_FLAGS = ["A", "N", "R"]
STATUSES = ["F", "O"]
ORDER_STATUSES = ["F", "O", "P"]
COMMENT_WORDS = [
    "blithely", "carefully", "express", "regular", "final", "ironic",
    "pending", "furiously", "quickly", "bold", "even", "special", "silent",
    "deposits", "packages", "requests", "accounts", "theodolites", "pinto",
    "beans", "foxes", "dependencies", "instructions", "platelets", "asymptotes",
]

# the spec's P_NAME word source (dbgen dists.dss "colors", 92 entries):
# part names are 5 words drawn from this list, so LIKE filters over colors
# (q9 '%green%', q20 'forest%') select at spec-like rates
P_NAME_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower", "cornsilk",
    "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
    "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod",
    "green", "grey", "honeydew", "hot", "indian", "ivory", "khaki", "lace",
    "lavender", "lawn", "lemon", "light", "lime", "linen", "magenta",
    "maroon", "medium", "metallic", "midnight", "mint", "misty", "moccasin",
    "navajo", "navy", "olive", "orange", "orchid", "pale", "papaya",
    "peach", "peru", "pink", "plum", "powder", "puff", "purple", "red",
    "rose", "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
    "sienna", "sky", "slate", "smoke", "snow", "spring", "steel", "tan",
    "thistle", "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]

LINES_PER_ORDER = 4  # AVERAGE fanout: 6M lineitems / 1.5M orders per SF

# Variable lines-per-order with a closed-form row mapping: each block of 7
# consecutive orders carries exactly 28 lineitems, split 1..7 per order by
# a hash-chosen permutation (dbgen draws counts uniform 1..7 per order; the
# fixed block sum keeps idx -> orderkey a pure function, which the
# device-side generator needs).  Orders past the last full block (at most
# 6) keep the fixed fanout of 4 so total rows stay exactly 4 * orders.
_LI_PERMS = None
_LI_CUM = None


def _li_perm_tables():
    global _LI_PERMS, _LI_CUM
    if _LI_CUM is None:
        import itertools
        _LI_PERMS = np.array(list(itertools.permutations(range(1, 8))),
                             dtype=np.int64)                 # (5040, 7)
        _LI_CUM = np.concatenate(
            [np.zeros((5040, 1), dtype=np.int64),
             np.cumsum(_LI_PERMS, axis=1)], axis=1)          # (5040, 8)
    return _LI_PERMS, _LI_CUM


def _li_order_map(idx: np.ndarray, sf: float):
    """lineitem row index -> (orderkey, linenumber), vectorized."""
    _, cum = _li_perm_tables()
    n_orders = _table_rows("orders", sf)
    full = (n_orders // 7) * 28
    b = idx // 28
    r = idx % 28
    pid = (_cell_hash("lineitem", "orderblock", b)
           % np.uint64(5040)).astype(np.int64)
    crows = cum[pid]                                         # (n, 8)
    pos = (r[:, None] >= crows[:, 1:]).sum(axis=1)           # 0..6
    start = np.take_along_axis(crows, pos[:, None], axis=1)[:, 0]
    orderkey = b * 7 + pos + 1
    linenumber = r - start + 1
    tail = idx >= full
    if tail.any():
        t = idx - full
        orderkey = np.where(tail, (n_orders // 7) * 7 + t // 4 + 1,
                            orderkey)
        linenumber = np.where(tail, t % 4 + 1, linenumber)
    return orderkey, linenumber


def _table_rows(table: str, sf: float) -> int:
    base = {
        "lineitem": 6_000_000, "orders": 1_500_000, "customer": 150_000,
        "part": 200_000, "partsupp": 800_000, "supplier": 10_000,
    }
    if table == "nation":
        return 25
    if table == "region":
        return 5
    return int(base[table] * sf)


# ---------------------------------------------------------------------------
# schemas
# ---------------------------------------------------------------------------

D12_2 = DecimalType(12, 2)

SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
    "lineitem": [
        ("orderkey", BIGINT), ("partkey", BIGINT), ("suppkey", BIGINT),
        ("linenumber", INTEGER), ("quantity", D12_2),
        ("extendedprice", D12_2), ("discount", D12_2), ("tax", D12_2),
        ("returnflag", VarcharType(1)), ("linestatus", VarcharType(1)),
        ("shipdate", DATE), ("commitdate", DATE), ("receiptdate", DATE),
        ("shipinstruct", VarcharType(25)), ("shipmode", VarcharType(10)),
        ("comment", VarcharType(44)),
    ],
    "orders": [
        ("orderkey", BIGINT), ("custkey", BIGINT),
        ("orderstatus", VarcharType(1)), ("totalprice", D12_2),
        ("orderdate", DATE), ("orderpriority", VarcharType(15)),
        ("clerk", VarcharType(15)), ("shippriority", INTEGER),
        ("comment", VarcharType(79)),
    ],
    "customer": [
        ("custkey", BIGINT), ("name", VarcharType(25)),
        ("address", VarcharType(40)), ("nationkey", BIGINT),
        ("phone", VarcharType(15)), ("acctbal", D12_2),
        ("mktsegment", VarcharType(10)), ("comment", VarcharType(117)),
    ],
    "part": [
        ("partkey", BIGINT), ("name", VarcharType(55)),
        ("mfgr", VarcharType(25)), ("brand", VarcharType(10)),
        ("type", VarcharType(25)), ("size", INTEGER),
        ("container", VarcharType(10)), ("retailprice", D12_2),
        ("comment", VarcharType(23)),
    ],
    "partsupp": [
        ("partkey", BIGINT), ("suppkey", BIGINT), ("availqty", INTEGER),
        ("supplycost", D12_2), ("comment", VarcharType(199)),
    ],
    "supplier": [
        ("suppkey", BIGINT), ("name", VarcharType(25)),
        ("address", VarcharType(40)), ("nationkey", BIGINT),
        ("phone", VarcharType(15)), ("acctbal", D12_2),
        ("comment", VarcharType(101)),
    ],
    "nation": [
        ("nationkey", BIGINT), ("name", VarcharType(25)),
        ("regionkey", BIGINT), ("comment", VarcharType(152)),
    ],
    "region": [
        ("regionkey", BIGINT), ("name", VarcharType(25)),
        ("comment", VarcharType(152)),
    ],
}


# query-text column prefix per table (canonical l_quantity -> quantity)
PREFIXES: Dict[str, str] = {
    "lineitem": "l_", "orders": "o_", "customer": "c_", "part": "p_",
    "partsupp": "ps_", "supplier": "s_", "nation": "n_", "region": "r_",
}


def column_type(table: str, column: str) -> Type:
    for name, typ in SCHEMAS[table]:
        if name == column:
            return typ
    raise KeyError(f"{table}.{column}")


# ---------------------------------------------------------------------------
# column generators.  Each returns either:
#   numpy int array           (bigint/int/date/decimal-unscaled)
#   (codes, value_list)       low-cardinality varchar as dictionary
#   list[str]                 formulaic varchar
# ---------------------------------------------------------------------------

def _retail_price(partkey: np.ndarray) -> np.ndarray:
    # spec: (90000 + ((partkey/10) % 20001) + 100*(partkey % 1000)) / 100
    return (90000 + ((partkey // 10) % 20001) + 100 * (partkey % 1000))


def _order_date(orderkey: np.ndarray) -> np.ndarray:
    return _uniform("orders", "orderdate", orderkey,
                    MIN_ORDER_DATE, MAX_ORDER_DATE)


def _comment(table: str, idx: np.ndarray, nwords: int = 4) -> list:
    h = _cell_hash(table, "comment", idx)
    w = len(COMMENT_WORDS)
    parts = []
    for k in range(nwords):
        parts.append((h >> np.uint64(8 * k)) % np.uint64(w))
    arr = np.stack(parts, axis=1)
    return [" ".join(COMMENT_WORDS[int(j)] for j in row) for row in arr]


def _gen_lineitem(column: str, idx: np.ndarray, sf: float):
    # (orderkey, linenumber) only where needed — the map costs a hash +
    # permutation gather per row, pure waste for order-independent columns
    if column == "orderkey":
        return _li_order_map(idx, sf)[0]
    if column == "linenumber":
        return _li_order_map(idx, sf)[1].astype(np.int64)
    if column == "partkey":
        return _uniform("lineitem", "partkey", idx, 1, _table_rows("part", sf))
    if column == "suppkey":
        # spec-style scattering keeps part->supp association lumpy
        partkey = _gen_lineitem("partkey", idx, sf)
        s = _table_rows("supplier", sf)
        j = _uniform("lineitem", "suppj", idx, 0, 3)
        return ((partkey + j * (s // 4 + (partkey - 1) // s)) % s) + 1
    if column == "quantity":
        return _uniform("lineitem", "quantity", idx, 1, 50) * 100
    if column == "extendedprice":
        partkey = _gen_lineitem("partkey", idx, sf)
        qty = _uniform("lineitem", "quantity", idx, 1, 50)
        return qty * _retail_price(partkey)
    if column == "discount":
        return _uniform("lineitem", "discount", idx, 0, 10)
    if column == "tax":
        return _uniform("lineitem", "tax", idx, 0, 8)
    if column == "shipdate":
        od = _order_date(_li_order_map(idx, sf)[0])
        return od + _uniform("lineitem", "shipdays", idx, 1, 121)
    if column == "commitdate":
        od = _order_date(_li_order_map(idx, sf)[0])
        return od + _uniform("lineitem", "commitdays", idx, 30, 90)
    if column == "receiptdate":
        sd = _gen_lineitem("shipdate", idx, sf)
        return sd + _uniform("lineitem", "receiptdays", idx, 1, 30)
    if column == "returnflag":
        rd = _gen_lineitem("receiptdate", idx, sf)
        coin = _uniform("lineitem", "rflagcoin", idx, 0, 1)
        codes = np.where(rd <= CURRENT_DATE, coin * 2, 1)  # A/R if old, else N
        return codes.astype(np.int32), RETURN_FLAGS
    if column == "linestatus":
        sd = _gen_lineitem("shipdate", idx, sf)
        return (sd > CURRENT_DATE).astype(np.int32), STATUSES
    if column == "shipinstruct":
        return (_uniform("lineitem", "instruct", idx, 0, 3).astype(np.int32),
                INSTRUCTIONS)
    if column == "shipmode":
        return (_uniform("lineitem", "shipmode", idx, 0, 6).astype(np.int32),
                MODES)
    if column == "comment":
        return _comment("lineitem", idx, 3)
    raise KeyError(column)


def _gen_orders(column: str, idx: np.ndarray, sf: float):
    orderkey = idx + 1
    if column == "orderkey":
        return orderkey
    if column == "custkey":
        # spec excludes custkeys % 3 == 0 (a third of customers have no
        # orders): raw 1,2,3,4.. -> 1,2,4,5,7,8..
        c = _table_rows("customer", sf)
        raw = _uniform("orders", "custkey", idx, 1, c // 3 * 2)
        return raw + (raw - 1) // 2 if c >= 3 else raw
    if column == "orderstatus":
        # F if all lines shipped (order fully before cutoff), O if none, else P
        od = _order_date(orderkey)
        codes = np.where(od + 121 <= CURRENT_DATE, 0,
                         np.where(od > CURRENT_DATE, 1, 2))
        return codes.astype(np.int32), ORDER_STATUSES
    if column == "totalprice":
        # plausible magnitude; self-consistent, not dbgen-exact (see module doc)
        return _uniform("orders", "totalprice", idx, 90000, 50000000)
    if column == "orderdate":
        return _order_date(orderkey)
    if column == "orderpriority":
        return (_uniform("orders", "priority", idx, 0, 4).astype(np.int32),
                PRIORITIES)
    if column == "clerk":
        k = _uniform("orders", "clerk", idx, 1, max(1, int(1000 * sf)))
        return [f"Clerk#{int(v):09d}" for v in k]
    if column == "shippriority":
        return np.zeros(len(idx), dtype=np.int64)
    if column == "comment":
        return _comment("orders", idx, 5)
    raise KeyError(column)


def _gen_customer(column: str, idx: np.ndarray, sf: float):
    custkey = idx + 1
    if column == "custkey":
        return custkey
    if column == "name":
        return [f"Customer#{int(v):09d}" for v in custkey]
    if column == "address":
        h = _cell_hash("customer", "address", idx)
        return [f"addr-{int(v):016x}" for v in h]
    if column == "nationkey":
        return _uniform("customer", "nationkey", idx, 0, 24)
    if column == "phone":
        nk = _gen_customer("nationkey", idx, sf)
        h1 = _uniform("customer", "ph1", idx, 100, 999)
        h2 = _uniform("customer", "ph2", idx, 100, 999)
        h3 = _uniform("customer", "ph3", idx, 1000, 9999)
        return [f"{10 + int(n)}-{int(a)}-{int(b)}-{int(c)}"
                for n, a, b, c in zip(nk, h1, h2, h3)]
    if column == "acctbal":
        return _uniform("customer", "acctbal", idx, -99999, 999999)
    if column == "mktsegment":
        return (_uniform("customer", "segment", idx, 0, 4).astype(np.int32),
                SEGMENTS)
    if column == "comment":
        return _comment("customer", idx, 6)
    raise KeyError(column)


# closed part-type domains (dictionary-encoded: stable codes table-wide)
MFGRS = [f"Manufacturer#{i}" for i in range(1, 6)]
BRANDS = [f"Brand#{m}{b}" for m in range(1, 6) for b in range(1, 6)]
TYPES = [f"{a} {b} {c}" for a in TYPE_SYLL1 for b in TYPE_SYLL2
         for c in TYPE_SYLL3]
CONTAINERS = [f"{a} {b}" for a in CONTAINER_SYLL1 for b in CONTAINER_SYLL2]


def _gen_part(column: str, idx: np.ndarray, sf: float):
    partkey = idx + 1
    if column == "partkey":
        return partkey
    if column == "name":
        # 5 words from the 92-entry P_NAME list (spec 4.2.3: P_NAME is a
        # concatenation of 5 variable-length words)
        h = _cell_hash("part", "name", idx)
        w = np.uint64(len(P_NAME_WORDS))
        cols = [(h >> np.uint64(8 * k)) % w for k in range(5)]
        arr = np.stack(cols, axis=1)
        return [" ".join(P_NAME_WORDS[int(j)] for j in row) for row in arr]
    if column == "mfgr":
        m = _uniform("part", "mfgr", idx, 1, 5)
        return ((m - 1).astype(np.int32), MFGRS)
    if column == "brand":
        m = _uniform("part", "mfgr", idx, 1, 5)
        b = _uniform("part", "brand", idx, 1, 5)
        return (((m - 1) * 5 + (b - 1)).astype(np.int32), BRANDS)
    if column == "type":
        h = _cell_hash("part", "type", idx)
        a = h % 6
        b = (h >> np.uint64(8)) % 5
        c = (h >> np.uint64(16)) % 5
        return ((a * 25 + b * 5 + c).astype(np.int32), TYPES)
    if column == "size":
        return _uniform("part", "size", idx, 1, 50)
    if column == "container":
        h = _cell_hash("part", "container", idx)
        a = h % 5
        b = (h >> np.uint64(8)) % 8
        return ((a * 8 + b).astype(np.int32), CONTAINERS)
    if column == "retailprice":
        return _retail_price(partkey)
    if column == "comment":
        return _comment("part", idx, 2)
    raise KeyError(column)


def _gen_partsupp(column: str, idx: np.ndarray, sf: float):
    # 4 suppliers per part
    partkey = idx // 4 + 1
    if column == "partkey":
        return partkey
    if column == "suppkey":
        s = _table_rows("supplier", sf)
        j = idx % 4
        return ((partkey + j * (s // 4 + (partkey - 1) // s)) % s) + 1
    if column == "availqty":
        return _uniform("partsupp", "availqty", idx, 1, 9999)
    if column == "supplycost":
        return _uniform("partsupp", "supplycost", idx, 100, 100000)
    if column == "comment":
        return _comment("partsupp", idx, 6)
    raise KeyError(column)


def _gen_supplier(column: str, idx: np.ndarray, sf: float):
    suppkey = idx + 1
    if column == "suppkey":
        return suppkey
    if column == "name":
        return [f"Supplier#{int(v):09d}" for v in suppkey]
    if column == "address":
        h = _cell_hash("supplier", "address", idx)
        return [f"addr-{int(v):016x}" for v in h]
    if column == "nationkey":
        return _uniform("supplier", "nationkey", idx, 0, 24)
    if column == "phone":
        nk = _gen_supplier("nationkey", idx, sf)
        h1 = _uniform("supplier", "ph1", idx, 100, 999)
        h2 = _uniform("supplier", "ph2", idx, 100, 999)
        h3 = _uniform("supplier", "ph3", idx, 1000, 9999)
        return [f"{10 + int(n)}-{int(a)}-{int(b)}-{int(c)}"
                for n, a, b, c in zip(nk, h1, h2, h3)]
    if column == "acctbal":
        return _uniform("supplier", "acctbal", idx, -99999, 999999)
    if column == "comment":
        return _comment("supplier", idx, 5)
    raise KeyError(column)


def _gen_nation(column: str, idx: np.ndarray, sf: float):
    if column == "nationkey":
        return idx.astype(np.int64)
    if column == "name":
        return (idx.astype(np.int32), [n for n, _ in NATIONS])
    if column == "regionkey":
        return np.array([NATIONS[int(i)][1] for i in idx], dtype=np.int64)
    if column == "comment":
        return _comment("nation", idx, 4)
    raise KeyError(column)


def _gen_region(column: str, idx: np.ndarray, sf: float):
    if column == "regionkey":
        return idx.astype(np.int64)
    if column == "name":
        return (idx.astype(np.int32), REGIONS)
    if column == "comment":
        return _comment("region", idx, 4)
    raise KeyError(column)


_GENERATORS = {
    "lineitem": _gen_lineitem, "orders": _gen_orders,
    "customer": _gen_customer, "part": _gen_part,
    "partsupp": _gen_partsupp, "supplier": _gen_supplier,
    "nation": _gen_nation, "region": _gen_region,
}


# ---------------------------------------------------------------------------
# public connector API
# ---------------------------------------------------------------------------

def table_row_count(table: str, sf: float) -> int:
    return _table_rows(table, sf)


def column_stats(table: str, column: str, sf: float):
    """Analytic column statistics from the generator specs (the
    ConnectorMetadata.getTableStatistics analog; consumed by sql/stats.py).
    Values are in LOGICAL units (decimals as fractional numbers, dates as
    epoch days) to match planner constants."""
    from ..sql.stats import ColumnStats
    n = float(_table_rows(table, sf))
    orders = float(_table_rows("orders", sf))
    uniform = {
        ("lineitem", "orderkey"): (1, orders, orders),
        ("lineitem", "partkey"): (1, _table_rows("part", sf), None),
        ("lineitem", "suppkey"): (1, _table_rows("supplier", sf), None),
        ("lineitem", "linenumber"): (1, 7, 7),
        ("lineitem", "quantity"): (1.0, 50.0, 50),
        ("lineitem", "extendedprice"): (900.0, 104949.50, None),
        ("lineitem", "discount"): (0.0, 0.10, 11),
        ("lineitem", "tax"): (0.0, 0.08, 9),
        ("lineitem", "shipdate"): (MIN_ORDER_DATE + 1,
                                   MAX_ORDER_DATE + 121, None),
        ("lineitem", "commitdate"): (MIN_ORDER_DATE + 30,
                                     MAX_ORDER_DATE + 90, None),
        ("lineitem", "receiptdate"): (MIN_ORDER_DATE + 2,
                                      MAX_ORDER_DATE + 151, None),
        ("lineitem", "returnflag"): (None, None, 3),
        ("lineitem", "linestatus"): (None, None, 2),
        ("lineitem", "shipinstruct"): (None, None, 4),
        ("lineitem", "shipmode"): (None, None, 7),
        ("orders", "orderkey"): (1, n, n),
        ("orders", "custkey"): (1, _table_rows("customer", sf),
                                _table_rows("customer", sf) * 2 / 3),
        ("orders", "orderdate"): (MIN_ORDER_DATE, MAX_ORDER_DATE,
                                  MAX_ORDER_DATE - MIN_ORDER_DATE + 1),
        ("orders", "totalprice"): (900.0, 500000.0, None),
        ("orders", "orderstatus"): (None, None, 3),
        ("orders", "orderpriority"): (None, None, 5),
        ("orders", "clerk"): (None, None, max(1.0, sf * 1000)),
        ("orders", "shippriority"): (0, 0, 1),
        ("customer", "custkey"): (1, n, n),
        ("customer", "nationkey"): (0, 24, 25),
        ("customer", "acctbal"): (-999.99, 9999.99, None),
        ("customer", "mktsegment"): (None, None, 5),
        ("part", "partkey"): (1, n, n),
        ("part", "mfgr"): (None, None, 5),
        ("part", "brand"): (None, None, 25),
        ("part", "type"): (None, None, 150),
        ("part", "size"): (1, 50, 50),
        ("part", "container"): (None, None, 40),
        ("part", "retailprice"): (900.0, 2098.99, None),
        ("partsupp", "partkey"): (1, _table_rows("part", sf),
                                  _table_rows("part", sf)),
        ("partsupp", "suppkey"): (1, _table_rows("supplier", sf),
                                  _table_rows("supplier", sf)),
        ("partsupp", "availqty"): (1, 9999, 9999),
        ("partsupp", "supplycost"): (1.0, 1000.0, None),
        ("supplier", "suppkey"): (1, n, n),
        ("supplier", "nationkey"): (0, 24, 25),
        ("supplier", "acctbal"): (-999.99, 9999.99, None),
        ("nation", "nationkey"): (0, 24, 25),
        ("nation", "regionkey"): (0, 4, 5),
        ("nation", "name"): (None, None, 25),
        ("region", "regionkey"): (0, 4, 5),
        ("region", "name"): (None, None, 5),
    }
    spec = uniform.get((table, column))
    if spec is None:
        return None
    lo, hi, ndv = spec
    if ndv is None and lo is not None:
        ndv = min(n, max(1.0, float(hi) - float(lo)))
    return ColumnStats(
        low=None if lo is None else float(lo),
        high=None if hi is None else float(hi),
        ndv=None if ndv is None else float(ndv))


# string columns with open (unbounded) value domains: these are produced
# lazily on device as row-id columns and materialized on output
# (late materialization — see exec/batch.py Column.lazy)
# open-domain columns whose generated values sort identically to their row
# ids ("Supplier#000000001"-style zero-padded sequence numbers): ORDER BY on
# these late-materialized columns can sort the row ids directly
ROWID_ORDERED = {("supplier", "name"), ("customer", "name")}

# open-domain columns whose generated values are distinct per row (key-derived
# names/phones, long random text): GROUP BY may use the row id as the group
# key.  Columns drawn from small pools (orders.clerk: sf*1000 values) are NOT
# here — grouping them requires materializing a real dictionary first.
ROWID_DISTINCT = {
    ("customer", "name"), ("customer", "address"), ("customer", "phone"),
    ("customer", "comment"), ("supplier", "name"), ("supplier", "address"),
    ("supplier", "phone"), ("supplier", "comment"), ("part", "name"),
    ("part", "comment"), ("partsupp", "comment"), ("orders", "comment"),
    ("lineitem", "comment"), ("nation", "comment"), ("region", "comment"),
}

OPEN_DOMAIN = {
    ("lineitem", "comment"), ("orders", "comment"), ("orders", "clerk"),
    ("customer", "name"), ("customer", "address"), ("customer", "phone"),
    ("customer", "comment"), ("part", "name"), ("part", "comment"),
    ("partsupp", "comment"), ("supplier", "name"), ("supplier", "address"),
    ("supplier", "phone"), ("supplier", "comment"), ("nation", "comment"),
    ("region", "comment"),
}


def generate_column(table: str, column: str, sf: float,
                    start: int, count: int):
    """Raw column data for rows [start, start+count): numpy int64 array, or
    (codes:int32, values:list) dictionary pair, or list[str]."""
    idx = np.arange(start, start + count, dtype=np.int64)
    return _GENERATORS[table](column, idx, sf)


def generate_values_at(table: str, column: str, sf: float,
                       idx: np.ndarray) -> list:
    """Materialize string values for arbitrary row indices (used to realize
    late-materialized columns at output boundaries)."""
    raw = _GENERATORS[table](column, np.asarray(idx, dtype=np.int64), sf)
    if isinstance(raw, tuple):
        codes, values = raw
        return [values[c] for c in codes]
    if isinstance(raw, list):
        return raw
    return raw.tolist()


def generate_block(table: str, column: str, sf: float, start: int, count: int):
    """Column data for rows [start, start+count) as a Block."""
    raw = generate_column(table, column, sf, start, count)
    typ = column_type(table, column)
    if isinstance(raw, tuple):
        codes, values = raw
        return DictionaryBlock(codes, VariableWidthBlock.from_strings(values))
    if isinstance(raw, list):
        return VariableWidthBlock.from_strings(raw)
    if typ.storage == "INT_ARRAY":
        return FixedWidthBlock(raw.astype(np.int32))
    return FixedWidthBlock(raw.astype(np.int64))


def generate_page(table: str, sf: float, start: int, count: int,
                  columns: Optional[Sequence[str]] = None) -> Page:
    cols = columns if columns is not None else [c for c, _ in SCHEMAS[table]]
    return Page([generate_block(table, c, sf, start, count) for c in cols],
                count)


# ---------------------------------------------------------------------------
# co-bucketed layout for grouped (lifespan) execution
#
# The reference bounds memory for huge joins by processing one bucket
# lifespan at a time when the joined tables are bucketed on the join key
# (Lifespan.java:30-37, GroupedExecutionTagger.java, session
# grouped_execution — SystemSessionProperties.java:105).  This generator
# gets the same property FOR FREE: orders.orderkey == row index + 1, and
# lineitem rows map to orders through fixed 7-order / 28-lineitem blocks
# (_li_order_map), so an ORDERKEY RANGE is a contiguous ROW RANGE in both
# tables — a bucket is just a pair of row-range splits, no repartitioning
# pass needed.  exec/grouped.py consumes this layout.
# ---------------------------------------------------------------------------

# tables co-partitioned on the "orderkey" domain, and the bucketing column
BUCKET_COLUMNS = {"orders": "orderkey", "lineitem": "orderkey"}


@dataclass
class TableBucket:
    """One lifespan: key range [key_lo, key_hi) and the contiguous row
    range it occupies in each co-bucketed table."""
    key_lo: int
    key_hi: int
    rows: Dict[str, Tuple[int, int]]


def bucket_layout(sf: float, n_buckets: int) -> List[TableBucket]:
    """Split the orderkey domain into up to n_buckets aligned lifespans.
    Buckets align to 7-order blocks (the lineitem row mapping's unit); the
    last bucket absorbs the fixed-fanout tail orders."""
    n_orders = _table_rows("orders", sf)
    n_lineitem = _table_rows("lineitem", sf)
    nblocks = n_orders // 7
    if nblocks == 0 or n_buckets <= 1:
        return [TableBucket(1, n_orders + 1,
                            {"orders": (0, n_orders),
                             "lineitem": (0, n_lineitem)})]
    bpb = max(1, -(-nblocks // n_buckets))      # ceil(nblocks / K)
    out: List[TableBucket] = []
    b0 = 0
    while b0 < nblocks:
        b1 = min(b0 + bpb, nblocks)
        o0, o1 = b0 * 7, b1 * 7
        l0, l1 = b0 * 28, b1 * 28
        if b1 == nblocks:           # tail orders: 4 lineitems each
            o1 = n_orders
            l1 = n_lineitem
        out.append(TableBucket(o0 + 1, o1 + 1,
                               {"orders": (o0, o1), "lineitem": (l0, l1)}))
        b0 = b1
    return out


@dataclass(frozen=True)
class TpchSplit:
    """A row-range shard of one table (reference TpchSplitManager splits by
    part index; ours are explicit ranges)."""
    table: str
    sf: float
    start: int
    end: int

    def to_dict(self):
        return {"connectorId": "tpch", "table": self.table, "sf": self.sf,
                "start": self.start, "end": self.end}

    @staticmethod
    def from_dict(d):
        return TpchSplit(d["table"], d["sf"], d["start"], d["end"])


def make_splits(table: str, sf: float, splits: int) -> List[TpchSplit]:
    total = table_row_count(table, sf)
    per = (total + splits - 1) // splits
    return [TpchSplit(table, sf, i * per, min((i + 1) * per, total))
            for i in range(splits) if i * per < total]


def split_pages(split: TpchSplit, columns: Optional[Sequence[str]] = None,
                page_rows: int = 1 << 20) -> Iterator[Page]:
    pos = split.start
    while pos < split.end:
        n = min(page_rows, split.end - pos)
        yield generate_page(split.table, split.sf, pos, n, columns)
        pos += n


# ---------------------------------------------------------------------------
# connector stats (feeds the fragmenter's join-distribution choice, the
# analog of TpchMetadata.getTableStatistics -> StatsCalculator)
# ---------------------------------------------------------------------------

def _connector_stats(handle) -> float:
    sf = dict(handle.extra).get("scaleFactor", 0.01)
    return float(table_row_count(handle.table_name, sf))


from ..sql.fragmenter import register_connector_stats as _reg_stats  # noqa: E402

_reg_stats("tpch", _connector_stats)
