"""System runtime tables: SQL-queryable cluster state — the analog of the
reference's system connector (presto-main-base/.../connector/system/:
system.runtime.nodes, system.runtime.queries; native SystemConnector in
presto_cpp/main/connectors/SystemConnector.{h,cpp} serves task info the
same way).

A SystemTablesConnector binds to a live WorkerServer and snapshots its
discovery map / dispatch registry at scan time, so
`SELECT * FROM runtime_nodes` (catalog "system") answers from the
coordinator's own state.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..common.types import BIGINT, BOOLEAN, DOUBLE, Type, VarcharType
from .catalog import HostColumn

V = VarcharType(128)

SCHEMAS_DEF: Dict[str, List[Tuple[str, Type]]] = {
    "runtime_nodes": [
        ("node_id", V), ("http_uri", V), ("node_version", V),
        ("coordinator", BOOLEAN), ("state", V),
    ],
    "runtime_queries": [
        ("query_id", V), ("state", V), ("user", V), ("source", V),
        ("resource_group_id", V), ("queued_time_ms", BIGINT),
        ("elapsed_time_ms", BIGINT),
    ],
    "runtime_tasks": [
        ("task_id", V), ("state", V), ("output_rows", BIGINT),
        ("output_bytes", BIGINT), ("memory_reservation", BIGINT),
    ],
}


class SystemTablesConnector:
    OPEN_DOMAIN: set = set()
    ROWID_ORDERED: set = set()
    ROWID_DISTINCT: set = set()
    SCHEMAS = SCHEMAS_DEF
    PREFIXES = {t: "" for t in SCHEMAS_DEF}

    def __init__(self, server):
        self.server = server
        # per-table snapshot, refreshed when a scan sizes its splits
        # (table_row_count) so every column of one scan reads one
        # consistent view of the live server state
        self._snap: Dict[str, List[list]] = {}

    # -- snapshots --------------------------------------------------------
    def _rows(self, table: str) -> List[list]:
        s = self.server
        if table == "runtime_nodes":
            out = [[s.node_id, s.uri, "presto-tpu-0.1", s.coordinator,
                    s.state]]
            if s.discovery is not None:
                with s.discovery_lock:
                    for nid, svc in s.discovery.items():
                        if nid == s.node_id:
                            continue
                        out.append([nid, svc.get("uri", ""),
                                    "presto-tpu-0.1", False, "ACTIVE"])
            return out
        if table == "runtime_queries":
            if getattr(s, "dispatch", None) is None:
                return []
            import time
            out = []
            with s.dispatch._lock:
                qs = list(s.dispatch._queries.values())
            for q in qs:
                now = q.finished_at or time.time()
                out.append([q.query_id, q.state, q.user, q.source,
                            q.resource_group,
                            int(((q.started_at or now) - q.created_at)
                                * 1000),
                            int((now - q.created_at) * 1000)])
            return out
        if table == "runtime_tasks":
            with s.task_manager._lock:
                tasks = list(s.task_manager.tasks.values())
            return [[t.task_id, t.state, t.output_rows, t.output_bytes,
                     t.memory_peak] for t in tasks]
        raise KeyError(table)

    # -- connector contract ----------------------------------------------
    def column_type(self, table: str, column: str) -> Type:
        return dict(SCHEMAS_DEF[table])[column]

    def table_row_count(self, table: str, sf: float) -> int:
        self._snap[table] = self._rows(table)
        return len(self._snap[table])

    def _snapshot(self, table: str) -> List[list]:
        snap = self._snap.get(table)
        if snap is None:
            snap = self._snap[table] = self._rows(table)
        return snap

    def generate_column(self, table: str, column: str, sf: float,
                        start: int, count: int):
        from .memory import _to_connector_column
        schema = SCHEMAS_DEF[table]
        ci = [n for n, _ in schema].index(column)
        typ = schema[ci][1]
        rows = self._snapshot(table)[start:start + count]
        vals = [r[ci] for r in rows]
        return _to_connector_column(typ, vals, [False] * len(vals))

    def generate_values_at(self, table: str, column: str, sf: float, ids):
        schema = SCHEMAS_DEF[table]
        ci = [n for n, _ in schema].index(column)
        rows = self._snapshot(table)
        return [rows[int(i)][ci] if int(i) < len(rows) else None
                for i in np.asarray(ids)]

    def column_stats(self, table: str, column: str, sf: float):
        return None
