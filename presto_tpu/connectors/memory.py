"""In-memory connector: writable tables living in process RAM, plus the
blackhole sink — the analogs of the reference's presto-memory (3,689 LoC,
MemoryPagesStore) and presto-blackhole utility connectors (SURVEY.md §2.8).

Same duck-typed connector contract as hive.py (catalog.register_connector):
SCHEMAS/PREFIXES/OPEN_DOMAIN/ROWID_*/table_row_count/generate_column/
generate_values_at/column_stats, with begin_write/staged/drop_table for
CTAS/INSERT (staged-then-commit, so aborted writes leave nothing behind —
TableWriterOperator.java:78 + TableFinishOperator semantics).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.block import block_to_values
from ..common.types import (BooleanType, CharType, DateType, DecimalType,
                            DoubleType, IntegerType, RealType, Type,
                            VarcharType)
from .catalog import HostColumn

_staging_ids = itertools.count(1)


class _MemTable:
    def __init__(self, schema: List[Tuple[str, Type]]):
        self.schema = schema
        # column name -> (list of python values, list of null flags)
        self.columns: Dict[str, tuple] = {n: ([], []) for n, _ in schema}
        self.rows = 0
        self._dicts: Dict[str, tuple] = {}   # table-wide varchar dicts

    def append_page(self, names: List[str], types: List[Type], page) -> int:
        for name, typ, block in zip(names, types, page.blocks):
            vals, nulls = self.columns[name]
            for v in block_to_values(typ, block):
                nulls.append(v is None)
                vals.append(v)
        self.rows += page.position_count
        self._dicts.clear()
        return page.position_count

    def read(self, column: str, start: int, count: int):
        vals, nulls = self.columns[column]
        typ = dict(self.schema)[column]
        if isinstance(typ, (VarcharType, CharType)):
            # dictionary must be TABLE-WIDE: scan chunks share one
            # code->string mapping (the engine groups/joins by codes)
            ent = self._dicts.get(column)
            if ent is None:
                uniq = sorted({v for v, n in zip(vals, nulls)
                               if not n and v is not None})
                index = {s: i for i, s in enumerate(uniq)}
                ent = (uniq or [""], index)
                self._dicts[column] = ent
            uniq, index = ent
            codes = np.array(
                [0 if (n or v is None) else index[v]
                 for v, n in zip(vals[start:start + count],
                                 nulls[start:start + count])],
                dtype=np.int32)
            nsel = nulls[start:start + count]
            if any(nsel):
                return HostColumn((codes, list(uniq)),
                                  np.array(nsel, dtype=bool))
            return (codes, list(uniq))
        sel = vals[start:start + count]
        nsel = nulls[start:start + count]
        return _to_connector_column(typ, sel, nsel)

    def values_at(self, column: str, ids) -> list:
        vals, nulls = self.columns[column]
        return [None if nulls[i] else vals[i] for i in np.asarray(ids)]


def _to_connector_column(typ: Type, vals: list, nulls: list):
    if isinstance(typ, (VarcharType, CharType)):
        uniq = sorted({v for v, n in zip(vals, nulls) if not n and
                       v is not None})
        index = {s: i for i, s in enumerate(uniq)}
        codes = np.array([0 if (n or v is None) else index[v]
                          for v, n in zip(vals, nulls)], dtype=np.int32)
        out = (codes, uniq or [""])
    elif isinstance(typ, DecimalType):
        scale = 10 ** typ.scale
        out = np.array([0 if n else int(round(float(v) * scale))
                        for v, n in zip(vals, nulls)], dtype=np.int64)
    elif isinstance(typ, (DoubleType, RealType)):
        out = np.array([0.0 if n else float(v)
                        for v, n in zip(vals, nulls)], dtype=np.float64)
    elif isinstance(typ, BooleanType):
        out = np.array([False if n else bool(v)
                        for v, n in zip(vals, nulls)], dtype=bool)
    elif isinstance(typ, DateType):
        import datetime
        epoch = datetime.date(1970, 1, 1)

        def days(v):
            if isinstance(v, str):
                v = datetime.date.fromisoformat(v)
            if isinstance(v, datetime.date):
                return (v - epoch).days
            return int(v)
        out = np.array([0 if n else days(v)
                        for v, n in zip(vals, nulls)], dtype=np.int32)
    else:
        dt = np.int32 if isinstance(typ, IntegerType) else np.int64
        out = np.array([0 if n else int(v)
                        for v, n in zip(vals, nulls)], dtype=dt)
    if any(nulls):
        return HostColumn(out, np.array(nulls, dtype=bool))
    return out


class _WriteHandle:
    def __init__(self, conn: "MemoryConnector", table: str,
                 names: List[str], types: List[Type]):
        self.conn = conn
        self.table = table
        self.names = names
        self.types = types
        self.staging_id = f"mem-{next(_staging_ids)}"
        self._staged = _MemTable(list(zip(names, types)))
        conn._staged[self.staging_id] = self

    def write_page(self, page) -> int:
        return self._staged.append_page(self.names, self.types, page)

    def commit(self) -> None:
        existing = self.conn._tables.get(self.table)
        if existing is None:
            self.conn._tables[self.table] = self._staged
        else:
            for name, (v, nl) in self._staged.columns.items():
                ev, en = existing.columns[name]
                ev.extend(v)
                en.extend(nl)
            existing.rows += self._staged.rows
        self.conn._staged.pop(self.staging_id, None)

    def abort(self) -> None:
        self.conn._staged.pop(self.staging_id, None)


class MemoryConnector:
    """Writable RAM-resident tables (presto-memory analog)."""

    OPEN_DOMAIN: set = set()
    ROWID_ORDERED: set = set()
    ROWID_DISTINCT: set = set()

    def __init__(self):
        self._tables: Dict[str, _MemTable] = {}
        self._staged: Dict[str, _WriteHandle] = {}

    @property
    def SCHEMAS(self):
        return {n: t.schema for n, t in self._tables.items()}

    @property
    def PREFIXES(self):
        return {n: "" for n in self._tables}

    def column_type(self, table: str, column: str) -> Type:
        return dict(self._tables[table].schema)[column]

    def table_row_count(self, table: str, sf: float) -> int:
        return self._tables[table].rows

    def generate_column(self, table: str, column: str, sf: float,
                        start: int, count: int):
        return self._tables[table].read(column, start, count)

    def generate_values_at(self, table: str, column: str, sf: float, ids):
        return self._tables[table].values_at(column, ids)

    def column_stats(self, table: str, column: str, sf: float):
        return None

    def begin_write(self, table: str, names: List[str],
                    types: List[Type]) -> _WriteHandle:
        return _WriteHandle(self, table, names, types)

    def staged(self, staging_id: str) -> _WriteHandle:
        return self._staged[staging_id]

    def drop_table(self, table: str):
        if table not in self._tables:
            raise KeyError(f"unknown table {table!r}")
        del self._tables[table]


class _BlackholeHandle:
    def __init__(self, conn, table):
        self.conn = conn
        self.staging_id = f"bh-{next(_staging_ids)}"
        conn._staged[self.staging_id] = self
        self.rows = 0

    def write_page(self, page) -> int:
        self.rows += page.position_count
        return page.position_count

    def commit(self) -> None:
        self.conn._staged.pop(self.staging_id, None)

    def abort(self) -> None:
        self.conn._staged.pop(self.staging_id, None)


class BlackholeConnector:
    """Swallows writes, serves no rows (presto-blackhole analog: the
    write-throughput benchmarking sink)."""

    OPEN_DOMAIN: set = set()
    ROWID_ORDERED: set = set()
    ROWID_DISTINCT: set = set()
    SCHEMAS: Dict[str, list] = {}
    PREFIXES: Dict[str, str] = {}

    def __init__(self):
        self._staged: Dict[str, _BlackholeHandle] = {}

    def begin_write(self, table, names, types) -> _BlackholeHandle:
        return _BlackholeHandle(self, table)

    def staged(self, staging_id: str) -> _BlackholeHandle:
        return self._staged[staging_id]
