"""SQLite cross-engine backend for the verifier: a second, fully
independent SQL engine (parser, planner, executor all from sqlite3) over
the SAME TPC-H data, giving the correctness anchor the round-1 verdict
asked for — engine-vs-own-oracle shares the plan IR, engine-vs-sqlite
shares only the generated rows.

The analog of the reference's H2 differential harness
(presto-tests/.../QueryAssertions.java:52 runs every query on Presto and
on H2 over identical TPC-H tables) with sqlite in H2's seat.

Storage mapping: BIGINT/INTEGER -> INTEGER, DOUBLE -> REAL,
DECIMAL(p,s) -> REAL (descaled; compared with float tolerance),
DATE -> INTEGER epoch days (queries use day('1994-01-01') literals),
VARCHAR/CHAR -> TEXT.
"""
from __future__ import annotations

import datetime
import sqlite3
from typing import Dict, List, Optional

import numpy as np

from ..common.types import (CharType, DateType, DecimalType, DoubleType,
                            RealType, VarcharType)
from . import catalog


def day(iso: str) -> int:
    """Epoch-day literal for sqlite query texts (our DATE storage)."""
    return (datetime.date.fromisoformat(iso)
            - datetime.date(1970, 1, 1)).days


_CHUNK = 1 << 16


def export_table(conn: sqlite3.Connection, table: str, sf: float,
                 connector_id: Optional[str] = None) -> None:
    cid = connector_id or catalog.resolve_table(table)
    schema = catalog.schema(table, cid)
    names = [n for n, _t in schema]
    types = [t for _n, t in schema]
    cols_sql = ", ".join(
        f"{n} {_sqlite_type(t)}" for n, t in schema)
    conn.execute(f"DROP TABLE IF EXISTS {table}")
    conn.execute(f"CREATE TABLE {table} ({cols_sql})")
    total = catalog.table_row_count(table, sf, cid)
    placeholders = ", ".join("?" * len(names))
    for start in range(0, total, _CHUNK):
        n = min(_CHUNK, total - start)
        cols = []
        for name, typ in zip(names, types):
            raw = catalog.generate_column(table, name, sf, start, n, cid)
            nulls = None
            if isinstance(raw, catalog.HostColumn):
                nulls = raw.nulls
                raw = raw.values
            if isinstance(raw, tuple):
                codes, values = raw
                vals = [values[c] for c in codes]
            elif isinstance(raw, list):
                vals = raw
            else:
                arr = np.asarray(raw)
                if isinstance(typ, DecimalType):
                    vals = (arr.astype(np.float64)
                            / (10.0 ** typ.scale)).tolist()
                elif isinstance(typ, (DoubleType, RealType)):
                    vals = arr.astype(np.float64).tolist()
                else:
                    vals = arr.tolist()
            if nulls is not None:
                vals = [None if nu else v for v, nu in zip(vals, nulls)]
            cols.append(vals)
        conn.executemany(
            f"INSERT INTO {table} VALUES ({placeholders})",
            list(zip(*cols)))
    conn.commit()


def _sqlite_type(t) -> str:
    if isinstance(t, (DoubleType, RealType, DecimalType)):
        return "REAL"
    if isinstance(t, (VarcharType, CharType)):
        return "TEXT"
    return "INTEGER"      # bigint / integer / date(epoch days) / boolean


class SqliteRunner:
    """Executes query text against the exported TPC-H tables; returns an
    object shaped like exec.runner.QueryResult for the verifier."""

    def __init__(self, sf: float, tables: Optional[List[str]] = None):
        self.conn = sqlite3.connect(":memory:")
        for t in tables or ("nation", "region", "supplier", "customer",
                            "part", "partsupp", "orders", "lineitem"):
            export_table(self.conn, t, sf)

    def execute(self, sql: str):
        from ..exec.runner import QueryResult
        cur = self.conn.execute(sql)
        names = [d[0] for d in cur.description]
        rows = [list(r) for r in cur.fetchall()]
        return QueryResult(names, [None] * len(names), rows)
