"""TPC-DS connector: deterministic in-memory columnar data generator.

The analog of the reference's presto-tpcds connector (presto-tpcds/
src/main/java/com/facebook/presto/tpcds/TpcdsConnectorFactory.java, backed by
the teradata dsdgen port) built on the same counter-hash scheme as the tpch
module: every cell is a pure function of (table, column, row index, scale
factor), so splits are stateless and workers generate their own shards.

Covers the dimensional core of the TPC-DS schema (date_dim, item, customer,
customer_address, store, web_site, warehouse, promotion) and the two biggest
fact-table families exercised by the BASELINE queries (store_sales,
web_sales + web_returns — TPC-DS Q95 is baseline config 5).  Row counts
follow the spec's SF1 values scaled linearly (dimension tables fixed or
floored); value distributions are self-consistent rather than dsdgen
bit-exact — correctness testing is differential (TPU engine vs the numpy
reference interpreter over identical generated data), as for tpch.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..common.types import (BIGINT, DATE, INTEGER, Type, DecimalType,
                            VarcharType)
# hashing core shared with tpch; seeds are namespaced "tpcds.<table>" so the
# two connectors' value streams stay independent
from .tpch import _splitmix64, _stream_seed


def _hash(table: str, column: str, idx: np.ndarray) -> np.ndarray:
    seed = _stream_seed("tpcds." + table, column)
    with np.errstate(over="ignore"):
        return _splitmix64(idx.astype(np.uint64)
                           * np.uint64(0x9E3779B97F4A7C15) + seed)


def _uniform(table, column, idx, lo, hi):
    h = _hash(table, column, idx)
    span = np.uint64(hi - lo + 1)
    return (h % span).astype(np.int64) + lo


def _days(datestr: str) -> int:
    return int(np.datetime64(datestr, "D").astype(np.int64))


# d_date_sk convention: Julian day number, 2415022 == 1900-01-02 (spec);
# date_dim row i is calendar day 1900-01-02 + i
JULIAN_BASE = 2415022
EPOCH_1900 = _days("1900-01-02")          # days since unix epoch (negative)
DATE_DIM_ROWS = 73049                     # 1900-01-02 .. 2100-01-01

# fact sales window (spec: 5 years ending 2003-01-02)
SALES_MIN = _days("1998-01-02") - EPOCH_1900
SALES_MAX = _days("2002-11-02") - EPOCH_1900

STATES = ["AL", "CA", "CO", "FL", "GA", "IA", "IL", "IN", "KS", "KY", "LA",
          "MI", "MN", "MO", "NC", "ND", "NE", "NY", "OH", "OK", "PA", "SD",
          "TN", "TX", "VA"]
CITIES = [f"{a} {b}" for a in ("Pleasant", "Oak", "Spring", "Center",
                               "Fair", "Green", "Union", "Walnut", "Cedar",
                               "Liberty")
          for b in ("Hill", "Grove", "Valley", "Ridge", "Creek", "Point")]
COUNTIES = [f"{c} County" for c in ("Williamson", "Walker", "Barrow",
                                    "Franklin", "Bronx", "Orange", "Jackson",
                                    "Mobile", "Salem", "Ziebach")]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry", "Men",
              "Music", "Shoes", "Sports", "Women"]
CLASSES = [f"{c} class {i}" for c in ("value", "economy", "standard",
                                      "premium", "luxury") for i in range(1, 5)]
COLORS = ["almond", "azure", "beige", "black", "blue", "brown", "coral",
          "cream", "cyan", "gold", "green", "grey", "indigo", "ivory",
          "khaki", "lime", "maroon", "navy", "olive", "orange", "peach",
          "pink", "plum", "purple", "red"]
BRANDS = [f"{m}brand #{i}" for m in ("amalg", "edu pack", "expo", "scholar",
                                     "import", "corp", "brand", "univ",
                                     "name", "max")
          for i in range(1, 11)]
FIRST_NAMES = ["James", "John", "Robert", "Michael", "William", "David",
               "Mary", "Patricia", "Linda", "Barbara", "Elizabeth", "Susan",
               "Jose", "Carlos", "Anna", "Laura", "Kevin", "Brian", "Sarah",
               "Emily", "Daniel", "Matthew", "Nancy", "Karen", "Paul"]
LAST_NAMES = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
              "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez",
              "Lopez", "Gonzalez", "Wilson", "Anderson", "Thomas", "Taylor",
              "Moore", "Jackson", "Martin", "Lee", "Perez", "Thompson",
              "White", "Harris"]
COMPANY_NAMES = ["pri", "able", "ought", "ation", "eing", "bar"]
WAREHOUSE_NAMES = ["Conventional childr", "Important issues liv",
                   "Doors canno", "Bad cards must make.", "Rooms cook "]
YN = ["N", "Y"]

LINES_PER_ORDER = 3


def _table_rows(table: str, sf: float) -> int:
    fixed = {"date_dim": DATE_DIM_ROWS, "web_site": 30, "warehouse": 5,
             "promotion": 300}
    if table in fixed:
        return fixed[table]
    if table == "store":
        return max(2, int(12 * sf))
    base = {
        "item": 18_000, "customer": 100_000, "customer_address": 50_000,
        "store_sales": 2_880_000, "web_sales": 720_000,
        "web_returns": 72_000,
    }
    floor = {"item": 200, "customer": 1_000, "customer_address": 500,
             "store_sales": 10_000, "web_sales": 7_200, "web_returns": 720}
    return max(floor[table], int(base[table] * sf))


D7_2 = DecimalType(7, 2)
D5_2 = DecimalType(5, 2)

SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
    "date_dim": [
        ("d_date_sk", BIGINT), ("d_date_id", VarcharType(16)),
        ("d_date", DATE), ("d_month_seq", INTEGER), ("d_week_seq", INTEGER),
        ("d_quarter_seq", INTEGER), ("d_year", INTEGER), ("d_dow", INTEGER),
        ("d_moy", INTEGER), ("d_dom", INTEGER), ("d_qoy", INTEGER),
        ("d_day_name", VarcharType(9)),
    ],
    "item": [
        ("i_item_sk", BIGINT), ("i_item_id", VarcharType(16)),
        ("i_current_price", D7_2), ("i_brand_id", INTEGER),
        ("i_brand", VarcharType(50)), ("i_class_id", INTEGER),
        ("i_class", VarcharType(50)), ("i_category_id", INTEGER),
        ("i_category", VarcharType(50)), ("i_manufact_id", INTEGER),
        ("i_color", VarcharType(20)), ("i_manager_id", INTEGER),
    ],
    "customer": [
        ("c_customer_sk", BIGINT), ("c_customer_id", VarcharType(16)),
        ("c_current_addr_sk", BIGINT), ("c_first_name", VarcharType(20)),
        ("c_last_name", VarcharType(30)), ("c_birth_year", INTEGER),
        ("c_birth_month", INTEGER), ("c_birth_country", VarcharType(20)),
        ("c_email_address", VarcharType(50)),
    ],
    "customer_address": [
        ("ca_address_sk", BIGINT), ("ca_address_id", VarcharType(16)),
        ("ca_city", VarcharType(60)), ("ca_county", VarcharType(30)),
        ("ca_state", VarcharType(2)), ("ca_zip", VarcharType(10)),
        ("ca_country", VarcharType(20)), ("ca_gmt_offset", D5_2),
    ],
    "store": [
        ("s_store_sk", BIGINT), ("s_store_id", VarcharType(16)),
        ("s_store_name", VarcharType(50)), ("s_number_employees", INTEGER),
        ("s_floor_space", INTEGER), ("s_market_id", INTEGER),
        ("s_state", VarcharType(2)), ("s_company_id", INTEGER),
    ],
    "web_site": [
        ("web_site_sk", BIGINT), ("web_site_id", VarcharType(16)),
        ("web_name", VarcharType(50)), ("web_company_id", INTEGER),
        ("web_company_name", VarcharType(50)),
    ],
    "warehouse": [
        ("w_warehouse_sk", BIGINT), ("w_warehouse_name", VarcharType(20)),
        ("w_warehouse_sq_ft", INTEGER), ("w_state", VarcharType(2)),
    ],
    "promotion": [
        ("p_promo_sk", BIGINT), ("p_promo_id", VarcharType(16)),
        ("p_channel_dmail", VarcharType(1)), ("p_channel_email", VarcharType(1)),
        ("p_channel_tv", VarcharType(1)),
    ],
    "store_sales": [
        ("ss_sold_date_sk", BIGINT), ("ss_item_sk", BIGINT),
        ("ss_customer_sk", BIGINT), ("ss_store_sk", BIGINT),
        ("ss_promo_sk", BIGINT), ("ss_ticket_number", BIGINT),
        ("ss_quantity", INTEGER), ("ss_wholesale_cost", D7_2),
        ("ss_list_price", D7_2), ("ss_sales_price", D7_2),
        ("ss_ext_discount_amt", D7_2), ("ss_ext_sales_price", D7_2),
        ("ss_net_paid", D7_2), ("ss_net_profit", D7_2),
    ],
    "web_sales": [
        ("ws_sold_date_sk", BIGINT), ("ws_ship_date_sk", BIGINT),
        ("ws_item_sk", BIGINT), ("ws_bill_customer_sk", BIGINT),
        ("ws_ship_addr_sk", BIGINT), ("ws_web_site_sk", BIGINT),
        ("ws_warehouse_sk", BIGINT), ("ws_promo_sk", BIGINT),
        ("ws_order_number", BIGINT), ("ws_quantity", INTEGER),
        ("ws_sales_price", D7_2), ("ws_ext_sales_price", D7_2),
        ("ws_ext_ship_cost", D7_2), ("ws_net_paid", D7_2),
        ("ws_net_profit", D7_2),
    ],
    "web_returns": [
        ("wr_returned_date_sk", BIGINT), ("wr_item_sk", BIGINT),
        ("wr_refunded_customer_sk", BIGINT), ("wr_order_number", BIGINT),
        ("wr_return_quantity", INTEGER), ("wr_return_amt", D7_2),
        ("wr_net_loss", D7_2),
    ],
}

# every table already carries its spec prefix in the column names
PREFIXES: Dict[str, str] = {t: "" for t in SCHEMAS}


def column_type(table: str, column: str) -> Type:
    for name, typ in SCHEMAS[table]:
        if name == column:
            return typ
    raise KeyError(f"{table}.{column}")


# open-domain (late-materialized) string columns, and which of them have
# row-id-compatible order / identity (see tpch.py for the rules)
OPEN_DOMAIN = {
    ("item", "i_item_id"), ("customer", "c_customer_id"),
    ("customer", "c_email_address"), ("customer_address", "ca_address_id"),
    ("customer_address", "ca_zip"), ("store", "s_store_id"),
    ("web_site", "web_site_id"), ("promotion", "p_promo_id"),
}
ROWID_ORDERED = {
    ("item", "i_item_id"), ("customer", "c_customer_id"),
    ("customer_address", "ca_address_id"), ("store", "s_store_id"),
    ("web_site", "web_site_id"), ("promotion", "p_promo_id"),
}
ROWID_DISTINCT = {
    ("item", "i_item_id"), ("customer", "c_customer_id"),
    ("customer", "c_email_address"), ("customer_address", "ca_address_id"),
    ("store", "s_store_id"), ("web_site", "web_site_id"),
    ("promotion", "p_promo_id"),
}


# ---------------------------------------------------------------------------
# per-table generators (same contract as tpch: numeric ndarray, or
# (codes, values) dictionary, or list[str] for OPEN_DOMAIN columns)
# ---------------------------------------------------------------------------

def _gen_date_dim(column: str, idx: np.ndarray, sf: float):
    days = EPOCH_1900 + idx                       # days since unix epoch
    dt = days.astype("datetime64[D]")
    if column == "d_date_sk":
        return JULIAN_BASE + idx
    if column == "d_date_id":
        return [f"AAAAAAAA{int(v):08d}" for v in JULIAN_BASE + idx]
    if column == "d_date":
        return days
    if column == "d_year":
        return dt.astype("datetime64[Y]").astype(np.int64) + 1970
    if column == "d_moy":
        return (dt.astype("datetime64[M]")
                - dt.astype("datetime64[Y]")).astype(np.int64) + 1
    if column == "d_dom":
        return (dt - dt.astype("datetime64[M]")).astype(np.int64) + 1
    if column == "d_qoy":
        moy = _gen_date_dim("d_moy", idx, sf)
        return (moy - 1) // 3 + 1
    if column == "d_dow":
        return (days + 4) % 7                     # 1970-01-01 was a Thursday
    if column == "d_day_name":
        return (((days + 4) % 7).astype(np.int32), DAY_NAMES)
    if column == "d_month_seq":
        y = _gen_date_dim("d_year", idx, sf)
        m = _gen_date_dim("d_moy", idx, sf)
        return (y - 1900) * 12 + m - 1
    if column == "d_week_seq":
        return idx // 7 + 1
    if column == "d_quarter_seq":
        y = _gen_date_dim("d_year", idx, sf)
        q = _gen_date_dim("d_qoy", idx, sf)
        return (y - 1900) * 4 + q - 1
    raise KeyError(column)


def _gen_item(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "i_item_sk":
        return sk
    if column == "i_item_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "i_current_price":
        return _uniform("item", "price", idx, 99, 9999)
    if column == "i_brand_id":
        return _uniform("item", "brand", idx, 0, len(BRANDS) - 1) + 1001
    if column == "i_brand":
        return (_uniform("item", "brand", idx, 0,
                         len(BRANDS) - 1).astype(np.int32), BRANDS)
    if column == "i_class_id":
        return _uniform("item", "class", idx, 0, len(CLASSES) - 1) + 1
    if column == "i_class":
        return (_uniform("item", "class", idx, 0,
                         len(CLASSES) - 1).astype(np.int32), CLASSES)
    if column == "i_category_id":
        return _uniform("item", "category", idx, 0, len(CATEGORIES) - 1) + 1
    if column == "i_category":
        return (_uniform("item", "category", idx, 0,
                         len(CATEGORIES) - 1).astype(np.int32), CATEGORIES)
    if column == "i_manufact_id":
        return _uniform("item", "manufact", idx, 1, 1000)
    if column == "i_color":
        return (_uniform("item", "color", idx, 0,
                         len(COLORS) - 1).astype(np.int32), COLORS)
    if column == "i_manager_id":
        return _uniform("item", "manager", idx, 1, 100)
    raise KeyError(column)


def _gen_customer(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "c_customer_sk":
        return sk
    if column == "c_customer_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "c_current_addr_sk":
        return _uniform("customer", "addr", idx, 1,
                        _table_rows("customer_address", sf))
    if column == "c_first_name":
        return (_uniform("customer", "first", idx, 0,
                         len(FIRST_NAMES) - 1).astype(np.int32), FIRST_NAMES)
    if column == "c_last_name":
        return (_uniform("customer", "last", idx, 0,
                         len(LAST_NAMES) - 1).astype(np.int32), LAST_NAMES)
    if column == "c_birth_year":
        return _uniform("customer", "byear", idx, 1924, 1992)
    if column == "c_birth_month":
        return _uniform("customer", "bmonth", idx, 1, 12)
    if column == "c_birth_country":
        return (_uniform("customer", "bcountry", idx, 0, 4).astype(np.int32),
                ["UNITED STATES", "CANADA", "MEXICO", "GERMANY", "JAPAN"])
    if column == "c_email_address":
        h = _hash("customer", "email", idx)
        return [f"user{int(v):016x}@example.com" for v in h]
    raise KeyError(column)


def _gen_customer_address(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "ca_address_sk":
        return sk
    if column == "ca_address_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "ca_city":
        return (_uniform("customer_address", "city", idx, 0,
                         len(CITIES) - 1).astype(np.int32), CITIES)
    if column == "ca_county":
        return (_uniform("customer_address", "county", idx, 0,
                         len(COUNTIES) - 1).astype(np.int32), COUNTIES)
    if column == "ca_state":
        return (_uniform("customer_address", "state", idx, 0,
                         len(STATES) - 1).astype(np.int32), STATES)
    if column == "ca_zip":
        z = _uniform("customer_address", "zip", idx, 10000, 99999)
        return [f"{int(v):05d}" for v in z]
    if column == "ca_country":
        return (np.zeros(len(idx), dtype=np.int32), ["United States"])
    if column == "ca_gmt_offset":
        return -100 * _uniform("customer_address", "gmt", idx, 5, 8)
    raise KeyError(column)


def _gen_store(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "s_store_sk":
        return sk
    if column == "s_store_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "s_store_name":
        return (_uniform("store", "name", idx, 0, 9).astype(np.int32),
                ["ought", "able", "pri", "ese", "anti", "cally", "ation",
                 "eing", "n st", "bar"])
    if column == "s_number_employees":
        return _uniform("store", "employees", idx, 200, 300)
    if column == "s_floor_space":
        return _uniform("store", "floor", idx, 5_000_000, 10_000_000)
    if column == "s_market_id":
        return _uniform("store", "market", idx, 1, 10)
    if column == "s_state":
        return (_uniform("store", "state", idx, 0,
                         len(STATES) - 1).astype(np.int32), STATES)
    if column == "s_company_id":
        return np.ones(len(idx), dtype=np.int64)
    raise KeyError(column)


def _gen_web_site(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "web_site_sk":
        return sk
    if column == "web_site_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "web_name":
        return ((idx % 15).astype(np.int32),
                [f"site_{i}" for i in range(15)])
    if column == "web_company_id":
        return idx % 6 + 1
    if column == "web_company_name":
        return ((idx % 6).astype(np.int32), COMPANY_NAMES)
    raise KeyError(column)


def _gen_warehouse(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "w_warehouse_sk":
        return sk
    if column == "w_warehouse_name":
        return ((idx % 5).astype(np.int32), WAREHOUSE_NAMES)
    if column == "w_warehouse_sq_ft":
        return _uniform("warehouse", "sqft", idx, 50_000, 1_000_000)
    if column == "w_state":
        return ((idx % len(STATES)).astype(np.int32), STATES)
    raise KeyError(column)


def _gen_promotion(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "p_promo_sk":
        return sk
    if column == "p_promo_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column in ("p_channel_dmail", "p_channel_email", "p_channel_tv"):
        return (_uniform("promotion", column, idx, 0, 1).astype(np.int32), YN)
    raise KeyError(column)


def _date_sk_from_offset(off: np.ndarray) -> np.ndarray:
    """days-since-1900 offset -> d_date_sk (date_dim row i == offset i)."""
    return JULIAN_BASE + off


def _gen_store_sales(column: str, idx: np.ndarray, sf: float):
    if column == "ss_sold_date_sk":
        return _date_sk_from_offset(
            _uniform("store_sales", "sold", idx // LINES_PER_ORDER,
                     SALES_MIN, SALES_MAX))
    if column == "ss_item_sk":
        return _uniform("store_sales", "item", idx, 1, _table_rows("item", sf))
    if column == "ss_customer_sk":
        return _uniform("store_sales", "cust", idx // LINES_PER_ORDER, 1,
                        _table_rows("customer", sf))
    if column == "ss_store_sk":
        return _uniform("store_sales", "store", idx // LINES_PER_ORDER, 1,
                        _table_rows("store", sf))
    if column == "ss_promo_sk":
        return _uniform("store_sales", "promo", idx, 1,
                        _table_rows("promotion", sf))
    if column == "ss_ticket_number":
        return idx // LINES_PER_ORDER + 1
    if column == "ss_quantity":
        return _uniform("store_sales", "qty", idx, 1, 100)
    if column == "ss_wholesale_cost":
        return _uniform("store_sales", "wholesale", idx, 100, 10000)
    if column == "ss_list_price":
        w = _gen_store_sales("ss_wholesale_cost", idx, sf)
        return w + w * _uniform("store_sales", "markup", idx, 0, 200) // 100
    if column == "ss_sales_price":
        lp = _gen_store_sales("ss_list_price", idx, sf)
        return lp * _uniform("store_sales", "dscnt", idx, 20, 100) // 100
    if column == "ss_ext_sales_price":
        return (_gen_store_sales("ss_sales_price", idx, sf)
                * _gen_store_sales("ss_quantity", idx, sf))
    if column == "ss_ext_discount_amt":
        lp = _gen_store_sales("ss_list_price", idx, sf)
        sp = _gen_store_sales("ss_sales_price", idx, sf)
        return (lp - sp) * _gen_store_sales("ss_quantity", idx, sf)
    if column == "ss_net_paid":
        return _gen_store_sales("ss_ext_sales_price", idx, sf)
    if column == "ss_net_profit":
        q = _gen_store_sales("ss_quantity", idx, sf)
        w = _gen_store_sales("ss_wholesale_cost", idx, sf)
        return _gen_store_sales("ss_net_paid", idx, sf) - q * w
    raise KeyError(column)


def _gen_web_sales(column: str, idx: np.ndarray, sf: float):
    order = idx // LINES_PER_ORDER
    if column == "ws_sold_date_sk":
        return _date_sk_from_offset(
            _uniform("web_sales", "sold", order, SALES_MIN, SALES_MAX))
    if column == "ws_ship_date_sk":
        sold = _uniform("web_sales", "sold", order, SALES_MIN, SALES_MAX)
        return _date_sk_from_offset(
            sold + _uniform("web_sales", "lag", idx, 1, 120))
    if column == "ws_item_sk":
        return _uniform("web_sales", "item", idx, 1, _table_rows("item", sf))
    if column == "ws_bill_customer_sk":
        return _uniform("web_sales", "cust", order, 1,
                        _table_rows("customer", sf))
    if column == "ws_ship_addr_sk":
        return _uniform("web_sales", "addr", order, 1,
                        _table_rows("customer_address", sf))
    if column == "ws_web_site_sk":
        return _uniform("web_sales", "site", order, 1,
                        _table_rows("web_site", sf))
    if column == "ws_warehouse_sk":
        return _uniform("web_sales", "wh", idx, 1,
                        _table_rows("warehouse", sf))
    if column == "ws_promo_sk":
        return _uniform("web_sales", "promo", idx, 1,
                        _table_rows("promotion", sf))
    if column == "ws_order_number":
        return order + 1
    if column == "ws_quantity":
        return _uniform("web_sales", "qty", idx, 1, 100)
    if column == "ws_sales_price":
        return _uniform("web_sales", "price", idx, 100, 30000)
    if column == "ws_ext_sales_price":
        return (_gen_web_sales("ws_sales_price", idx, sf)
                * _gen_web_sales("ws_quantity", idx, sf))
    if column == "ws_ext_ship_cost":
        return _uniform("web_sales", "shipcost", idx, 0, 50000)
    if column == "ws_net_paid":
        return _gen_web_sales("ws_ext_sales_price", idx, sf)
    if column == "ws_net_profit":
        return (_gen_web_sales("ws_net_paid", idx, sf)
                - _uniform("web_sales", "cost", idx, 50, 40000)
                * _gen_web_sales("ws_quantity", idx, sf))
    raise KeyError(column)


def _gen_web_returns(column: str, idx: np.ndarray, sf: float):
    n_orders = _table_rows("web_sales", sf) // LINES_PER_ORDER
    if column == "wr_order_number":
        return _uniform("web_returns", "order", idx, 1, max(1, n_orders))
    if column == "wr_returned_date_sk":
        return _date_sk_from_offset(
            _uniform("web_returns", "ret", idx, SALES_MIN, SALES_MAX + 60))
    if column == "wr_item_sk":
        return _uniform("web_returns", "item", idx, 1,
                        _table_rows("item", sf))
    if column == "wr_refunded_customer_sk":
        return _uniform("web_returns", "cust", idx, 1,
                        _table_rows("customer", sf))
    if column == "wr_return_quantity":
        return _uniform("web_returns", "qty", idx, 1, 50)
    if column == "wr_return_amt":
        return _uniform("web_returns", "amt", idx, 100, 500000)
    if column == "wr_net_loss":
        return _uniform("web_returns", "loss", idx, 50, 100000)
    raise KeyError(column)


_GENERATORS = {
    "date_dim": _gen_date_dim, "item": _gen_item, "customer": _gen_customer,
    "customer_address": _gen_customer_address, "store": _gen_store,
    "web_site": _gen_web_site, "warehouse": _gen_warehouse,
    "promotion": _gen_promotion, "store_sales": _gen_store_sales,
    "web_sales": _gen_web_sales, "web_returns": _gen_web_returns,
}


# ---------------------------------------------------------------------------
# public connector API (same shape as tpch's)
# ---------------------------------------------------------------------------

def table_row_count(table: str, sf: float) -> int:
    return _table_rows(table, sf)


def generate_column(table: str, column: str, sf: float,
                    start: int, count: int):
    idx = np.arange(start, start + count, dtype=np.int64)
    return _GENERATORS[table](column, idx, sf)


def generate_values_at(table: str, column: str, sf: float,
                       ids: np.ndarray) -> list:
    out = _GENERATORS[table](column, np.asarray(ids, dtype=np.int64), sf)
    if isinstance(out, tuple):
        codes, values = out
        return [values[int(c)] for c in codes]
    return out


def _connector_stats(handle) -> float:
    sf = dict(handle.extra).get("scaleFactor", 0.01)
    return float(table_row_count(handle.table_name, sf))


from ..sql.fragmenter import register_connector_stats as _reg_stats  # noqa: E402

_reg_stats("tpcds", _connector_stats)
