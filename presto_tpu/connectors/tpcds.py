"""TPC-DS connector: deterministic in-memory columnar data generator.

The analog of the reference's presto-tpcds connector (presto-tpcds/
src/main/java/com/facebook/presto/tpcds/TpcdsConnectorFactory.java, backed by
the teradata dsdgen port) built on the same counter-hash scheme as the tpch
module: every cell is a pure function of (table, column, row index, scale
factor), so splits are stateless and workers generate their own shards.

Covers the dimensional core of the TPC-DS schema (date_dim, item, customer,
customer_address, store, web_site, warehouse, promotion) and the two biggest
fact-table families exercised by the BASELINE queries (store_sales,
web_sales + web_returns — TPC-DS Q95 is baseline config 5).  Row counts
follow the spec's SF1 values scaled linearly (dimension tables fixed or
floored); value distributions are self-consistent rather than dsdgen
bit-exact — correctness testing is differential (TPU engine vs the numpy
reference interpreter over identical generated data), as for tpch.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..common.types import (BIGINT, DATE, INTEGER, Type, DecimalType,
                            VarcharType)
# hashing core shared with tpch; seeds are namespaced "tpcds.<table>" so the
# two connectors' value streams stay independent
from .tpch import TableBucket, _splitmix64, _stream_seed


def _hash(table: str, column: str, idx: np.ndarray) -> np.ndarray:
    seed = _stream_seed("tpcds." + table, column)
    with np.errstate(over="ignore"):
        return _splitmix64(idx.astype(np.uint64)
                           * np.uint64(0x9E3779B97F4A7C15) + seed)


def _uniform(table, column, idx, lo, hi):
    h = _hash(table, column, idx)
    span = np.uint64(hi - lo + 1)
    return (h % span).astype(np.int64) + lo


def _days(datestr: str) -> int:
    return int(np.datetime64(datestr, "D").astype(np.int64))


# d_date_sk convention: Julian day number, 2415022 == 1900-01-02 (spec);
# date_dim row i is calendar day 1900-01-02 + i
JULIAN_BASE = 2415022
EPOCH_1900 = _days("1900-01-02")          # days since unix epoch (negative)
DATE_DIM_ROWS = 73049                     # 1900-01-02 .. 2100-01-01

# fact sales window (spec: 5 years ending 2003-01-02)
SALES_MIN = _days("1998-01-02") - EPOCH_1900
SALES_MAX = _days("2002-11-02") - EPOCH_1900

STATES = ["AL", "CA", "CO", "FL", "GA", "IA", "IL", "IN", "KS", "KY", "LA",
          "MI", "MN", "MO", "NC", "ND", "NE", "NY", "OH", "OK", "PA", "SD",
          "TN", "TX", "VA"]
CITIES = [f"{a} {b}" for a in ("Pleasant", "Oak", "Spring", "Center",
                               "Fair", "Green", "Union", "Walnut", "Cedar",
                               "Liberty")
          for b in ("Hill", "Grove", "Valley", "Ridge", "Creek", "Point")]
COUNTIES = [f"{c} County" for c in ("Williamson", "Walker", "Barrow",
                                    "Franklin", "Bronx", "Orange", "Jackson",
                                    "Mobile", "Salem", "Ziebach")]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry", "Men",
              "Music", "Shoes", "Sports", "Women"]
CLASSES = [f"{c} class {i}" for c in ("value", "economy", "standard",
                                      "premium", "luxury") for i in range(1, 5)]
COLORS = ["almond", "azure", "beige", "black", "blue", "brown", "coral",
          "cream", "cyan", "gold", "green", "grey", "indigo", "ivory",
          "khaki", "lime", "maroon", "navy", "olive", "orange", "peach",
          "pink", "plum", "purple", "red"]
BRANDS = [f"{m}brand #{i}" for m in ("amalg", "edu pack", "expo", "scholar",
                                     "import", "corp", "brand", "univ",
                                     "name", "max")
          for i in range(1, 11)]
FIRST_NAMES = ["James", "John", "Robert", "Michael", "William", "David",
               "Mary", "Patricia", "Linda", "Barbara", "Elizabeth", "Susan",
               "Jose", "Carlos", "Anna", "Laura", "Kevin", "Brian", "Sarah",
               "Emily", "Daniel", "Matthew", "Nancy", "Karen", "Paul"]
LAST_NAMES = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
              "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez",
              "Lopez", "Gonzalez", "Wilson", "Anderson", "Thomas", "Taylor",
              "Moore", "Jackson", "Martin", "Lee", "Perez", "Thompson",
              "White", "Harris"]
COMPANY_NAMES = ["pri", "able", "ought", "ation", "eing", "bar"]
WAREHOUSE_NAMES = ["Conventional childr", "Important issues liv",
                   "Doors canno", "Bad cards must make.", "Rooms cook "]
YN = ["N", "Y"]

LINES_PER_ORDER = 3


INVENTORY_WEEKS = 261       # weekly snapshots, 1998-01-01 .. 2002-12-31


def _table_rows(table: str, sf: float) -> int:
    fixed = {"date_dim": DATE_DIM_ROWS, "web_site": 30, "warehouse": 5,
             "promotion": 300, "ship_mode": 20, "reason": 35,
             "income_band": 20, "household_demographics": 7_200,
             "customer_demographics": 1_920_800, "time_dim": 86_400,
             "call_center": 6, "catalog_page": 11_718, "web_page": 60}
    if table in fixed:
        return fixed[table]
    if table == "store":
        return max(2, int(12 * sf))
    if table == "inventory":
        # weekly (item x warehouse) snapshots, spec 2.5 layout
        return INVENTORY_WEEKS * _table_rows("item", sf) \
            * _table_rows("warehouse", sf)
    base = {
        "item": 18_000, "customer": 100_000, "customer_address": 50_000,
        "store_sales": 2_880_000, "web_sales": 720_000,
        "web_returns": 72_000, "catalog_sales": 1_440_000,
        "catalog_returns": 144_000, "store_returns": 288_000,
    }
    floor = {"item": 200, "customer": 1_000, "customer_address": 500,
             "store_sales": 10_000, "web_sales": 7_200, "web_returns": 720,
             "catalog_sales": 9_000, "catalog_returns": 900,
             "store_returns": 1_000}
    return max(floor[table], int(base[table] * sf))


D7_2 = DecimalType(7, 2)
D5_2 = DecimalType(5, 2)

SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
    "date_dim": [
        ("d_date_sk", BIGINT), ("d_date_id", VarcharType(16)),
        ("d_date", DATE), ("d_month_seq", INTEGER), ("d_week_seq", INTEGER),
        ("d_quarter_seq", INTEGER), ("d_year", INTEGER), ("d_dow", INTEGER),
        ("d_moy", INTEGER), ("d_dom", INTEGER), ("d_qoy", INTEGER),
        ("d_day_name", VarcharType(9)),
        ("d_quarter_name", VarcharType(6)),
    ],
    "item": [
        ("i_item_sk", BIGINT), ("i_item_id", VarcharType(16)),
        ("i_current_price", D7_2), ("i_brand_id", INTEGER),
        ("i_brand", VarcharType(50)), ("i_class_id", INTEGER),
        ("i_class", VarcharType(50)), ("i_category_id", INTEGER),
        ("i_category", VarcharType(50)), ("i_manufact_id", INTEGER),
        ("i_color", VarcharType(20)), ("i_manager_id", INTEGER),
        ("i_manufact", VarcharType(50)), ("i_product_name", VarcharType(50)),
        ("i_item_desc", VarcharType(200)), ("i_size", VarcharType(20)),
        ("i_units", VarcharType(10)), ("i_wholesale_cost", D7_2),
    ],
    "customer": [
        ("c_customer_sk", BIGINT), ("c_customer_id", VarcharType(16)),
        ("c_current_addr_sk", BIGINT), ("c_current_cdemo_sk", BIGINT),
        ("c_current_hdemo_sk", BIGINT),
        ("c_first_name", VarcharType(20)),
        ("c_last_name", VarcharType(30)), ("c_birth_year", INTEGER),
        ("c_birth_month", INTEGER), ("c_birth_country", VarcharType(20)),
        ("c_email_address", VarcharType(50)),
        ("c_preferred_cust_flag", VarcharType(1)),
        ("c_salutation", VarcharType(10)), ("c_login", VarcharType(13)),
        ("c_birth_day", INTEGER), ("c_first_sales_date_sk", BIGINT),
        ("c_first_shipto_date_sk", BIGINT),
        ("c_last_review_date_sk", BIGINT),
    ],
    "customer_address": [
        ("ca_address_sk", BIGINT), ("ca_address_id", VarcharType(16)),
        ("ca_city", VarcharType(60)), ("ca_county", VarcharType(30)),
        ("ca_state", VarcharType(2)), ("ca_zip", VarcharType(10)),
        ("ca_country", VarcharType(20)), ("ca_gmt_offset", D5_2),
        ("ca_street_number", VarcharType(10)),
        ("ca_street_name", VarcharType(60)),
        ("ca_street_type", VarcharType(15)),
        ("ca_suite_number", VarcharType(10)),
        ("ca_location_type", VarcharType(20)),
    ],
    "store": [
        ("s_store_sk", BIGINT), ("s_store_id", VarcharType(16)),
        ("s_store_name", VarcharType(50)), ("s_number_employees", INTEGER),
        ("s_floor_space", INTEGER), ("s_market_id", INTEGER),
        ("s_state", VarcharType(2)), ("s_company_id", INTEGER),
        ("s_city", VarcharType(60)), ("s_county", VarcharType(30)),
        ("s_zip", VarcharType(10)), ("s_gmt_offset", D5_2),
        ("s_street_number", VarcharType(10)),
        ("s_street_name", VarcharType(60)),
        ("s_street_type", VarcharType(15)),
        ("s_suite_number", VarcharType(10)),
        ("s_company_name", VarcharType(50)),
    ],
    "web_site": [
        ("web_site_sk", BIGINT), ("web_site_id", VarcharType(16)),
        ("web_name", VarcharType(50)), ("web_company_id", INTEGER),
        ("web_company_name", VarcharType(50)),
    ],
    "warehouse": [
        ("w_warehouse_sk", BIGINT), ("w_warehouse_name", VarcharType(20)),
        ("w_warehouse_sq_ft", INTEGER), ("w_state", VarcharType(2)),
        ("w_city", VarcharType(60)), ("w_county", VarcharType(30)),
        ("w_country", VarcharType(20)),
    ],
    "promotion": [
        ("p_promo_sk", BIGINT), ("p_promo_id", VarcharType(16)),
        ("p_channel_dmail", VarcharType(1)), ("p_channel_email", VarcharType(1)),
        ("p_channel_tv", VarcharType(1)),
        ("p_channel_event", VarcharType(1)),
        ("p_channel_catalog", VarcharType(1)),
    ],
    "store_sales": [
        ("ss_sold_date_sk", BIGINT), ("ss_sold_time_sk", BIGINT),
        ("ss_item_sk", BIGINT),
        ("ss_customer_sk", BIGINT), ("ss_cdemo_sk", BIGINT),
        ("ss_hdemo_sk", BIGINT), ("ss_addr_sk", BIGINT),
        ("ss_store_sk", BIGINT),
        ("ss_promo_sk", BIGINT), ("ss_ticket_number", BIGINT),
        ("ss_quantity", INTEGER), ("ss_wholesale_cost", D7_2),
        ("ss_list_price", D7_2), ("ss_sales_price", D7_2),
        ("ss_ext_discount_amt", D7_2), ("ss_ext_sales_price", D7_2),
        ("ss_ext_list_price", D7_2), ("ss_coupon_amt", D7_2),
        ("ss_net_paid", D7_2), ("ss_net_profit", D7_2),
        ("ss_ext_tax", D7_2), ("ss_ext_wholesale_cost", D7_2),
        ("ss_net_paid_inc_tax", D7_2),
    ],
    "web_sales": [
        ("ws_sold_date_sk", BIGINT), ("ws_ship_date_sk", BIGINT),
        ("ws_ship_mode_sk", BIGINT),
        ("ws_item_sk", BIGINT), ("ws_bill_customer_sk", BIGINT),
        ("ws_ship_addr_sk", BIGINT), ("ws_web_site_sk", BIGINT),
        ("ws_warehouse_sk", BIGINT), ("ws_promo_sk", BIGINT),
        ("ws_order_number", BIGINT), ("ws_quantity", INTEGER),
        ("ws_sales_price", D7_2), ("ws_ext_sales_price", D7_2),
        ("ws_ext_ship_cost", D7_2), ("ws_net_paid", D7_2),
        ("ws_net_profit", D7_2), ("ws_sold_time_sk", BIGINT),
        ("ws_bill_addr_sk", BIGINT), ("ws_bill_cdemo_sk", BIGINT),
        ("ws_bill_hdemo_sk", BIGINT), ("ws_ship_customer_sk", BIGINT),
        ("ws_ship_cdemo_sk", BIGINT), ("ws_ship_hdemo_sk", BIGINT),
        ("ws_web_page_sk", BIGINT), ("ws_wholesale_cost", D7_2),
        ("ws_list_price", D7_2), ("ws_ext_list_price", D7_2),
        ("ws_ext_discount_amt", D7_2), ("ws_ext_wholesale_cost", D7_2),
        ("ws_ext_tax", D7_2), ("ws_coupon_amt", D7_2),
        ("ws_net_paid_inc_tax", D7_2), ("ws_net_paid_inc_ship", D7_2),
    ],
    "web_returns": [
        ("wr_returned_date_sk", BIGINT), ("wr_item_sk", BIGINT),
        ("wr_refunded_customer_sk", BIGINT), ("wr_order_number", BIGINT),
        ("wr_return_quantity", INTEGER), ("wr_return_amt", D7_2),
        ("wr_net_loss", D7_2), ("wr_returning_customer_sk", BIGINT),
        ("wr_refunded_addr_sk", BIGINT), ("wr_returning_addr_sk", BIGINT),
        ("wr_refunded_cdemo_sk", BIGINT), ("wr_returning_cdemo_sk", BIGINT),
        ("wr_refunded_hdemo_sk", BIGINT), ("wr_web_page_sk", BIGINT),
        ("wr_reason_sk", BIGINT), ("wr_returned_time_sk", BIGINT),
        ("wr_refunded_cash", D7_2), ("wr_reversed_charge", D7_2),
        ("wr_account_credit", D7_2), ("wr_fee", D7_2),
        ("wr_return_ship_cost", D7_2), ("wr_return_amt_inc_tax", D7_2),
        ("wr_return_tax", D7_2),
    ],
    "store_returns": [
        ("sr_returned_date_sk", BIGINT), ("sr_item_sk", BIGINT),
        ("sr_customer_sk", BIGINT), ("sr_cdemo_sk", BIGINT),
        ("sr_hdemo_sk", BIGINT), ("sr_store_sk", BIGINT),
        ("sr_reason_sk", BIGINT), ("sr_ticket_number", BIGINT),
        ("sr_return_quantity", INTEGER), ("sr_return_amt", D7_2),
        ("sr_net_loss", D7_2),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", BIGINT), ("cs_ship_date_sk", BIGINT),
        ("cs_bill_customer_sk", BIGINT), ("cs_bill_cdemo_sk", BIGINT),
        ("cs_bill_hdemo_sk", BIGINT), ("cs_bill_addr_sk", BIGINT),
        ("cs_ship_addr_sk", BIGINT), ("cs_call_center_sk", BIGINT),
        ("cs_catalog_page_sk", BIGINT), ("cs_ship_mode_sk", BIGINT),
        ("cs_warehouse_sk", BIGINT), ("cs_item_sk", BIGINT),
        ("cs_promo_sk", BIGINT), ("cs_order_number", BIGINT),
        ("cs_quantity", INTEGER), ("cs_wholesale_cost", D7_2),
        ("cs_list_price", D7_2), ("cs_sales_price", D7_2),
        ("cs_ext_discount_amt", D7_2), ("cs_ext_sales_price", D7_2),
        ("cs_ext_ship_cost", D7_2), ("cs_net_paid", D7_2),
        ("cs_net_profit", D7_2), ("cs_sold_time_sk", BIGINT),
        ("cs_ship_customer_sk", BIGINT), ("cs_ship_cdemo_sk", BIGINT),
        ("cs_ship_hdemo_sk", BIGINT), ("cs_coupon_amt", D7_2),
        ("cs_ext_list_price", D7_2), ("cs_ext_wholesale_cost", D7_2),
        ("cs_ext_tax", D7_2), ("cs_net_paid_inc_tax", D7_2),
        ("cs_net_paid_inc_ship", D7_2), ("cs_net_paid_inc_ship_tax", D7_2),
    ],
    "catalog_returns": [
        ("cr_returned_date_sk", BIGINT), ("cr_item_sk", BIGINT),
        ("cr_refunded_customer_sk", BIGINT),
        ("cr_returning_customer_sk", BIGINT),
        ("cr_call_center_sk", BIGINT), ("cr_reason_sk", BIGINT),
        ("cr_order_number", BIGINT), ("cr_return_quantity", INTEGER),
        ("cr_return_amount", D7_2), ("cr_net_loss", D7_2),
        ("cr_catalog_page_sk", BIGINT), ("cr_refunded_addr_sk", BIGINT),
        ("cr_returning_addr_sk", BIGINT), ("cr_refunded_cash", D7_2),
        ("cr_reversed_charge", D7_2), ("cr_store_credit", D7_2),
        ("cr_fee", D7_2), ("cr_return_ship_cost", D7_2),
        ("cr_return_amt_inc_tax", D7_2), ("cr_return_tax", D7_2),
        ("cr_warehouse_sk", BIGINT),
    ],
    "inventory": [
        ("inv_date_sk", BIGINT), ("inv_item_sk", BIGINT),
        ("inv_warehouse_sk", BIGINT), ("inv_quantity_on_hand", INTEGER),
    ],
    "catalog_page": [
        ("cp_catalog_page_sk", BIGINT), ("cp_catalog_page_id", VarcharType(16)),
        ("cp_department", VarcharType(50)), ("cp_catalog_number", INTEGER),
        ("cp_catalog_page_number", INTEGER),
    ],
    "ship_mode": [
        ("sm_ship_mode_sk", BIGINT), ("sm_ship_mode_id", VarcharType(16)),
        ("sm_type", VarcharType(30)), ("sm_code", VarcharType(10)),
        ("sm_carrier", VarcharType(20)),
    ],
    "reason": [
        ("r_reason_sk", BIGINT), ("r_reason_id", VarcharType(16)),
        ("r_reason_desc", VarcharType(100)),
    ],
    "income_band": [
        ("ib_income_band_sk", BIGINT), ("ib_lower_bound", INTEGER),
        ("ib_upper_bound", INTEGER),
    ],
    "household_demographics": [
        ("hd_demo_sk", BIGINT), ("hd_income_band_sk", BIGINT),
        ("hd_buy_potential", VarcharType(15)), ("hd_dep_count", INTEGER),
        ("hd_vehicle_count", INTEGER),
    ],
    "customer_demographics": [
        ("cd_demo_sk", BIGINT), ("cd_gender", VarcharType(1)),
        ("cd_marital_status", VarcharType(1)),
        ("cd_education_status", VarcharType(20)),
        ("cd_purchase_estimate", INTEGER),
        ("cd_credit_rating", VarcharType(10)),
        ("cd_dep_count", INTEGER), ("cd_dep_employed_count", INTEGER),
        ("cd_dep_college_count", INTEGER),
    ],
    "time_dim": [
        ("t_time_sk", BIGINT), ("t_time_id", VarcharType(16)),
        ("t_time", INTEGER), ("t_hour", INTEGER), ("t_minute", INTEGER),
        ("t_second", INTEGER), ("t_am_pm", VarcharType(2)),
        ("t_shift", VarcharType(20)), ("t_meal_time", VarcharType(20)),
    ],
    "call_center": [
        ("cc_call_center_sk", BIGINT), ("cc_call_center_id", VarcharType(16)),
        ("cc_name", VarcharType(50)), ("cc_class", VarcharType(50)),
        ("cc_employees", INTEGER), ("cc_manager", VarcharType(40)),
        ("cc_county", VarcharType(30)), ("cc_state", VarcharType(2)),
    ],
    "web_page": [
        ("wp_web_page_sk", BIGINT), ("wp_web_page_id", VarcharType(16)),
        ("wp_url", VarcharType(100)), ("wp_char_count", INTEGER),
        ("wp_link_count", INTEGER),
    ],
}

# every table already carries its spec prefix in the column names
PREFIXES: Dict[str, str] = {t: "" for t in SCHEMAS}


def column_type(table: str, column: str) -> Type:
    for name, typ in SCHEMAS[table]:
        if name == column:
            return typ
    raise KeyError(f"{table}.{column}")


# open-domain (late-materialized) string columns, and which of them have
# row-id-compatible order / identity (see tpch.py for the rules)
OPEN_DOMAIN = {
    ("item", "i_item_id"), ("customer", "c_customer_id"),
    ("item", "i_product_name"), ("item", "i_item_desc"),
    ("store", "s_street_number"), ("store", "s_suite_number"),
    ("customer_address", "ca_street_number"),
    ("customer_address", "ca_suite_number"),
    ("customer", "c_email_address"), ("customer_address", "ca_address_id"),
    ("customer_address", "ca_zip"), ("store", "s_store_id"),
    ("store", "s_zip"),
    ("web_site", "web_site_id"), ("promotion", "p_promo_id"),
    ("catalog_page", "cp_catalog_page_id"), ("ship_mode", "sm_ship_mode_id"),
    ("reason", "r_reason_id"), ("time_dim", "t_time_id"),
    ("call_center", "cc_call_center_id"), ("web_page", "wp_web_page_id"),
}
ROWID_ORDERED = {
    ("item", "i_item_id"), ("customer", "c_customer_id"),
    ("item", "i_product_name"),
    ("customer_address", "ca_address_id"), ("store", "s_store_id"),
    ("web_site", "web_site_id"), ("promotion", "p_promo_id"),
    ("catalog_page", "cp_catalog_page_id"), ("ship_mode", "sm_ship_mode_id"),
    ("reason", "r_reason_id"), ("time_dim", "t_time_id"),
    ("call_center", "cc_call_center_id"), ("web_page", "wp_web_page_id"),
}
ROWID_DISTINCT = {
    ("item", "i_item_id"), ("customer", "c_customer_id"),
    ("item", "i_product_name"),
    ("customer", "c_email_address"), ("customer_address", "ca_address_id"),
    ("store", "s_store_id"), ("web_site", "web_site_id"),
    ("promotion", "p_promo_id"),
    ("catalog_page", "cp_catalog_page_id"), ("ship_mode", "sm_ship_mode_id"),
    ("reason", "r_reason_id"), ("time_dim", "t_time_id"),
    ("call_center", "cc_call_center_id"), ("web_page", "wp_web_page_id"),
}


# ---------------------------------------------------------------------------
# co-bucketed layout for grouped (lifespan) execution (see tpch.py for the
# model): web_sales rows map to order numbers through fixed
# LINES_PER_ORDER blocks, and wr_order_number is generated monotone in the
# row index, so a ws_order_number RANGE is a contiguous ROW RANGE in both
# tables — a bucket is a pair of row-range splits, no repartitioning.
# This is the layout BASELINE config #5 (TPC-DS Q95, whose 72M-row
# web_sales self-join build exhausts HBM at SF100) needs to run one
# lifespan at a time.  Bucket keys are NON-NULL by the catalog contract
# (connectors/catalog.py bucket_column).
# ---------------------------------------------------------------------------

BUCKET_COLUMNS = {"web_sales": "ws_order_number",
                  "web_returns": "wr_order_number"}


def _wr_rows_below(key: int, n_orders: int, n_returns: int) -> int:
    """Number of web_returns rows with wr_order_number < key.  The
    generator maps row idx -> (idx*n_orders)//n_returns + 1, so the first
    row at-or-above `key` is ceil((key-1)*n_returns/n_orders)."""
    k = min(max(key - 1, 0), n_orders)
    return min(n_returns, -(-(k * n_returns) // n_orders))


def bucket_layout(sf: float, n_buckets: int) -> List[TableBucket]:
    """Split the ws_order_number domain into up to n_buckets lifespans;
    the last bucket absorbs any partial tail order of web_sales."""
    n_ws = _table_rows("web_sales", sf)
    n_wr = _table_rows("web_returns", sf)
    n_orders = max(1, n_ws // LINES_PER_ORDER)
    # distinct order numbers (a partial tail block still owns one key)
    n_keys = -(-n_ws // LINES_PER_ORDER)
    if n_buckets <= 1 or n_keys <= 1:
        return [TableBucket(1, n_keys + 1, {"web_sales": (0, n_ws),
                                            "web_returns": (0, n_wr)})]
    per = max(1, -(-n_keys // n_buckets))           # ceil(n_keys / K)
    out: List[TableBucket] = []
    k0 = 1
    while k0 <= n_keys:
        k1 = min(k0 + per, n_keys + 1)
        ws = ((k0 - 1) * LINES_PER_ORDER,
              n_ws if k1 > n_keys else (k1 - 1) * LINES_PER_ORDER)
        wr = (_wr_rows_below(k0, n_orders, n_wr),
              _wr_rows_below(k1, n_orders, n_wr))
        out.append(TableBucket(k0, k1,
                               {"web_sales": ws, "web_returns": wr}))
        k0 = k1
    return out


# ---------------------------------------------------------------------------
# per-table generators (same contract as tpch: numeric ndarray, or
# (codes, values) dictionary, or list[str] for OPEN_DOMAIN columns)
# ---------------------------------------------------------------------------

def _gen_date_dim(column: str, idx: np.ndarray, sf: float):
    days = EPOCH_1900 + idx                       # days since unix epoch
    dt = days.astype("datetime64[D]")
    if column == "d_date_sk":
        return JULIAN_BASE + idx
    if column == "d_date_id":
        return [f"AAAAAAAA{int(v):08d}" for v in JULIAN_BASE + idx]
    if column == "d_date":
        return days
    if column == "d_year":
        return dt.astype("datetime64[Y]").astype(np.int64) + 1970
    if column == "d_moy":
        return (dt.astype("datetime64[M]")
                - dt.astype("datetime64[Y]")).astype(np.int64) + 1
    if column == "d_dom":
        return (dt - dt.astype("datetime64[M]")).astype(np.int64) + 1
    if column == "d_qoy":
        moy = _gen_date_dim("d_moy", idx, sf)
        return (moy - 1) // 3 + 1
    if column == "d_dow":
        return (days + 4) % 7                     # 1970-01-01 was a Thursday
    if column == "d_day_name":
        return (((days + 4) % 7).astype(np.int32), DAY_NAMES)
    if column == "d_month_seq":
        y = _gen_date_dim("d_year", idx, sf)
        m = _gen_date_dim("d_moy", idx, sf)
        return (y - 1900) * 12 + m - 1
    if column == "d_week_seq":
        return idx // 7 + 1
    if column == "d_quarter_seq":
        y = _gen_date_dim("d_year", idx, sf)
        q = _gen_date_dim("d_qoy", idx, sf)
        return (y - 1900) * 4 + q - 1
    if column == "d_quarter_name":
        y = _gen_date_dim("d_year", idx, sf)
        q = _gen_date_dim("d_qoy", idx, sf)
        # closed domain (years x 4): dictionary codes
        names = [f"{yy}Q{qq}" for yy in range(1900, 2101)
                 for qq in range(1, 5)]
        return (((y - 1900) * 4 + q - 1).astype(np.int32), names)
    raise KeyError(column)


def _gen_item(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "i_item_sk":
        return sk
    if column == "i_item_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "i_current_price":
        return _uniform("item", "price", idx, 99, 9999)
    if column == "i_brand_id":
        return _uniform("item", "brand", idx, 0, len(BRANDS) - 1) + 1001
    if column == "i_brand":
        return (_uniform("item", "brand", idx, 0,
                         len(BRANDS) - 1).astype(np.int32), BRANDS)
    if column == "i_class_id":
        return _uniform("item", "class", idx, 0, len(CLASSES) - 1) + 1
    if column == "i_class":
        return (_uniform("item", "class", idx, 0,
                         len(CLASSES) - 1).astype(np.int32), CLASSES)
    if column == "i_category_id":
        return _uniform("item", "category", idx, 0, len(CATEGORIES) - 1) + 1
    if column == "i_category":
        return (_uniform("item", "category", idx, 0,
                         len(CATEGORIES) - 1).astype(np.int32), CATEGORIES)
    if column == "i_manufact_id":
        return _uniform("item", "manufact", idx, 1, 1000)
    if column == "i_color":
        return (_uniform("item", "color", idx, 0,
                         len(COLORS) - 1).astype(np.int32), COLORS)
    if column == "i_manager_id":
        return _uniform("item", "manager", idx, 1, 100)
    if column == "i_manufact":
        m = _gen_item("i_manufact_id", idx, sf)
        names = [f"manufact#{i}" for i in range(1001)]
        return (m.astype(np.int32), names)
    if column == "i_product_name":
        return [f"product{int(v):011d}" for v in idx + 1]
    if column == "i_item_desc":
        h = _hash("item", "desc", idx)
        return [f"Item description {int(v) % 10000:04d} text body"
                for v in h]
    if column == "i_size":
        return (_uniform("item", "size", idx, 0, 6).astype(np.int32),
                ["N/A", "petite", "small", "medium", "large",
                 "extra large", "economy"])
    if column == "i_units":
        return (_uniform("item", "units", idx, 0, 4).astype(np.int32),
                ["Each", "Dozen", "Case", "Pallet", "Unknown"])
    if column == "i_wholesale_cost":
        return _uniform("item", "wholesale", idx, 100, 8800)
    raise KeyError(column)


def _gen_customer(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "c_current_cdemo_sk":
        return _uniform("customer", "cdemo", idx, 1,
                        _table_rows("customer_demographics", sf))
    if column == "c_current_hdemo_sk":
        return _uniform("customer", "hdemo", idx, 1,
                        _table_rows("household_demographics", sf))
    if column == "c_customer_sk":
        return sk
    if column == "c_customer_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "c_current_addr_sk":
        return _uniform("customer", "addr", idx, 1,
                        _table_rows("customer_address", sf))
    if column == "c_first_name":
        return (_uniform("customer", "first", idx, 0,
                         len(FIRST_NAMES) - 1).astype(np.int32), FIRST_NAMES)
    if column == "c_last_name":
        return (_uniform("customer", "last", idx, 0,
                         len(LAST_NAMES) - 1).astype(np.int32), LAST_NAMES)
    if column == "c_birth_year":
        return _uniform("customer", "byear", idx, 1924, 1992)
    if column == "c_birth_month":
        return _uniform("customer", "bmonth", idx, 1, 12)
    if column == "c_birth_country":
        return (_uniform("customer", "bcountry", idx, 0, 4).astype(np.int32),
                ["UNITED STATES", "CANADA", "MEXICO", "GERMANY", "JAPAN"])
    if column == "c_email_address":
        h = _hash("customer", "email", idx)
        return [f"user{int(v):016x}@example.com" for v in h]
    if column == "c_preferred_cust_flag":
        return (_uniform("customer", "pref", idx, 0, 1).astype(np.int32),
                YN)
    if column == "c_salutation":
        return (_uniform("customer", "salut", idx, 0, 5).astype(np.int32),
                ["Mr.", "Mrs.", "Ms.", "Dr.", "Sir", "Miss"])
    if column == "c_login":
        return (np.zeros(len(idx), dtype=np.int32), [""])
    if column == "c_birth_day":
        return _uniform("customer", "bday", idx, 1, 28)
    if column == "c_first_sales_date_sk":
        return _date_sk_from_offset(
            _uniform("customer", "fsale", idx, SALES_MIN, SALES_MAX))
    if column == "c_first_shipto_date_sk":
        return _gen_customer("c_first_sales_date_sk", idx, sf) \
            + _uniform("customer", "fship", idx, 1, 30)
    if column == "c_last_review_date_sk":
        return _date_sk_from_offset(
            _uniform("customer", "lastrev", idx, SALES_MIN, SALES_MAX))
    raise KeyError(column)


def _gen_customer_address(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "ca_address_sk":
        return sk
    if column == "ca_address_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "ca_city":
        return (_uniform("customer_address", "city", idx, 0,
                         len(CITIES) - 1).astype(np.int32), CITIES)
    if column == "ca_county":
        return (_uniform("customer_address", "county", idx, 0,
                         len(COUNTIES) - 1).astype(np.int32), COUNTIES)
    if column == "ca_state":
        return (_uniform("customer_address", "state", idx, 0,
                         len(STATES) - 1).astype(np.int32), STATES)
    if column == "ca_zip":
        z = _uniform("customer_address", "zip", idx, 10000, 99999)
        return [f"{int(v):05d}" for v in z]
    if column == "ca_country":
        return (np.zeros(len(idx), dtype=np.int32), ["United States"])
    if column == "ca_gmt_offset":
        return -100 * _uniform("customer_address", "gmt", idx, 5, 8)
    if column == "ca_street_number":
        n = _uniform("customer_address", "stno", idx, 1, 999)
        return [str(int(v)) for v in n]
    if column == "ca_street_name":
        return (_uniform("customer_address", "stname", idx, 0,
                         len(COUNTIES) - 1).astype(np.int32), COUNTIES)
    if column == "ca_street_type":
        return (_uniform("customer_address", "sttype", idx, 0,
                         4).astype(np.int32),
                ["Street", "Ave", "Blvd", "Ct.", "Lane"])
    if column == "ca_suite_number":
        n = _uniform("customer_address", "suite", idx, 0, 99)
        return [f"Suite {int(v)}" for v in n]
    if column == "ca_location_type":
        return (_uniform("customer_address", "loctype", idx, 0,
                         2).astype(np.int32),
                ["apartment", "condo", "single family"])
    raise KeyError(column)


def _gen_store(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "s_city":
        return (_uniform("store", "city", idx, 0,
                         len(CITIES) - 1).astype(np.int32), CITIES)
    if column == "s_county":
        return (_uniform("store", "county", idx, 0,
                         len(COUNTIES) - 1).astype(np.int32), COUNTIES)
    if column == "s_zip":
        z = _uniform("store", "zip", idx, 10000, 99999)
        return [f"{int(v):05d}" for v in z]
    if column == "s_gmt_offset":
        return -100 * _uniform("store", "gmt", idx, 5, 8)
    if column == "s_store_sk":
        return sk
    if column == "s_store_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "s_store_name":
        return (_uniform("store", "name", idx, 0, 9).astype(np.int32),
                ["ought", "able", "pri", "ese", "anti", "cally", "ation",
                 "eing", "n st", "bar"])
    if column == "s_number_employees":
        return _uniform("store", "employees", idx, 200, 300)
    if column == "s_floor_space":
        return _uniform("store", "floor", idx, 5_000_000, 10_000_000)
    if column == "s_market_id":
        return _uniform("store", "market", idx, 1, 10)
    if column == "s_state":
        return (_uniform("store", "state", idx, 0,
                         len(STATES) - 1).astype(np.int32), STATES)
    if column == "s_company_id":
        return np.ones(len(idx), dtype=np.int64)
    if column == "s_street_number":
        n = _uniform("store", "stno", idx, 1, 999)
        return [str(int(v)) for v in n]
    if column == "s_street_name":
        return (_uniform("store", "stname", idx, 0,
                         len(COUNTIES) - 1).astype(np.int32), COUNTIES)
    if column == "s_street_type":
        return (_uniform("store", "sttype", idx, 0, 4).astype(np.int32),
                ["Street", "Ave", "Blvd", "Ct.", "Lane"])
    if column == "s_suite_number":
        n = _uniform("store", "suite", idx, 0, 99)
        return [f"Suite {int(v)}" for v in n]
    if column == "s_company_name":
        return (np.zeros(len(idx), dtype=np.int32), ["Unknown"])
    raise KeyError(column)


def _gen_web_site(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "web_site_sk":
        return sk
    if column == "web_site_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "web_name":
        return ((idx % 15).astype(np.int32),
                [f"site_{i}" for i in range(15)])
    if column == "web_company_id":
        return idx % 6 + 1
    if column == "web_company_name":
        return ((idx % 6).astype(np.int32), COMPANY_NAMES)
    raise KeyError(column)


def _gen_warehouse(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "w_warehouse_sk":
        return sk
    if column == "w_warehouse_name":
        return ((idx % 5).astype(np.int32), WAREHOUSE_NAMES)
    if column == "w_warehouse_sq_ft":
        return _uniform("warehouse", "sqft", idx, 50_000, 1_000_000)
    if column == "w_state":
        return ((idx % len(STATES)).astype(np.int32), STATES)
    if column == "w_city":
        return ((idx % len(CITIES)).astype(np.int32), CITIES)
    if column == "w_county":
        return ((idx % len(COUNTIES)).astype(np.int32), COUNTIES)
    if column == "w_country":
        return (np.zeros(len(idx), dtype=np.int32), ["United States"])
    raise KeyError(column)


def _gen_promotion(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "p_promo_sk":
        return sk
    if column == "p_promo_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column in ("p_channel_dmail", "p_channel_email", "p_channel_tv",
                  "p_channel_event", "p_channel_catalog"):
        return (_uniform("promotion", column, idx, 0, 1).astype(np.int32), YN)
    raise KeyError(column)


def _date_sk_from_offset(off: np.ndarray) -> np.ndarray:
    """days-since-1900 offset -> d_date_sk (date_dim row i == offset i)."""
    return JULIAN_BASE + off


def _gen_store_sales(column: str, idx: np.ndarray, sf: float):
    if column == "ss_sold_time_sk":
        return _uniform("store_sales", "time", idx // LINES_PER_ORDER,
                        28800, 75600)      # store hours 8:00-21:00
    if column == "ss_cdemo_sk":
        return _uniform("store_sales", "cdemo", idx // LINES_PER_ORDER, 1,
                        _table_rows("customer_demographics", sf))
    if column == "ss_hdemo_sk":
        return _uniform("store_sales", "hdemo", idx // LINES_PER_ORDER, 1,
                        _table_rows("household_demographics", sf))
    if column == "ss_addr_sk":
        return _uniform("store_sales", "addr", idx // LINES_PER_ORDER, 1,
                        _table_rows("customer_address", sf))
    if column == "ss_ext_list_price":
        return (_gen_store_sales("ss_list_price", idx, sf)
                * _gen_store_sales("ss_quantity", idx, sf))
    if column == "ss_coupon_amt":
        return _uniform("store_sales", "coupon", idx, 0, 50000) \
            * (_uniform("store_sales", "hascoup", idx, 0, 9) == 0)
    if column == "ss_sold_date_sk":
        return _date_sk_from_offset(
            _uniform("store_sales", "sold", idx // LINES_PER_ORDER,
                     SALES_MIN, SALES_MAX))
    if column == "ss_item_sk":
        return _uniform("store_sales", "item", idx, 1, _table_rows("item", sf))
    if column == "ss_customer_sk":
        return _uniform("store_sales", "cust", idx // LINES_PER_ORDER, 1,
                        _table_rows("customer", sf))
    if column == "ss_store_sk":
        return _uniform("store_sales", "store", idx // LINES_PER_ORDER, 1,
                        _table_rows("store", sf))
    if column == "ss_promo_sk":
        return _uniform("store_sales", "promo", idx, 1,
                        _table_rows("promotion", sf))
    if column == "ss_ticket_number":
        return idx // LINES_PER_ORDER + 1
    if column == "ss_quantity":
        return _uniform("store_sales", "qty", idx, 1, 100)
    if column == "ss_wholesale_cost":
        return _uniform("store_sales", "wholesale", idx, 100, 10000)
    if column == "ss_list_price":
        w = _gen_store_sales("ss_wholesale_cost", idx, sf)
        return w + w * _uniform("store_sales", "markup", idx, 0, 200) // 100
    if column == "ss_sales_price":
        lp = _gen_store_sales("ss_list_price", idx, sf)
        return lp * _uniform("store_sales", "dscnt", idx, 20, 100) // 100
    if column == "ss_ext_sales_price":
        return (_gen_store_sales("ss_sales_price", idx, sf)
                * _gen_store_sales("ss_quantity", idx, sf))
    if column == "ss_ext_discount_amt":
        lp = _gen_store_sales("ss_list_price", idx, sf)
        sp = _gen_store_sales("ss_sales_price", idx, sf)
        return (lp - sp) * _gen_store_sales("ss_quantity", idx, sf)
    if column == "ss_net_paid":
        return _gen_store_sales("ss_ext_sales_price", idx, sf)
    if column == "ss_net_profit":
        q = _gen_store_sales("ss_quantity", idx, sf)
        w = _gen_store_sales("ss_wholesale_cost", idx, sf)
        return _gen_store_sales("ss_net_paid", idx, sf) - q * w
    if column == "ss_ext_tax":
        return _gen_store_sales("ss_ext_sales_price", idx, sf) * 9 // 100
    if column == "ss_ext_wholesale_cost":
        return (_gen_store_sales("ss_wholesale_cost", idx, sf)
                * _gen_store_sales("ss_quantity", idx, sf))
    if column == "ss_net_paid_inc_tax":
        return (_gen_store_sales("ss_net_paid", idx, sf)
                + _gen_store_sales("ss_ext_tax", idx, sf))
    raise KeyError(column)


def _gen_web_sales(column: str, idx: np.ndarray, sf: float):
    order = idx // LINES_PER_ORDER
    if column == "ws_ship_mode_sk":
        return _uniform("web_sales", "shipmode", order, 1,
                        _table_rows("ship_mode", sf))
    if column == "ws_sold_date_sk":
        return _date_sk_from_offset(
            _uniform("web_sales", "sold", order, SALES_MIN, SALES_MAX))
    if column == "ws_ship_date_sk":
        sold = _uniform("web_sales", "sold", order, SALES_MIN, SALES_MAX)
        return _date_sk_from_offset(
            sold + _uniform("web_sales", "lag", idx, 1, 120))
    if column == "ws_item_sk":
        return _uniform("web_sales", "item", idx, 1, _table_rows("item", sf))
    if column == "ws_bill_customer_sk":
        return _uniform("web_sales", "cust", order, 1,
                        _table_rows("customer", sf))
    if column == "ws_ship_addr_sk":
        return _uniform("web_sales", "addr", order, 1,
                        _table_rows("customer_address", sf))
    if column == "ws_web_site_sk":
        return _uniform("web_sales", "site", order, 1,
                        _table_rows("web_site", sf))
    if column == "ws_warehouse_sk":
        return _uniform("web_sales", "wh", idx, 1,
                        _table_rows("warehouse", sf))
    if column == "ws_promo_sk":
        return _uniform("web_sales", "promo", idx, 1,
                        _table_rows("promotion", sf))
    if column == "ws_order_number":
        return order + 1
    if column == "ws_quantity":
        return _uniform("web_sales", "qty", idx, 1, 100)
    if column == "ws_sales_price":
        return _uniform("web_sales", "price", idx, 100, 30000)
    if column == "ws_ext_sales_price":
        return (_gen_web_sales("ws_sales_price", idx, sf)
                * _gen_web_sales("ws_quantity", idx, sf))
    if column == "ws_ext_ship_cost":
        return _uniform("web_sales", "shipcost", idx, 0, 50000)
    if column == "ws_net_paid":
        return _gen_web_sales("ws_ext_sales_price", idx, sf)
    if column == "ws_net_profit":
        return (_gen_web_sales("ws_net_paid", idx, sf)
                - _uniform("web_sales", "cost", idx, 50, 40000)
                * _gen_web_sales("ws_quantity", idx, sf))
    if column == "ws_sold_time_sk":
        return _uniform("web_sales", "time", order, 0, 86399)
    if column == "ws_bill_addr_sk":
        return _uniform("web_sales", "baddr", order, 1,
                        _table_rows("customer_address", sf))
    if column == "ws_bill_cdemo_sk":
        return _uniform("web_sales", "bcdemo", order, 1,
                        _table_rows("customer_demographics", sf))
    if column == "ws_bill_hdemo_sk":
        return _uniform("web_sales", "bhdemo", order, 1,
                        _table_rows("household_demographics", sf))
    if column == "ws_ship_customer_sk":
        # usually the buyer, sometimes a gift recipient
        buyer = _gen_web_sales("ws_bill_customer_sk", idx, sf)
        other = _uniform("web_sales", "shipcust", order, 1,
                         _table_rows("customer", sf))
        same = _uniform("web_sales", "shipsame", order, 0, 9) < 7
        return np.where(same, buyer, other)
    if column == "ws_ship_cdemo_sk":
        return _uniform("web_sales", "scdemo", order, 1,
                        _table_rows("customer_demographics", sf))
    if column == "ws_ship_hdemo_sk":
        return _uniform("web_sales", "shdemo", order, 1,
                        _table_rows("household_demographics", sf))
    if column == "ws_web_page_sk":
        return _uniform("web_sales", "page", order, 1,
                        _table_rows("web_page", sf))
    if column == "ws_wholesale_cost":
        return _uniform("web_sales", "wholesale", idx, 100, 10000)
    if column == "ws_list_price":
        w = _gen_web_sales("ws_wholesale_cost", idx, sf)
        return w + w * _uniform("web_sales", "markup", idx, 0, 200) // 100
    if column == "ws_ext_list_price":
        return (_gen_web_sales("ws_list_price", idx, sf)
                * _gen_web_sales("ws_quantity", idx, sf))
    if column == "ws_ext_discount_amt":
        lp = _gen_web_sales("ws_list_price", idx, sf)
        return ((lp - _gen_web_sales("ws_sales_price", idx, sf))
                * _gen_web_sales("ws_quantity", idx, sf)).clip(0)
    if column == "ws_ext_wholesale_cost":
        return (_gen_web_sales("ws_wholesale_cost", idx, sf)
                * _gen_web_sales("ws_quantity", idx, sf))
    if column == "ws_ext_tax":
        return _gen_web_sales("ws_ext_sales_price", idx, sf) * 9 // 100
    if column == "ws_coupon_amt":
        return _uniform("web_sales", "coupon", idx, 0, 50000) \
            * (_uniform("web_sales", "hascoup", idx, 0, 9) == 0)
    if column == "ws_net_paid_inc_tax":
        return (_gen_web_sales("ws_net_paid", idx, sf)
                + _gen_web_sales("ws_ext_tax", idx, sf))
    if column == "ws_net_paid_inc_ship":
        return (_gen_web_sales("ws_net_paid", idx, sf)
                + _gen_web_sales("ws_ext_ship_cost", idx, sf))
    raise KeyError(column)


def _gen_web_returns(column: str, idx: np.ndarray, sf: float):
    n_orders = _table_rows("web_sales", sf) // LINES_PER_ORDER
    if column == "wr_order_number":
        # monotone in the row index so an order-number range is a
        # contiguous web_returns row range (the co-bucket property
        # bucket_layout depends on); strictly increasing whenever
        # n_orders >= n_returns, so returned order numbers are also
        # distinct.  The generator is self-consistent rather than
        # dsdgen-bit-exact, so redefining the draw is fair game — every
        # web_returns test is differential.
        n_returns = _table_rows("web_returns", sf)
        return (idx * max(1, n_orders)) // n_returns + 1
    if column == "wr_returned_date_sk":
        return _date_sk_from_offset(
            _uniform("web_returns", "ret", idx, SALES_MIN, SALES_MAX + 60))
    if column == "wr_item_sk":
        return _uniform("web_returns", "item", idx, 1,
                        _table_rows("item", sf))
    if column == "wr_refunded_customer_sk":
        return _uniform("web_returns", "cust", idx, 1,
                        _table_rows("customer", sf))
    if column == "wr_return_quantity":
        return _uniform("web_returns", "qty", idx, 1, 50)
    if column == "wr_return_amt":
        return _uniform("web_returns", "amt", idx, 100, 500000)
    if column == "wr_net_loss":
        return _uniform("web_returns", "loss", idx, 50, 100000)
    if column == "wr_returning_customer_sk":
        buyer = _gen_web_returns("wr_refunded_customer_sk", idx, sf)
        other = _uniform("web_returns", "rcust", idx, 1,
                         _table_rows("customer", sf))
        same = _uniform("web_returns", "rsame", idx, 0, 9) < 8
        return np.where(same, buyer, other)
    if column == "wr_refunded_addr_sk":
        return _uniform("web_returns", "faddr", idx, 1,
                        _table_rows("customer_address", sf))
    if column == "wr_returning_addr_sk":
        return _uniform("web_returns", "raddr", idx, 1,
                        _table_rows("customer_address", sf))
    if column == "wr_refunded_cdemo_sk":
        return _uniform("web_returns", "fcdemo", idx, 1,
                        _table_rows("customer_demographics", sf))
    if column == "wr_returning_cdemo_sk":
        return _uniform("web_returns", "rcdemo", idx, 1,
                        _table_rows("customer_demographics", sf))
    if column == "wr_refunded_hdemo_sk":
        return _uniform("web_returns", "fhdemo", idx, 1,
                        _table_rows("household_demographics", sf))
    if column == "wr_web_page_sk":
        return _uniform("web_returns", "page", idx, 1,
                        _table_rows("web_page", sf))
    if column == "wr_reason_sk":
        return _uniform("web_returns", "reason", idx, 1,
                        _table_rows("reason", sf))
    if column == "wr_returned_time_sk":
        return _uniform("web_returns", "time", idx, 0, 86399)
    if column == "wr_refunded_cash":
        amt = _gen_web_returns("wr_return_amt", idx, sf)
        return amt * _uniform("web_returns", "cashfrac", idx, 0, 100) // 100
    if column == "wr_reversed_charge":
        amt = _gen_web_returns("wr_return_amt", idx, sf)
        cash = _gen_web_returns("wr_refunded_cash", idx, sf)
        return (amt - cash) // 2
    if column == "wr_account_credit":
        amt = _gen_web_returns("wr_return_amt", idx, sf)
        cash = _gen_web_returns("wr_refunded_cash", idx, sf)
        rev = _gen_web_returns("wr_reversed_charge", idx, sf)
        return amt - cash - rev
    if column == "wr_fee":
        return _uniform("web_returns", "fee", idx, 50, 10000)
    if column == "wr_return_ship_cost":
        return _uniform("web_returns", "shipc", idx, 0, 25000)
    if column == "wr_return_tax":
        return _gen_web_returns("wr_return_amt", idx, sf) * 9 // 100
    if column == "wr_return_amt_inc_tax":
        return (_gen_web_returns("wr_return_amt", idx, sf)
                + _gen_web_returns("wr_return_tax", idx, sf))
    raise KeyError(column)


SM_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY"]
SM_CODES = ["AIR", "SURFACE", "SEA", "SHIP"]
SM_CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
               "ZOUROS", "MSC", "LATVIAN", "ALLIANCE", "ORIENTAL",
               "BARIAN", "BOXBUNDLES", "CARGO", "DIAMOND", "RUPEKSA",
               "GERMA", "HARMSTORF", "PRIVATECARRIER"]
REASONS = [f"reason {i}" for i in range(1, 36)]
BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000",
                 ">10000", "Unknown"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
CREDIT_RATING = ["Low Risk", "Good", "High Risk", "Unknown"]
DEPARTMENTS = ["DEPARTMENT"]
CC_NAMES = ["NY Metro", "Mid Atlantic", "North Midwest", "California",
            "Pacific Northwest", "Central"]
CC_CLASSES = ["small", "medium", "large"]


def _gen_store_returns(column: str, idx: np.ndarray, sf: float):
    # each return references a deterministic store_sales row (spec: ~10%
    # of tickets are returned), so returned keys join back to real sales
    sale = _uniform("store_returns", "sale", idx, 0,
                    _table_rows("store_sales", sf) - 1)
    if column == "sr_returned_date_sk":
        sold = _gen_store_sales("ss_sold_date_sk", sale, sf)
        return sold + _uniform("store_returns", "lag", idx, 1, 60)
    if column == "sr_item_sk":
        return _gen_store_sales("ss_item_sk", sale, sf)
    if column == "sr_customer_sk":
        return _gen_store_sales("ss_customer_sk", sale, sf)
    if column == "sr_cdemo_sk":
        return _gen_store_sales("ss_cdemo_sk", sale, sf)
    if column == "sr_hdemo_sk":
        return _gen_store_sales("ss_hdemo_sk", sale, sf)
    if column == "sr_store_sk":
        return _gen_store_sales("ss_store_sk", sale, sf)
    if column == "sr_ticket_number":
        return _gen_store_sales("ss_ticket_number", sale, sf)
    if column == "sr_reason_sk":
        return _uniform("store_returns", "reason", idx, 1,
                        _table_rows("reason", sf))
    if column == "sr_return_quantity":
        return _uniform("store_returns", "qty", idx, 1, 50)
    if column == "sr_return_amt":
        return _uniform("store_returns", "amt", idx, 100, 500000)
    if column == "sr_net_loss":
        return _uniform("store_returns", "loss", idx, 50, 100000)
    raise KeyError(column)


def _gen_catalog_sales(column: str, idx: np.ndarray, sf: float):
    order = idx // LINES_PER_ORDER
    if column == "cs_sold_date_sk":
        return _date_sk_from_offset(
            _uniform("catalog_sales", "sold", order, SALES_MIN, SALES_MAX))
    if column == "cs_ship_date_sk":
        sold = _uniform("catalog_sales", "sold", order,
                        SALES_MIN, SALES_MAX)
        return _date_sk_from_offset(sold) \
            + _uniform("catalog_sales", "lag", idx, 2, 90)
    if column == "cs_bill_customer_sk":
        return _uniform("catalog_sales", "cust", order, 1,
                        _table_rows("customer", sf))
    if column == "cs_bill_cdemo_sk":
        return _uniform("catalog_sales", "cdemo", order, 1,
                        _table_rows("customer_demographics", sf))
    if column == "cs_bill_hdemo_sk":
        return _uniform("catalog_sales", "hdemo", order, 1,
                        _table_rows("household_demographics", sf))
    if column == "cs_bill_addr_sk":
        return _uniform("catalog_sales", "baddr", order, 1,
                        _table_rows("customer_address", sf))
    if column == "cs_ship_addr_sk":
        return _uniform("catalog_sales", "saddr", order, 1,
                        _table_rows("customer_address", sf))
    if column == "cs_call_center_sk":
        return _uniform("catalog_sales", "cc", order, 1,
                        _table_rows("call_center", sf))
    if column == "cs_catalog_page_sk":
        return _uniform("catalog_sales", "page", idx, 1,
                        _table_rows("catalog_page", sf))
    if column == "cs_ship_mode_sk":
        return _uniform("catalog_sales", "shipmode", order, 1,
                        _table_rows("ship_mode", sf))
    if column == "cs_warehouse_sk":
        return _uniform("catalog_sales", "wh", idx, 1,
                        _table_rows("warehouse", sf))
    if column == "cs_item_sk":
        return _uniform("catalog_sales", "item", idx, 1,
                        _table_rows("item", sf))
    if column == "cs_promo_sk":
        return _uniform("catalog_sales", "promo", idx, 1,
                        _table_rows("promotion", sf))
    if column == "cs_order_number":
        return order + 1
    if column == "cs_quantity":
        return _uniform("catalog_sales", "qty", idx, 1, 100)
    if column == "cs_wholesale_cost":
        return _uniform("catalog_sales", "wholesale", idx, 100, 10000)
    if column == "cs_list_price":
        w = _gen_catalog_sales("cs_wholesale_cost", idx, sf)
        return w + w * _uniform("catalog_sales", "markup", idx, 0, 200) // 100
    if column == "cs_sales_price":
        lp = _gen_catalog_sales("cs_list_price", idx, sf)
        return lp * _uniform("catalog_sales", "dscnt", idx, 20, 100) // 100
    if column == "cs_ext_discount_amt":
        lp = _gen_catalog_sales("cs_list_price", idx, sf)
        sp = _gen_catalog_sales("cs_sales_price", idx, sf)
        return (lp - sp) * _gen_catalog_sales("cs_quantity", idx, sf)
    if column == "cs_ext_sales_price":
        return (_gen_catalog_sales("cs_sales_price", idx, sf)
                * _gen_catalog_sales("cs_quantity", idx, sf))
    if column == "cs_ext_ship_cost":
        return _uniform("catalog_sales", "shipc", idx, 0, 50000)
    if column == "cs_net_paid":
        return _gen_catalog_sales("cs_ext_sales_price", idx, sf)
    if column == "cs_net_profit":
        q = _gen_catalog_sales("cs_quantity", idx, sf)
        w = _gen_catalog_sales("cs_wholesale_cost", idx, sf)
        return _gen_catalog_sales("cs_net_paid", idx, sf) - q * w
    if column == "cs_sold_time_sk":
        return _uniform("catalog_sales", "time", order, 0, 86399)
    if column == "cs_ship_customer_sk":
        buyer = _gen_catalog_sales("cs_bill_customer_sk", idx, sf)
        other = _uniform("catalog_sales", "shipcust", order, 1,
                         _table_rows("customer", sf))
        same = _uniform("catalog_sales", "shipsame", order, 0, 9) < 7
        return np.where(same, buyer, other)
    if column == "cs_ship_cdemo_sk":
        return _uniform("catalog_sales", "scdemo", order, 1,
                        _table_rows("customer_demographics", sf))
    if column == "cs_ship_hdemo_sk":
        return _uniform("catalog_sales", "shdemo", order, 1,
                        _table_rows("household_demographics", sf))
    if column == "cs_coupon_amt":
        return _uniform("catalog_sales", "coupon", idx, 0, 50000) \
            * (_uniform("catalog_sales", "hascoup", idx, 0, 9) == 0)
    if column == "cs_ext_list_price":
        return (_gen_catalog_sales("cs_list_price", idx, sf)
                * _gen_catalog_sales("cs_quantity", idx, sf))
    if column == "cs_ext_wholesale_cost":
        return (_gen_catalog_sales("cs_wholesale_cost", idx, sf)
                * _gen_catalog_sales("cs_quantity", idx, sf))
    if column == "cs_ext_tax":
        return _gen_catalog_sales("cs_ext_sales_price", idx, sf) * 9 // 100
    if column == "cs_net_paid_inc_tax":
        return (_gen_catalog_sales("cs_net_paid", idx, sf)
                + _gen_catalog_sales("cs_ext_tax", idx, sf))
    if column == "cs_net_paid_inc_ship":
        return (_gen_catalog_sales("cs_net_paid", idx, sf)
                + _gen_catalog_sales("cs_ext_ship_cost", idx, sf))
    if column == "cs_net_paid_inc_ship_tax":
        return (_gen_catalog_sales("cs_net_paid_inc_ship", idx, sf)
                + _gen_catalog_sales("cs_ext_tax", idx, sf))
    raise KeyError(column)


def _gen_catalog_returns(column: str, idx: np.ndarray, sf: float):
    sale = _uniform("catalog_returns", "sale", idx, 0,
                    _table_rows("catalog_sales", sf) - 1)
    if column == "cr_returned_date_sk":
        sold = _gen_catalog_sales("cs_sold_date_sk", sale, sf)
        return sold + _uniform("catalog_returns", "lag", idx, 1, 60)
    if column == "cr_item_sk":
        return _gen_catalog_sales("cs_item_sk", sale, sf)
    if column == "cr_refunded_customer_sk":
        return _gen_catalog_sales("cs_bill_customer_sk", sale, sf)
    if column == "cr_returning_customer_sk":
        # 80% returned by the buyer, else a random customer
        buyer = _gen_catalog_sales("cs_bill_customer_sk", sale, sf)
        other = _uniform("catalog_returns", "other", idx, 1,
                         _table_rows("customer", sf))
        same = _uniform("catalog_returns", "same", idx, 0, 9) < 8
        return np.where(same, buyer, other)
    if column == "cr_call_center_sk":
        return _gen_catalog_sales("cs_call_center_sk", sale, sf)
    if column == "cr_reason_sk":
        return _uniform("catalog_returns", "reason", idx, 1,
                        _table_rows("reason", sf))
    if column == "cr_order_number":
        return _gen_catalog_sales("cs_order_number", sale, sf)
    if column == "cr_return_quantity":
        return _uniform("catalog_returns", "qty", idx, 1, 50)
    if column == "cr_return_amount":
        return _uniform("catalog_returns", "amt", idx, 100, 500000)
    if column == "cr_net_loss":
        return _uniform("catalog_returns", "loss", idx, 50, 100000)
    if column == "cr_catalog_page_sk":
        return _gen_catalog_sales("cs_catalog_page_sk", sale, sf)
    if column == "cr_refunded_addr_sk":
        return _gen_catalog_sales("cs_bill_addr_sk", sale, sf)
    if column == "cr_returning_addr_sk":
        return _uniform("catalog_returns", "raddr", idx, 1,
                        _table_rows("customer_address", sf))
    if column == "cr_refunded_cash":
        amt = _gen_catalog_returns("cr_return_amount", idx, sf)
        return amt * _uniform("catalog_returns", "cashfrac", idx,
                              0, 100) // 100
    if column == "cr_reversed_charge":
        amt = _gen_catalog_returns("cr_return_amount", idx, sf)
        cash = _gen_catalog_returns("cr_refunded_cash", idx, sf)
        return (amt - cash) // 2
    if column == "cr_store_credit":
        amt = _gen_catalog_returns("cr_return_amount", idx, sf)
        cash = _gen_catalog_returns("cr_refunded_cash", idx, sf)
        rev = _gen_catalog_returns("cr_reversed_charge", idx, sf)
        return amt - cash - rev
    if column == "cr_fee":
        return _uniform("catalog_returns", "fee", idx, 50, 10000)
    if column == "cr_return_ship_cost":
        return _uniform("catalog_returns", "shipc", idx, 0, 25000)
    if column == "cr_return_tax":
        return _gen_catalog_returns("cr_return_amount", idx, sf) * 9 // 100
    if column == "cr_return_amt_inc_tax":
        return (_gen_catalog_returns("cr_return_amount", idx, sf)
                + _gen_catalog_returns("cr_return_tax", idx, sf))
    if column == "cr_warehouse_sk":
        return _gen_catalog_sales("cs_warehouse_sk", sale, sf)
    raise KeyError(column)


def _gen_inventory(column: str, idx: np.ndarray, sf: float):
    n_wh = _table_rows("warehouse", sf)
    n_item = _table_rows("item", sf)
    if column == "inv_warehouse_sk":
        return idx % n_wh + 1
    if column == "inv_item_sk":
        return (idx // n_wh) % n_item + 1
    if column == "inv_date_sk":
        week = idx // (n_wh * n_item)
        return JULIAN_BASE + (_days("1998-01-01") - EPOCH_1900) + week * 7
    if column == "inv_quantity_on_hand":
        return _uniform("inventory", "qoh", idx, 0, 1000)
    raise KeyError(column)


def _gen_catalog_page(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "cp_catalog_page_sk":
        return sk
    if column == "cp_catalog_page_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "cp_department":
        return (np.zeros(len(idx), dtype=np.int32), DEPARTMENTS)
    if column == "cp_catalog_number":
        return idx // 108 + 1
    if column == "cp_catalog_page_number":
        return idx % 108 + 1
    raise KeyError(column)


def _gen_ship_mode(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "sm_ship_mode_sk":
        return sk
    if column == "sm_ship_mode_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "sm_type":
        return ((idx % len(SM_TYPES)).astype(np.int32), SM_TYPES)
    if column == "sm_code":
        return ((idx // 5 % len(SM_CODES)).astype(np.int32), SM_CODES)
    if column == "sm_carrier":
        return ((idx % len(SM_CARRIERS)).astype(np.int32), SM_CARRIERS)
    raise KeyError(column)


def _gen_reason(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "r_reason_sk":
        return sk
    if column == "r_reason_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "r_reason_desc":
        return ((idx % len(REASONS)).astype(np.int32), REASONS)
    raise KeyError(column)


def _gen_income_band(column: str, idx: np.ndarray, sf: float):
    if column == "ib_income_band_sk":
        return idx + 1
    if column == "ib_lower_bound":
        return idx * 10000 + 1
    if column == "ib_upper_bound":
        return (idx + 1) * 10000
    raise KeyError(column)


def _gen_household_demographics(column: str, idx: np.ndarray, sf: float):
    # cross product: income_band(20) x buy_potential(6) x dep(10) x veh(6)
    if column == "hd_demo_sk":
        return idx + 1
    if column == "hd_income_band_sk":
        return idx % 20 + 1
    if column == "hd_buy_potential":
        return ((idx // 20 % 6).astype(np.int32), BUY_POTENTIAL)
    if column == "hd_dep_count":
        return idx // 120 % 10
    if column == "hd_vehicle_count":
        return idx // 1200 % 6 - 1       # -1..4 per spec
    raise KeyError(column)


def _gen_customer_demographics(column: str, idx: np.ndarray, sf: float):
    # spec layout: cross product over gender(2) x marital(5) x
    # education(7) x purchase_estimate(20) x credit(4) x deps(7) x ...
    if column == "cd_demo_sk":
        return idx + 1
    if column == "cd_gender":
        return ((idx % 2).astype(np.int32), ["M", "F"])
    if column == "cd_marital_status":
        return ((idx // 2 % 5).astype(np.int32), ["M", "S", "D", "W", "U"])
    if column == "cd_education_status":
        return ((idx // 10 % 7).astype(np.int32), EDUCATION)
    if column == "cd_purchase_estimate":
        return (idx // 70 % 20 + 1) * 500
    if column == "cd_credit_rating":
        return ((idx // 1400 % 4).astype(np.int32), CREDIT_RATING)
    if column == "cd_dep_count":
        return idx // 5600 % 7
    if column == "cd_dep_employed_count":
        return idx // 39200 % 7
    if column == "cd_dep_college_count":
        return idx // 274400 % 7
    raise KeyError(column)


def _gen_time_dim(column: str, idx: np.ndarray, sf: float):
    if column == "t_time_sk":
        return idx
    if column == "t_time_id":
        return [f"AAAAAAAA{int(v):08d}" for v in idx]
    if column == "t_time":
        return idx
    if column == "t_hour":
        return idx // 3600
    if column == "t_minute":
        return idx // 60 % 60
    if column == "t_second":
        return idx % 60
    if column == "t_am_pm":
        return ((idx // 43200).astype(np.int32), ["AM", "PM"])
    if column == "t_shift":
        return ((idx // 28800).astype(np.int32),
                ["third", "first", "second"])
    if column == "t_meal_time":
        h = idx // 3600
        code = np.where((h >= 6) & (h <= 8), 1,
                        np.where((h >= 11) & (h <= 13), 2,
                                 np.where((h >= 17) & (h <= 19), 3, 0)))
        return (code.astype(np.int32),
                ["", "breakfast", "lunch", "dinner"])
    raise KeyError(column)


def _gen_call_center(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "cc_call_center_sk":
        return sk
    if column == "cc_call_center_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "cc_name":
        return ((idx % len(CC_NAMES)).astype(np.int32), CC_NAMES)
    if column == "cc_class":
        return ((idx % len(CC_CLASSES)).astype(np.int32), CC_CLASSES)
    if column == "cc_employees":
        return _uniform("call_center", "emp", idx, 1, 7)
    if column == "cc_manager":
        return (_uniform("call_center", "mgr", idx, 0,
                         len(FIRST_NAMES) - 1).astype(np.int32), FIRST_NAMES)
    if column == "cc_county":
        return (_uniform("call_center", "county", idx, 0,
                         len(COUNTIES) - 1).astype(np.int32), COUNTIES)
    if column == "cc_state":
        return (_uniform("call_center", "state", idx, 0,
                         len(STATES) - 1).astype(np.int32), STATES)
    raise KeyError(column)


def _gen_web_page(column: str, idx: np.ndarray, sf: float):
    sk = idx + 1
    if column == "wp_web_page_sk":
        return sk
    if column == "wp_web_page_id":
        return [f"AAAAAAAA{int(v):08d}" for v in sk]
    if column == "wp_url":
        return (np.zeros(len(idx), dtype=np.int32),
                ["http://www.foo.com"])
    if column == "wp_char_count":
        return _uniform("web_page", "chars", idx, 100, 8000)
    if column == "wp_link_count":
        return _uniform("web_page", "links", idx, 2, 25)
    raise KeyError(column)


_GENERATORS = {
    "date_dim": _gen_date_dim, "item": _gen_item, "customer": _gen_customer,
    "customer_address": _gen_customer_address, "store": _gen_store,
    "web_site": _gen_web_site, "warehouse": _gen_warehouse,
    "promotion": _gen_promotion, "store_sales": _gen_store_sales,
    "web_sales": _gen_web_sales, "web_returns": _gen_web_returns,
    "store_returns": _gen_store_returns,
    "catalog_sales": _gen_catalog_sales,
    "catalog_returns": _gen_catalog_returns,
    "inventory": _gen_inventory, "catalog_page": _gen_catalog_page,
    "ship_mode": _gen_ship_mode, "reason": _gen_reason,
    "income_band": _gen_income_band,
    "household_demographics": _gen_household_demographics,
    "customer_demographics": _gen_customer_demographics,
    "time_dim": _gen_time_dim, "call_center": _gen_call_center,
    "web_page": _gen_web_page,
}


# ---------------------------------------------------------------------------
# public connector API (same shape as tpch's)
# ---------------------------------------------------------------------------

def table_row_count(table: str, sf: float) -> int:
    return _table_rows(table, sf)


def generate_column(table: str, column: str, sf: float,
                    start: int, count: int):
    idx = np.arange(start, start + count, dtype=np.int64)
    return _GENERATORS[table](column, idx, sf)


def generate_values_at(table: str, column: str, sf: float,
                       ids: np.ndarray) -> list:
    out = _GENERATORS[table](column, np.asarray(ids, dtype=np.int64), sf)
    if isinstance(out, tuple):
        codes, values = out
        return [values[int(c)] for c in codes]
    return out


def _connector_stats(handle) -> float:
    sf = dict(handle.extra).get("scaleFactor", 0.01)
    return float(table_row_count(handle.table_name, sf))


from ..sql.fragmenter import register_connector_stats as _reg_stats  # noqa: E402

_reg_stats("tpcds", _connector_stats)
