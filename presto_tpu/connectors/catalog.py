"""Connector catalog: registry + dispatch over connector modules.

The slim analog of the reference's connector SPI surface
(presto-spi/.../spi/connector/ConnectorMetadata.java:73 for table/column
metadata, ConnectorSplitManager.java:23 for splits): the engine layers
(planner, pipeline compiler, scheduler, reference interpreter) call this
module instead of a concrete connector.  Connector modules are duck-typed —
they expose SCHEMAS / PREFIXES / OPEN_DOMAIN / ROWID_* / table_row_count /
generate_column / generate_values_at / column_type (see tpch.py, tpcds.py).

Table names are resolved with a session-preferred connector first (the
reference's session catalog), then any other registered connector — the two
built-ins overlap only on `customer`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import tpch as _tpch
from . import tpcds as _tpcds

_CONNECTORS = {"tpch": _tpch, "tpcds": _tpcds}

# merged (table, column) property sets; cross-connector collisions are
# impossible in practice (tpcds columns carry their table prefix)
OPEN_DOMAIN = set(_tpch.OPEN_DOMAIN) | set(_tpcds.OPEN_DOMAIN)
ROWID_ORDERED = set(_tpch.ROWID_ORDERED) | set(_tpcds.ROWID_ORDERED)
ROWID_DISTINCT = set(_tpch.ROWID_DISTINCT) | set(_tpcds.ROWID_DISTINCT)


@dataclass
class HostColumn:
    """Host-generated column carrying a null mask (storage connectors can
    produce NULLs; the generated tpch/tpcds columns never do).  `values` is
    a numpy array or a (codes, dictionary-values) tuple."""
    values: object
    nulls: Optional[np.ndarray] = None


def _rebuild_property_sets() -> None:
    """Recompute the merged per-column property sets from the registered
    connectors (mutated in place: engine code holds references)."""
    for merged, attr in ((OPEN_DOMAIN, "OPEN_DOMAIN"),
                         (ROWID_ORDERED, "ROWID_ORDERED"),
                         (ROWID_DISTINCT, "ROWID_DISTINCT")):
        merged.clear()
        for conn in _CONNECTORS.values():
            merged.update(getattr(conn, attr))


def register_connector(connector_id: str, connector) -> None:
    """Register a connector instance/module at runtime (the Plugin.java:42 /
    ConnectorFactory analog).  `connector` is duck-typed: see module doc."""
    _CONNECTORS[connector_id] = connector
    _rebuild_property_sets()


def unregister_connector(connector_id: str) -> None:
    _CONNECTORS.pop(connector_id, None)
    _rebuild_property_sets()


def module(connector_id: str):
    return _CONNECTORS[connector_id]


def resolve_table(name: str, preferred: str = "tpch") -> Optional[str]:
    """Table name -> connector id (session-preferred connector wins)."""
    order = [preferred] + [c for c in _CONNECTORS if c != preferred]
    for cid in order:
        if name in _CONNECTORS[cid].SCHEMAS:
            return cid
    return None


def _module_for_table(table: str):
    cid = resolve_table(table)
    if cid is None:
        raise KeyError(f"unknown table {table!r}")
    return _CONNECTORS[cid]


# ---------------------------------------------------------------------------
# dispatching mirrors of the connector API (by table name; the two built-in
# catalogs agree on `customer`'s generator module only via resolve order, so
# engine code that may see either passes the connector id explicitly where
# it has one — the lazy-column tag and TableHandle carry it)
# ---------------------------------------------------------------------------

def schema(table: str, connector_id: Optional[str] = None):
    m = _CONNECTORS[connector_id] if connector_id else _module_for_table(table)
    return m.SCHEMAS[table]

def prefix(table: str, connector_id: Optional[str] = None) -> str:
    m = _CONNECTORS[connector_id] if connector_id else _module_for_table(table)
    return m.PREFIXES[table]

def column_type(table: str, column: str, connector_id: Optional[str] = None):
    m = _CONNECTORS[connector_id] if connector_id else _module_for_table(table)
    return m.column_type(table, column)

def table_row_count(table: str, sf: float,
                    connector_id: Optional[str] = None) -> int:
    m = _CONNECTORS[connector_id] if connector_id else _module_for_table(table)
    return m.table_row_count(table, sf)

def generate_column(table: str, column: str, sf: float, start: int,
                    count: int, connector_id: Optional[str] = None):
    m = _CONNECTORS[connector_id] if connector_id else _module_for_table(table)
    return m.generate_column(table, column, sf, start, count)

def generate_values_at(table: str, column: str, sf: float, ids,
                       connector_id: Optional[str] = None) -> list:
    m = _CONNECTORS[connector_id] if connector_id else _module_for_table(table)
    return m.generate_values_at(table, column, sf, ids)


# ---------------------------------------------------------------------------
# splits (reference ConnectorSplitManager / TpchSplitManager)
# ---------------------------------------------------------------------------

@dataclass
class TableSplit:
    """A row-range shard of one generated table."""
    connector: str
    table: str
    sf: float
    start: int
    end: int

    def to_dict(self):
        return {"connectorId": self.connector, "table": self.table,
                "sf": self.sf, "start": self.start, "end": self.end}

    @staticmethod
    def from_dict(d):
        return TableSplit(d.get("connectorId", "tpch"), d["table"], d["sf"],
                          d["start"], d["end"])


def make_splits(table: str, sf: float, splits: int,
                connector_id: Optional[str] = None) -> List[TableSplit]:
    cid = connector_id or resolve_table(table)
    total = table_row_count(table, sf, cid)
    per = (total + splits - 1) // splits
    return [TableSplit(cid, table, sf, i * per, min((i + 1) * per, total))
            for i in range(splits) if i * per < total]


# ---------------------------------------------------------------------------
# bucketing metadata for grouped (lifespan) execution — the
# ConnectorMetadata bucketing surface the reference's
# GroupedExecutionTagger consults (see connectors/tpch.py BUCKET_COLUMNS)
# ---------------------------------------------------------------------------

def bucket_column(table: str,
                  connector_id: Optional[str] = None) -> Optional[str]:
    """The column this table is range-bucketed on, or None.

    Contract: a declared bucket column is NON-NULL.  Grouped execution
    assigns each output group to exactly one lifespan by its bucket-key
    value; a NULL key has no home bucket, so its group would be replayed
    (and its aggregate duplicated) across lifespans.  The engine
    re-checks this at eligibility time (exec/grouped.py rejects plans
    whose anchor key can be null), but a connector must never declare a
    nullable column here."""
    m = _CONNECTORS.get(connector_id) if connector_id \
        else _module_for_table(table)
    if m is None:
        return None
    return getattr(m, "BUCKET_COLUMNS", {}).get(table)


def bucket_layout(sf: float, n_buckets: int,
                  connector_id: Optional[str] = None):
    """Co-bucketed lifespan layout (list of TableBucket), or None when the
    connector has no bucketing.  Each TableBucket's key range
    [key_lo, key_hi) maps to the contiguous row range holding exactly
    those (non-null — see bucket_column) keys in every co-bucketed
    table; successive buckets tile both the key domain and each table's
    rows."""
    m = _CONNECTORS.get(connector_id)
    fn = getattr(m, "bucket_layout", None) if m is not None else None
    return None if fn is None else fn(sf, n_buckets)
