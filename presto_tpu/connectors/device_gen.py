"""Device-side (jitted) column generation for the tpch/tpcds connectors.

The host generators in tpch.py / tpcds.py are pure counter-hash functions of
the row index, so the numeric and dictionary-coded columns can be produced
DIRECTLY ON THE TPU: the table scan becomes an XLA kernel that materializes
columns into HBM, removing both the host-side numpy generation and the
host->device transfer from the scan path (which dominate scan cost — the
reference's analog is Velox reading Arrow buffers straight into memory;
here the "storage" is a hash function, so the idiomatic TPU move is to
evaluate it on-chip).

Every function here mirrors its numpy twin bit-exactly (same splitmix64,
same seeds, same arithmetic); test_device_gen.py asserts exact equality per
column.  Open-domain string columns keep the lazy row-id path; formula
strings and tiny dimension tables stay on the host.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from . import tpch as H
from . import tpcds as DS

_U = jnp.uint64


def _dsplitmix64(x):
    x = x.astype(jnp.uint64)
    x = x + _U(0x9E3779B97F4A7C15)
    x = (x ^ (x >> _U(30))) * _U(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> _U(27))) * _U(0x94D049BB133111EB)
    return x ^ (x >> _U(31))


def _cell(stream: str, column: str, idx):
    seed = H._stream_seed(stream, column)        # static numpy scalar
    return _dsplitmix64(idx.astype(jnp.uint64) * _U(0x9E3779B97F4A7C15)
                        + _U(int(seed)))


def _uniform(stream: str, column: str, idx, lo: int, hi: int):
    h = _cell(stream, column, idx)
    return (h % _U(hi - lo + 1)).astype(jnp.int64) + lo


# ---------------------------------------------------------------------------
# tpch
# ---------------------------------------------------------------------------

def _order_date(orderkey):
    return _uniform("orders", "orderdate", orderkey,
                    H.MIN_ORDER_DATE, H.MAX_ORDER_DATE)


def _retail_price(partkey):
    return 90000 + ((partkey // 10) % 20001) + 100 * (partkey % 1000)


def _li_suppkey(idx, sf):
    partkey = _uniform("lineitem", "partkey", idx, 1,
                       H._table_rows("part", sf))
    s = H._table_rows("supplier", sf)
    j = _uniform("lineitem", "suppj", idx, 0, 3)
    return ((partkey + j * (s // 4 + (partkey - 1) // s)) % s) + 1


def _li_cum_table():
    """(5040, 8) cumulative lines-per-order permutation table (numpy host
    constant; jnp.asarray per call so a traced constant is never cached
    across jit scopes)."""
    _, cum = H._li_perm_tables()
    return jnp.asarray(cum.astype(np.int32))


def _li_order_map(idx, sf: float):
    """Device mirror of tpch._li_order_map: idx -> (orderkey, linenumber)
    under the 28-lineitems-per-7-orders block scheme."""
    cum = _li_cum_table()
    n_orders = H._table_rows("orders", sf)
    full = (n_orders // 7) * 28
    b = idx // 28
    r = (idx % 28).astype(jnp.int32)
    pid = (_cell("lineitem", "orderblock", b)
           % _U(5040)).astype(jnp.int32)
    crows = cum[pid]                                     # (n, 8)
    pos = jnp.sum(r[:, None] >= crows[:, 1:], axis=1).astype(jnp.int32)
    start = jnp.take_along_axis(crows, pos[:, None], axis=1)[:, 0]
    orderkey = b * 7 + pos.astype(idx.dtype) + 1
    linenumber = (r - start + 1).astype(idx.dtype)
    tail = idx >= full
    t = idx - full
    orderkey = jnp.where(tail, (n_orders // 7) * 7 + t // 4 + 1, orderkey)
    linenumber = jnp.where(tail, t % 4 + 1, linenumber)
    return orderkey, linenumber


def _tpch_lineitem(column: str, idx, sf: float):
    # (orderkey, linenumber) only where needed, mirroring the host gen
    if column == "orderkey":
        return _li_order_map(idx, sf)[0]
    if column == "linenumber":
        return _li_order_map(idx, sf)[1]
    if column == "partkey":
        return _uniform("lineitem", "partkey", idx, 1,
                        H._table_rows("part", sf))
    if column == "suppkey":
        return _li_suppkey(idx, sf)
    if column == "quantity":
        return _uniform("lineitem", "quantity", idx, 1, 50) * 100
    if column == "extendedprice":
        partkey = _uniform("lineitem", "partkey", idx, 1,
                           H._table_rows("part", sf))
        qty = _uniform("lineitem", "quantity", idx, 1, 50)
        return qty * _retail_price(partkey)
    if column == "discount":
        return _uniform("lineitem", "discount", idx, 0, 10)
    if column == "tax":
        return _uniform("lineitem", "tax", idx, 0, 8)
    if column == "shipdate":
        return _order_date(_li_order_map(idx, sf)[0]) \
            + _uniform("lineitem", "shipdays", idx, 1, 121)
    if column == "commitdate":
        return _order_date(_li_order_map(idx, sf)[0]) \
            + _uniform("lineitem", "commitdays", idx, 30, 90)
    if column == "receiptdate":
        sd = _tpch_lineitem("shipdate", idx, sf)
        return sd + _uniform("lineitem", "receiptdays", idx, 1, 30)
    if column == "returnflag":
        rd = _tpch_lineitem("receiptdate", idx, sf)
        coin = _uniform("lineitem", "rflagcoin", idx, 0, 1)
        return jnp.where(rd <= H.CURRENT_DATE, coin * 2, 1).astype(jnp.int32)
    if column == "linestatus":
        sd = _tpch_lineitem("shipdate", idx, sf)
        return (sd > H.CURRENT_DATE).astype(jnp.int32)
    if column == "shipinstruct":
        return _uniform("lineitem", "instruct", idx, 0, 3).astype(jnp.int32)
    if column == "shipmode":
        return _uniform("lineitem", "shipmode", idx, 0, 6).astype(jnp.int32)
    raise KeyError(column)


def _tpch_orders(column: str, idx, sf: float):
    orderkey = idx + 1
    if column == "orderkey":
        return orderkey
    if column == "custkey":
        c = H._table_rows("customer", sf)
        raw = _uniform("orders", "custkey", idx, 1, c // 3 * 2)
        return raw + (raw - 1) // 2 if c >= 3 else raw
    if column == "orderstatus":
        od = _order_date(orderkey)
        return jnp.where(od + 121 <= H.CURRENT_DATE, 0,
                         jnp.where(od > H.CURRENT_DATE, 1, 2)) \
            .astype(jnp.int32)
    if column == "totalprice":
        return _uniform("orders", "totalprice", idx, 90000, 50000000)
    if column == "orderdate":
        return _order_date(orderkey)
    if column == "orderpriority":
        return _uniform("orders", "priority", idx, 0, 4).astype(jnp.int32)
    if column == "shippriority":
        return jnp.zeros(idx.shape, dtype=jnp.int64)
    raise KeyError(column)


def _tpch_customer(column: str, idx, sf: float):
    if column == "custkey":
        return idx + 1
    if column == "nationkey":
        return _uniform("customer", "nationkey", idx, 0, 24)
    if column == "acctbal":
        return _uniform("customer", "acctbal", idx, -99999, 999999)
    if column == "mktsegment":
        return _uniform("customer", "segment", idx, 0, 4).astype(jnp.int32)
    raise KeyError(column)


def _tpch_part(column: str, idx, sf: float):
    partkey = idx + 1
    if column == "partkey":
        return partkey
    if column == "mfgr":
        return (_uniform("part", "mfgr", idx, 1, 5) - 1).astype(jnp.int32)
    if column == "brand":
        m = _uniform("part", "mfgr", idx, 1, 5)
        b = _uniform("part", "brand", idx, 1, 5)
        return ((m - 1) * 5 + (b - 1)).astype(jnp.int32)
    if column == "type":
        h = _cell("part", "type", idx)
        a = h % _U(6)
        b = (h >> _U(8)) % _U(5)
        c = (h >> _U(16)) % _U(5)
        return (a * _U(25) + b * _U(5) + c).astype(jnp.int32)
    if column == "size":
        return _uniform("part", "size", idx, 1, 50)
    if column == "container":
        h = _cell("part", "container", idx)
        a = h % _U(5)
        b = (h >> _U(8)) % _U(8)
        return (a * _U(8) + b).astype(jnp.int32)
    if column == "retailprice":
        return _retail_price(partkey)
    raise KeyError(column)


def _tpch_partsupp(column: str, idx, sf: float):
    partkey = idx // 4 + 1
    if column == "partkey":
        return partkey
    if column == "suppkey":
        s = H._table_rows("supplier", sf)
        j = idx % 4
        return ((partkey + j * (s // 4 + (partkey - 1) // s)) % s) + 1
    if column == "availqty":
        return _uniform("partsupp", "availqty", idx, 1, 9999)
    if column == "supplycost":
        return _uniform("partsupp", "supplycost", idx, 100, 100000)
    raise KeyError(column)


def _tpch_supplier(column: str, idx, sf: float):
    if column == "suppkey":
        return idx + 1
    if column == "nationkey":
        return _uniform("supplier", "nationkey", idx, 0, 24)
    if column == "acctbal":
        return _uniform("supplier", "acctbal", idx, -99999, 999999)
    raise KeyError(column)


# ---------------------------------------------------------------------------
# tpcds (seeds are namespaced "tpcds.<table>")
# ---------------------------------------------------------------------------

def _ds_uniform(table, column, idx, lo, hi):
    return _uniform("tpcds." + table, column, idx, lo, hi)


def _ds_store_sales(column: str, idx, sf: float):
    L = DS.LINES_PER_ORDER
    if column == "ss_sold_time_sk":
        return _ds_uniform("store_sales", "time", idx // L, 28800, 75600)
    if column == "ss_cdemo_sk":
        return _ds_uniform("store_sales", "cdemo", idx // L, 1,
                           DS._table_rows("customer_demographics", sf))
    if column == "ss_hdemo_sk":
        return _ds_uniform("store_sales", "hdemo", idx // L, 1,
                           DS._table_rows("household_demographics", sf))
    if column == "ss_addr_sk":
        return _ds_uniform("store_sales", "addr", idx // L, 1,
                           DS._table_rows("customer_address", sf))
    if column == "ss_ext_list_price":
        return (_ds_store_sales("ss_list_price", idx, sf)
                * _ds_store_sales("ss_quantity", idx, sf))
    if column == "ss_coupon_amt":
        return _ds_uniform("store_sales", "coupon", idx, 0, 50000) \
            * (_ds_uniform("store_sales", "hascoup", idx, 0, 9) == 0)
    if column == "ss_sold_date_sk":
        return DS.JULIAN_BASE + _ds_uniform("store_sales", "sold", idx // L,
                                            DS.SALES_MIN, DS.SALES_MAX)
    if column == "ss_item_sk":
        return _ds_uniform("store_sales", "item", idx, 1,
                           DS._table_rows("item", sf))
    if column == "ss_customer_sk":
        return _ds_uniform("store_sales", "cust", idx // L, 1,
                           DS._table_rows("customer", sf))
    if column == "ss_store_sk":
        return _ds_uniform("store_sales", "store", idx // L, 1,
                           DS._table_rows("store", sf))
    if column == "ss_promo_sk":
        return _ds_uniform("store_sales", "promo", idx, 1,
                           DS._table_rows("promotion", sf))
    if column == "ss_ticket_number":
        return idx // L + 1
    if column == "ss_quantity":
        return _ds_uniform("store_sales", "qty", idx, 1, 100)
    if column == "ss_wholesale_cost":
        return _ds_uniform("store_sales", "wholesale", idx, 100, 10000)
    if column == "ss_list_price":
        w = _ds_store_sales("ss_wholesale_cost", idx, sf)
        return w + w * _ds_uniform("store_sales", "markup", idx, 0, 200) // 100
    if column == "ss_sales_price":
        lp = _ds_store_sales("ss_list_price", idx, sf)
        return lp * _ds_uniform("store_sales", "dscnt", idx, 20, 100) // 100
    if column == "ss_ext_sales_price":
        return (_ds_store_sales("ss_sales_price", idx, sf)
                * _ds_store_sales("ss_quantity", idx, sf))
    if column == "ss_ext_discount_amt":
        lp = _ds_store_sales("ss_list_price", idx, sf)
        sp = _ds_store_sales("ss_sales_price", idx, sf)
        return (lp - sp) * _ds_store_sales("ss_quantity", idx, sf)
    if column == "ss_net_paid":
        return _ds_store_sales("ss_ext_sales_price", idx, sf)
    if column == "ss_net_profit":
        q = _ds_store_sales("ss_quantity", idx, sf)
        w = _ds_store_sales("ss_wholesale_cost", idx, sf)
        return _ds_store_sales("ss_net_paid", idx, sf) - q * w
    if column == "ss_ext_tax":
        return _ds_store_sales("ss_ext_sales_price", idx, sf) * 9 // 100
    if column == "ss_ext_wholesale_cost":
        return (_ds_store_sales("ss_wholesale_cost", idx, sf)
                * _ds_store_sales("ss_quantity", idx, sf))
    if column == "ss_net_paid_inc_tax":
        return (_ds_store_sales("ss_net_paid", idx, sf)
                + _ds_store_sales("ss_ext_tax", idx, sf))
    raise KeyError(column)


def _ds_web_sales(column: str, idx, sf: float):
    order = idx // DS.LINES_PER_ORDER
    if column == "ws_ship_mode_sk":
        return _ds_uniform("web_sales", "shipmode", order, 1,
                           DS._table_rows("ship_mode", sf))
    if column == "ws_sold_date_sk":
        return DS.JULIAN_BASE + _ds_uniform("web_sales", "sold", order,
                                            DS.SALES_MIN, DS.SALES_MAX)
    if column == "ws_ship_date_sk":
        sold = _ds_uniform("web_sales", "sold", order,
                           DS.SALES_MIN, DS.SALES_MAX)
        return DS.JULIAN_BASE + sold + _ds_uniform("web_sales", "lag",
                                                   idx, 1, 120)
    if column == "ws_item_sk":
        return _ds_uniform("web_sales", "item", idx, 1,
                           DS._table_rows("item", sf))
    if column == "ws_bill_customer_sk":
        return _ds_uniform("web_sales", "cust", order, 1,
                           DS._table_rows("customer", sf))
    if column == "ws_ship_addr_sk":
        return _ds_uniform("web_sales", "addr", order, 1,
                           DS._table_rows("customer_address", sf))
    if column == "ws_web_site_sk":
        return _ds_uniform("web_sales", "site", order, 1,
                           DS._table_rows("web_site", sf))
    if column == "ws_warehouse_sk":
        return _ds_uniform("web_sales", "wh", idx, 1,
                           DS._table_rows("warehouse", sf))
    if column == "ws_promo_sk":
        return _ds_uniform("web_sales", "promo", idx, 1,
                           DS._table_rows("promotion", sf))
    if column == "ws_order_number":
        return order + 1
    if column == "ws_quantity":
        return _ds_uniform("web_sales", "qty", idx, 1, 100)
    if column == "ws_sales_price":
        return _ds_uniform("web_sales", "price", idx, 100, 30000)
    if column == "ws_ext_sales_price":
        return (_ds_web_sales("ws_sales_price", idx, sf)
                * _ds_web_sales("ws_quantity", idx, sf))
    if column == "ws_ext_ship_cost":
        return _ds_uniform("web_sales", "shipcost", idx, 0, 50000)
    if column == "ws_net_paid":
        return _ds_web_sales("ws_ext_sales_price", idx, sf)
    if column == "ws_net_profit":
        return (_ds_web_sales("ws_net_paid", idx, sf)
                - _ds_uniform("web_sales", "cost", idx, 50, 40000)
                * _ds_web_sales("ws_quantity", idx, sf))
    if column == "ws_sold_time_sk":
        return _ds_uniform("web_sales", "time", order, 0, 86399)
    if column == "ws_bill_addr_sk":
        return _ds_uniform("web_sales", "baddr", order, 1,
                           DS._table_rows("customer_address", sf))
    if column == "ws_bill_cdemo_sk":
        return _ds_uniform("web_sales", "bcdemo", order, 1,
                           DS._table_rows("customer_demographics", sf))
    if column == "ws_bill_hdemo_sk":
        return _ds_uniform("web_sales", "bhdemo", order, 1,
                           DS._table_rows("household_demographics", sf))
    if column == "ws_ship_customer_sk":
        buyer = _ds_web_sales("ws_bill_customer_sk", idx, sf)
        other = _ds_uniform("web_sales", "shipcust", order, 1,
                            DS._table_rows("customer", sf))
        same = _ds_uniform("web_sales", "shipsame", order, 0, 9) < 7
        return jnp.where(same, buyer, other)
    if column == "ws_ship_cdemo_sk":
        return _ds_uniform("web_sales", "scdemo", order, 1,
                           DS._table_rows("customer_demographics", sf))
    if column == "ws_ship_hdemo_sk":
        return _ds_uniform("web_sales", "shdemo", order, 1,
                           DS._table_rows("household_demographics", sf))
    if column == "ws_web_page_sk":
        return _ds_uniform("web_sales", "page", order, 1,
                           DS._table_rows("web_page", sf))
    if column == "ws_wholesale_cost":
        return _ds_uniform("web_sales", "wholesale", idx, 100, 10000)
    if column == "ws_list_price":
        w = _ds_web_sales("ws_wholesale_cost", idx, sf)
        return w + w * _ds_uniform("web_sales", "markup", idx, 0, 200) // 100
    if column == "ws_ext_list_price":
        return (_ds_web_sales("ws_list_price", idx, sf)
                * _ds_web_sales("ws_quantity", idx, sf))
    if column == "ws_ext_discount_amt":
        lp = _ds_web_sales("ws_list_price", idx, sf)
        return ((lp - _ds_web_sales("ws_sales_price", idx, sf))
                * _ds_web_sales("ws_quantity", idx, sf)).clip(0)
    if column == "ws_ext_wholesale_cost":
        return (_ds_web_sales("ws_wholesale_cost", idx, sf)
                * _ds_web_sales("ws_quantity", idx, sf))
    if column == "ws_ext_tax":
        return _ds_web_sales("ws_ext_sales_price", idx, sf) * 9 // 100
    if column == "ws_coupon_amt":
        return _ds_uniform("web_sales", "coupon", idx, 0, 50000) \
            * (_ds_uniform("web_sales", "hascoup", idx, 0, 9) == 0)
    if column == "ws_net_paid_inc_tax":
        return (_ds_web_sales("ws_net_paid", idx, sf)
                + _ds_web_sales("ws_ext_tax", idx, sf))
    if column == "ws_net_paid_inc_ship":
        return (_ds_web_sales("ws_net_paid", idx, sf)
                + _ds_web_sales("ws_ext_ship_cost", idx, sf))
    raise KeyError(column)


def _ds_web_returns(column: str, idx, sf: float):
    n_orders = DS._table_rows("web_sales", sf) // DS.LINES_PER_ORDER
    if column == "wr_order_number":
        # monotone in the row index (host mirror: tpcds._gen_web_returns)
        # so order-number ranges are contiguous row ranges — the
        # co-bucket property bucket_layout depends on
        n_returns = DS._table_rows("web_returns", sf)
        return (idx.astype(jnp.int64) * max(1, n_orders)) // n_returns + 1
    if column == "wr_returned_date_sk":
        return DS.JULIAN_BASE + _ds_uniform("web_returns", "ret", idx,
                                            DS.SALES_MIN, DS.SALES_MAX + 60)
    if column == "wr_item_sk":
        return _ds_uniform("web_returns", "item", idx, 1,
                           DS._table_rows("item", sf))
    if column == "wr_refunded_customer_sk":
        return _ds_uniform("web_returns", "cust", idx, 1,
                           DS._table_rows("customer", sf))
    if column == "wr_return_quantity":
        return _ds_uniform("web_returns", "qty", idx, 1, 50)
    if column == "wr_return_amt":
        return _ds_uniform("web_returns", "amt", idx, 100, 500000)
    if column == "wr_net_loss":
        return _ds_uniform("web_returns", "loss", idx, 50, 100000)
    if column == "wr_returning_customer_sk":
        buyer = _ds_web_returns("wr_refunded_customer_sk", idx, sf)
        other = _ds_uniform("web_returns", "rcust", idx, 1,
                            DS._table_rows("customer", sf))
        same = _ds_uniform("web_returns", "rsame", idx, 0, 9) < 8
        return jnp.where(same, buyer, other)
    if column == "wr_refunded_addr_sk":
        return _ds_uniform("web_returns", "faddr", idx, 1,
                           DS._table_rows("customer_address", sf))
    if column == "wr_returning_addr_sk":
        return _ds_uniform("web_returns", "raddr", idx, 1,
                           DS._table_rows("customer_address", sf))
    if column == "wr_refunded_cdemo_sk":
        return _ds_uniform("web_returns", "fcdemo", idx, 1,
                           DS._table_rows("customer_demographics", sf))
    if column == "wr_returning_cdemo_sk":
        return _ds_uniform("web_returns", "rcdemo", idx, 1,
                           DS._table_rows("customer_demographics", sf))
    if column == "wr_refunded_hdemo_sk":
        return _ds_uniform("web_returns", "fhdemo", idx, 1,
                           DS._table_rows("household_demographics", sf))
    if column == "wr_web_page_sk":
        return _ds_uniform("web_returns", "page", idx, 1,
                           DS._table_rows("web_page", sf))
    if column == "wr_reason_sk":
        return _ds_uniform("web_returns", "reason", idx, 1,
                           DS._table_rows("reason", sf))
    if column == "wr_returned_time_sk":
        return _ds_uniform("web_returns", "time", idx, 0, 86399)
    if column == "wr_refunded_cash":
        amt = _ds_web_returns("wr_return_amt", idx, sf)
        return amt * _ds_uniform("web_returns", "cashfrac", idx,
                                 0, 100) // 100
    if column == "wr_reversed_charge":
        amt = _ds_web_returns("wr_return_amt", idx, sf)
        cash = _ds_web_returns("wr_refunded_cash", idx, sf)
        return (amt - cash) // 2
    if column == "wr_account_credit":
        amt = _ds_web_returns("wr_return_amt", idx, sf)
        cash = _ds_web_returns("wr_refunded_cash", idx, sf)
        rev = _ds_web_returns("wr_reversed_charge", idx, sf)
        return amt - cash - rev
    if column == "wr_fee":
        return _ds_uniform("web_returns", "fee", idx, 50, 10000)
    if column == "wr_return_ship_cost":
        return _ds_uniform("web_returns", "shipc", idx, 0, 25000)
    if column == "wr_return_tax":
        return _ds_web_returns("wr_return_amt", idx, sf) * 9 // 100
    if column == "wr_return_amt_inc_tax":
        return (_ds_web_returns("wr_return_amt", idx, sf)
                + _ds_web_returns("wr_return_tax", idx, sf))
    raise KeyError(column)


def _ds_item(column: str, idx, sf: float):
    if column == "i_item_sk":
        return idx + 1
    if column == "i_current_price":
        return _ds_uniform("item", "price", idx, 99, 9999)
    if column == "i_brand_id":
        return _ds_uniform("item", "brand", idx, 0, len(DS.BRANDS) - 1) + 1001
    if column == "i_brand":
        return _ds_uniform("item", "brand", idx, 0,
                           len(DS.BRANDS) - 1).astype(jnp.int32)
    if column == "i_class_id":
        return _ds_uniform("item", "class", idx, 0, len(DS.CLASSES) - 1) + 1
    if column == "i_class":
        return _ds_uniform("item", "class", idx, 0,
                           len(DS.CLASSES) - 1).astype(jnp.int32)
    if column == "i_category_id":
        return _ds_uniform("item", "category", idx, 0,
                           len(DS.CATEGORIES) - 1) + 1
    if column == "i_category":
        return _ds_uniform("item", "category", idx, 0,
                           len(DS.CATEGORIES) - 1).astype(jnp.int32)
    if column == "i_manufact_id":
        return _ds_uniform("item", "manufact", idx, 1, 1000)
    if column == "i_color":
        return _ds_uniform("item", "color", idx, 0,
                           len(DS.COLORS) - 1).astype(jnp.int32)
    if column == "i_manager_id":
        return _ds_uniform("item", "manager", idx, 1, 100)
    raise KeyError(column)


def _ds_customer(column: str, idx, sf: float):
    if column == "c_customer_sk":
        return idx + 1
    if column == "c_current_addr_sk":
        return _ds_uniform("customer", "addr", idx, 1,
                           DS._table_rows("customer_address", sf))
    if column == "c_first_name":
        return _ds_uniform("customer", "first", idx, 0,
                           len(DS.FIRST_NAMES) - 1).astype(jnp.int32)
    if column == "c_last_name":
        return _ds_uniform("customer", "last", idx, 0,
                           len(DS.LAST_NAMES) - 1).astype(jnp.int32)
    if column == "c_birth_year":
        return _ds_uniform("customer", "byear", idx, 1924, 1992)
    if column == "c_birth_month":
        return _ds_uniform("customer", "bmonth", idx, 1, 12)
    if column == "c_birth_country":
        return _ds_uniform("customer", "bcountry", idx, 0, 4) \
            .astype(jnp.int32)
    raise KeyError(column)


def _ds_customer_address(column: str, idx, sf: float):
    if column == "ca_address_sk":
        return idx + 1
    if column == "ca_city":
        return _ds_uniform("customer_address", "city", idx, 0,
                           len(DS.CITIES) - 1).astype(jnp.int32)
    if column == "ca_county":
        return _ds_uniform("customer_address", "county", idx, 0,
                           len(DS.COUNTIES) - 1).astype(jnp.int32)
    if column == "ca_state":
        return _ds_uniform("customer_address", "state", idx, 0,
                           len(DS.STATES) - 1).astype(jnp.int32)
    if column == "ca_country":
        return jnp.zeros(idx.shape, dtype=jnp.int32)
    if column == "ca_gmt_offset":
        return -100 * _ds_uniform("customer_address", "gmt", idx, 5, 8)
    raise KeyError(column)


# ---------------------------------------------------------------------------
# registry + public API
# ---------------------------------------------------------------------------

_TABLES = {
    ("tpch", "lineitem"): (_tpch_lineitem, {
        "orderkey", "linenumber", "partkey", "suppkey", "quantity",
        "extendedprice", "discount", "tax", "shipdate", "commitdate",
        "receiptdate", "returnflag", "linestatus", "shipinstruct",
        "shipmode"}),
    ("tpch", "orders"): (_tpch_orders, {
        "orderkey", "custkey", "orderstatus", "totalprice", "orderdate",
        "orderpriority", "shippriority"}),
    ("tpch", "customer"): (_tpch_customer, {
        "custkey", "nationkey", "acctbal", "mktsegment"}),
    ("tpch", "part"): (_tpch_part, {
        "partkey", "mfgr", "brand", "type", "size", "container",
        "retailprice"}),
    ("tpch", "partsupp"): (_tpch_partsupp, {
        "partkey", "suppkey", "availqty", "supplycost"}),
    ("tpch", "supplier"): (_tpch_supplier, {
        "suppkey", "nationkey", "acctbal"}),
    ("tpcds", "store_sales"): (_ds_store_sales, set(
        c for c, _ in DS.SCHEMAS["store_sales"])),
    ("tpcds", "web_sales"): (_ds_web_sales, set(
        c for c, _ in DS.SCHEMAS["web_sales"])),
    ("tpcds", "web_returns"): (_ds_web_returns, set(
        c for c, _ in DS.SCHEMAS["web_returns"])),
    ("tpcds", "item"): (_ds_item, {
        "i_item_sk", "i_current_price", "i_brand_id", "i_brand",
        "i_class_id", "i_class", "i_category_id", "i_category",
        "i_manufact_id", "i_color", "i_manager_id"}),
    ("tpcds", "customer"): (_ds_customer, {
        "c_customer_sk", "c_current_addr_sk", "c_first_name", "c_last_name",
        "c_birth_year", "c_birth_month", "c_birth_country"}),
    ("tpcds", "customer_address"): (_ds_customer_address, {
        "ca_address_sk", "ca_city", "ca_county", "ca_state", "ca_country",
        "ca_gmt_offset"}),
}

# dictionary value lists for the dict-coded columns above
_DICTS: Dict[Tuple[str, str, str], tuple] = {
    ("tpch", "lineitem", "returnflag"): tuple(H.RETURN_FLAGS),
    ("tpch", "lineitem", "linestatus"): tuple(H.STATUSES),
    ("tpch", "lineitem", "shipinstruct"): tuple(H.INSTRUCTIONS),
    ("tpch", "lineitem", "shipmode"): tuple(H.MODES),
    ("tpch", "orders", "orderstatus"): tuple(H.ORDER_STATUSES),
    ("tpch", "orders", "orderpriority"): tuple(H.PRIORITIES),
    ("tpch", "customer", "mktsegment"): tuple(H.SEGMENTS),
    ("tpch", "part", "mfgr"): tuple(H.MFGRS),
    ("tpch", "part", "brand"): tuple(H.BRANDS),
    ("tpch", "part", "type"): tuple(H.TYPES),
    ("tpch", "part", "container"): tuple(H.CONTAINERS),
    ("tpcds", "item", "i_brand"): tuple(DS.BRANDS),
    ("tpcds", "item", "i_class"): tuple(DS.CLASSES),
    ("tpcds", "item", "i_category"): tuple(DS.CATEGORIES),
    ("tpcds", "item", "i_color"): tuple(DS.COLORS),
    ("tpcds", "customer", "c_first_name"): tuple(DS.FIRST_NAMES),
    ("tpcds", "customer", "c_last_name"): tuple(DS.LAST_NAMES),
    ("tpcds", "customer", "c_birth_country"): (
        "UNITED STATES", "CANADA", "MEXICO", "GERMANY", "JAPAN"),
    ("tpcds", "customer_address", "ca_city"): tuple(DS.CITIES),
    ("tpcds", "customer_address", "ca_county"): tuple(DS.COUNTIES),
    ("tpcds", "customer_address", "ca_state"): tuple(DS.STATES),
    ("tpcds", "customer_address", "ca_country"): ("United States",),
}


# resident-storage encoding hints (presto_tpu/storage/encodings.py):
# columns KNOWN monotone in the row index from the generator structure
# ("rle" — run-length encodes without paying the empirical run probe's
# stricter compression bar) or known degenerate ("rle" constants).  The
# store falls back to empirical selection for unhinted columns.
_ENCODING_HINTS: Dict[Tuple[str, str, str], str] = {
    # lineitem rows are grouped by order: orderkey is monotone (~4-row
    # runs); orders/part/etc. keys are 1-row runs and stay unhinted
    ("tpch", "lineitem", "orderkey"): "rle",
    ("tpch", "orders", "shippriority"): "rle",     # constant 0
    # tpcds co-bucket layouts: sales/returns rows grouped by order
    ("tpcds", "web_sales", "ws_order_number"): "rle",
    ("tpcds", "web_returns", "wr_order_number"): "rle",
    ("tpcds", "store_sales", "ss_ticket_number"): "rle",
}


def encoding_hint(connector: str, table: str, column: str) -> Optional[str]:
    return _ENCODING_HINTS.get((connector, table, column))


def supported(connector: str, table: str, column: str) -> bool:
    entry = _TABLES.get((connector, table))
    return entry is not None and column in entry[1]


def dictionary(connector: str, table: str, column: str) -> Optional[tuple]:
    return _DICTS.get((connector, table, column))


def column(connector: str, table: str, column_name: str, sf: float, idx):
    """Generate one column for device row indices `idx` (traceable)."""
    fn, _cols = _TABLES[(connector, table)]
    return fn(column_name, idx, sf)
