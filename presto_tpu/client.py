"""Statement-protocol client (the StatementClientV1 analog).

Speaks only the REST protocol of worker/statement.py — POST /v1/statement
then follow `nextUri` until it disappears (StatementClientV1.java:88,
advance() :359-372) — so it works against any coordinator implementing the
protocol.  Values arrive as JSON and are mapped back to python types from
the column type signatures (decimals -> Decimal)."""
from __future__ import annotations

import json
import re
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from decimal import Decimal
from typing import Dict, List, Optional


class QueryError(RuntimeError):
    def __init__(self, message: str, error: dict):
        super().__init__(message)
        self.error = error


@dataclass
class StatementResult:
    query_id: str
    columns: List[dict] = field(default_factory=list)   # {name, type}
    rows: list = field(default_factory=list)
    stats: dict = field(default_factory=dict)

    @property
    def column_names(self) -> List[str]:
        return [c["name"] for c in self.columns]


class StatementClient:
    """One client session against a coordinator base URI."""

    def __init__(self, base_uri: str, user: str = "user",
                 source: str = "presto-tpu-cli",
                 catalog: str = "tpch", schema: str = "sf0.01",
                 session: Optional[Dict[str, str]] = None,
                 timeout_s: float = 120.0, trace_token: str = ""):
        self.base_uri = base_uri.rstrip("/")
        self.user = user
        self.source = source
        self.catalog = catalog
        self.schema = schema
        self.session: Dict[str, str] = dict(session or {})
        # client-supplied trace token (X-Presto-Trace-Token): replayed on
        # every request so coordinator and worker logs join on one id; the
        # coordinator mints one per query when this is empty
        self.trace_token = trace_token
        # server-side prepared statements, replayed as headers on every
        # request and updated from X-Presto-Added-Prepare /
        # X-Presto-Deallocated-Prepare responses (StatementClientV1's
        # preparedStatements map)
        self.prepared: Dict[str, str] = {}
        self.timeout_s = timeout_s

    def _request(self, url: str, method: str = "GET",
                 data: Optional[bytes] = None, _hops: int = 0) -> dict:
        from urllib.parse import quote_plus, unquote_plus
        headers = {
            "X-Presto-User": self.user,
            "X-Presto-Source": self.source,
            "X-Presto-Catalog": self.catalog,
            "X-Presto-Schema": self.schema,
        }
        if self.session:
            headers["X-Presto-Session"] = ",".join(
                f"{k}={v}" for k, v in self.session.items())
        if self.trace_token:
            headers["X-Presto-Trace-Token"] = self.trace_token
        if self.prepared:
            headers["X-Presto-Prepared-Statement"] = ",".join(
                f"{quote_plus(k)}={quote_plus(v)}"
                for k, v in self.prepared.items())
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = resp.read()
                added = resp.headers.get("X-Presto-Added-Prepare")
                if added and "=" in added:
                    k, v = added.split("=", 1)
                    self.prepared[unquote_plus(k)] = unquote_plus(v)
                dealloc = resp.headers.get("X-Presto-Deallocated-Prepare")
                if dealloc:
                    self.prepared.pop(unquote_plus(dealloc), None)
        except urllib.error.HTTPError as e:
            if e.code in (307, 308) and "Location" in e.headers:
                if _hops >= 5:
                    raise QueryError("redirect loop (more than 5 hops)",
                                     {"location": e.headers["Location"]})
                # a query router redirects POST /v1/statement to the chosen
                # cluster (urllib won't re-POST a redirect by itself)
                return self._request(e.headers["Location"], method, data,
                                     _hops + 1)
            raise
        return json.loads(body) if body else {}

    def execute(self, sql: str) -> StatementResult:
        """Submit and poll to completion (the CLI's blocking path).

        A coordinator restart empties the server-side prepared-statement
        registry; when the server rejects a statement over a template
        this client still holds, the template is re-PREPAREd from the
        local copy and the statement replayed ONCE, transparently — the
        dbapi layer and long-lived CLI sessions survive a rolling
        coordinator restart without re-preparing by hand."""
        try:
            return self._execute_once(sql)
        except QueryError as e:
            m = re.search(r"prepared statement '(\w+)' does not exist",
                          str(e))
            if m is None or m.group(1) not in self.prepared:
                raise
            name = m.group(1)
            self._execute_once(f"prepare {name} from "
                               f"{self.prepared[name]}")
            return self._execute_once(sql)

    def _execute_once(self, sql: str) -> StatementResult:
        resp = self._request(f"{self.base_uri}/v1/statement", "POST",
                             sql.encode())
        result = StatementResult(resp.get("id", ""))
        deadline = time.time() + self.timeout_s
        while True:
            if "error" in resp:
                raise QueryError(resp["error"].get("message", "failed"),
                                 resp["error"])
            if resp.get("columns") and not result.columns:
                result.columns = resp["columns"]
            for row in resp.get("data", []) or []:
                result.rows.append(self._decode_row(row, result.columns))
            result.stats = resp.get("stats", result.stats)
            nxt = resp.get("nextUri")
            if not nxt:
                return result
            if time.time() > deadline:
                self.cancel(nxt)
                raise TimeoutError(f"query {result.query_id} timed out")
            resp = self._request(nxt)

    def cancel(self, next_uri: str) -> None:
        """Cancel via DELETE on the current nextUri (it carries the
        per-query slug, like StatementClientV1.close)."""
        try:
            self._request(next_uri, "DELETE")
        except OSError:
            pass

    @staticmethod
    def _decode_row(row: list, columns: List[dict]) -> list:
        out = []
        for v, c in zip(row, columns or [{}] * len(row)):
            t = c.get("type", "")
            if v is not None and t.startswith("decimal"):
                v = Decimal(v)
            out.append(v)
        return out
