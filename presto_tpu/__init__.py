"""presto-tpu-execution: a TPU-native Presto worker backend.

See SURVEY.md for the structural analysis of the reference (PrestoDB) this
framework is built against, and README.md for the architecture overview.
"""
import jax as _jax

# The engine's value domains are 64-bit (BIGINT, DOUBLE, long decimal
# accumulators), mirroring the JVM's long/double.  x64 must be on before any
# array is created.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: pipeline shapes recur across queries and
# processes, and TPU sort/scan kernels can take tens of seconds to compile.
# Opt out with PRESTO_TPU_NO_COMPILE_CACHE=1.
import os as _os

def _host_fingerprint() -> str:
    """Short id of this host's CPU capabilities.  XLA:CPU persists AOT
    results whose machine features must match the executing host; loading
    an entry compiled on a different CPU can SIGILL/segfault (observed as
    cpu_aot_loader 'machine type ... doesn't match' faults).  Scoping the
    cache directory per host-CPU makes foreign entries invisible."""
    import hashlib
    import platform
    feats = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    feats += " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    return hashlib.sha256(feats.encode()).hexdigest()[:12]


# The XLA:CPU backend persists AOT executables whose recorded machine
# features can mismatch even the producing host's runtime detection
# (cpu_aot_loader warns "could lead to execution errors such as SIGILL",
# and full-suite runs twice segfaulted inside
# compilation_cache.get_executable_and_time) — so the persistent cache
# stays OFF for the CPU backend and ON for TPU, where compiles are the
# expensive path it exists for.  The backend is taken from the FIRST
# JAX_PLATFORMS entry when set; otherwise from the resolved default
# backend (initializing it — every real process does so moments later).
def _wants_persistent_cache() -> bool:
    plat = (_os.environ.get("JAX_PLATFORMS")
            or _os.environ.get("JAX_PLATFORM_NAME") or "")
    first = plat.split(",")[0].strip().lower()
    if first:
        return first != "cpu"
    try:
        return _jax.default_backend() != "cpu"
    except Exception:
        return False


if not _os.environ.get("PRESTO_TPU_NO_COMPILE_CACHE") \
        and _wants_persistent_cache():
    _cache_dir = _os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if _cache_dir is None:
        _cache_dir = _os.path.join(
            _os.path.expanduser("~/.cache/presto_tpu_xla"),
            _host_fingerprint())
    try:
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:   # cache is best-effort
        pass

__version__ = "0.1.0"
