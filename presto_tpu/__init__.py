"""presto-tpu-execution: a TPU-native Presto worker backend.

See SURVEY.md for the structural analysis of the reference (PrestoDB) this
framework is built against, and README.md for the architecture overview.
"""
import jax as _jax

# The engine's value domains are 64-bit (BIGINT, DOUBLE, long decimal
# accumulators), mirroring the JVM's long/double.  x64 must be on before any
# array is created.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
