"""Retention-bounded query history: terminal QueryInfo snapshots that
survive worker restarts.

The in-memory DispatchManager keeps a bounded dict of done queries for
/v1/query, but it dies with the process; this store is the durable tier
(the reference's QueryHistory / system.runtime.queries over completed
queries).  One JSON record per line, append-on-record; retention is
enforced by count AND age, and the file is compacted (rewritten from the
live entries) once the appended backlog doubles the retention bound, so
an immortal worker cannot grow the spool without bound.
"""
from __future__ import annotations

import json
import os
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from ..common.locks import OrderedLock
from ..worker.events import EventListener


class QueryHistoryStore:
    """`path=None` keeps history in memory only (tests, embedded runs);
    with a path, records append to a JSONL spool reloaded on restart."""

    def __init__(self, path: Optional[str] = None, max_count: int = 200,
                 max_age_s: Optional[float] = None,
                 clock=time.time):
        if max_count <= 0:
            raise ValueError("history max_count must be positive")
        self.path = path
        self.max_count = max_count
        self.max_age_s = max_age_s
        self._clock = clock
        # rank 60: held across the spool file I/O, never nests deeper
        self._lock = OrderedLock("query-history", 60)  # lint: guarded-by(_lock)
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._appended_since_compact = 0
        self.loaded = 0          # records reloaded from the spool
        self.recorded = 0
        self.evicted = 0
        self.load_errors = 0     # malformed spool lines skipped
        if path:
            self._load()

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        # locked even though only __init__ calls it: subclasses / reload
        # paths must not mutate _entries while readers hold the lock
        with self._lock:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                        qid = rec["queryId"]
                    except Exception:
                        self.load_errors += 1
                        continue
                    # later lines win: a re-recorded id supersedes
                    self._entries.pop(qid, None)
                    self._entries[qid] = rec
                    self.loaded += 1
            self._evict_locked()
            self._compact_locked()

    def _compact_locked(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for rec in self._entries.values():
                f.write(json.dumps(rec, default=str) + "\n")
        os.replace(tmp, self.path)
        self._appended_since_compact = 0

    # -- retention ---------------------------------------------------------

    def _evict_locked(self) -> None:
        if self.max_age_s is not None:
            cutoff = self._clock() - self.max_age_s
            stale = [qid for qid, rec in self._entries.items()
                     if rec.get("recordedAt", 0) < cutoff]
            for qid in stale:
                del self._entries[qid]
                self.evicted += 1
        while len(self._entries) > self.max_count:
            self._entries.popitem(last=False)
            self.evicted += 1

    # -- API ---------------------------------------------------------------

    def record(self, info: dict) -> None:
        """Persist one terminal QueryInfo-shaped record (must carry
        queryId).  Re-recording a query id supersedes the old record."""
        qid = info.get("queryId")
        if not qid:
            raise ValueError("history record needs a queryId")
        rec = dict(info)
        rec.setdefault("recordedAt", self._clock())
        with self._lock:
            self._entries.pop(qid, None)
            self._entries[qid] = rec
            self.recorded += 1
            self._evict_locked()
            if self.path:
                with open(self.path, "a") as f:
                    f.write(json.dumps(rec, default=str) + "\n")
                self._appended_since_compact += 1
                if self._appended_since_compact > 2 * self.max_count:
                    self._compact_locked()

    def get(self, query_id: str) -> Optional[dict]:
        with self._lock:
            self._evict_locked()
            rec = self._entries.get(query_id)
            return dict(rec) if rec else None

    def list(self, state: Optional[str] = None) -> List[dict]:
        """Newest-first listing, optionally filtered by terminal state
        (FINISHED / FAILED / CANCELED)."""
        with self._lock:
            self._evict_locked()
            recs = [dict(r) for r in reversed(self._entries.values())]
        if state:
            state = state.upper()
            recs = [r for r in recs if r.get("state") == state]
        return recs

    def find_by_template(self, template_key: str,
                         state: Optional[str] = "FINISHED"
                         ) -> Optional[dict]:
        """Newest record whose "planTemplate" matches — the lookup behind
        history-based sizing (exec/runner.py): a repeat run of the same
        canonical plan template seeds its task counts / aggregation slots
        / admission estimate from what the last run actually observed."""
        if not template_key:
            return None
        with self._lock:
            self._evict_locked()
            for rec in reversed(self._entries.values()):
                if rec.get("planTemplate") != template_key:
                    continue
                if state and rec.get("state") != state:
                    continue
                return dict(rec)
        return None

    def counts_by_state(self) -> Dict[str, int]:
        with self._lock:
            self._evict_locked()
            out: Dict[str, int] = {}
            for rec in self._entries.values():
                s = rec.get("state", "UNKNOWN")
                out[s] = out.get(s, 0) + 1
            return out

    def __len__(self) -> int:
        with self._lock:
            self._evict_locked()
            return len(self._entries)

    def counters(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "recorded": self.recorded, "loaded": self.loaded,
                    "evicted": self.evicted,
                    "load_errors": self.load_errors}


class HistoryEventListener(EventListener):
    """Bridges QueryCompletedEvent -> the history store.  Registered by
    the WorkerServer on its dispatch event manager; the extra fields
    callback lets the server enrich records with state the event does
    not carry (profiler trace dir, query_info_extra)."""

    def __init__(self, store: QueryHistoryStore, extra_fields=None):
        self.store = store
        self._extra_fields = extra_fields

    def query_completed(self, event) -> None:
        rec = {
            "queryId": event.query_id,
            "query": event.sql,
            "user": event.user,
            "state": event.state,
            "traceToken": getattr(event, "trace_token", ""),
            "resourceGroup": getattr(event, "resource_group", ""),
            "createTime": event.create_time,
            "endTime": event.end_time,
            "wallTimeSeconds": event.wall_time_s,
            "queuedTimeSeconds": event.queued_time_s,
            "rows": event.rows,
            "errorMessage": event.error,
            "peakMemoryBytes": event.peak_memory_bytes,
        }
        if self._extra_fields is not None:
            try:
                rec.update(self._extra_fields(event) or {})
            except Exception:
                pass  # enrichment is best-effort; the base record lands
        self.store.record(rec)
