"""Per-query device profiler capture.

The `profile` session property wraps ONE query's execution in
jax.profiler.trace(), writing a TensorBoard-loadable trace directory
per query — the device-level twin of EXPLAIN ANALYZE: operator stats
say WHERE the rows went, the profiler trace says what the chip did
(XLA program timelines, DMA waits, fusion boundaries).  "Accelerating
Presto with GPUs" locates its wins with exactly this kind of capture.

Hard rule: profiling is best-effort.  A profiler failure (unsupported
backend, a concurrent capture already holding the singleton profiler
session, a read-only profile dir) must NEVER fail the query — the
capture silently degrades to None and the query runs unprofiled.
"""
from __future__ import annotations

import os
import re
import threading
import time
from contextlib import contextmanager
from typing import Iterator, Optional

# jax.profiler supports one active trace session per process; a second
# start_trace raises.  Serialize via non-blocking acquire: a query that
# loses the race runs unprofiled rather than queueing behind another
# query's capture.
_capture_lock = threading.Lock()


def _safe_dirname(query_id: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", query_id) or "query"


@contextmanager
def profile_capture(profile_dir: Optional[str],
                    query_id: str,
                    enabled: bool = True,
                    clock=time.time) -> Iterator[Optional[str]]:
    """Yield the per-query trace directory being captured, or None when
    capture is disabled/unavailable.  The yielded path is what QueryInfo
    and the EXPLAIN ANALYZE footer report."""
    if not enabled or not profile_dir:
        yield None
        return
    if not _capture_lock.acquire(blocking=False):
        yield None
        return
    trace_dir = os.path.join(
        profile_dir, f"{_safe_dirname(query_id)}-{int(clock() * 1000)}")
    started = False
    try:
        try:
            os.makedirs(trace_dir, exist_ok=True)
            import jax.profiler
            jax.profiler.start_trace(trace_dir)
            started = True
        except Exception:
            trace_dir = None
        yield trace_dir if started else None
    finally:
        if started:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            except Exception:
                pass
        _capture_lock.release()
