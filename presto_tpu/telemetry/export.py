"""The telemetry export pipeline: bounded queue -> background flush
thread -> pluggable sink.

Design contract (the acceptance bar for this subsystem):

  * the query path NEVER blocks on telemetry: enqueue is put_nowait on a
    bounded queue; when the sink cannot keep up, payloads are DROPPED and
    the drop is metered (`dropped`), exactly like the reference's
    query-completion event queue under load.
  * delivery failures retry with the PR 2 exponential-backoff + full-
    jitter error budget (worker/exchange.py _backoff): transient sink
    outages are absorbed, a sink dead past `max_error_duration_s` drops
    the payload (`dropped_after_retry`) instead of wedging the flush
    thread forever.
  * sinks are pluggable: JSONL file (ops spool), HTTP OTLP-JSON (a real
    collector's /v1/traces + /v1/metrics), and an in-process collector
    for tests/e2e assertions.
"""
from __future__ import annotations

import json
import queue
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..common.locks import OrderedCondition, OrderedLock
from .otlp import (metrics_to_resource_metrics, scrape_metric_points,
                   spans_to_resource_spans)

_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 2.0


class TelemetrySink:
    """SPI: receives OTLP-shaped payload dicts (one export call per
    batch item).  Implementations must be thread-safe enough for ONE
    flush thread plus close()."""

    def export(self, payload: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CollectorSink(TelemetrySink):
    """In-process collector for tests: keeps every payload, with helpers
    that answer the questions e2e tests ask (which trace ids arrived,
    which spans, which metric names)."""

    def __init__(self):
        # rank 74: sink locks are taken by the flush thread holding nothing
        self._lock = OrderedLock("telemetry-sink", 74)  # lint: guarded-by(_lock)
        self.payloads: List[dict] = []

    def export(self, payload: dict) -> None:
        with self._lock:
            self.payloads.append(payload)

    def spans(self) -> List[dict]:
        with self._lock:
            snap = list(self.payloads)
        out = []
        for p in snap:
            for rs in p.get("resourceSpans", []):
                for ss in rs.get("scopeSpans", []):
                    out.extend(ss.get("spans", []))
        return out

    def trace_ids(self) -> List[str]:
        return sorted({s["traceId"] for s in self.spans()})

    def metric_names(self) -> List[str]:
        with self._lock:
            snap = list(self.payloads)
        names = set()
        for p in snap:
            for rm in p.get("resourceMetrics", []):
                for sm in rm.get("scopeMetrics", []):
                    names.update(m["name"] for m in sm.get("metrics", []))
        return sorted(names)


class JsonlFileSink(TelemetrySink):
    """One JSON payload per line, append-only (the ops spool shape the
    FileEventListener uses for query events)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = OrderedLock("telemetry-sink", 74)  # lint: guarded-by(_lock)

    def export(self, payload: dict) -> None:
        line = json.dumps(payload, default=str)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")


class HttpOtlpSink(TelemetrySink):
    """POST OTLP-JSON to a collector endpoint: trace payloads go to
    {endpoint}/v1/traces, metric payloads to {endpoint}/v1/metrics (the
    OTLP/HTTP default paths)."""

    def __init__(self, endpoint: str, timeout_s: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def export(self, payload: dict) -> None:
        import urllib.request
        path = ("/v1/traces" if "resourceSpans" in payload
                else "/v1/metrics")
        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(payload, default=str).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()


def make_sink(kind: str, endpoint: str = "",
              path: str = "") -> Optional[TelemetrySink]:
    """telemetry.sink property -> sink instance (None disables export)."""
    kind = (kind or "none").lower()
    if kind in ("", "none", "off"):
        return None
    if kind == "jsonl":
        if not path:
            raise ValueError("telemetry.sink=jsonl needs telemetry.path")
        return JsonlFileSink(path)
    if kind in ("http", "otlp"):
        if not endpoint:
            raise ValueError(
                "telemetry.sink=http needs telemetry.otlp-endpoint")
        return HttpOtlpSink(endpoint)
    if kind == "collector":
        return CollectorSink()
    raise ValueError(f"unknown telemetry.sink {kind!r}; "
                     "expected none|jsonl|http|collector")


class TelemetryExporter:
    """Bounded batching exporter.

    enqueue() is wait-free for callers; a daemon flush thread drains the
    queue every `flush_interval_s` (or immediately when woken by
    flush()/close()) and delivers each payload through the sink with the
    budgeted-backoff retry loop.  `metrics_interval_s` > 0 additionally
    self-scrapes the process metric registries into OTLP gauge payloads
    on that period."""

    def __init__(self, sink: TelemetrySink, queue_bound: int = 256,
                 flush_interval_s: float = 0.2,
                 max_error_duration_s: float = 10.0,
                 metrics_interval_s: float = 0.0,
                 resource: Optional[dict] = None):
        if queue_bound <= 0:
            raise ValueError("queue_bound must be positive")
        self._sink = sink
        self._queue: "queue.Queue[dict]" = queue.Queue(maxsize=queue_bound)
        self.queue_bound = queue_bound
        self.flush_interval_s = flush_interval_s
        self.max_error_duration_s = max_error_duration_s
        self.metrics_interval_s = metrics_interval_s
        self.resource = dict(resource or {})
        # counters (exported via counters() into /v1/metrics)
        self._clock = 0
        self.enqueued = 0                # lint: guarded-by(_lock)
        self.exported = 0                # lint: guarded-by(_lock)
        self.dropped = 0                 # lint: guarded-by(_lock)
        self.dropped_after_retry = 0     # lint: guarded-by(_lock)
        self.retries = 0                 # lint: guarded-by(_lock)
        self.export_errors = 0           # lint: guarded-by(_lock)
        self.flushes = 0                 # lint: guarded-by(_lock)
        # rank 70: counter lock; the idle condition (72) is never held
        # while taking it, and neither nests into engine locks
        self._lock = OrderedLock("telemetry-exporter", 70)
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._idle = OrderedCondition("telemetry-idle", 72)
        self._in_flight = 0              # lint: guarded-by(_idle)
        self._thread = threading.Thread(
            target=self._flush_loop, name="telemetry-flush", daemon=True)
        self._thread.start()

    # -- producer side (query path: must never block) ----------------------

    def enqueue(self, payload: dict) -> bool:
        try:
            self._queue.put_nowait(payload)
        except queue.Full:
            with self._lock:
                self.dropped += 1
            return False
        with self._lock:
            self.enqueued += 1
        return True

    def export_spans(self, trace_token: str, spans,
                     resource: Optional[dict] = None) -> bool:
        """Convert one process's span slice for `trace_token` and queue
        it.  `resource` augments the exporter-level resource attributes
        (service.name etc.)."""
        spans = list(spans)
        if not spans:
            return True
        merged = dict(self.resource)
        merged.update(resource or {})
        return self.enqueue(
            spans_to_resource_spans(trace_token, spans, merged))

    def scrape_metrics(self) -> bool:
        """One scrape of the process metric registries -> one queued
        OTLP metrics payload."""
        points = scrape_metric_points()
        return self.enqueue(metrics_to_resource_metrics(
            points, time_unix_nano=int(time.time() * 1e9),
            resource=self.resource))

    # -- consumer side (flush thread) --------------------------------------

    def _deliver(self, payload: dict) -> bool:
        """Budgeted retry loop: the exchange client's _backoff pattern
        (exp backoff + full jitter under a wall-clock error budget),
        except exhaustion DROPS the payload instead of raising — a dead
        collector must never wedge the flush thread."""
        error_since = None
        attempt = 0
        while True:
            try:
                self._sink.export(payload)
                with self._lock:
                    self.exported += 1
                return True
            except Exception:
                now = time.monotonic()
                if error_since is None:
                    error_since = now
                with self._lock:
                    self.export_errors += 1
                if (now - error_since >= self.max_error_duration_s
                        or self._stop.is_set()):
                    with self._lock:
                        self.dropped_after_retry += 1
                    return False
                with self._lock:
                    self.retries += 1
                delay = min(_BACKOFF_CAP_S, _BACKOFF_BASE_S * (2 ** attempt))
                # full jitter keeps a worker fleet from re-probing a
                # recovering collector in lockstep
                self._stop.wait(delay * (0.5 + random.random() * 0.5))
                attempt += 1

    def _drain_once(self) -> int:
        n = 0
        while True:
            try:
                payload = self._queue.get_nowait()
            except queue.Empty:
                break
            with self._idle:
                self._in_flight += 1
            try:
                self._deliver(payload)
            finally:
                with self._idle:
                    self._in_flight -= 1
                    self._idle.notify_all()
            n += 1
        if n:
            with self._lock:
                self.flushes += 1
        return n

    def _flush_loop(self) -> None:
        last_scrape = time.monotonic()
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            if (self.metrics_interval_s > 0
                    and time.monotonic() - last_scrape
                    >= self.metrics_interval_s):
                last_scrape = time.monotonic()
                self.scrape_metrics()
            self._drain_once()
        self._drain_once()  # final drain on close

    # -- control -----------------------------------------------------------

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block (caller, never the query path) until everything queued
        so far has been delivered or dropped."""
        deadline = time.monotonic() + timeout_s
        self._wake.set()
        with self._idle:
            while not self._queue.empty() or self._in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._wake.set()
                self._idle.wait(min(remaining, 0.05))
        return True

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return {
                "enqueued": self.enqueued,
                "exported": self.exported,
                "dropped": self.dropped,
                "dropped_after_retry": self.dropped_after_retry,
                "retries": self.retries,
                "export_errors": self.export_errors,
                "flushes": self.flushes,
                "queue_depth": self._queue.qsize(),
                "queue_bound": self.queue_bound,
            }

    def close(self, timeout_s: float = 5.0) -> None:
        self.flush(timeout_s)
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout_s)
        self._sink.close()


# ---------------------------------------------------------------------------
# process-wide exporter registry
# ---------------------------------------------------------------------------
# Worker tasks and coordinator executions run deep inside the engine with
# no handle on the server that owns telemetry; like the metric registry
# singletons they reach the exporter through a process slot.  The
# WorkerServer that configured telemetry owns (and closes) it.

_process_exporter: Optional[TelemetryExporter] = None
_process_lock = threading.Lock()


def set_process_exporter(exp: Optional[TelemetryExporter]) -> None:
    global _process_exporter
    with _process_lock:
        _process_exporter = exp


def get_process_exporter() -> Optional[TelemetryExporter]:
    with _process_lock:
        return _process_exporter
