"""Telemetry export: OTLP-shaped span/metric export, the query history
store, and per-query device profiler capture.

This package is the boundary where in-process observability (the PR 9
Tracer spans, the exchange/fabric/serving/storage metric registries,
terminal QueryInfo snapshots) leaves the worker process — the analog of
the reference's OpenTelemetry TracerProvider plugin, event-listener
shipping of QueryCompletedEvents, and ClusterStatsResource.

Layers:

  * otlp.py     — pure conversion: Tracer span trees -> OTLP
                  `resourceSpans`, metric registry snapshots -> OTLP
                  `resourceMetrics`.  Trace ids derive from the
                  X-Presto-Trace-Token so coordinator and worker spans
                  stitch into ONE distributed trace.
  * export.py   — the pipeline: bounded queue + background flush thread
                  with the PR 2 jittered-backoff error budget, pluggable
                  sinks (JSONL file / HTTP OTLP-JSON / in-process
                  collector), drop/flush/retry counters.
  * history.py  — retention-bounded JSONL query history store (count +
                  age limits, reload across worker restarts).
  * profiler.py — `profile` session property: wrap one query's execution
                  in jax.profiler.trace() writing a per-query directory.
"""
from .otlp import (trace_id_for, span_id_for, spans_to_resource_spans,
                   metrics_to_resource_metrics, scrape_metric_points)
from .export import (TelemetrySink, CollectorSink, JsonlFileSink,
                     HttpOtlpSink, TelemetryExporter, make_sink,
                     set_process_exporter, get_process_exporter)
from .history import QueryHistoryStore, HistoryEventListener
from .profiler import profile_capture

__all__ = [
    "trace_id_for", "span_id_for", "spans_to_resource_spans",
    "metrics_to_resource_metrics", "scrape_metric_points",
    "TelemetrySink", "CollectorSink", "JsonlFileSink", "HttpOtlpSink",
    "TelemetryExporter", "make_sink",
    "set_process_exporter", "get_process_exporter",
    "QueryHistoryStore", "HistoryEventListener",
    "profile_capture",
]
