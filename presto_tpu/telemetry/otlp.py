"""Tracer spans / metric registry snapshots -> OTLP-JSON shaped payloads.

Pure conversion, no IO.  The payloads follow the OTLP/JSON encoding of
ExportTraceServiceRequest / ExportMetricsServiceRequest closely enough
that a real collector's /v1/traces //v1/metrics endpoints accept them:

  {"resourceSpans": [{"resource": {"attributes": [...]},
                      "scopeSpans": [{"scope": {"name": ...},
                                      "spans": [{traceId, spanId,
                                                 parentSpanId, name,
                                                 startTimeUnixNano,
                                                 endTimeUnixNano,
                                                 attributes}]}]}]}

Identity model: the trace id is derived deterministically from the
X-Presto-Trace-Token (sha256, 16 bytes hex) and every span id from
(token, span name) (sha256, 8 bytes hex).  Span names are unique within
one query's span tree by construction — "query", "fragment {fid}",
"task {fid}.{ti}", "operator {fid}.{ti}.{nid}" — so the coordinator and
each worker can export their span subsets independently and the ids
stitch into one distributed trace without any id handshake beyond the
trace token that already rides every coordinator<->worker request.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Optional, Tuple

OTLP_SCOPE = {"name": "presto_tpu.telemetry", "version": "1"}


def trace_id_for(trace_token: str) -> str:
    """Deterministic 16-byte (32 hex chars) OTLP trace id."""
    return hashlib.sha256(
        ("trace:" + trace_token).encode()).hexdigest()[:32]


def span_id_for(trace_token: str, span_name: str) -> str:
    """Deterministic 8-byte (16 hex chars) OTLP span id.  Derived from
    (token, name) so independently-exporting processes agree on ids."""
    return hashlib.sha256(
        ("span:" + trace_token + "\x00" + span_name).encode()
    ).hexdigest()[:16]


def _attr_value(v) -> dict:
    """AnyValue encoding (intValue is a decimal string per OTLP/JSON)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _attrs(d: Optional[dict]) -> List[dict]:
    return [{"key": str(k), "value": _attr_value(v)}
            for k, v in (d or {}).items()]


def _span_fields(s) -> dict:
    """Accept Span dataclasses or their to_dict() form."""
    if isinstance(s, dict):
        return s
    return {"name": s.name, "parent": s.parent, "start": s.start,
            "end": s.end, "attributes": dict(s.attributes)}


def spans_to_resource_spans(trace_token: str, spans: Iterable,
                            resource: Optional[dict] = None) -> dict:
    """Convert one process's slice of a query span tree into an OTLP
    ExportTraceServiceRequest-shaped dict.  `spans` are
    utils.runtime_stats.Span objects (or their dict form) whose `parent`
    is the parent span's NAME ("" = root)."""
    tid = trace_id_for(trace_token)
    out = []
    for s in spans:
        f = _span_fields(s)
        name = f["name"]
        parent = f.get("parent", "")
        end = f.get("end", 0.0) or f.get("start", 0.0)
        out.append({
            "traceId": tid,
            "spanId": span_id_for(trace_token, name),
            "parentSpanId": (span_id_for(trace_token, parent)
                             if parent else ""),
            "name": name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(int(f.get("start", 0.0) * 1e9)),
            "endTimeUnixNano": str(int(end * 1e9)),
            "attributes": _attrs(f.get("attributes")),
        })
    return {"resourceSpans": [{
        "resource": {"attributes": _attrs(resource)},
        "scopeSpans": [{"scope": dict(OTLP_SCOPE), "spans": out}],
    }]}


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def metrics_to_resource_metrics(points: Iterable[Tuple[str, float, dict]],
                                time_unix_nano: int,
                                resource: Optional[dict] = None) -> dict:
    """(name, value, attributes) points -> ExportMetricsServiceRequest-
    shaped dict.  Everything is encoded as a gauge: the registries expose
    monotonically-growing process counters, but a scrape reports their
    current value, which is gauge semantics for a pull-less export."""
    metrics = []
    for name, value, attrs in points:
        dp = {"timeUnixNano": str(time_unix_nano),
              "asDouble": float(value)}
        if attrs:
            dp["attributes"] = _attrs(attrs)
        metrics.append({"name": name,
                        "gauge": {"dataPoints": [dp]}})
    return {"resourceMetrics": [{
        "resource": {"attributes": _attrs(resource)},
        "scopeMetrics": [{"scope": dict(OTLP_SCOPE), "metrics": metrics}],
    }]}


def scrape_metric_points() -> List[Tuple[str, float, dict]]:
    """Flatten the process metric registries (exchange, fabric, serving,
    storage, kernel decline/DMA counters, memory arbitration/spill) into
    OTLP gauge points.  Import
    inside the function: the registries live in packages this one must
    not import at module load (telemetry is imported by worker startup)."""
    points: List[Tuple[str, float, dict]] = []

    from ..worker.exchange import EXCHANGE_METRICS
    for k, v in EXCHANGE_METRICS.snapshot().items():
        points.append((f"presto_tpu.exchange.{k}", float(v), {}))

    from ..parallel.fabric import FABRIC_METRICS
    for fabric, fields in FABRIC_METRICS.snapshot().items():
        for k, v in fields.items():
            points.append((f"presto_tpu.exchange_fabric.{k}", float(v),
                           {"fabric": fabric}))

    from ..serving.metrics import SERVING_METRICS
    for k, v in SERVING_METRICS.snapshot().items():
        if isinstance(v, dict):
            # servingBatchOccupancy histogram: lanes-per-drain -> count
            for occupancy, n in v.items():
                points.append((f"presto_tpu.serving.{k}", float(n),
                               {"occupancy": str(occupancy)}))
        else:
            points.append((f"presto_tpu.serving.{k}", float(v), {}))

    from ..storage.store import STORAGE_METRICS
    for k, v in STORAGE_METRICS.items():
        points.append((f"presto_tpu.storage.{k}", float(v), {}))

    from ..exec.kernels.scan_kernel import KERNEL_METRICS
    for k, v in KERNEL_METRICS.snapshot().items():
        if isinstance(v, dict):
            for reason, n in v.items():
                points.append((f"presto_tpu.kernel.{k}", float(n),
                               {"reason": reason}))
        else:
            points.append((f"presto_tpu.kernel.{k}", float(v), {}))

    from ..exec.memory import MEMORY_METRICS
    for k, v in MEMORY_METRICS.snapshot().items():
        points.append((f"presto_tpu.memory.{k}", float(v), {}))

    from ..exec.adaptive import ADAPTIVE_METRICS
    for k, v in ADAPTIVE_METRICS.snapshot().items():
        points.append((f"presto_tpu.adaptive.{k}", float(v), {}))

    return points
