"""presto-tpu-execution worker: the HTTP protocol shell around the TPU
pipeline engine (the analog of presto-native-execution/presto_cpp — see
SURVEY.md §2.6, §3.3)."""
from .server import WorkerServer              # noqa: F401
from .coordinator import HttpQueryRunner      # noqa: F401
