"""Thrift binary-protocol serde for the TaskStatus/TaskInfo hot path.

The reference negotiates three transports for coordinator<->worker
control messages: JSON, SMILE, and Thrift (HttpRemoteTask.java:915-931;
native worker: TaskResource.cpp:218-224 switches on the
"application/x-thrift+binary" mime type, HttpConstants.h:27).  This
module implements the Apache Thrift BINARY protocol from the public
Thrift specification (field header = type byte + i16 field id,
big-endian fixed-width ints, varint-free) — not a port of fbthrift — and
the struct schemas from the reference IDL
(presto-native-execution/presto_cpp/main/thrift/presto_thrift.thrift:
TaskStatus :292-314, ExecutionFailureInfo :505-515, Lifespan :99-102,
ErrorCode :315-320, TaskInfo :547-557).

Schemas are declarative tables, so decode skips unknown fields and
encode skips absent ones — the same forward-compatibility contract
Thrift gives the reference.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

CONTENT_TYPE = "application/x-thrift+binary"

# Thrift protocol type ids (Thrift spec, TBinaryProtocol)
T_STOP = 0
T_BOOL = 2
T_BYTE = 3
T_DOUBLE = 4
T_I16 = 6
T_I32 = 8
T_I64 = 10
T_STRING = 11
T_STRUCT = 12
T_MAP = 13
T_SET = 14
T_LIST = 15

_WIRE_TYPE = {"bool": T_BOOL, "byte": T_BYTE, "double": T_DOUBLE,
              "i16": T_I16, "i32": T_I32, "i64": T_I64,
              "string": T_STRING, "enum": T_I32}


def _wire_type(spec) -> int:
    if isinstance(spec, str):
        return _WIRE_TYPE[spec]
    kind = spec[0]
    if kind in ("list",):
        return T_LIST
    if kind == "set":
        return T_SET
    if kind == "struct":
        return T_STRUCT
    if kind == "enum":
        return T_I32
    if kind == "map":
        return T_MAP
    raise ValueError(f"bad type spec {spec!r}")


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def _enc_value(out: List[bytes], spec, value) -> None:
    if isinstance(spec, str):
        if spec == "bool":
            out.append(b"\x01" if value else b"\x00")
        elif spec == "byte":
            out.append(struct.pack(">b", int(value)))
        elif spec == "double":
            out.append(struct.pack(">d", float(value)))
        elif spec == "i16":
            out.append(struct.pack(">h", int(value)))
        elif spec == "i32":
            out.append(struct.pack(">i", int(value)))
        elif spec == "i64":
            out.append(struct.pack(">q", int(value)))
        elif spec == "string":
            raw = str(value).encode("utf-8")
            out.append(struct.pack(">i", len(raw)))
            out.append(raw)
        else:
            raise ValueError(spec)
        return
    kind = spec[0]
    if kind == "enum":
        out.append(struct.pack(">i", int(spec[1].get(value, 0))
                               if isinstance(value, str) else int(value)))
    elif kind in ("list", "set"):
        elem = spec[1]
        items = list(value)
        out.append(struct.pack(">bi", _wire_type(elem), len(items)))
        for it in items:
            _enc_value(out, elem, it)
    elif kind == "struct":
        _enc_struct(out, spec[1], value)
    else:
        raise ValueError(spec)


def _enc_struct(out: List[bytes], fields, value: dict) -> None:
    for fid, name, fspec in _fields(fields):
        v = value.get(name)
        if v is None:
            continue
        out.append(struct.pack(">bh", _wire_type(fspec), fid))
        _enc_value(out, fspec, v)
    out.append(b"\x00")         # T_STOP


def encode_struct(fields, value: dict) -> bytes:
    out: List[bytes] = []
    _enc_struct(out, fields, value)
    return b"".join(out)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def _skip(buf: memoryview, pos: int, ttype: int) -> int:
    if ttype == T_BOOL or ttype == T_BYTE:
        return pos + 1
    if ttype in (T_I16,):
        return pos + 2
    if ttype in (T_I32,):
        return pos + 4
    if ttype in (T_I64, T_DOUBLE):
        return pos + 8
    if ttype == T_STRING:
        n, = struct.unpack_from(">i", buf, pos)
        return pos + 4 + n
    if ttype in (T_LIST, T_SET):
        et, n = struct.unpack_from(">bi", buf, pos)
        pos += 5
        for _ in range(n):
            pos = _skip(buf, pos, et)
        return pos
    if ttype == T_STRUCT:
        while True:
            ft, = struct.unpack_from(">b", buf, pos)
            pos += 1
            if ft == T_STOP:
                return pos
            pos += 2
            pos = _skip(buf, pos, ft)
    if ttype == T_MAP:
        kt, vt, n = struct.unpack_from(">bbi", buf, pos)
        pos += 6
        for _ in range(n):
            pos = _skip(buf, pos, kt)
            pos = _skip(buf, pos, vt)
        return pos
    raise ValueError(f"cannot skip thrift type {ttype}")


def _dec_value(buf: memoryview, pos: int, spec):
    if isinstance(spec, str):
        if spec == "bool":
            return bool(buf[pos]), pos + 1
        if spec == "byte":
            return struct.unpack_from(">b", buf, pos)[0], pos + 1
        if spec == "double":
            return struct.unpack_from(">d", buf, pos)[0], pos + 8
        if spec == "i16":
            return struct.unpack_from(">h", buf, pos)[0], pos + 2
        if spec == "i32":
            return struct.unpack_from(">i", buf, pos)[0], pos + 4
        if spec == "i64":
            return struct.unpack_from(">q", buf, pos)[0], pos + 8
        if spec == "string":
            n, = struct.unpack_from(">i", buf, pos)
            pos += 4
            return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n
        raise ValueError(spec)
    kind = spec[0]
    if kind == "enum":
        v, = struct.unpack_from(">i", buf, pos)
        rev = {n: s for s, n in spec[1].items()}
        return rev.get(v, v), pos + 4
    if kind in ("list", "set"):
        et, n = struct.unpack_from(">bi", buf, pos)
        pos += 5
        out = []
        for _ in range(n):
            v, pos = _dec_value(buf, pos, spec[1])
            out.append(v)
        return out, pos
    if kind == "struct":
        return decode_struct(spec[1], buf, pos)
    raise ValueError(spec)


def decode_struct(fields, buf: memoryview, pos: int = 0):
    by_id = {fid: (name, fspec) for fid, name, fspec in _fields(fields)}
    out: dict = {}
    while True:
        ft, = struct.unpack_from(">b", buf, pos)
        pos += 1
        if ft == T_STOP:
            return out, pos
        fid, = struct.unpack_from(">h", buf, pos)
        pos += 2
        ent = by_id.get(fid)
        if ent is None or _wire_type(ent[1]) != ft:
            pos = _skip(buf, pos, ft)       # forward compatibility
            continue
        name, fspec = ent
        out[name], pos = _dec_value(buf, pos, fspec)


def _fields(fields):
    return fields() if callable(fields) else fields


# ---------------------------------------------------------------------------
# presto_thrift.thrift schemas
# ---------------------------------------------------------------------------

TASK_STATE = ("enum", {"PLANNED": 0, "RUNNING": 1, "FINISHED": 2,
                       "CANCELED": 3, "ABORTED": 4, "FAILED": 5})
ERROR_TYPE = ("enum", {"USER_ERROR": 0, "INTERNAL_ERROR": 1,
                       "INSUFFICIENT_RESOURCES": 2, "EXTERNAL": 3})
ERROR_CAUSE = ("enum", {"UNKNOWN": 0, "LOW_PARTITION_COUNT": 1,
                        "EXCEEDS_BROADCAST_MEMORY_LIMIT": 2})

LIFESPAN = [(1, "grouped", "bool"), (2, "groupId", "i32")]

ERROR_LOCATION = [(1, "lineNumber", "i32"), (2, "columnNumber", "i32")]

ERROR_CODE = [(1, "code", "i32"), (2, "name", "string"),
              (3, "type", ERROR_TYPE), (4, "retriable", "bool")]

HOST_ADDRESS = [(1, "hostPortString", "string")]


def _failure_fields():
    # ExecutionFailureInfo is self-recursive (field 3 cause, field 4
    # suppressed); a callable schema breaks the definition cycle
    return [(1, "type", "string"),
            (2, "message", "string"),
            (3, "cause", ("struct", _failure_fields)),
            (4, "suppressed", ("list", ("struct", _failure_fields))),
            (5, "stack", ("list", "string")),
            (6, "errorLocation", ("struct", ERROR_LOCATION)),
            (7, "errorCode", ("struct", ERROR_CODE)),
            (8, "remoteHost", ("struct", HOST_ADDRESS)),
            (9, "errorCause", ERROR_CAUSE)]


EXECUTION_FAILURE_INFO = _failure_fields

# presto_thrift.thrift:292-314
TASK_STATUS = [
    (1, "taskInstanceIdLeastSignificantBits", "i64"),
    (2, "taskInstanceIdMostSignificantBits", "i64"),
    (3, "version", "i64"),
    (4, "state", TASK_STATE),
    (5, "selfUri", "string"),
    (6, "completedDriverGroups", ("set", ("struct", LIFESPAN))),
    (7, "failures", ("list", ("struct", EXECUTION_FAILURE_INFO))),
    (8, "queuedPartitionedDrivers", "i32"),
    (9, "runningPartitionedDrivers", "i32"),
    (10, "outputBufferUtilization", "double"),
    (11, "outputBufferOverutilized", "bool"),
    (12, "physicalWrittenDataSizeInBytes", "i64"),
    (13, "memoryReservationInBytes", "i64"),
    (14, "systemMemoryReservationInBytes", "i64"),
    (15, "fullGcCount", "i64"),
    (16, "fullGcTimeInMillis", "i64"),
    (17, "peakNodeTotalMemoryReservationInBytes", "i64"),
    (18, "totalCpuTimeInNanos", "i64"),
    (19, "taskAgeInMillis", "i64"),
    (20, "queuedPartitionedSplitsWeight", "i64"),
    (21, "runningPartitionedSplitsWeight", "i64"),
]


# ---------------------------------------------------------------------------
# JSON-dict <-> thrift bridges for the repo's wire DTOs
# ---------------------------------------------------------------------------

def task_status_to_thrift(d: dict) -> bytes:
    """Repo/reference JSON TaskStatus dict -> thrift bytes.  JSON field
    names match the thrift names except selfUri, which Jackson spells
    "self" (TaskStatus.java @JsonProperty("self"))."""
    msg = {k: v for k, v in d.items() if k != "failures"}
    if "self" in d:
        msg["selfUri"] = d["self"]
    failures = []
    for f in d.get("failures") or []:
        if isinstance(f, str):
            f = {"message": f, "type": "TASK_FAILURE"}
        failures.append(f)
    msg["failures"] = failures
    return encode_struct(TASK_STATUS, msg)


def task_status_from_thrift(raw: bytes) -> dict:
    """thrift bytes -> JSON-shaped TaskStatus dict (the inverse bridge the
    coordinator-side fetcher uses)."""
    msg, _ = decode_struct(TASK_STATUS, memoryview(raw))
    if "selfUri" in msg:
        msg["self"] = msg.pop("selfUri")
    return msg
