"""Task output buffers with the token-acknowledge pull protocol.

The analog of the reference's OutputBuffer family
(presto-main-base/.../execution/buffer/PartitionedOutputBuffer.java,
BroadcastOutputBuffer.java) and the results endpoint semantics of
TaskResource (presto-main/.../server/TaskResource.java:256-308): a consumer
GETs /results/{bufferId}/{token}, pages at sequence numbers >= token are
returned, an acknowledge GET frees everything below the new token, and a
complete flag tells the consumer the stream is finished.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Tuple


DEFAULT_MAX_BUFFERED_BYTES = 64 << 20


class PageBuffer:
    """One buffer id: an append-only sequence of serialized pages with
    client-driven compaction and producer backpressure (the reference's
    OutputBufferMemoryManager bounds buffered bytes and blocks the
    producer; acknowledges free memory and unblock it).

    With `retain=True` (fault-tolerant streaming: remote task retry
    enabled) acknowledged pages stay resident instead of being freed, so
    a RESTARTED consumer task can replay the stream from token 0 exactly
    — the streaming analog of the batch scheduler's durable shuffle
    files, paid in buffer memory.  Backpressure still counts only
    UNacknowledged bytes, matching the non-retain threshold behavior."""

    def __init__(self, max_buffered_bytes: int = DEFAULT_MAX_BUFFERED_BYTES,
                 retain: bool = False, coalesce_target_bytes: int = 0):
        self._pages: List[bytes] = []
        self._base = 0                    # sequence number of _pages[0]
        self._bytes = 0                   # UNacknowledged bytes (backpressure)
        self._max_bytes = max_buffered_bytes
        self._retain = retain
        self._acked = 0                   # retain mode: acknowledge watermark
        # coalescing (exchange.max-response-size): small serialized pages
        # accumulate in _pending until ~target bytes, then flush as ONE
        # buffer entry so tiny-page stages stop paying a pull round trip
        # per page.  SerializedPages are self-delimiting, so concatenation
        # is transparent to every consumer.  A get() that would otherwise
        # wait flushes first — coalescing never withholds available data.
        self._coalesce_target = max(0, int(coalesce_target_bytes))
        self._pending: List[bytes] = []
        self._pending_bytes = 0
        self._complete = False
        self._destroyed = False
        self._error: Optional[str] = None
        self._cond = threading.Condition()

    def _flush_pending_locked(self) -> None:
        if self._pending:
            self._pages.append(b"".join(self._pending))
            self._pending = []
            self._pending_bytes = 0
            self._cond.notify_all()

    def add(self, page_bytes: bytes) -> None:
        with self._cond:
            while (self._bytes >= self._max_bytes
                   and not self._destroyed and self._error is None):
                self._cond.wait(1.0)
            if self._destroyed:
                return
            self._bytes += len(page_bytes)  # pending counts for backpressure
            if self._coalesce_target > 0:
                self._pending.append(page_bytes)
                self._pending_bytes += len(page_bytes)
                if self._pending_bytes >= self._coalesce_target:
                    self._flush_pending_locked()
                else:
                    # wake a parked long-poll getter: a caught-up consumer
                    # demand-flushes rather than sleeping out its maxWait
                    self._cond.notify_all()
            else:
                self._pages.append(page_bytes)
                self._cond.notify_all()

    def set_complete(self) -> None:
        with self._cond:
            self._flush_pending_locked()  # flush boundaries are now final:
            self._complete = True         # replay after retry is identical
            self._cond.notify_all()

    def set_error(self, message: str) -> None:
        with self._cond:
            self._error = message
            self._complete = True
            self._cond.notify_all()

    def get(self, token: int, max_wait_s: float,
            max_bytes: Optional[int] = None
            ) -> Tuple[List[bytes], int, bool]:
        """Pages from `token` on; blocks up to max_wait_s for data.
        Returns (pages, next_token, buffer_complete).  `max_bytes` caps the
        response size (always at least one page) — the consumer's
        X-Presto-Max-Size.  Raises on task failure (propagates the
        producer's error to the consumer)."""
        deadline = None
        with self._cond:
            while True:
                if self._error is not None:
                    raise BufferError(self._error)
                end = self._base + len(self._pages)
                if token >= end and self._pending:
                    # the consumer caught up to the coalescer: flush the
                    # partial batch rather than make it wait for more data
                    self._flush_pending_locked()
                    end = self._base + len(self._pages)
                if token < end or self._complete:
                    pages = self._pages[max(0, token - self._base):]
                    if max_bytes is not None and len(pages) > 1:
                        taken, size = [], 0
                        for p in pages:
                            if taken and size + len(p) > max_bytes:
                                break
                            taken.append(p)
                            size += len(p)
                        pages = taken
                    next_token = max(token, self._base) + len(pages)
                    at_end = self._complete and next_token >= end
                    return pages, next_token, at_end
                import time
                if deadline is None:
                    deadline = time.monotonic() + max_wait_s
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], token, False
                self._cond.wait(remaining)

    def acknowledge(self, token: int) -> None:
        with self._cond:
            if self._retain:
                # advance the watermark and release backpressure, but keep
                # the pages for replay by a retried consumer
                upto = max(self._acked, min(token, len(self._pages)))
                if upto > self._acked:
                    self._bytes -= sum(len(p) for p in
                                       self._pages[self._acked:upto])
                    self._acked = upto
                    self._cond.notify_all()
                return
            drop = max(0, min(token - self._base, len(self._pages)))
            if drop:
                self._bytes -= sum(len(p) for p in self._pages[:drop])
                self._pages = self._pages[drop:]
                self._base += drop
                self._cond.notify_all()  # unblock a backpressured producer

    def destroy(self, force: bool = True) -> None:
        # a retained buffer survives the consumer's end-of-stream DELETE
        # (a retried consumer may still need to replay it); only task
        # teardown (cancel/evict -> destroy_all) reclaims it
        with self._cond:
            if self._retain and not force:
                return
            self._pages = []
            self._pending = []
            self._pending_bytes = 0
            self._bytes = 0
            self._complete = True
            self._destroyed = True
            self._cond.notify_all()


class OutputBufferManager:
    """All buffers of one task.  PARTITIONED routes page partition p to
    buffer p; BROADCAST replicates every page into each consumer's buffer."""

    def __init__(self, buffer_type: str, n_buffers: int,
                 retain: bool = False, coalesce_target_bytes: int = 0):
        self.buffer_type = buffer_type
        self.buffers = [PageBuffer(retain=retain,
                                   coalesce_target_bytes=coalesce_target_bytes)
                        for _ in range(max(1, n_buffers))]

    def add(self, partition: int, page_bytes: bytes) -> None:
        if self.buffer_type == "BROADCAST":
            for b in self.buffers:
                b.add(page_bytes)
        else:
            self.buffers[partition].add(page_bytes)

    def set_complete(self) -> None:
        for b in self.buffers:
            b.set_complete()

    def set_error(self, message: str) -> None:
        for b in self.buffers:
            b.set_error(message)

    def get(self, buffer_id: int, token: int, max_wait_s: float,
            max_bytes: Optional[int] = None):
        return self.buffers[buffer_id].get(token, max_wait_s,
                                           max_bytes=max_bytes)

    def acknowledge(self, buffer_id: int, token: int) -> None:
        self.buffers[buffer_id].acknowledge(token)

    def destroy(self, buffer_id: int) -> None:
        # consumer-driven destroy: honored immediately unless retained
        self.buffers[buffer_id].destroy(force=False)

    def destroy_all(self) -> None:
        for b in self.buffers:
            b.destroy(force=True)
